"""End-to-end op tracing (docs/observability.md).

Covers the contracts the tracing PR established:

- tracing OFF is free and invisible: the wire encoding is byte-identical
  to the pre-trace format and the server records zero ticks;
- a traced loopback batched op round-trips its trace id: the client span's
  stamps and the server's tick ring join on the same id, on one monotonic
  timeline;
- the flight recorder is a bounded ring (wrap evicts oldest; counters
  stay honest);
- the slow-op watchdog captures the FULL span tree of an over-threshold op
  into the protected buffer and counts it;
- /trace serves the span dump with the stage schema, and ?fmt=chrome is
  schema-valid Chrome trace-event JSON (Perfetto-loadable);
- (chaos) a traced op that trips a cluster circuit breaker still closes
  its span with an error status — failures are never invisible to traces.
"""

import asyncio
import json
import struct

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import tracing, wire
from infinistore_tpu.lib import InfiniStoreException


@pytest.fixture()
def traced():
    """Tracing enabled for the test, restored to off afterwards."""
    rec = tracing.configure(enabled=True, capacity=256, slow_op_us=0)
    rec.clear()
    yield rec
    tracing.configure(enabled=False)


@pytest.fixture(autouse=True)
def _tracing_off_after():
    yield
    tracing.configure(enabled=False)


# ---------------------------------------------------------------------------
# Wire byte-identity with tracing off.
# ---------------------------------------------------------------------------


class TestWireIdentity:
    def test_untraced_batchmeta_is_pre_trace_bytes(self):
        legacy = struct.pack("<I", 4096) + wire.encode_str_list(["a", "bb"])
        assert wire.BatchMeta(block_size=4096, keys=["a", "bb"]).encode() == legacy

    def test_untraced_segbatchmeta_is_pre_trace_bytes(self):
        legacy = (
            struct.pack("<IH", 4096, 3)
            + wire.encode_str_list(["a"])
            + struct.pack("<I", 1)
            + struct.pack("<Q", 64)
        )
        m = wire.SegBatchMeta(block_size=4096, seg_id=3, keys=["a"], offsets=[64])
        assert m.encode() == legacy

    def test_traced_op_roundtrips_and_forces_priority_byte(self):
        m = wire.BatchMeta(
            block_size=64, keys=["k"], trace_id=0xDEAD, trace_parent=0xBEEF
        )
        d = wire.BatchMeta.decode(m.encode())
        assert (d.trace_id, d.trace_parent, d.priority) == (0xDEAD, 0xBEEF, 0)
        # Traced foreground = legacy + priority byte + 16 trace bytes.
        legacy = wire.BatchMeta(block_size=64, keys=["k"]).encode()
        assert len(m.encode()) == len(legacy) + 1 + 16

    def test_traced_background_segmeta_roundtrip(self):
        m = wire.SegBatchMeta(
            block_size=64, seg_id=1, keys=["k"], offsets=[0],
            priority=wire.PRIORITY_BACKGROUND, trace_id=7, trace_parent=9,
        )
        d = wire.SegBatchMeta.decode(m.encode())
        assert (d.priority, d.trace_id, d.trace_parent) == (
            wire.PRIORITY_BACKGROUND, 7, 9,
        )

    def test_tracing_off_records_no_server_ticks(self, conn):
        assert not tracing.enabled()
        buf = np.zeros(4096, dtype=np.uint8)
        conn.register_mr(buf)

        async def go():
            await conn.write_cache_async([("off-k", 0)], 4096, buf.ctypes.data)

        asyncio.run(go())
        assert conn.get_stats()["trace"]["recorded"] == 0


# ---------------------------------------------------------------------------
# Trace-id round trip through a real loopback batched op.
# ---------------------------------------------------------------------------


class TestRoundTrip:
    def test_trace_id_reaches_server_ring(self, conn, traced):
        n, block = 8, 4096
        buf = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
        conn.register_mr(buf)
        pairs = [(f"rt-{i}", i * block) for i in range(n)]

        async def go():
            with tracing.trace_op("batched_put", stage="enqueue") as sp:
                await conn.write_cache_async(pairs, block, buf.ctypes.data)
            with tracing.trace_op("batched_get", stage="enqueue") as sg:
                await conn.read_cache_async(pairs, block, buf.ctypes.data)
            return sp, sg

        sp, sg = asyncio.run(go())
        stats = conn.get_stats()
        entries = {e["trace_id"]: e for e in stats["trace"]["entries"]}
        assert sp.trace_id in entries and sg.trace_id in entries
        tick = entries[sg.trace_id]
        # Ticks are ordered on one monotonic clock...
        assert (
            tick["recv_us"] <= tick["first_slice_us"]
            <= tick["last_slice_us"] <= tick["done_us"]
        )
        assert tick["ok"] == 1 and tick["bytes"] == n * block
        # ...and the server's work happened between the client's submit and
        # completion_ring stamps (same CLOCK_MONOTONIC timebase).
        submit = sg.stage_ts("submit")
        done = sg.stage_ts("completion_ring")
        assert submit is not None and done is not None
        assert submit <= tick["recv_us"] and tick["done_us"] <= done
        # The wire parent is the client span, so the tree joins.
        assert tick["parent_id"] == sg.span_id
        # Both spans landed in the flight recorder with ok status.
        names = {s["name"]: s for s in tracing.recorder().snapshot()}
        assert names["batched_get"]["status"] == "ok"

    def test_sync_path_stamps_and_traces(self, conn, traced):
        buf = np.random.randint(0, 256, size=4096, dtype=np.uint8)
        conn.register_mr(buf)
        with tracing.trace_op("sync_put", stage="enqueue") as sp:
            conn.write_cache([("sy-0", 0)], 4096, buf.ctypes.data)
        assert sp.stage_ts("submit") is not None
        assert sp.stage_ts("completion_ring") is not None
        assert sp.trace_id in {
            e["trace_id"] for e in conn.get_stats()["trace"]["entries"]
        }

    def test_untraced_coalesced_group_does_not_inherit_sibling_span(
        self, conn, traced
    ):
        """The coalescer's flush task inherits the SCHEDULING submitter's
        contextvars; an untraced group merged in the same tick must still
        ride trace id 0 on the wire (override_span clears the inherited
        binding), or its bytes would be attributed to an unrelated span."""
        from infinistore_tpu.connector import FetchCoalescer

        block = 4096
        buf = np.random.randint(0, 256, size=2 * block, dtype=np.uint8)
        conn.register_mr(buf)
        conn.write_cache(
            [("cg-0", 0), ("cg-1", block)], block, buf.ctypes.data
        )
        before = len(conn.get_stats()["trace"]["entries"])
        coal = FetchCoalescer(conn, block, buf.ctypes.data)

        async def go():
            with tracing.trace_op("lead", stage="enqueue") as sp:
                # Traced FOREGROUND submission: schedules the flush task,
                # whose context therefore carries sp.
                f1 = coal.submit([("cg-0", 0)], priority=0)
            # Untraced BACKGROUND submission, same tick: its own class
            # group, must NOT inherit sp from the flush task's context.
            f2 = coal.submit([("cg-1", block)], priority=1)
            await asyncio.gather(f1, f2)
            return sp

        sp = asyncio.run(go())
        entries = conn.get_stats()["trace"]["entries"][before:]
        traced_ids = [e["trace_id"] for e in entries]
        # Exactly the traced group's op recorded a tick — the untraced
        # group rode trace id 0 (untraced ops never enter the ring).
        assert traced_ids.count(sp.trace_id) == 1
        assert len(traced_ids) == 1, traced_ids

    def test_untraced_context_rides_zero_ids(self, conn, traced):
        # Tracing enabled but no span bound: ops stay untraced on the wire.
        buf = np.zeros(4096, dtype=np.uint8)
        conn.register_mr(buf)
        conn.write_cache([("nt-0", 0)], 4096, buf.ctypes.data)
        assert conn.get_stats()["trace"]["recorded"] == 0


# ---------------------------------------------------------------------------
# Flight recorder ring + watchdog.
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wrap_evicts_oldest(self):
        rec = tracing.FlightRecorder(capacity=4)
        for i in range(10):
            s = tracing.Span(f"op-{i}")
            s.status = ""  # fresh
            s.finish()  # publishes to the global recorder, not rec
        # Drive rec directly (the global recorder is configure()'s).
        rec2 = tracing.FlightRecorder(capacity=4)
        spans = [tracing.Span(f"n-{i}") for i in range(10)]
        for s in spans:
            s.t1_us = s.t0_us
            s.status = "ok"
            rec2.record(s)
        assert rec2.recorded == 10
        assert rec2.dropped == 6
        snap = rec2.snapshot()
        assert [s["name"] for s in snap] == ["n-6", "n-7", "n-8", "n-9"]

    def test_watchdog_captures_full_tree_and_counts(self):
        rec = tracing.FlightRecorder(capacity=8, slow_op_us=50_000)
        parent = tracing.Span("slow_parent")
        child = tracing.Span(
            "chunk", trace_id=parent.trace_id, parent_id=parent.span_id
        )
        child.t1_us = child.t0_us + 10
        child.status = "ok"
        rec.record(child)
        parent.t1_us = parent.t0_us + 60_000  # over threshold
        parent.status = "ok"
        rec.record(parent)
        assert rec.slow_ops_total == 1
        slow = rec.slow_snapshot()
        assert len(slow) == 1
        tree_names = {s["name"] for s in slow[0]["spans"]}
        assert tree_names == {"slow_parent", "chunk"}
        # Protected from ring wrap: flood the ring, the capture survives.
        for i in range(32):
            s = tracing.Span(f"flood-{i}")
            s.t1_us = s.t0_us
            s.status = "ok"
            rec.record(s)
        assert len(rec.slow_snapshot()) == 1
        assert rec.slow_ops_total == 1

    def test_fast_ops_do_not_trip_watchdog(self):
        rec = tracing.FlightRecorder(capacity=8, slow_op_us=10_000_000)
        s = tracing.Span("fast")
        s.t1_us = s.t0_us + 5
        s.status = "ok"
        rec.record(s)
        assert rec.slow_ops_total == 0 and rec.slow_snapshot() == []

    def test_disabled_tracing_is_noop(self):
        assert tracing.configure(enabled=False) is not None or True
        assert tracing.active_span() is None
        assert tracing.start_span("x") is None
        with tracing.trace_op("x") as sp:
            assert sp is None
        assert tracing.wire_ids(None) == (0, 0)


# ---------------------------------------------------------------------------
# Chrome trace-event export schema.
# ---------------------------------------------------------------------------


class TestChromeExport:
    def _spans(self, traced, conn):
        n, block = 4, 4096
        buf = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
        conn.register_mr(buf)
        pairs = [(f"ch-{i}", i * block) for i in range(n)]

        async def go():
            with tracing.trace_op("batched_put", stage="enqueue"):
                await conn.write_cache_async(pairs, block, buf.ctypes.data)
            with tracing.trace_op("batched_get", stage="enqueue"):
                await conn.read_cache_async(pairs, block, buf.ctypes.data)

        asyncio.run(go())
        server = tracing.server_tick_spans(conn.get_stats()["trace"])
        return tracing.recorder().snapshot() + server

    def test_events_are_schema_valid_json(self, conn, traced):
        events = tracing.chrome_trace_events(self._spans(traced, conn))
        assert events
        # JSON round trip (what a file handed to Perfetto must survive).
        events = json.loads(json.dumps({"traceEvents": events}))["traceEvents"]
        for e in events:
            assert isinstance(e["name"], str) and e["name"]
            assert e["ph"] in ("X", "i")
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            if e["ph"] == "X":
                assert e["dur"] >= 0
            else:
                assert e.get("s") == "t"  # instant scope

    def test_stage_instants_use_vocabulary(self, conn, traced):
        events = tracing.chrome_trace_events(self._spans(traced, conn))
        stage_names = {e["name"] for e in events if e["ph"] == "i"}
        assert stage_names <= set(tracing.STAGES)
        # The server side contributes its stages to the same trace.
        assert "server_recv" in stage_names

    def test_stage_breakdown_fractions_sum_to_one(self, conn, traced):
        spans = [s for s in self._spans(traced, conn) if len(s["stages"]) >= 2]
        assert spans
        # Per-span chains each contribute fractions summing to 1.0, so the
        # averaged breakdown sums to 1.0 too (the bench receipt's invariant).
        breakdown = tracing.stage_breakdown(spans)
        total = sum(v for k, v in breakdown.items() if k != "total_us")
        assert abs(total - 1.0) < 1e-6
        assert breakdown["total_us"] > 0


# ---------------------------------------------------------------------------
# GET /trace manage endpoint.
# ---------------------------------------------------------------------------


class TestTraceEndpoint:
    async def _get(self, port, path):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(f"GET {path} HTTP/1.1\r\n\r\n".encode())
        await writer.drain()
        raw = await reader.read()
        writer.close()
        return json.loads(raw.split(b"\r\n\r\n", 1)[1])

    def test_trace_endpoint_json_and_chrome(self, server, traced):
        from infinistore_tpu import lib as its_lib
        from infinistore_tpu.server import ManageServer

        cfg = its.ServerConfig(
            host="127.0.0.1", service_port=server["port"], manage_port=1,
            prealloc_size=1, minimal_allocate_size=16, log_level="error",
        )
        c = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=server["port"],
            log_level="error",
        ))
        c.connect()
        buf = np.random.randint(0, 256, size=4096, dtype=np.uint8)
        c.register_mr(buf)

        async def run():
            manage = ManageServer(cfg)
            manage._server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = manage._server.sockets[0].getsockname()[1]
            try:
                with tracing.trace_op("ep_put", stage="enqueue"):
                    await c.write_cache_async([("ep-0", 0)], 4096, buf.ctypes.data)
                doc = await self._get(port, "/trace")
                chrome = await self._get(port, "/trace?fmt=chrome")
                return doc, chrome
            finally:
                manage._server.close()
                await manage._server.wait_closed()

        old = its_lib._server_handle
        its_lib._server_handle = server["handle"]
        try:
            doc, chrome = asyncio.run(run())
        finally:
            its_lib._server_handle = old
        c.close()
        assert doc["enabled"] is True
        assert doc["stages"] == list(tracing.STAGES)
        assert any(s["name"] == "ep_put" for s in doc["spans"])
        assert doc["server_recorded"] >= 1
        assert any(
            s["attrs"].get("side") == "server" for s in doc["server_spans"]
        )
        events = chrome["traceEvents"]
        assert events and all("ph" in e and "ts" in e for e in events)


# ---------------------------------------------------------------------------
# Chaos: a traced op through a tripped circuit breaker closes with error.
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestBreakerSpanClose:
    def test_breaker_trip_closes_span_with_error(self, server, traced):
        import jax.numpy as jnp

        from infinistore_tpu.cluster import ClusterKVConnector
        from infinistore_tpu.faults import FaultRule, FaultyConnection
        from infinistore_tpu.tpu.paged import PagedKVCacheSpec

        spec = PagedKVCacheSpec(
            num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2,
            head_dim=32, dtype=jnp.bfloat16,
        )
        inner = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=server["port"],
            log_level="error",
        ))
        inner.connect()
        faulty = FaultyConnection(
            inner, [FaultRule(op="get_match_last_index", action="error")]
        )
        cluster = ClusterKVConnector(
            [faulty], spec, "m", max_blocks=8, degrade=False
        )
        tokens = list(range(16))
        spans = []
        # Strict mode: every routed lookup raises; after fail_threshold
        # consecutive transport errors the member's breaker OPENs.
        for _ in range(4):
            with pytest.raises(InfiniStoreException):
                with tracing.trace_op("cluster_lookup", stage="enqueue") as sp:
                    cluster.lookup(tokens)
            spans.append(sp)
        assert all(s.status.startswith("error:") for s in spans)
        assert all(s.t1_us >= s.t0_us for s in spans)
        health = cluster.health()["members"][0]
        assert health["breaker_state"] == "open"
        # The errored spans are in the recorder — the failure is traceable.
        recorded = [
            s for s in tracing.recorder().snapshot()
            if s["name"] == "cluster_lookup"
        ]
        assert len(recorded) == 4
        assert all(s["status"].startswith("error:") for s in recorded)
        inner.close()
