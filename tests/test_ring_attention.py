"""Ring attention (context parallelism) against the dense oracle on the
virtual 8-device CPU mesh: forward exactness, gradient exactness (long-
context training shards sequence too), and bf16 behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from infinistore_tpu.models.ring_attention import (
    dense_attention_reference,
    ring_attention,
)

B, S, H, D = 2, 32, 4, 16


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    return tuple(
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D), dtype=jnp.float32)
        for i in range(3)
    )


@pytest.mark.parametrize("ring", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense_attention(qkv, ring, causal):
    mesh = Mesh(np.array(jax.devices()[:ring]), ("sp",))
    got = ring_attention(*qkv, mesh=mesh, axis="sp", causal=causal)
    ref = dense_attention_reference(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-6, rtol=2e-6)


def test_gradients_match_dense(qkv):
    """Long-context TRAINING shards sequence too: grads through the rotating
    ppermutes must equal the dense oracle's."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    def ring_loss(q, k, v):
        return (ring_attention(q, k, v, mesh=mesh, axis="sp", causal=True) ** 2).mean()

    def dense_loss(q, k, v):
        return (dense_attention_reference(*(q, k, v), causal=True) ** 2).mean()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(*qkv)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(*qkv)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=3e-6, rtol=3e-6)


def test_bf16_inputs_fp32_accumulation(qkv):
    """bf16 inputs: the online accumulation runs in fp32, so the result must
    match the dense oracle computed on the same bf16 inputs."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
    got = np.asarray(ring_attention(q, k, v, mesh=mesh, axis="sp"), dtype=np.float32)
    ref = np.asarray(dense_attention_reference(q, k, v), dtype=np.float32)
    np.testing.assert_allclose(got, ref, atol=2e-2, rtol=2e-2)


# ---- Ulysses (all-to-all) sequence parallelism: the other canonical long-
# context sharding; same oracle, same exactness bar. ------------------------


@pytest.mark.parametrize("ring", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense_attention(qkv, ring, causal):
    from infinistore_tpu.models.ulysses import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:ring]), ("sp",))
    got = ulysses_attention(*qkv, mesh=mesh, axis="sp", causal=causal)
    ref = dense_attention_reference(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-6, rtol=2e-6)


def test_ulysses_gradients_match_dense(qkv):
    from infinistore_tpu.models.ulysses import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    def u_loss(q, k, v):
        return (ulysses_attention(q, k, v, mesh=mesh, axis="sp") ** 2).mean()

    def d_loss(q, k, v):
        return (dense_attention_reference(q, k, v) ** 2).mean()

    gu = jax.grad(u_loss, argnums=(0, 1, 2))(*qkv)
    gd = jax.grad(d_loss, argnums=(0, 1, 2))(*qkv)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-6, rtol=3e-6)


def test_ulysses_equals_ring(qkv):
    """The two sequence-parallel schedules compute the same attention."""
    from infinistore_tpu.models.ulysses import ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    u = ulysses_attention(*qkv, mesh=mesh, axis="sp", causal=True)
    r = ring_attention(*qkv, mesh=mesh, axis="sp", causal=True)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r), atol=2e-6, rtol=2e-6)
