"""End-to-end loopback integration: InfinityConnection against the native
server. Mirrors the reference's behavioral coverage
(reference infinistore/test_infinistore.py) without needing RDMA NICs or
GPUs: roundtrips per dtype, batched async write/read, check_exist,
get_match_last_index, typed KeyNotFound, delete_keys, TCP put/get, overwrite,
concurrent clients."""

import asyncio

import numpy as np
import pytest

import infinistore_tpu as its


def _staging(nbytes: int) -> np.ndarray:
    return np.zeros(nbytes, dtype=np.uint8)


# ---- single-key TCP path (reference test_basic_read_write_cache etc.) ------


def test_tcp_roundtrip(conn):
    data = np.random.randint(0, 256, size=256 << 10, dtype=np.uint8)
    conn.tcp_write_cache("tcp-key", data.ctypes.data, data.nbytes)
    out = conn.tcp_read_cache("tcp-key")
    assert np.array_equal(out, data)


def test_tcp_overwrite(conn):
    a = np.full(4096, 1, dtype=np.uint8)
    b = np.full(8192, 2, dtype=np.uint8)
    conn.tcp_write_cache("ow", a.ctypes.data, a.nbytes)
    conn.tcp_write_cache("ow", b.ctypes.data, b.nbytes)
    out = conn.tcp_read_cache("ow")
    assert out.nbytes == 8192
    assert np.array_equal(out, b)


def test_tcp_read_missing_raises(conn):
    with pytest.raises(its.InfiniStoreKeyNotFound):
        conn.tcp_read_cache("never-written")


# ---- batched async data plane (reference test_batch_read_write_cache) ------


@pytest.mark.parametrize("dtype", [np.float16, np.float32])
def test_batch_roundtrip_dtypes(conn, dtype):
    block_elems = 4096
    nblocks = 10
    src = np.random.randn(nblocks, block_elems).astype(dtype)
    block_size = src.itemsize * block_elems
    conn.register_mr(src)

    blocks = [(f"dt-{dtype.__name__}-{i}", i * block_size) for i in range(nblocks)]

    async def run():
        await conn.rdma_write_cache_async(blocks, block_size, src.ctypes.data)
        dst = np.zeros_like(src)
        conn.register_mr(dst)
        await conn.rdma_read_cache_async(blocks, block_size, dst.ctypes.data)
        return dst

    dst = asyncio.run(run())
    assert np.array_equal(src, dst)


def test_batch_requires_registered_mr(conn):
    src = _staging(4096)

    async def run():
        await conn.rdma_write_cache_async([("k", 0)], 4096, src.ctypes.data)

    with pytest.raises(its.InfiniStoreException):
        asyncio.run(run())


def test_batch_read_missing_raises_typed(conn):
    buf = _staging(4096)
    conn.register_mr(buf)

    async def run():
        await conn.rdma_read_cache_async([("missing-key", 0)], 4096, buf.ctypes.data)

    with pytest.raises(its.InfiniStoreKeyNotFound):
        asyncio.run(run())


def test_sync_batch_roundtrip(conn):
    """Blocking batched ops (the low-latency path: calling thread waits on
    the native completion, no event-loop hop). Runs on both data planes via
    the conn fixture."""
    n, block = 8, 4096
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    blocks = [(f"sync-{i}", i * block) for i in range(n)]
    conn.write_cache(blocks, block, src.ctypes.data)
    conn.read_cache(blocks, block, dst.ctypes.data)
    assert np.array_equal(src, dst)


def test_sync_batch_missing_raises_typed(conn):
    buf = _staging(4096)
    conn.register_mr(buf)
    with pytest.raises(its.InfiniStoreKeyNotFound):
        conn.read_cache([("sync-missing", 0)], 4096, buf.ctypes.data)


def test_sync_batch_requires_registered_mr(conn):
    buf = _staging(4096)
    with pytest.raises(its.InfiniStoreException):
        conn.write_cache([("sync-unreg", 0)], 4096, buf.ctypes.data)


def test_many_inflight_gather(conn):
    """1000-key asyncio.gather batch (reference example/client_async.py)."""
    n = 1000
    block = 1024
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)

    async def run():
        writes = [
            conn.rdma_write_cache_async([(f"g{i}", i * block)], block, src.ctypes.data)
            for i in range(n)
        ]
        await asyncio.gather(*writes)
        reads = [
            conn.rdma_read_cache_async([(f"g{i}", i * block)], block, dst.ctypes.data)
            for i in range(n)
        ]
        await asyncio.gather(*reads)

    asyncio.run(run())
    assert np.array_equal(src, dst)


# ---- control ops -----------------------------------------------------------


def test_check_exist(conn):
    data = _staging(1024)
    conn.tcp_write_cache("exists", data.ctypes.data, data.nbytes)
    assert conn.check_exist("exists") is True
    assert conn.check_exist("nope") is False


def test_get_match_last_index(conn):
    buf = np.ones(4 * 4096, dtype=np.uint8)
    conn.register_mr(buf)

    async def run():
        blocks = [(f"chain-{i}", i * 4096) for i in range(4)]
        await conn.rdma_write_cache_async(blocks, 4096, buf.ctypes.data)

    asyncio.run(run())
    keys = [f"chain-{i}" for i in range(8)]  # only first 4 present
    assert conn.get_match_last_index(keys) == 3


def test_get_match_no_match_raises(conn):
    with pytest.raises(its.InfiniStoreException):
        conn.get_match_last_index(["m1", "m2"])


def test_delete_keys(conn):
    data = _staging(1024)
    for i in range(3):
        conn.tcp_write_cache(f"del-{i}", data.ctypes.data, data.nbytes)
    assert conn.delete_keys(["del-0", "del-1", "not-there"]) == 2
    assert conn.check_exist("del-0") is False
    assert conn.check_exist("del-2") is True


def test_stats(conn):
    data = _staging(1024)
    conn.tcp_write_cache("stat-key", data.ctypes.data, data.nbytes)
    stats = conn.get_stats()
    assert stats["kvmap_len"] >= 1
    assert "P" in stats["ops"]
    assert stats["ops"]["P"]["count"] >= 1


# ---- server control API ----------------------------------------------------


def test_server_purge_and_len(server, conn):
    lib, handle = server["lib"], server["handle"]
    data = _staging(1024)
    for i in range(5):
        conn.tcp_write_cache(f"p-{i}", data.ctypes.data, data.nbytes)
    assert lib.its_server_kvmap_len(handle) == 5
    assert lib.its_server_purge(handle) == 5
    assert lib.its_server_kvmap_len(handle) == 0


def test_oom_returns_507_and_connection_survives(server, conn):
    """A write bigger than the whole pool must fail with 507 (eviction cannot
    help) but the connection stays usable because the server drains the
    streamed payload before answering."""
    big = _staging(96 << 20)  # > 64MB pool
    conn.register_mr(big)

    async def run():
        with pytest.raises(its.InfiniStoreException):
            await conn.rdma_write_cache_async([("big-0", 0)], 96 << 20, big.ctypes.data)

    asyncio.run(run())
    # Connection still works.
    small = _staging(1024)
    conn.tcp_write_cache("after-oom", small.ctypes.data, small.nbytes)
    assert conn.check_exist("after-oom") is True


def test_eviction_makes_room(server, conn):
    """On-demand LRU eviction: overfilling with small blocks evicts the oldest
    (reference evict_cache, infinistore.cpp:223)."""
    lib, handle = server["lib"], server["handle"]
    chunk = _staging(1 << 20)
    # 64MB pool; write 80 x 1MB so eviction must kick in (threshold 0.95).
    for i in range(80):
        conn.tcp_write_cache(f"ev-{i}", chunk.ctypes.data, chunk.nbytes)
    assert lib.its_server_usage(handle) <= 0.96
    # Oldest keys evicted, newest present.
    assert conn.check_exist("ev-79") is True
    assert conn.check_exist("ev-0") is False


def test_concurrent_clients(server):
    """Two client connections interleaving (reference runs two processes,
    test_infinistore.py:217-268; threads exercise the same server paths)."""
    import threading

    errors = []

    def worker(tag):
        try:
            cfg = its.ClientConfig(
                host_addr="127.0.0.1", service_port=server["port"], log_level="error"
            )
            c = its.InfinityConnection(cfg)
            c.connect()
            data = np.full(4096, ord(tag[0]) % 256, dtype=np.uint8)
            for i in range(50):
                c.tcp_write_cache(f"{tag}-{i}", data.ctypes.data, data.nbytes)
            for i in range(50):
                out = c.tcp_read_cache(f"{tag}-{i}")
                assert np.array_equal(out, data)
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in ("alpha", "beta")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_sync_ops_time_out_on_hung_server():
    """A server that accepts but never responds must fail sync control ops
    with a typed error after op_timeout_ms — never hang the caller
    (reference risk: its sync paths block on loop.run_until_complete with no
    deadline; here every sync wait is bounded by config)."""
    import socket as socklib
    import threading
    import time

    listener = socklib.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    accepted = []

    def accept_and_stall():
        s, _ = listener.accept()
        accepted.append(s)  # keep it open, read nothing, answer nothing

    t = threading.Thread(target=accept_and_stall, daemon=True)
    t.start()
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1",
            service_port=port,
            log_level="error",
            enable_shm=False,  # skip the (also bounded) shm handshake
            op_timeout_ms=300,
        )
    )
    c.connect()
    t0 = time.time()
    with pytest.raises(its.InfiniStoreException):
        c.check_exist("any-key")
    elapsed = time.time() - t0
    assert elapsed < 5, f"sync op took {elapsed:.1f}s — timeout not applied"
    # tcp_put is bounded too (buffer kept alive past close: the abandoned
    # request may still reference its own copy, never caller memory).
    payload = np.zeros(16, np.uint8)
    t0 = time.time()
    with pytest.raises(its.InfiniStoreException):
        c.tcp_write_cache("k", payload.ctypes.data, 16)
    assert time.time() - t0 < 5
    c.close()
    listener.close()
    for s in accepted:
        s.close()


def test_sync_ops_from_many_threads():
    """The sync data plane is documented as callable from any thread (the
    ctypes call releases the GIL): hammer one connection from 8 threads
    with interleaved sync puts/gets on disjoint buffers and verify every
    byte. Guards the reactor's promise-based completion path against
    cross-thread mixups (FIFO matching is per-connection)."""
    import threading

    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    block = 16 << 10
    errors = []

    def worker(tid):
        try:
            src = np.full(block, (tid * 37) % 251, dtype=np.uint8)
            dst = np.zeros_like(src)
            c.register_mr(src)
            c.register_mr(dst)
            for i in range(25):
                key = f"mt-{tid}-{i}"
                c.write_cache([(key, 0)], block, src.ctypes.data)
                c.read_cache([(key, 0)], block, dst.ctypes.data)
                assert np.array_equal(src, dst), f"thread {tid} iter {i} mismatch"
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    c.close()
    srv.stop()


def test_auto_reconnect_after_server_restart():
    """Opt-in recovery (the reference has none, SURVEY §5.3): when the store
    restarts, blocking ops on an auto_reconnect connection transparently
    reconnect + retry once, re-registering plain MRs; the restarted store
    looks like a COLD CACHE (keys gone), never a dead engine."""
    import time

    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    port = srv.port
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=port, log_level="error",
            enable_shm=False, auto_reconnect=True,
        )
    )
    c.connect()
    block = 16 << 10
    buf = np.random.randint(0, 256, size=2 * block, dtype=np.uint8)
    c.register_mr(buf)
    c.write_cache([("ar-a", 0), ("ar-b", block)], block, buf.ctypes.data)
    assert c.check_exist("ar-a") is True

    srv.stop()
    # Rebind the SAME port so reconnect finds the restarted server.
    for _ in range(20):
        try:
            srv2 = its.start_local_server(
                host="127.0.0.1", service_port=port,
                prealloc_bytes=32 << 20, block_bytes=16 << 10,
            )
            break
        except its.InfiniStoreException:
            time.sleep(0.1)
    else:
        pytest.skip("could not rebind the port for the restarted server")

    # First op after the restart: the dead connection is detected, the
    # client reconnects, and the restarted store reports a cold cache.
    assert c.check_exist("ar-a") is False
    assert c.is_connected
    # Plain MRs were re-registered: batched ops work without user action.
    buf2 = np.zeros_like(buf)
    c.register_mr(buf2)
    c.write_cache([("ar2-a", 0), ("ar2-b", block)], block, buf.ctypes.data)
    c.read_cache([("ar2-a", 0), ("ar2-b", block)], block, buf2.ctypes.data)
    assert np.array_equal(buf, buf2)
    c.close()
    srv2.stop()


def test_failed_reconnect_stays_retryable():
    """A reconnect attempt while the server is STILL down must not brick
    the connection: once the server returns, the next op recovers and the
    MR list is intact (re-registered on the successful attempt)."""
    import time

    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    port = srv.port
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=port, log_level="error",
            enable_shm=False, auto_reconnect=True, connect_timeout_ms=300,
        )
    )
    c.connect()
    block = 16 << 10
    buf = np.random.randint(0, 256, size=block, dtype=np.uint8)
    c.register_mr(buf)
    c.write_cache([("fr-a", 0)], block, buf.ctypes.data)
    srv.stop()

    # Server down: the auto-reconnect attempt itself fails and surfaces.
    with pytest.raises(its.InfiniStoreException):
        for _ in range(10):
            c.check_exist("fr-a")
    assert not c.is_connected

    # Server returns on the same port: the connection must recover, with
    # the registered MR usable again.
    for _ in range(20):
        try:
            srv2 = its.start_local_server(
                host="127.0.0.1", service_port=port,
                prealloc_bytes=32 << 20, block_bytes=16 << 10,
            )
            break
        except its.InfiniStoreException:
            time.sleep(0.1)
    else:
        pytest.skip("could not rebind the port for the restarted server")
    assert c.check_exist("fr-a") is False  # cold cache
    c.write_cache([("fr-b", 0)], block, buf.ctypes.data)  # MR re-registered
    assert c.check_exist("fr-b") is True
    c.close()
    srv2.stop()


def test_dead_connection_without_auto_reconnect_raises():
    """Default behavior unchanged: no auto_reconnect -> the op raises."""
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error",
            enable_shm=False,
        )
    )
    c.connect()
    srv.stop()
    with pytest.raises(its.InfiniStoreException):
        for _ in range(10):  # first op may still squeak through a socket buffer
            c.check_exist("x")
    c.close()


def test_abandoned_sync_read_never_touches_buffer():
    """A sync get that times out must NEVER scatter a late server response
    into the caller's buffer — the caller may free it after catching the
    exception. The reactor drains the late payload into scratch instead
    (SyncState::abandoned, client.cpp). Regression for the abandoned-op
    use-after-free window."""
    import socket as socklib
    import struct
    import threading
    import time

    from infinistore_tpu import wire

    listener = socklib.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    served = threading.Event()

    def serve_late():
        s, _ = listener.accept()
        s.settimeout(5)
        hdr = b""
        while len(hdr) < 9:
            hdr += s.recv(9 - len(hdr))
        _, op, body_size = struct.unpack("<IBI", hdr)
        assert op == wire.OP_GET_BATCH
        body = b""
        while len(body) < body_size:
            body += s.recv(body_size - len(body))
        meta = wire.BatchMeta.decode(body)
        time.sleep(0.8)  # well past the client's 300ms deadline
        n = len(meta.keys)
        sizes = struct.pack("<I", n) + struct.pack("<I", meta.block_size) * n
        payload = b"\xab" * (meta.block_size * n)
        s.sendall(
            wire.pack_resp_header(wire.STATUS_OK, len(sizes), len(payload))
            + sizes
            + payload
        )
        served.set()
        time.sleep(0.5)  # give the reactor time to drain before we close
        s.close()

    t = threading.Thread(target=serve_late, daemon=True)
    t.start()
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1",
            service_port=port,
            log_level="error",
            enable_shm=False,
            op_timeout_ms=300,
        )
    )
    c.connect()
    block = 4096
    buf = np.zeros(2 * block, dtype=np.uint8)
    c.register_mr(buf)
    t0 = time.time()
    with pytest.raises(its.InfiniStoreException):
        c.read_cache([("a", 0), ("b", block)], block, buf.ctypes.data)
    assert time.time() - t0 < 3
    buf[:] = 0x55  # the caller reuses (or could have freed) the buffer
    assert served.wait(5), "fake server never sent the late response"
    time.sleep(0.5)  # let the reactor consume the late payload
    assert (buf == 0x55).all(), "late response was scattered into caller memory"
    c.close()
    listener.close()


def test_striped_reconnect_after_server_restart():
    """StripedConnection.reconnect() rebuilds every dead stripe (a restart
    kills all of them; without this only stripe 0 could self-heal) and
    batched ops work again with re-registered MRs."""
    import time

    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    port = srv.port
    c = its.StripedConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=port, log_level="error",
                         enable_shm=False),
        streams=3,
    )
    c.connect()
    n, block = 12, 16 << 10
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    dst = np.zeros_like(src)
    c.register_mr(src)
    c.register_mr(dst)
    pairs = [(f"sr-{i}", i * block) for i in range(n)]
    asyncio.run(c.write_cache_async(pairs, block, src.ctypes.data))

    srv.stop()
    for _ in range(20):
        try:
            srv2 = its.start_local_server(
                host="127.0.0.1", service_port=port,
                prealloc_bytes=32 << 20, block_bytes=16 << 10,
            )
            break
        except its.InfiniStoreException:
            time.sleep(0.1)
    else:
        pytest.skip("could not rebind the port")

    with pytest.raises(its.InfiniStoreException):
        for _ in range(10):
            asyncio.run(c.write_cache_async(pairs, block, src.ctypes.data))
    # The failed batch quarantined the dead stripes (and their background
    # revive may already have healed some — the quarantine layer's job);
    # reconnect() deterministically rebuilds whatever is still dead.
    assert c.data_plane_stats()["quarantines"] >= 1
    c.reconnect()
    assert c.is_connected
    asyncio.run(c.write_cache_async(pairs, block, src.ctypes.data))
    asyncio.run(c.read_cache_async(pairs, block, dst.ctypes.data))
    assert np.array_equal(src, dst)
    assert c.data_plane_stats()["quarantined"] == [False] * 3  # all rejoined
    c.close()
    srv2.stop()


def test_striped_connection_roundtrip():
    """StripedConnection splits batched ops across N sockets while keeping
    the single-connection API: data correctness, control ops, shm segment on
    stripe 0, per-stripe traffic actually spread (docs/multistream.md)."""
    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
    c = its.StripedConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error"),
        streams=3,
    )
    c.connect()
    assert c.shm_active
    n, block = 24, 16 << 10
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    dst = np.zeros_like(src)
    c.register_mr(src)
    c.register_mr(dst)
    pairs = [(f"st-{i}", i * block) for i in range(n)]
    asyncio.run(c.write_cache_async(pairs, block, src.ctypes.data))
    asyncio.run(c.read_cache_async(pairs, block, dst.ctypes.data))
    assert np.array_equal(src, dst)
    # Each stripe carried part of the batch (server sees 3 connections).
    assert c.get_stats()["conns_accepted"] >= 3
    # Control ops work (stripe 0).
    assert c.check_exist("st-0")
    assert c.get_match_last_index([f"st-{i}" for i in range(n)]) == n - 1
    assert c.delete_keys([f"st-{i}" for i in range(n)]) == n
    # Segment path on stripe 0, plain registration on the others.
    seg = c.alloc_shm_mr(2 * block)
    seg[:] = 7
    asyncio.run(c.write_cache_async([("seg-a", 0), ("seg-b", block)], block, seg.ctypes.data))
    seg[:] = 0
    asyncio.run(c.read_cache_async([("seg-a", 0), ("seg-b", block)], block, seg.ctypes.data))
    assert (seg == 7).all()
    # Small batches stay on one stripe (no pointless splitting).
    asyncio.run(c.write_cache_async([("tiny", 0)], block, src.ctypes.data))
    c.close()
    srv.stop()


def test_closed_connection_is_not_resurrected():
    """close() is final: auto_reconnect must never silently reopen a
    connection the application tore down."""
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port,
                         log_level="error", auto_reconnect=True)
    )
    c.connect()
    assert c.check_exist("x") is False
    c.close()
    with pytest.raises(its.InfiniStoreException, match="not connected"):
        c.check_exist("x")
    assert c._handle is None  # really not resurrected
    srv.stop()


def test_striped_reconnect_does_not_reregister_foreign_segment():
    """Stripes 1..N register stripe 0's shm segment as an alias; after a
    restart + reconnect the alias must NOT come back (the segment is gone) —
    ops using the stale pointer get a clean error, never a crash."""
    import time

    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    port = srv.port
    c = its.StripedConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=port, log_level="error"),
        streams=3,
    )
    c.connect()
    seg = c.alloc_shm_mr(4 * 16 << 10)
    if seg is None:
        pytest.skip("shm unavailable")
    stale_ptr = seg.ctypes.data
    seg[:] = 7
    pairs = [(f"fs-{i}", i * (16 << 10)) for i in range(4)]
    asyncio.run(c.write_cache_async(pairs, 16 << 10, stale_ptr))

    srv.stop()
    for _ in range(20):
        try:
            srv2 = its.start_local_server(
                host="127.0.0.1", service_port=port,
                prealloc_bytes=32 << 20, block_bytes=16 << 10,
            )
            break
        except its.InfiniStoreException:
            time.sleep(0.1)
    else:
        pytest.skip("could not rebind the port")
    with pytest.raises(its.InfiniStoreException):
        for _ in range(10):
            asyncio.run(c.write_cache_async(pairs, 16 << 10, stale_ptr))
    c.reconnect()
    # The stale segment pointer is no longer a registered region anywhere —
    # a clean submit error (or typed shm error), never memory access.
    with pytest.raises(its.InfiniStoreException):
        asyncio.run(c.write_cache_async(pairs, 16 << 10, stale_ptr))
    # Fresh segment works end to end.
    seg2 = c.alloc_shm_mr(4 * 16 << 10)
    seg2[:] = 9
    asyncio.run(c.write_cache_async(pairs, 16 << 10, seg2.ctypes.data))
    seg2[:] = 0
    asyncio.run(c.read_cache_async(pairs, 16 << 10, seg2.ctypes.data))
    assert (seg2 == 9).all()
    c.close()
    srv2.stop()
