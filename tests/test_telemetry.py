"""Fleet telemetry plane (docs/observability.md, fleet section).

Covers the contracts the telemetry PR established:

- the event journal is a bounded causal ring: seq-monotone, per-kind
  counts survive eviction, events stamp the ACTIVE trace id;
- the SLO window math is deterministic under an injected clock (no
  sleeps): burn-rate monotonicity, window roll-off, multi-window firing,
  alert hysteresis, latency-bucket classification and windowed p99;
- the fleet scraper is breaker-aware (a dead target is skipped until its
  backoff elapses) and feeds the SLO engine from scraped deltas;
- `GET /trace?scope=cluster` merges spans from TWO real server processes
  for one traced fan-out op, joined by trace id, over real HTTP — with
  one Perfetto lane per member in ?fmt=chrome;
- `/slo`, `/events` and the SLO-aware `/health` verdict over real HTTP;
- satellites: OpenMetrics exemplars behind ?exemplars=1 (default output
  unchanged), Logger trace context;
- (chaos) a breaker trip + recovery lands in the journal with the
  correct trace link.
"""

import asyncio
import json
import socket
import subprocess
import sys
import time
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import telemetry, tracing
from infinistore_tpu.lib import InfiniStoreException, Logger
from infinistore_tpu.server import ManageServer, _prometheus_text


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()
    tracing.configure(enabled=False)


@pytest.fixture()
def traced():
    rec = tracing.configure(enabled=True, capacity=256, slow_op_us=0)
    rec.clear()
    yield rec
    tracing.configure(enabled=False)


# ---------------------------------------------------------------------------
# Event journal.
# ---------------------------------------------------------------------------


class TestEventJournal:
    def test_ring_bounded_and_seq_monotone(self):
        j = telemetry.EventJournal(capacity=4)
        for i in range(10):
            j.emit("slow_op", idx=i)
        snap = j.snapshot()
        assert len(snap) == 4
        assert [e["seq"] for e in snap] == [7, 8, 9, 10]
        assert [e["attrs"]["idx"] for e in snap] == [6, 7, 8, 9]
        # Counts survive ring eviction.
        assert j.counts() == {"slow_op": 10}
        assert j.emitted == 10

    def test_since_seq_and_limit(self):
        j = telemetry.EventJournal(capacity=16)
        for i in range(6):
            j.emit("breaker_open", member=f"m{i}")
        assert [e["seq"] for e in j.snapshot(since_seq=4)] == [5, 6]
        assert [e["seq"] for e in j.snapshot(limit=2)] == [5, 6]

    def test_active_span_trace_id_is_stamped(self, traced):
        j = telemetry.EventJournal()
        with tracing.trace_op("op", stage="enqueue") as sp:
            j.emit("breaker_open", member="m0")
        j.emit("breaker_closed", member="m0")
        ev = j.snapshot()
        assert ev[0]["trace_id"] == sp.trace_id
        assert ev[1]["trace_id"] == 0
        assert j.for_trace({sp.trace_id}) == [ev[0]]

    def test_slow_op_hook_journals_watchdog_captures(self):
        rec = tracing.FlightRecorder(capacity=8, slow_op_us=50_000)
        s = tracing.Span("slow_thing")
        s.t1_us = s.t0_us + 60_000
        s.status = "ok"
        rec.record(s)
        events = telemetry.get_journal().snapshot()
        assert len(events) == 1
        assert events[0]["kind"] == "slow_op"
        assert events[0]["trace_id"] == s.trace_id
        assert events[0]["attrs"]["span"] == "slow_thing"
        assert events[0]["attrs"]["duration_us"] >= 50_000


class TestStormDetector:
    def test_edge_trigger_and_rearm_hysteresis(self):
        clk = [0.0]
        d = telemetry._StormDetector(
            threshold=4, window_s=1.0, clock=lambda: clk[0]
        )
        assert d.note(3) == 0
        assert d.note(1) == 4          # edge fires at the threshold
        assert d.note(10) == 0         # sustained storm: no refire
        clk[0] = 2.5                   # quiet window drains the deque
        # Production-shaped re-arm: the callers only ever note(>=1), so the
        # empty-window check must happen before this note's escapes land.
        assert d.note(1) == 0          # re-arms, 1 in window: below edge
        assert d.note(3) == 4          # the NEXT storm fires again
        assert d.note(4) == 0          # and is again edge-triggered


# ---------------------------------------------------------------------------
# SLO window math (injected clock; no sleeps anywhere).
# ---------------------------------------------------------------------------


def make_engine(clk, windows=((10.0, 60.0, 10.0),), target=0.99,
                clear_ratio=0.5, journal=None):
    return telemetry.SloEngine(
        objectives=[
            telemetry.SloObjective("availability", target=target),
            telemetry.SloObjective(
                "fg_latency", target=0.9, kind="latency",
                latency_threshold_us=1000.0,
            ),
        ],
        windows=windows, clear_ratio=clear_ratio, bucket_s=1.0,
        clock=lambda: clk[0], journal=journal,
    )


class TestSloWindows:
    def test_idle_sli_is_met_and_burn_zero(self):
        clk = [1000.0]
        e = make_engine(clk)
        assert e.sli("availability") == 1.0
        assert e.burn_rate("availability", 10.0) == 0.0
        assert e.status()["verdict"] == "ok"

    def test_burn_rate_monotone_in_bad_samples(self):
        clk = [1000.0]
        e = make_engine(clk)
        e.record("availability", good=100)
        last = e.burn_rate("availability", 10.0)
        for _ in range(20):
            e.record("availability", bad=1)
            burn = e.burn_rate("availability", 10.0)
            assert burn >= last  # more bad at fixed time never lowers burn
            last = burn
        # 20 bad / 120 total at a 1% budget: ~16.7x burn.
        assert last == pytest.approx((20 / 120) / 0.01, rel=1e-6)

    def test_window_roll_off(self):
        clk = [1000.0]
        e = make_engine(clk)
        e.record("availability", bad=10)
        assert e.burn_rate("availability", 10.0) > 0
        clk[0] += 11.0  # the short window passed: old badness ages out
        assert e.burn_rate("availability", 10.0) == 0.0
        # ...but the long window still sees it.
        assert e.burn_rate("availability", 60.0) > 0
        clk[0] += 60.0
        assert e.burn_rate("availability", 60.0) == 0.0

    def test_alert_needs_both_windows(self):
        clk = [1000.0]
        e = make_engine(clk)
        # Short-window spike only: old GOOD traffic fills the long window.
        clk[0] = 1000.0
        e.record("availability", good=10000)
        clk[0] = 1055.0
        e.record("availability", bad=30, good=0)
        short = e.burn_rate("availability", 10.0)
        long = e.burn_rate("availability", 60.0)
        assert short >= 10.0 > long  # sanity of the setup
        assert e.evaluate() == []    # long window vetoes the page
        # Sustained burn crosses both -> fires.
        for t in range(60):
            clk[0] = 1060.0 + t
            e.record("availability", bad=5, good=5)
        firing = e.evaluate()
        assert len(firing) == 1
        assert firing[0]["objective"] == "availability"

    def test_alert_hysteresis(self):
        clk = [1000.0]
        j = telemetry.EventJournal()
        e = make_engine(clk, journal=j)
        for t in range(60):
            clk[0] = 1000.0 + t
            e.record("availability", bad=1, good=1)  # 50% bad = 50x burn
        assert len(e.evaluate()) == 1
        assert e.alerts_total == 1
        # Burn drops BELOW the fire threshold but above clear_ratio*thr
        # (10x fire, 5x clear): 6% bad = 6x burn -> still firing.
        clk[0] = 1070.0
        e.record("availability", bad=6, good=94)
        clk[0] = 1070.5
        assert len(e.evaluate()) == 1, "hysteresis must hold the alert up"
        # Full roll-off of the short window -> burn under clear -> clears.
        clk[0] = 1090.0
        e.record("availability", good=100)
        assert e.evaluate() == []
        # Edges (fire + clear), not levels, were journaled.
        kinds = [ev["attrs"]["state"] for ev in j.snapshot()]
        assert kinds == ["firing", "cleared"]
        assert e.alerts_total == 1

    def test_latency_buckets_classify_and_p99(self):
        clk = [1000.0]
        e = make_engine(clk)
        # 99 fast samples (le=500us) + 1 slow (le=2000us > 1000us threshold)
        e.record_latency_bucket("fg_latency", 500.0, count=99)
        e.record_latency_bucket("fg_latency", 2000.0, count=1)
        assert e.sli("fg_latency") == pytest.approx(0.99)
        assert e.p99_us("fg_latency") == 500.0
        # Push the tail past 1%: p99 moves to the slow bucket.
        e.record_latency_bucket("fg_latency", 2000.0, count=4)
        assert e.p99_us("fg_latency") == 2000.0

    def test_status_vocabulary_and_verdict(self):
        clk = [1000.0]
        e = make_engine(clk)
        st = e.status()
        for key in ("slo_availability", "slo_fg_p99_us", "slo_miss_rate",
                    "slo_reshard_drain", "slo_burn_rate_max",
                    "slo_alerts_firing", "slo_alerts_total"):
            assert key in st, key
        assert st["verdict"] == "ok"
        for t in range(60):
            clk[0] = 1000.0 + t
            e.record("availability", bad=1)
        st = e.status()
        assert st["verdict"] == "burning" and st["slo_alerts_firing"] == 1


# ---------------------------------------------------------------------------
# Fleet scraper: breaker-aware HTTP pulls + SLO feeding.
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _http_get(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    return json.loads(raw.split(b"\r\n\r\n", 1)[1])


class TestFleetScraper:
    def test_scrape_feeds_slo_and_breakers_dead_targets(self, server, traced):
        from infinistore_tpu import lib as its_lib

        c = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=server["port"],
            log_level="error",
        ))
        c.connect()
        buf = np.random.randint(0, 256, size=4096, dtype=np.uint8)
        c.register_mr(buf)
        with tracing.trace_op("scrape_put", stage="enqueue"):
            c.write_cache([("sc-0", 0)], 4096, buf.ctypes.data)

        clk = [0.0]
        engine = telemetry.configure_slo(telemetry.SloEngine(
            windows=((5.0, 20.0, 10.0),), bucket_s=1.0, clock=lambda: clk[0]
        ))
        dead_port = _free_port()  # nothing listens here

        async def run():
            manage = ManageServer(server["config"])
            manage._server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = manage._server.sockets[0].getsockname()[1]
            scraper = telemetry.FleetScraper(
                targets=[("m0", "127.0.0.1", port),
                         ("dead", "127.0.0.1", dead_port)],
                slo=engine, timeout_s=1.0, fail_threshold=2, backoff_s=30.0,
                clock=lambda: clk[0],
            )
            try:
                summaries = [await asyncio.to_thread(scraper.scrape_once)
                             for _ in range(3)]
            finally:
                manage._server.close()
                await manage._server.wait_closed()
            return scraper, summaries

        old = its_lib._server_handle
        its_lib._server_handle = server["handle"]
        try:
            scraper, summaries = asyncio.run(run())
        finally:
            its_lib._server_handle = old
        c.close()

        # Pass 1: live target ok, dead target fails. Pass 2: dead fails
        # again and trips its breaker. Pass 3: dead is SKIPPED (backoff).
        assert [s["ok"] for s in summaries] == [1, 1, 1]
        assert [s["failed"] for s in summaries] == [1, 1, 0]
        assert summaries[2]["skipped"] == 1
        status = scraper.status()
        by_id = {m["member"]: m for m in status["members"]}
        assert by_id["m0"]["ok"] and by_id["m0"]["scrapes"] == 3
        assert not by_id["dead"]["ok"]
        # The live member's op counters fed the availability SLI, and its
        # histogram deltas fed the latency objective.
        assert engine.sli("availability") == 1.0
        assert engine._buckets.get("availability")
        assert engine.p99_us("fg_latency") > 0
        # The traced op's spans were pulled and tagged with the member id.
        spans = scraper.member_spans()["m0"]
        assert spans and all(s["attrs"]["member"] == "m0" for s in spans)
        assert any(s["name"] == "scrape_put" for s in spans)

    def test_reshard_drain_fed_from_cluster(self):
        clk = [0.0]
        engine = telemetry.SloEngine(
            windows=((5.0, 20.0, 10.0),), bucket_s=1.0, clock=lambda: clk[0]
        )

        class FakeCluster:
            debt = 5

            def membership_status(self):
                return {"reshard_debt_roots": self.debt}

        cluster = FakeCluster()
        scraper = telemetry.FleetScraper(
            slo=engine, cluster=cluster, clock=lambda: clk[0]
        )
        scraper.scrape_once()           # first look: no trend yet
        cluster.debt = 3
        scraper.scrape_once()           # draining: good
        scraper.scrape_once()           # stuck at 3: bad
        cluster.debt = 0
        scraper.scrape_once()           # drained: good
        good, bad = engine._window_counts("reshard_drain", 20.0, clk[0])
        assert (good, bad) == (2, 1)


# ---------------------------------------------------------------------------
# Cluster trace join over real HTTP: 2 real server processes, one trace.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet():
    """TWO real server subprocesses (distinct processes, own manage
    planes) — the fleet the cluster-scope trace join is specified
    against. Spawn + readiness live in tools.fleet, shared with the
    bench telemetry leg so the two fleets cannot diverge."""
    from tools.fleet import spawn_fleet_servers

    try:
        members = spawn_fleet_servers(2)
    except RuntimeError as e:
        pytest.fail(str(e))
    procs = [m["proc"] for m in members]
    yield members
    for p in procs:
        p.send_signal(2)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


class TestClusterTraceJoin:
    def _mk_cluster(self, fleet):
        import jax.numpy as jnp

        from infinistore_tpu.cluster import ClusterKVConnector
        from infinistore_tpu.tpu.paged import PagedKVCacheSpec

        spec = PagedKVCacheSpec(
            num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2,
            head_dim=32, dtype=jnp.bfloat16,
        )
        conns = []
        for m in fleet:
            c = its.InfinityConnection(its.ClientConfig(
                host_addr="127.0.0.1", service_port=m["service_port"],
                log_level="error",
            ))
            c.connect()
            conns.append(c)
        cluster = ClusterKVConnector(
            conns, spec, "fleet-test", max_blocks=8, replicas=2,
        )
        return spec, conns, cluster

    def test_cluster_scope_merges_two_processes(self, fleet, traced):
        import jax
        import jax.numpy as jnp

        spec, conns, cluster = self._mk_cluster(fleet)
        member_ids = list(cluster.member_ids)
        caches = []
        for layer in range(spec.num_layers):
            k = jax.random.normal(
                jax.random.PRNGKey(layer), spec.cache_shape, jnp.float32
            ).astype(spec.dtype)
            caches.append((k, k))
        tokens = list(range(2 * spec.block_tokens))
        blocks = np.array([1, 4], np.int32)

        async def go():
            # replicas=2 over 2 members: ONE traced save fans out to BOTH
            # server processes with the same trace context on the wire.
            with tracing.trace_op("fanout_save", stage="enqueue") as sp:
                n = await cluster.save(tokens, caches, blocks)
            assert n > 0
            return sp

        sp = asyncio.run(go())

        async def fetch():
            scraper = telemetry.FleetScraper(
                targets=[
                    (member_ids[i], "127.0.0.1", fleet[i]["manage_port"])
                    for i in range(2)
                ],
                timeout_s=2.0,
            )
            manage = ManageServer(
                its.ServerConfig(host="127.0.0.1", manage_port=0),
                scraper=scraper,
            )
            manage._server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = manage._server.sockets[0].getsockname()[1]
            try:
                doc = await _http_get(port, "/trace?scope=cluster")
                chrome = await _http_get(
                    port, "/trace?scope=cluster&fmt=chrome"
                )
            finally:
                manage._server.close()
                await manage._server.wait_closed()
            return doc, chrome

        doc, chrome = asyncio.run(fetch())
        for c in conns:
            c.close()

        assert doc["scope"] == "cluster"
        assert set(member_ids) <= set(doc["members"])
        ours = [s for s in doc["spans"] if s["trace_id"] == sp.trace_id]
        served_members = {
            s["attrs"]["member"] for s in ours
            if s["attrs"].get("side") == "server"
        }
        # THE criterion: one traced fan-out op's spans, joined by trace id,
        # from >= 2 distinct server processes on one timeline.
        assert len(served_members) >= 2, (served_members, ours)
        # The local client span rides the same timeline.
        assert any(s["attrs"]["member"] == "local" for s in ours)
        # Timeline is monotonic and ordered: the client span opened before
        # every server-side tick of the fan-out (same CLOCK_MONOTONIC).
        client = [s for s in ours if s["attrs"]["member"] == "local"]
        servers = [s for s in ours if s["attrs"].get("side") == "server"]
        assert client and servers
        t0 = min(s["start_us"] for s in client)
        assert all(s["start_us"] >= t0 for s in servers)
        # Chrome form: one lane (pid) per member, lanes labeled.
        events = chrome["traceEvents"]
        lanes = {
            e["args"]["name"]: e["pid"] for e in events if e["ph"] == "M"
        }
        assert {f"member:{m}" for m in member_ids} <= set(lanes)
        span_pids = {e["pid"] for e in events if e["ph"] == "X"}
        assert len(span_pids) >= 3  # local + 2 members

    def test_fleet_slo_events_health_over_http(self, fleet):
        clk = [0.0]
        engine = telemetry.configure_slo(telemetry.SloEngine(
            windows=((5.0, 20.0, 10.0),), bucket_s=1.0, clock=lambda: clk[0],
            journal=telemetry.get_journal(),
        ))
        telemetry.emit("membership_epoch", member="m-x", epoch=7,
                       action="add")

        async def run(paths):
            manage = ManageServer(
                its.ServerConfig(host="127.0.0.1", manage_port=0)
            )
            manage._server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = manage._server.sockets[0].getsockname()[1]
            try:
                return [await _http_get(port, p) for p in paths]
            finally:
                manage._server.close()
                await manage._server.wait_closed()

        slo, events, health = asyncio.run(run(["/slo", "/events", "/health"]))
        assert slo["verdict"] == "ok" and "slo_availability" in slo
        assert events["counts"] == {"membership_epoch": 1}
        assert events["events"][0]["member"] == "m-x"
        assert health["status"] == "ok"

        # Burn the budget -> /health consumes the verdict and degrades.
        for t in range(30):
            clk[0] = float(t)
            engine.record("availability", bad=1)
        clk[0] = 30.0
        (health2,) = asyncio.run(run(["/health"]))
        assert health2["status"] == "degraded"
        assert health2["slo_verdict"] == "burning"
        assert health2["slo_alerts_firing"] >= 1
        # The alert edge itself was journaled.
        kinds = [e["kind"] for e in telemetry.get_journal().snapshot()]
        assert "slo_alert" in kinds


# ---------------------------------------------------------------------------
# Satellites: OpenMetrics exemplars + Logger trace context.
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_exemplar_links_bucket_to_trace(self, server, traced):
        c = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=server["port"],
            log_level="error",
        ))
        c.connect()
        buf = np.random.randint(0, 256, size=4096, dtype=np.uint8)
        c.register_mr(buf)
        with tracing.trace_op("ex_put", stage="enqueue") as sp:
            c.write_cache([("ex-0", 0)], 4096, buf.ctypes.data)
        stats = c.get_stats()
        plain_hdr, plain = (
            _prometheus_text(stats).decode().split("\r\n\r\n", 1)
        )
        ex_hdr, with_ex = (
            _prometheus_text(stats, exemplars=True)
            .decode().split("\r\n\r\n", 1)
        )
        c.close()
        # Default output carries NO exemplar syntax (plain Prometheus).
        assert " # {" not in plain
        assert "# EOF" not in plain
        # The exemplar variant declares OpenMetrics (whose parser requires
        # exemplar syntax + the trailing ``# EOF``); the default stays plain.
        assert "openmetrics-text" in ex_hdr
        assert "openmetrics-text" not in plain_hdr
        assert with_ex.rstrip("\n").endswith("# EOF")
        # The flagged output attaches the slow op's trace id to exactly the
        # histogram family, in OpenMetrics exemplar syntax.
        ex_lines = [ln for ln in with_ex.splitlines() if " # {" in ln]
        assert ex_lines
        assert all(
            ln.startswith("infinistore_op_duration_us_bucket") for ln in ex_lines
        )
        assert any(f'trace_id="{sp.trace_id:#x}"' in ln for ln in ex_lines)
        # Additivity: stripping exemplars recovers the plain SAMPLE lines
        # exactly — only TYPE declarations may adapt to OpenMetrics
        # counter-naming rules (family declared by base name, or
        # downgraded to ``unknown`` for legacy names without ``_total``).
        om_samples = [
            ln.split(" # ", 1)[0] for ln in with_ex.splitlines()
            if not ln.startswith("#")
        ]
        plain_samples = [
            ln for ln in plain.splitlines() if not ln.startswith("#")
        ]
        assert om_samples == plain_samples, "exemplars must be additive"
        om_types = [
            ln for ln in with_ex.splitlines() if ln.startswith("# TYPE ")
        ]
        plain_types = [
            ln for ln in plain.splitlines() if ln.startswith("# TYPE ")
        ]
        assert len(om_types) == len(plain_types)
        for ln in om_types:
            family, typ = ln.split(" ")[2], ln.split(" ")[3]
            if typ == "counter":
                # Conformant: base-named family with _total samples.
                assert not family.endswith("_total"), ln
                assert any(
                    s.startswith(family + "_total") for s in om_samples
                ), ln


class TestLoggerContext:
    def test_lines_carry_trace_context_inside_span(self, traced):
        with tracing.trace_op("log_op", stage="enqueue") as sp:
            text = Logger.with_context("hello")
            assert f"trace_id={sp.trace_id:#x}" in text
            assert f"span={sp.span_id:#x}" in text
            assert "member=" not in text
            sp.annotate(cluster_member=3)
            assert Logger.with_context("x").endswith("member=3")

    def test_plain_outside_span_or_disabled(self, traced):
        assert Logger.with_context("plain") == "plain"
        tracing.configure(enabled=False)
        assert Logger.with_context("off") == "off"


# ---------------------------------------------------------------------------
# Chaos: breaker trip + recovery journaled with the trace link.
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestBreakerEventsTraceLink:
    def test_trip_and_recovery_events_link_to_trace(self, server, traced):
        import jax.numpy as jnp

        from infinistore_tpu.cluster import CircuitBreaker, ClusterKVConnector
        from infinistore_tpu.faults import FaultRule, FaultyConnection
        from infinistore_tpu.tpu.paged import PagedKVCacheSpec

        spec = PagedKVCacheSpec(
            num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2,
            head_dim=32, dtype=jnp.bfloat16,
        )
        inner = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=server["port"],
            log_level="error",
        ))
        inner.connect()
        faulty = FaultyConnection(
            inner, [FaultRule(op="get_match_last_index", action="error")]
        )
        cluster = ClusterKVConnector(
            [faulty], spec, "ev", max_blocks=8, degrade=False,
            breaker_factory=lambda i: CircuitBreaker(
                fail_threshold=2, probe_backoff_s=0.05, max_backoff_s=0.2,
                seed=i,
            ),
        )
        member = cluster.member_ids[0]
        tokens = list(range(16))
        spans = []
        for _ in range(2):
            with pytest.raises(InfiniStoreException):
                with tracing.trace_op("trip_lookup", stage="enqueue") as sp:
                    cluster.lookup(tokens)
            spans.append(sp)
        assert cluster.health()["members"][0]["breaker_state"] == "open"

        events = telemetry.get_journal().snapshot()
        opens = [e for e in events if e["kind"] == "breaker_open"]
        assert len(opens) == 1
        assert opens[0]["member"] == member
        assert opens[0]["epoch"] >= 1
        # THE causal link: the trip event carries the trace id of the op
        # that tripped it — and that span is in the flight recorder.
        assert opens[0]["trace_id"] == spans[-1].trace_id
        recorded = {s["trace_id"] for s in tracing.recorder().snapshot()}
        assert opens[0]["trace_id"] in recorded

        # Heal the fault; the half-open probe recovers and is journaled.
        faulty.rules.clear()
        deadline = time.time() + 5
        while time.time() < deadline:
            with tracing.trace_op("heal_lookup", stage="enqueue"):
                try:
                    cluster.lookup(tokens)
                except InfiniStoreException:
                    pass
            if cluster.health()["members"][0]["breaker_state"] == "closed":
                break
            time.sleep(0.02)
        kinds = [e["kind"] for e in telemetry.get_journal().snapshot()]
        assert "breaker_half_open" in kinds
        assert "breaker_closed" in kinds
        closed = [
            e for e in telemetry.get_journal().snapshot()
            if e["kind"] == "breaker_closed"
        ]
        assert closed[-1]["member"] == member
        assert closed[-1]["trace_id"] != 0  # recovery rode a traced lookup
        inner.close()
