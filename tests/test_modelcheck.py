"""Tests for the ITS-M model-checking layer (tools/analysis/specs,
tools/analysis/modelcheck) and the counterexample->test replay bridge
(tools/analysis/interleave.replay_schedule).

Three layers:

1. **Explorer mechanics**: BFS over all interleavings with state
   hashing — shortest counterexamples, nondeterministic actions,
   deadlock detection, AG EF liveness, the state-cap backstop.
2. **Schedule replay against the REAL classes**: model-generated action
   schedules drive real ``Membership`` peers and a real ``DurableLog``
   file through ``replay_schedule``, asserting in LOCKSTEP that the
   model state and the real state agree step for step — the PR-13
   workflow that turns any future ITS-M counterexample into a
   deterministic regression test.
3. **Spec sanity**: the four shipped specs explore completely at HEAD
   (the acceptance gate the `analysis` CI job re-checks via --all).
"""

import os
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from infinistore_tpu.membership import DurableLog, Membership  # noqa: E402
from tools.analysis import modelcheck  # noqa: E402,F401 (registers checker)
from tools.analysis.interleave import replay_schedule  # noqa: E402
from tools.analysis.specs import (  # noqa: E402
    Action,
    Spec,
    all_specs,
    durable_log_spec,
    explore,
    membership_spec,
)


# ---------------------------------------------------------------------------
# Explorer mechanics.
# ---------------------------------------------------------------------------

def counter_spec(limit=3, invariant_below=None, cap=200_000):
    invs = ()
    if invariant_below is not None:
        invs = (("below", lambda s: s[0] < invariant_below),)
    return Spec(
        name="counter",
        doc="test",
        initial_states=lambda: [(0,)],
        actions=(
            Action("inc", guard=lambda s: s[0] < limit,
                   apply=lambda s: (s[0] + 1,)),
        ),
        invariants=invs,
        state_cap=cap,
    )


class TestExplorer:
    def test_full_exploration_is_complete(self):
        res = explore(counter_spec(limit=5))
        assert res.complete
        assert res.states == 6  # 0..5
        assert res.edges == 5
        assert not res.violations

    def test_invariant_violation_has_shortest_schedule(self):
        res = explore(counter_spec(limit=5, invariant_below=3))
        assert not res.complete
        v = res.violations[0]
        assert v.kind == "invariant" and v.prop == "below"
        # BFS: the first reported counterexample is minimal.
        assert v.schedule == ["inc", "inc", "inc"]

    def test_nondeterministic_apply_explores_all_outcomes(self):
        spec = Spec(
            name="fork", doc="test",
            initial_states=lambda: [("start",)],
            actions=(
                Action("fork", guard=lambda s: s[0] == "start",
                       apply=lambda s: [("a",), ("b",)]),
            ),
            invariants=(("not-b", lambda s: s[0] != "b"),),
        )
        res = explore(spec)
        assert res.states == 3
        assert [v.prop for v in res.violations] == ["not-b"]
        assert res.violations[0].schedule == ["fork"]

    def test_step_invariant_anchors_on_edge(self):
        spec = counter_spec(limit=2)
        spec.step_invariants = (
            ("never-two", lambda prev, a, nxt: nxt[0] != 2),
        )
        res = explore(spec)
        v = res.violations[0]
        assert v.kind == "step"
        assert v.schedule == ["inc", "inc"]

    def test_deadlock_detected_with_schedule(self):
        spec = Spec(
            name="wedge", doc="test",
            initial_states=lambda: [(0,)],
            actions=(
                Action("step", guard=lambda s: s[0] == 0,
                       apply=lambda s: (1,)),
            ),
            is_done=lambda s: False,  # nothing is a legal stop
        )
        res = explore(spec)
        kinds = {v.kind for v in res.violations}
        assert "deadlock" in kinds
        dead = [v for v in res.violations if v.kind == "deadlock"]
        assert dead[0].schedule == ["step"]

    def test_liveness_trap_state_detected(self):
        # 0 -> 1 (goal) or 0 -> 2 (trap, self-loops forever).
        spec = Spec(
            name="trap", doc="test",
            initial_states=lambda: [(0,)],
            actions=(
                Action("good", guard=lambda s: s[0] == 0,
                       apply=lambda s: (1,)),
                Action("bad", guard=lambda s: s[0] == 0,
                       apply=lambda s: (2,)),
            ),
            liveness=(("reach-goal", lambda s: s[0] == 1),),
        )
        res = explore(spec)
        assert [v.prop for v in res.violations] == ["reach-goal"]
        assert res.violations[0].kind == "liveness"
        assert not res.complete

    def test_state_cap_marks_incomplete(self):
        res = explore(counter_spec(limit=10_000, cap=16))
        assert not res.complete
        assert res.states == 16
        assert not res.violations  # incomplete != violated

    def test_replay_schedule_strict_raises_on_unmapped(self):
        with pytest.raises(KeyError):
            replay_schedule(["mystery"], {})
        assert replay_schedule(["mystery"], {}, strict=False) == [None]


# ---------------------------------------------------------------------------
# Membership: model schedules drive REAL peers in lockstep.
# ---------------------------------------------------------------------------

_STATE_NAME = {
    "J": "joining", "A": "active", "L": "leaving",
    "D": "dead", "R": "removed",
}
N = membership_spec.N_PEERS


class RealPeers:
    """Three real Membership instances (one shared steady member) driven
    by model action names; the contested member id is ``x``."""

    def __init__(self):
        self.ms = [Membership(["seed"]) for _ in range(N)]

    def actions(self):
        acts = {}
        for i in range(N):
            acts[f"add@{i}"] = lambda i=i: self.ms[i].add_member("x")
            acts[f"readd@{i}"] = lambda i=i: self.ms[i].add_member("x")
            acts[f"remove@{i}"] = lambda i=i: self.ms[i].remove_member("x")
            acts[f"mark_dead@{i}"] = lambda i=i: self.ms[i].mark_dead("x")
            acts[f"finalize@{i}"] = (
                lambda i=i: self.ms[i].finalize_transitions()
            )
            for j in range(N):
                if j != i:
                    acts[f"exchange@{i}<-{j}"] = (
                        lambda i=i, j=j: self._exchange(i, j)
                    )
        return acts

    def _exchange(self, i, j):
        payload = self.ms[j].view().as_dict()
        return self.ms[i].merge_apply(payload["members"], payload["epoch"])

    def snapshot(self, i):
        """(entry, epoch) of peer i in the model's vocabulary: the latest
        ``x`` entry as (state_name, since_epoch), or None."""
        v = self.ms[i].view()
        for m, s, se in zip(
            reversed(v.member_ids), reversed(v.states), reversed(v.since)
        ):
            if m == "x":
                return (s, int(se)), v.epoch
        return None, v.epoch


def run_model(schedule):
    """Apply a schedule to the membership model, asserting every step's
    guard (a guard-invalid schedule is a test bug, not a model result)."""
    state = membership_spec.initial_states()[0]
    by_name = {a.name: a for a in membership_spec.SPEC.actions}
    for name in schedule:
        action = by_name[name]
        assert action.guard(state), f"model guard rejects {name} in {state}"
        state = action.apply(state)
    return state


def assert_lockstep(schedule):
    """Drive model and real peers through ``schedule``; final states must
    agree peer for peer (state name, since_epoch, epoch)."""
    model = run_model(schedule)
    real = RealPeers()
    replay_schedule(schedule, real.actions())
    for i in range(N):
        (m_entry, m_epoch) = model[0][i]
        r_entry, r_epoch = real.snapshot(i)
        expect = (
            None if m_entry is None
            else (_STATE_NAME[m_entry[0]], m_entry[1])
        )
        assert r_entry == expect, f"peer {i}: real {r_entry} != model {expect}"
        assert r_epoch == m_epoch, f"peer {i}: epoch {r_epoch} != {m_epoch}"
    return real


class TestMembershipReplay:
    def test_concurrent_dead_vs_removed_converges(self):
        # The schedule the checker surfaced in development: peer0 marks x
        # DEAD at epoch 4 while peer1 finalizes its LEAVING to REMOVED at
        # epoch 4 — same incarnation, concurrent terminal knowledge. The
        # rank order picks REMOVED on every peer (a legal terminal->
        # terminal join, NOT a resurrection).
        real = assert_lockstep([
            "add@0", "remove@0", "exchange@1<-0", "mark_dead@0",
            "finalize@1", "exchange@0<-1", "exchange@1<-0",
            "exchange@2<-0", "exchange@2<-1",
        ])
        for i in range(N):
            entry, _epoch = real.snapshot(i)
            assert entry == ("removed", 4)

    def test_readd_after_dead_is_a_new_incarnation(self):
        real = assert_lockstep([
            "add@0", "mark_dead@0", "exchange@1<-0", "readd@1",
            "exchange@0<-1", "exchange@2<-1", "exchange@2<-0",
        ])
        for i in range(N):
            entry, epoch = real.snapshot(i)
            assert entry == ("joining", 4)
            assert epoch == 4
        # The dead incarnation's entry index survives (tombstones are
        # never reused): peers that HELD the tombstone append the re-add
        # as a NEW entry; peer2 only ever heard the new incarnation.
        for i, expect in ((0, ["dead", "joining"]),
                          (1, ["dead", "joining"]),
                          (2, ["joining"])):
            v = real.ms[i].view()
            states = [
                e for mid, e in zip(v.member_ids, v.states) if mid == "x"
            ]
            assert states == expect, f"peer {i}"

    def test_exchange_order_insensitive(self):
        # The convergence invariant, demonstrated on the REAL class: peer2
        # hears peer0 and peer1 in either order and lands identically.
        base = ["add@0", "remove@0", "exchange@1<-0", "mark_dead@0",
                "finalize@1"]
        a = RealPeers()
        replay_schedule(base + ["exchange@2<-0", "exchange@2<-1"],
                        a.actions())
        b = RealPeers()
        replay_schedule(base + ["exchange@2<-1", "exchange@2<-0"],
                        b.actions())
        assert a.snapshot(2) == b.snapshot(2)
        assert a.snapshot(2)[0] == ("removed", 4)

    def test_stale_liveness_never_resurrects_tombstone(self):
        # peer1 holds stale ACTIVE knowledge; peer0's DEAD tombstone of
        # the same incarnation must dominate on exchange in BOTH
        # directions (the no-resurrection property on the real class).
        sched = ["add@0", "finalize@0", "exchange@1<-0", "mark_dead@0"]
        real = assert_lockstep(sched + ["exchange@1<-0", "exchange@0<-1"])
        # x: JOINING@2 -> ACTIVE@3 (peer1's stale knowledge) -> DEAD@4;
        # the tombstone dominates in both exchange directions.
        assert real.snapshot(0)[0] == ("dead", 4)
        assert real.snapshot(1)[0] == ("dead", 4)


# ---------------------------------------------------------------------------
# DurableLog: crash/replay schedules against a REAL journal file.
# ---------------------------------------------------------------------------

def op_to_record(op):
    if op[0] == "root":
        return {"kind": "root", "root": op[1]}
    if op[0] == "drop":
        return {"kind": "drop", "root": op[1]}
    if op[0] == "plan":
        return {"kind": "plan", "epoch": op[1], "roots": list(op[2])}
    if op[0] == "migrated":
        return {"kind": "migrated", "epoch": op[1], "root": op[2]}
    if op[0] == "fin":
        return {"kind": "fin", "epoch": op[1]}
    raise AssertionError(op)


def record_to_op(rec):
    k = rec["kind"]
    if k == "root":
        return ("root", rec["root"])
    if k == "drop":
        return ("drop", rec["root"])
    if k == "plan":
        return ("plan", rec["epoch"], tuple(rec["roots"]))
    if k == "migrated":
        return ("migrated", rec["epoch"], rec["root"])
    if k == "fin":
        return ("fin", rec["epoch"])
    raise AssertionError(rec)


class RealLog:
    """A real DurableLog driven by the durable_log spec's action names,
    mirroring the model state (frames) alongside for lockstep asserts."""

    def __init__(self, path):
        self.path = str(path)
        self.log = DurableLog(self.path, fsync_interval_s=0.0)
        self.state = durable_log_spec.initial_states()[0]
        self._by_name = {
            a.name: a for a in durable_log_spec.SPEC.actions
        }
        self.replayed_ops = None

    def _model_step(self, name, pick=0):
        action = self._by_name[name]
        assert action.guard(self.state), (name, self.state)
        nxt = action.apply(self.state)
        self.state = nxt[pick] if isinstance(nxt, list) else nxt

    def _next_record(self):
        idx = self.state[durable_log_spec.IDX]
        return op_to_record(durable_log_spec.SCRIPT[idx])

    def actions(self):
        return {
            "append": self.do_append,
            "append_badcrc": self.do_append_badcrc,
            "crash": self.do_crash,
            "crash_torn": self.do_crash_torn,
            "compact": self.do_compact,
            "replay": self.do_replay,
        }

    def do_append(self):
        rec = self._next_record()
        self.log.append(rec)
        self._model_step("append")

    def do_append_badcrc(self):
        # Append an intact frame, then flip one payload byte on disk —
        # the crc no longer matches (bit rot / torn mid-frame rewrite).
        before = os.path.getsize(self.path)
        rec = self._next_record()
        self.log.append(rec)
        with open(self.path, "r+b") as f:
            f.seek(before + 8)  # past the [u32 len][u32 crc] header
            b = f.read(1)
            f.seek(before + 8)
            f.write(bytes([b[0] ^ 0xFF]))
        self._model_step("append_badcrc")

    def do_crash(self):
        # A crash is the absence of further writes; appends already
        # flushed, so abandoning the handle preserves exactly the bytes
        # a real crash would.
        self.log.close()
        self._model_step("crash")

    def do_crash_torn(self):
        rec = self._next_record()
        self.log.append(rec)
        self.log.close()
        # Cut the in-flight frame mid-payload: a torn tail.
        size = os.path.getsize(self.path)
        os.truncate(self.path, size - 3)
        self._model_step("crash_torn")

    def do_compact(self):
        snap = durable_log_spec.snapshot_ops(
            self.state[durable_log_spec.FILE]
        )
        self.log.compact([op_to_record(op) for op in snap])
        self.log.close()
        self._model_step("compact", pick=2)  # the non-crashing outcome

    def do_replay(self):
        self.log = DurableLog(self.path, fsync_interval_s=0.0)
        self.replayed_ops = tuple(
            record_to_op(r) for r in self.log.replay()
        )
        self._model_step("replay")


def drive_log(tmp_path, schedule):
    real = RealLog(tmp_path / "journal.log")
    replay_schedule(schedule, real.actions())
    return real


class TestDurableLogReplay:
    def test_torn_drop_is_not_durable(self, tmp_path):
        # Crash mid-write of the `drop r1` tombstone: the drop is NOT
        # durable, so r1 stays live — and real framing agrees with the
        # model's durable-prefix policy byte for byte.
        real = drive_log(
            tmp_path, ["append"] * 4 + ["crash_torn", "replay"]
        )
        prefix = durable_log_spec.durable_prefix(
            real.state[durable_log_spec.FILE]
        )
        assert real.replayed_ops == prefix
        live, plan_epoch, debt = durable_log_spec.interpret(
            real.replayed_ops
        )
        assert live == ("r1", "r2")
        assert (plan_epoch, debt) == (2, ("r2",))  # analytic resume debt
        assert real.log.replay_torn == 1
        assert real.log.replay_bad_checksum == 0

    def test_durable_drop_never_resurrects(self, tmp_path):
        real = drive_log(tmp_path, ["append"] * 5 + ["crash", "replay"])
        live, _epoch, debt = durable_log_spec.interpret(real.replayed_ops)
        assert "r1" not in live
        assert live == ("r2",)
        assert debt == ("r2",)  # fin not yet durable
        assert real.log.replay_torn == 0

    def test_bad_checksum_frame_is_skipped_not_fatal(self, tmp_path):
        # Frame 2 (`root r2`) rots; everything after it still parses —
        # skip-and-continue, unlike the torn-tail stop.
        real = drive_log(
            tmp_path,
            ["append", "append_badcrc"] + ["append"] * 4
            + ["crash", "replay"],
        )
        prefix = durable_log_spec.durable_prefix(
            real.state[durable_log_spec.FILE]
        )
        assert real.replayed_ops == prefix
        assert ("root", "r2") not in real.replayed_ops
        assert ("fin", 2) in real.replayed_ops  # later frames survived
        live, plan_epoch, debt = durable_log_spec.interpret(
            real.replayed_ops
        )
        assert live == ()  # r1 dropped, r2's add rotted away
        assert (plan_epoch, debt) == (0, ())
        assert real.log.replay_bad_checksum == 1

    def test_compaction_preserves_semantics_and_shrinks(self, tmp_path):
        full = drive_log(tmp_path, ["append"] * 6 + ["crash", "replay"])
        before = durable_log_spec.interpret(full.replayed_ops)
        size_before = os.path.getsize(full.path)

        cdir = tmp_path / "c"
        cdir.mkdir()
        compacted = drive_log(cdir, ["append"] * 6 + ["compact"])
        # Re-open and replay the compacted file.
        log2 = DurableLog(compacted.path, fsync_interval_s=0.0)
        ops = tuple(record_to_op(r) for r in log2.replay())
        assert durable_log_spec.interpret(ops) == before
        assert os.path.getsize(compacted.path) < size_before
        assert compacted.log.compactions == 1


# ---------------------------------------------------------------------------
# Shipped specs at HEAD.
# ---------------------------------------------------------------------------

class TestShippedSpecs:
    def test_all_specs_explore_completely_and_cleanly(self):
        for spec, mirrors in all_specs():
            res = explore(spec)
            assert res.complete, f"{spec.name}: incomplete"
            assert res.states > 0, f"{spec.name}: empty state space"
            assert not res.violations, (
                f"{spec.name}: {[(v.kind, v.prop, v.schedule) for v in res.violations]}"
            )
            assert mirrors["file"], spec.name
