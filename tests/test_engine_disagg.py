"""Cross-process prefill→decode disaggregation through the ENGINE adapter —
BASELINE config 5 in the shape a real deployment has: the prefill engine and
the decode engine are separate OS processes that share nothing but the store
(reference scenario (a), README.md:13-14; its splitwise-demos analogue).

The prefill process runs the demo Llama over the prompt and saves its KV
through EngineKVAdapter. The decode process — fresh JAX runtime, fresh
params from the same seed — probes the prefix at admission, loads every
block through the adapter into ITS OWN block layout, verifies the KV against
a locally recomputed prefill oracle, and runs a real decode step over the
loaded cache. Byte movement crosses process boundaries on the store's data
planes; nothing else is shared."""

import subprocess
import sys

import pytest

import infinistore_tpu as its

_COMMON = r"""
import asyncio, sys
from infinistore_tpu.hostmesh import force_cpu_devices
force_cpu_devices(1)
import numpy as np
import jax
import jax.numpy as jnp
import infinistore_tpu as its
from infinistore_tpu import EngineKVAdapter, KVConnector
from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill

port = int(sys.argv[1])
want_shm = sys.argv[2] == "shm" 
CFG = LlamaConfig(vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                  ffn_dim=128, block_tokens=8, dtype=jnp.float32)
NUM_BLOCKS, REQ_BLOCKS = 16, 4
params = init_params(CFG, jax.random.PRNGKey(0))  # same seed -> same engine
prompt = (np.arange(REQ_BLOCKS * CFG.block_tokens) * 37 % CFG.vocab).tolist()
conn = its.InfinityConnection(its.ClientConfig(
    host_addr="127.0.0.1", service_port=port, log_level="error"))
conn.connect()
# The plane under test must actually be the plane in use (the shm handshake
# degrading to socket would silently collapse both parametrizations).
assert conn.shm_active == want_shm, f"shm_active={conn.shm_active}"
adapter = EngineKVAdapter(
    KVConnector(conn, CFG.kv_spec(NUM_BLOCKS), "disagg-engine", max_blocks=REQ_BLOCKS))
"""

_PREFILL = _COMMON + r"""
caches = CFG.kv_spec(NUM_BLOCKS).make_caches()
table = np.asarray([2, 5, 11, 7], np.int32)  # prefill engine's block layout
_, caches = prefill(params, jnp.asarray(prompt, jnp.int32), caches,
                    jnp.asarray(table), CFG)
wrote = asyncio.run(adapter.save_kv(prompt, caches, table))
assert wrote == 2 * CFG.n_layers * REQ_BLOCKS, wrote
conn.close()
print("prefill ok")
"""

_DECODE = _COMMON + r"""
hit = adapter.get_num_matched_tokens(prompt)
assert hit == len(prompt), f"expected full prefix hit, got {hit}"
caches = CFG.kv_spec(NUM_BLOCKS).make_caches()
table = np.asarray([9, 0, 3, 14], np.int32)  # DIFFERENT block layout
caches, loaded = asyncio.run(adapter.load_kv(prompt, caches, table))
assert loaded == len(prompt), f"loaded {loaded}"

# Oracle: recompute the prefill locally (same params by construction).
oracle = CFG.kv_spec(REQ_BLOCKS).make_caches()
_, oracle = prefill(params, jnp.asarray(prompt, jnp.int32), oracle,
                    jnp.arange(REQ_BLOCKS, dtype=jnp.int32), CFG)
for layer in range(CFG.n_layers):
    for kind in range(2):
        got = np.asarray(caches[layer][kind][table], np.float32)
        want = np.asarray(oracle[layer][kind], np.float32)
        assert np.array_equal(got, want), f"KV mismatch L{layer} kind{kind}"

# Real decode step over the loaded cache: the new token needs its OWN block
# slot (position // block_tokens == REQ_BLOCKS), so the decode table carries
# one spare entry beyond the loaded prefix.
decode_table = np.append(table, np.int32(6))
logits, _ = decode_step(params, jnp.int32(42), jnp.int32(len(prompt)),
                        caches, jnp.asarray(decode_table), CFG, REQ_BLOCKS + 1)
assert np.isfinite(np.asarray(logits)).all()
conn.close()
print("decode ok")
"""


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_cross_process_engine_disagg(plane):
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=64 << 10,
        enable_shm=plane == "shm",
    )
    try:
        for script, want in ((_PREFILL, "prefill ok"), (_DECODE, "decode ok")):
            r = subprocess.run(
                [sys.executable, "-c", script, str(srv.port), plane],
                capture_output=True, text=True, timeout=300,
            )
            assert r.returncode == 0, f"{want} process failed:\n{r.stderr[-2000:]}"
            assert want in r.stdout
    finally:
        srv.stop()
