"""File-backed spill tier: capacity beyond RAM, the tier the reference only
aspired to (reference docs/source/design.rst:36 lists SSD as a future pool;
its kv_map is in-RAM only, so eviction is data loss).

With ``spill_dir`` set, eviction demotes LRU blocks into an mmap'd
(immediately unlinked — crash-safe by construction) file, and access
promotes them back into a RAM pool. Everything below runs through the public
surface against a live server.
"""

import numpy as np
import pytest

import infinistore_tpu as its

BLOCK = 64 << 10


def _server(**kw):
    defaults = dict(
        prealloc_bytes=4 << 20,  # 64 blocks of RAM
        block_bytes=BLOCK,
        spill_dir="/tmp",
        spill_bytes=64 << 20,
    )
    defaults.update(kw)
    return its.start_local_server(**defaults)


def _connect(srv):
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    return c


def test_capacity_beyond_ram_with_data_intact():
    """Write 2x the RAM pool; every key stays present and byte-correct."""
    srv = _server()
    c = _connect(srv)
    n = 128  # 8MB through a 4MB pool
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"sp-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    spill = c.get_stats()["spill"]
    assert spill["entries"] > 0, "nothing spilled — pool should have overflowed"
    assert spill["dropped"] == 0

    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    for i in range(n):
        assert c.check_exist(f"sp-{i}"), f"sp-{i} lost despite spill tier"
        c.read_cache([(f"sp-{i}", 0)], BLOCK, dst.ctypes.data)
        assert np.array_equal(dst, src[i * BLOCK : (i + 1) * BLOCK]), f"sp-{i} corrupt"
    assert c.get_stats()["spill"]["promotions"] >= n - 64  # spilled ones came back
    c.close()
    srv.stop()


def test_prefix_match_and_delete_cover_spilled_entries():
    """Control ops see spilled entries as present (no promotion), and delete
    frees their slots."""
    srv = _server()
    c = _connect(srv)
    n = 100
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"ch-{i:04d}", i * BLOCK)], BLOCK, src.ctypes.data)
    # Chain over all keys: early ones are spilled by now, yet the match must
    # cover the full chain.
    assert c.get_match_last_index([f"ch-{i:04d}" for i in range(n)]) == n - 1
    before = c.get_stats()["spill"]["bytes"]
    assert before > 0
    assert c.delete_keys([f"ch-{i:04d}" for i in range(n)]) == n
    assert c.get_stats()["spill"]["bytes"] == 0, "delete must free spill slots"
    c.close()
    srv.stop()


def test_spill_full_drops_coldest_only():
    """When the spill file itself fills, only the coldest spilled entries are
    dropped; the hottest data survives."""
    srv = _server(spill_bytes=2 << 20)  # RAM 4MB + spill 2MB << data 12MB
    c = _connect(srv)
    n = 192
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"fd-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    spill = c.get_stats()["spill"]
    assert spill["dropped"] > 0, "spill file should have overflowed"
    # The most recent writes are still resident or spilled — readable.
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    for i in range(n - 16, n):
        c.read_cache([(f"fd-{i}", 0)], BLOCK, dst.ctypes.data)
        assert np.array_equal(dst, src[i * BLOCK : (i + 1) * BLOCK])
    # The oldest were dropped for real (cache semantics).
    assert c.check_exist("fd-0") is False
    c.close()
    srv.stop()


def test_overwrite_of_spilled_key_frees_slot():
    srv = _server()
    c = _connect(srv)
    n = 96
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"ow-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"]["entries"] > 0
    # Overwrite an old (spilled) key with fresh bytes; read must see them.
    fresh = np.full(BLOCK, 0xA5, dtype=np.uint8)
    c.register_mr(fresh)
    c.write_cache([("ow-0", 0)], BLOCK, fresh.ctypes.data)
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    c.read_cache([("ow-0", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 0xA5).all()
    c.close()
    srv.stop()


def test_spill_disabled_keeps_reference_behavior():
    """Without spill_dir, eviction drops — the pre-existing (reference)
    semantics are untouched."""
    srv = its.start_local_server(prealloc_bytes=4 << 20, block_bytes=BLOCK)
    c = _connect(srv)
    src = np.random.randint(0, 256, size=BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(128):
        c.write_cache([(f"nd-{i}", 0)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"] == {
        "entries": 0, "bytes": 0, "capacity": 0, "promotions": 0, "dropped": 0
    }
    assert c.check_exist("nd-0") is False  # evicted = gone
    assert c.check_exist("nd-127") is True
    c.close()
    srv.stop()


def test_large_batch_reclaims_instead_of_507():
    """A batch larger than the eviction-ratio slack must evict/demote what
    it needs rather than fail 507 while reclaimable entries exist (the
    reference 507s here). Both with and without the spill tier."""
    for spill in (True, False):
        srv = _server() if spill else its.start_local_server(
            prealloc_bytes=4 << 20, block_bytes=BLOCK
        )
        c = _connect(srv)
        half = 32  # 2MB batches against a 4MB pool
        buf = np.random.randint(0, 256, size=half * BLOCK, dtype=np.uint8)
        c.register_mr(buf)
        for r in range(6):  # 12MB total: far past the pool, batch by batch
            pairs = [(f"big{spill}-{r}-{i}", i * BLOCK) for i in range(half)]
            c.write_cache(pairs, BLOCK, buf.ctypes.data)  # must not raise
        # Latest batch readable; with spill the earlier ones survive too.
        dst = np.zeros(BLOCK, dtype=np.uint8)
        c.register_mr(dst)
        c.read_cache([(f"big{spill}-5-0", 0)], BLOCK, dst.ctypes.data)
        assert np.array_equal(dst, buf[:BLOCK])
        if spill:
            assert c.check_exist(f"big{spill}-0-0") is True
        c.close()
        srv.stop()


def test_bad_spill_dir_disables_tier_not_server():
    srv = its.start_local_server(
        prealloc_bytes=2 << 20, block_bytes=BLOCK,
        spill_dir="/nonexistent-dir-xyz", spill_bytes=8 << 20,
    )
    c = _connect(srv)
    src = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(src)
    c.write_cache([("ok", 0)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"]["capacity"] == 0  # tier off, server fine
    c.close()
    srv.stop()


def test_unpromotable_batch_errors_but_data_survives():
    """A single batch read of more spilled data than RAM can hold must fail
    with a resource error — and the spilled bytes must SURVIVE, readable by
    smaller batches afterwards (a failed promotion used to erase entries)."""
    srv = _server()  # 4MB RAM / 64MB spill
    c = _connect(srv)
    n = 128  # 8MB of keys; >=64 spilled
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"up-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"]["entries"] > 0

    # One batch spanning everything: promoted blocks get pinned by the batch
    # refs until RAM runs out -> typed error, NOT a silent miss or crash.
    dst = np.zeros(n * BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    pairs = [(f"up-{i}", i * BLOCK) for i in range(n)]
    with pytest.raises(its.InfiniStoreException) as ei:
        c.read_cache(pairs, BLOCK, dst.ctypes.data)
    assert "404" not in str(ei.value), "resource pressure must not read as a miss"

    # Every key is still present and readable in small batches.
    small = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(small)
    for i in range(n):
        assert c.check_exist(f"up-{i}"), f"up-{i} destroyed by failed promotion"
        c.read_cache([(f"up-{i}", 0)], BLOCK, small.ctypes.data)
        assert np.array_equal(small, src[i * BLOCK : (i + 1) * BLOCK])
    c.close()
    srv.stop()


def test_delete_racing_sliced_read_is_typed_never_hung():
    """A batched read of spilled keys runs budget-sliced across reactor
    ticks (ServerConfig::slice_bytes); a delete from another connection can
    land BETWEEN slices. The read must finish with either correct bytes or
    the typed KeyNotFound — never a hang (the stale slice_capped_ retry
    loop this test pins down) and never a 507 for a key that is simply
    gone (507 stays reserved for batches whose pins genuinely exceed RAM).
    The connection stays usable afterwards."""
    import asyncio
    import threading

    srv = _server()
    reader = _connect(srv)
    deleter = _connect(srv)
    try:
        n = 128  # 8MB working set over a 4MB pool -> most blocks spilled
        buf = reader.alloc_shm_mr(n * BLOCK)
        assert buf is not None
        buf[:] = 3
        pairs = [(f"race-{i}", i * BLOCK) for i in range(n)]

        def read_in_thread(span, deleted_span):
            outcome = {}

            def run_read():
                try:
                    asyncio.run(reader.read_cache_async(span, BLOCK, buf.ctypes.data))
                    outcome["r"] = "ok"
                except its.InfiniStoreKeyNotFound:
                    outcome["r"] = "miss"
                except its.InfiniStoreResourcePressure:
                    outcome["r"] = "pressure"
                except its.InfiniStoreException as e:
                    outcome["r"] = f"err:{e}"

            th = threading.Thread(target=run_read)
            th.start()
            deleter.delete_keys([k for k, _ in deleted_span])
            th.join(timeout=30)
            assert not th.is_alive(), "sliced read hung after racing delete"
            return outcome["r"]

        for attempt in range(6):
            # Rewrite everything so each round starts complete (and mostly
            # spilled: the writes evict/demote the earlier promoted blocks).
            for s in range(0, n, 32):
                reader.write_cache(pairs[s : s + 32], BLOCK, buf.ctypes.data)
            # RAM-fitting batch (48 blocks = 3MB < 4MB pool): pins cannot
            # exceed RAM, so the only legal outcomes are correct bytes or
            # the typed miss — a 507 would be the deleted-key-as-pressure
            # bug; a hang would be the stale slice_capped_ loop.
            got = read_in_thread(pairs[:48], pairs[32:48])
            assert got in ("ok", "miss"), got
        # Oversized batch (all 128 = 8MB of pins > 4MB RAM) racing the same
        # delete: typed pressure is now legitimate; hangs/crashes are not.
        for s in range(0, n, 32):
            reader.write_cache(pairs[s : s + 32], BLOCK, buf.ctypes.data)
        got = read_in_thread(pairs, pairs[96:])
        assert got in ("ok", "miss", "pressure"), got
        # Connection still serves ops.
        reader.write_cache([pairs[0]], BLOCK, buf.ctypes.data)
        reader.read_cache([pairs[0]], BLOCK, buf.ctypes.data)
    finally:
        reader.close()
        deleter.close()
        srv.stop()
