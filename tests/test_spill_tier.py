"""File-backed spill tier + the cluster-wide tiered capacity plane.

Local tier (the original suite): with ``spill_dir`` set, eviction demotes
LRU blocks into an mmap'd (immediately unlinked — crash-safe by
construction) file, and access promotes them back into a RAM pool.
Everything runs through the public surface against a live server.

Tiered capacity plane (docs/tiering.md): the temperature sketch /
admission policy units, the typed "cold but alive" 512 status, spill
config validation, and the cluster demotion/promotion transitions —
promote-on-hit restores byte-identical data, a fault-injected cold member
routes through breakers and never wedges, a Zipf working set converges to
hot-set-in-RAM, and (chaos-marked) the cold member is killed outright.
"""

import asyncio
import time

import numpy as np
import pytest

import infinistore_tpu as its

BLOCK = 64 << 10


def _server(**kw):
    defaults = dict(
        prealloc_bytes=4 << 20,  # 64 blocks of RAM
        block_bytes=BLOCK,
        spill_dir="/tmp",
        spill_bytes=64 << 20,
    )
    defaults.update(kw)
    return its.start_local_server(**defaults)


def _connect(srv):
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    return c


def test_capacity_beyond_ram_with_data_intact():
    """Write 2x the RAM pool; every key stays present and byte-correct."""
    srv = _server()
    c = _connect(srv)
    n = 128  # 8MB through a 4MB pool
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"sp-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    spill = c.get_stats()["spill"]
    assert spill["entries"] > 0, "nothing spilled — pool should have overflowed"
    assert spill["dropped"] == 0

    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    for i in range(n):
        assert c.check_exist(f"sp-{i}"), f"sp-{i} lost despite spill tier"
        c.read_cache([(f"sp-{i}", 0)], BLOCK, dst.ctypes.data)
        assert np.array_equal(dst, src[i * BLOCK : (i + 1) * BLOCK]), f"sp-{i} corrupt"
    assert c.get_stats()["spill"]["promotions"] >= n - 64  # spilled ones came back
    c.close()
    srv.stop()


def test_prefix_match_and_delete_cover_spilled_entries():
    """Control ops see spilled entries as present (no promotion), and delete
    frees their slots."""
    srv = _server()
    c = _connect(srv)
    n = 100
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"ch-{i:04d}", i * BLOCK)], BLOCK, src.ctypes.data)
    # Chain over all keys: early ones are spilled by now, yet the match must
    # cover the full chain.
    assert c.get_match_last_index([f"ch-{i:04d}" for i in range(n)]) == n - 1
    before = c.get_stats()["spill"]["bytes"]
    assert before > 0
    assert c.delete_keys([f"ch-{i:04d}" for i in range(n)]) == n
    assert c.get_stats()["spill"]["bytes"] == 0, "delete must free spill slots"
    c.close()
    srv.stop()


def test_spill_full_drops_coldest_only():
    """When the spill file itself fills, only the coldest spilled entries are
    dropped; the hottest data survives."""
    srv = _server(spill_bytes=2 << 20)  # RAM 4MB + spill 2MB << data 12MB
    c = _connect(srv)
    n = 192
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"fd-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    spill = c.get_stats()["spill"]
    assert spill["dropped"] > 0, "spill file should have overflowed"
    # The most recent writes are still resident or spilled — readable.
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    for i in range(n - 16, n):
        c.read_cache([(f"fd-{i}", 0)], BLOCK, dst.ctypes.data)
        assert np.array_equal(dst, src[i * BLOCK : (i + 1) * BLOCK])
    # The oldest were dropped for real (cache semantics).
    assert c.check_exist("fd-0") is False
    c.close()
    srv.stop()


def test_overwrite_of_spilled_key_frees_slot():
    srv = _server()
    c = _connect(srv)
    n = 96
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"ow-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"]["entries"] > 0
    # Overwrite an old (spilled) key with fresh bytes; read must see them.
    fresh = np.full(BLOCK, 0xA5, dtype=np.uint8)
    c.register_mr(fresh)
    c.write_cache([("ow-0", 0)], BLOCK, fresh.ctypes.data)
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    c.read_cache([("ow-0", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 0xA5).all()
    c.close()
    srv.stop()


def test_spill_disabled_keeps_reference_behavior():
    """Without spill_dir, eviction drops — the pre-existing (reference)
    semantics are untouched."""
    srv = its.start_local_server(prealloc_bytes=4 << 20, block_bytes=BLOCK)
    c = _connect(srv)
    src = np.random.randint(0, 256, size=BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(128):
        c.write_cache([(f"nd-{i}", 0)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"] == {
        "entries": 0, "bytes": 0, "capacity": 0, "promotions": 0, "dropped": 0
    }
    assert c.check_exist("nd-0") is False  # evicted = gone
    assert c.check_exist("nd-127") is True
    c.close()
    srv.stop()


def test_large_batch_reclaims_instead_of_507():
    """A batch larger than the eviction-ratio slack must evict/demote what
    it needs rather than fail 507 while reclaimable entries exist (the
    reference 507s here). Both with and without the spill tier."""
    for spill in (True, False):
        srv = _server() if spill else its.start_local_server(
            prealloc_bytes=4 << 20, block_bytes=BLOCK
        )
        c = _connect(srv)
        half = 32  # 2MB batches against a 4MB pool
        buf = np.random.randint(0, 256, size=half * BLOCK, dtype=np.uint8)
        c.register_mr(buf)
        for r in range(6):  # 12MB total: far past the pool, batch by batch
            pairs = [(f"big{spill}-{r}-{i}", i * BLOCK) for i in range(half)]
            c.write_cache(pairs, BLOCK, buf.ctypes.data)  # must not raise
        # Latest batch readable; with spill the earlier ones survive too.
        dst = np.zeros(BLOCK, dtype=np.uint8)
        c.register_mr(dst)
        c.read_cache([(f"big{spill}-5-0", 0)], BLOCK, dst.ctypes.data)
        assert np.array_equal(dst, buf[:BLOCK])
        if spill:
            assert c.check_exist(f"big{spill}-0-0") is True
        c.close()
        srv.stop()


def test_bad_spill_dir_disables_tier_not_server():
    srv = its.start_local_server(
        prealloc_bytes=2 << 20, block_bytes=BLOCK,
        spill_dir="/nonexistent-dir-xyz", spill_bytes=8 << 20,
    )
    c = _connect(srv)
    src = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(src)
    c.write_cache([("ok", 0)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"]["capacity"] == 0  # tier off, server fine
    c.close()
    srv.stop()


def test_unpromotable_batch_errors_but_data_survives():
    """A single batch read of more spilled data than RAM can hold must fail
    with a resource error — and the spilled bytes must SURVIVE, readable by
    smaller batches afterwards (a failed promotion used to erase entries)."""
    srv = _server()  # 4MB RAM / 64MB spill
    c = _connect(srv)
    n = 128  # 8MB of keys; >=64 spilled
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"up-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    assert c.get_stats()["spill"]["entries"] > 0

    # One batch spanning everything: promoted blocks get pinned by the batch
    # refs until RAM runs out -> typed error, NOT a silent miss or crash.
    dst = np.zeros(n * BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    pairs = [(f"up-{i}", i * BLOCK) for i in range(n)]
    with pytest.raises(its.InfiniStoreException) as ei:
        c.read_cache(pairs, BLOCK, dst.ctypes.data)
    assert "404" not in str(ei.value), "resource pressure must not read as a miss"
    # The typed 512 "cold but alive" (docs/tiering.md): the keys are
    # PRESENT, just unpromotable — callers must be able to tell this from
    # genuine allocation exhaustion (507) and from a miss (404). Still a
    # ResourcePressure subclass, so pre-tier handlers keep working.
    assert isinstance(ei.value, its.InfiniStoreColdTier)
    assert isinstance(ei.value, its.InfiniStoreResourcePressure)

    # Every key is still present and readable in small batches.
    small = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(small)
    for i in range(n):
        assert c.check_exist(f"up-{i}"), f"up-{i} destroyed by failed promotion"
        c.read_cache([(f"up-{i}", 0)], BLOCK, small.ctypes.data)
        assert np.array_equal(small, src[i * BLOCK : (i + 1) * BLOCK])
    c.close()
    srv.stop()


def test_delete_racing_sliced_read_is_typed_never_hung():
    """A batched read of spilled keys runs budget-sliced across reactor
    ticks (ServerConfig::slice_bytes); a delete from another connection can
    land BETWEEN slices. The read must finish with either correct bytes or
    the typed KeyNotFound — never a hang (the stale slice_capped_ retry
    loop this test pins down) and never a 507 for a key that is simply
    gone (507 stays reserved for batches whose pins genuinely exceed RAM).
    The connection stays usable afterwards."""
    import asyncio
    import threading

    srv = _server()
    reader = _connect(srv)
    deleter = _connect(srv)
    try:
        n = 128  # 8MB working set over a 4MB pool -> most blocks spilled
        buf = reader.alloc_shm_mr(n * BLOCK)
        assert buf is not None
        buf[:] = 3
        pairs = [(f"race-{i}", i * BLOCK) for i in range(n)]

        def read_in_thread(span, deleted_span):
            outcome = {}

            def run_read():
                try:
                    asyncio.run(reader.read_cache_async(span, BLOCK, buf.ctypes.data))
                    outcome["r"] = "ok"
                except its.InfiniStoreKeyNotFound:
                    outcome["r"] = "miss"
                except its.InfiniStoreResourcePressure:
                    outcome["r"] = "pressure"
                except its.InfiniStoreException as e:
                    outcome["r"] = f"err:{e}"

            th = threading.Thread(target=run_read)
            th.start()
            deleter.delete_keys([k for k, _ in deleted_span])
            th.join(timeout=30)
            assert not th.is_alive(), "sliced read hung after racing delete"
            return outcome["r"]

        for attempt in range(6):
            # Rewrite everything so each round starts complete (and mostly
            # spilled: the writes evict/demote the earlier promoted blocks).
            for s in range(0, n, 32):
                reader.write_cache(pairs[s : s + 32], BLOCK, buf.ctypes.data)
            # RAM-fitting batch (48 blocks = 3MB < 4MB pool): pins cannot
            # exceed RAM, so the only legal outcomes are correct bytes or
            # the typed miss — a 507 would be the deleted-key-as-pressure
            # bug; a hang would be the stale slice_capped_ loop.
            got = read_in_thread(pairs[:48], pairs[32:48])
            assert got in ("ok", "miss"), got
        # Oversized batch (all 128 = 8MB of pins > 4MB RAM) racing the same
        # delete: typed pressure is now legitimate; hangs/crashes are not.
        for s in range(0, n, 32):
            reader.write_cache(pairs[s : s + 32], BLOCK, buf.ctypes.data)
        got = read_in_thread(pairs, pairs[96:])
        assert got in ("ok", "miss", "pressure"), got
        # Connection still serves ops.
        reader.write_cache([pairs[0]], BLOCK, buf.ctypes.data)
        reader.read_cache([pairs[0]], BLOCK, buf.ctypes.data)
    finally:
        reader.close()
        deleter.close()
        srv.stop()


# ---------------------------------------------------------------------------
# Tiered capacity plane (docs/tiering.md).
# ---------------------------------------------------------------------------


def test_serverconfig_validates_spill_at_construction(tmp_path):
    """Spill misconfiguration must fail AT CONSTRUCTION with a clear
    message, not as a native-layer failure at the first demotion."""
    from infinistore_tpu.config import ServerConfig

    with pytest.raises(ValueError, match="spill_size must be >= 0"):
        ServerConfig(spill_dir=str(tmp_path), spill_size=-1)
    with pytest.raises(ValueError, match="spill_size is 0"):
        ServerConfig(spill_dir=str(tmp_path), spill_size=0)
    with pytest.raises(ValueError, match="spill_dir is empty"):
        ServerConfig(spill_size=4)
    with pytest.raises(ValueError, match="does not exist"):
        ServerConfig(spill_dir=str(tmp_path / "nope"), spill_size=4)
    cfg = ServerConfig(spill_dir=str(tmp_path), spill_size=4)
    cfg.verify()  # the valid shape passes end to end
    ServerConfig().verify()  # tier off stays valid


def test_temperature_sketch_bounded_ghost_list():
    """Fixed slots, evict-coldest on probe-window overflow, streak resets
    past the reuse window — the policy's reuse-distance proxy."""
    from infinistore_tpu.tiering import TemperatureSketch, TierPolicy, TierPolicyConfig

    t = [0.0]
    sk = TemperatureSketch(capacity=16, reuse_window_s=10.0, clock=lambda: t[0])
    assert sk.touch("r1") == (1, float("inf"))
    t[0] = 1.0
    assert sk.touch("r1") == (2, 1.0)  # short reuse distance: streak grows
    t[0] = 100.0
    assert sk.touch("r1")[0] == 1  # past the window: back to a scan
    # Bounded: flooding far past capacity evicts, never grows.
    for i in range(500):
        sk.touch(f"flood-{i}")
    assert sk.tracked <= sk.capacity
    assert sk.evictions > 0
    # Policy decisions over the sketch.
    pol = TierPolicy(
        TierPolicyConfig(admit_min_streak=2, demote_idle_s=5.0,
                         reuse_window_s=10.0, sketch_capacity=64),
        clock=lambda: t[0],
    )
    pol.on_access("hot")
    assert not pol.should_promote("hot")  # one touch = a scan
    t[0] += 1.0
    pol.on_access("hot")
    assert pol.should_promote("hot")  # provable short-distance reuse
    assert not pol.should_demote("hot")
    t[0] += 6.0
    assert pol.should_demote("hot")  # idle past the threshold
    assert pol.should_demote("never-seen")  # unknown/ghost-evicted = cold


def test_cold_but_alive_counts_demotion_hit_not_miss():
    """The connector's degrade path must count the typed 512 as a tier
    DEMOTION HIT (data alive one tier down), never a miss."""
    import jax.numpy as jnp

    from infinistore_tpu import tiering
    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.tpu.layerwise import PartialReadError
    from infinistore_tpu.tpu.paged import PagedKVCacheSpec

    spec = PagedKVCacheSpec(
        num_layers=1, num_blocks=8, block_tokens=8, num_kv_heads=2,
        head_dim=32, dtype=jnp.bfloat16,
    )
    srv = its.start_local_server(prealloc_bytes=4 << 20, block_bytes=16 << 10)
    c = _connect(srv)
    try:
        kv = KVConnector(c, spec, "demo", max_blocks=8)
        caches = [
            (jnp.zeros(spec.cache_shape, spec.dtype),
             jnp.zeros(spec.cache_shape, spec.dtype))
        ]

        class _ColdReader:
            async def read(self, caches, block_ids, keys, on_layer=None):
                raise PartialReadError(
                    list(caches), its.InfiniStoreColdTier("cold but alive")
                )

        kv._reader = _ColdReader()
        kv._lookup_chains = lambda chains: len(chains)
        tiering.reset_demotion_hits()
        out, n = asyncio.run(
            kv.load(list(range(16)), caches, np.array([0, 1]))
        )
        assert n == 0  # degrades like a miss for the ENGINE (recompute)...
        assert tiering.demotion_hits() == 1  # ...but the tier ledger knows
    finally:
        tiering.reset_demotion_hits()
        c.close()
        srv.stop()


# -- cluster-plane fixtures --------------------------------------------------


def _tier_spec():
    import jax.numpy as jnp

    from infinistore_tpu.tpu import PagedKVCacheSpec

    return PagedKVCacheSpec(
        num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2,
        head_dim=32, dtype=jnp.bfloat16,
    )


def _tier_caches(spec, seed):
    import jax
    import jax.numpy as jnp

    out = []
    for layer in range(spec.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), spec.cache_shape, jnp.float32
        ).astype(spec.dtype)
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), spec.cache_shape,
            jnp.float32,
        ).astype(spec.dtype)
        out.append((k, v))
    return out


class _TierPool:
    """2 serving + 1 cold loopback servers under one ClusterKVConnector
    with a manually-paced TierManager (tiering_interval_s=0)."""

    def __init__(self, policy=None, wrap_cold=None):
        from infinistore_tpu import ClusterKVConnector

        self.spec = _tier_spec()
        self.servers, self.conns = [], []
        for _ in range(3):
            srv = its.start_local_server(
                prealloc_bytes=64 << 20, block_bytes=16 << 10
            )
            conn = its.InfinityConnection(its.ClientConfig(
                host_addr="127.0.0.1", service_port=srv.port, log_level="error"
            ))
            conn.connect()
            self.servers.append(srv)
            self.conns.append(conn)
        self.cold_conn = (
            wrap_cold(self.conns[2]) if wrap_cold else self.conns[2]
        )
        self.cluster = ClusterKVConnector(
            self.conns[:2], self.spec, "demo", max_blocks=8,
            cold_members=[self.cold_conn],
            cold_member_ids=[
                f"127.0.0.1:{self.servers[2].port}"
            ],
            tier_policy=policy, tiering_interval_s=0,
        )
        self.saved = {}  # root key -> (tokens, caches, block_ids)

    def save_root(self, seed):
        tokens = [1000 + seed] + list(range(1, 2 * self.spec.block_tokens))
        caches = _tier_caches(self.spec, seed)
        ids = np.array([3, 9], dtype=np.int32)
        written = asyncio.run(self.cluster.save(tokens, caches, ids))
        assert written == 2 * 2 * self.spec.num_layers
        self.saved[seed] = (tokens, caches, ids)
        return tokens

    def load_and_verify(self, seed):
        import jax.numpy as jnp

        from infinistore_tpu.tpu import gather_blocks

        tokens, caches, ids = self.saved[seed]
        fresh = _tier_caches(self.spec, 9000 + seed)
        dst = np.array([5, 12], dtype=np.int32)
        out, n = asyncio.run(self.cluster.load(tokens, fresh, dst))
        if n == 0:
            return 0
        for layer in range(self.spec.num_layers):
            for kind in (0, 1):
                got = np.asarray(
                    gather_blocks(out[layer][kind], jnp.asarray(dst)),
                    np.float32,
                )
                want = np.asarray(
                    gather_blocks(caches[layer][kind], jnp.asarray(ids)),
                    np.float32,
                )
                assert np.array_equal(got, want), (seed, layer, kind)
        return n

    def close(self):
        self.cluster.close()
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass
        for s in self.servers:
            s.stop()


def test_tier_demote_promote_roundtrip_byte_identical():
    """The core transition property: demote ships the root cold and frees
    the serving copies; the read falls through to cold BYTE-IDENTICAL;
    promotion-on-hit brings it back serving-side, still byte-identical."""
    from infinistore_tpu.tiering import TierPolicy, TierPolicyConfig

    pool = _TierPool(policy=TierPolicy(
        TierPolicyConfig(demote_idle_s=0.0, admit_min_streak=2)
    ))
    try:
        tokens = pool.save_root(1)
        assert pool.cluster.tier_location(tokens) == "hot"
        res = pool.cluster.tiering.run_pass()
        assert res["demoted"] == 1
        assert pool.cluster.tier_location(tokens) == "cold"
        # The serving copies are really gone (capacity reclaimed): the
        # serving members answer 0 and the fall-through serves from cold.
        st = pool.cluster.tiering.status()
        assert st["tier_demotions"] == 1 and st["tier_demoted_keys"] > 0
        assert pool.cluster.lookup(tokens) == 2  # cold fall-through
        assert pool.load_and_verify(1) == 2  # byte-identical from cold
        st = pool.cluster.tiering.status()
        assert st["tier_cold_hits"] >= 2 and st["tier_cold_reads"] >= 1
        assert st["tier_promote_backlog"] >= 1  # admitted (streak >= 2)
        res = pool.cluster.tiering.run_pass()
        assert res["promoted"] == 1
        assert pool.cluster.tier_location(tokens) == "hot"
        assert pool.load_and_verify(1) == 2  # byte-identical, serving-side
        st = pool.cluster.tiering.status()
        assert st["tier_promotions"] == 1
        assert st["tier_wrong_reads"] == 0
        # Cold-read latency reached the SLO engine's cold_latency objective.
        from infinistore_tpu import telemetry

        assert telemetry.slo_engine().status()["slo_cold_p99_us"] > 0
    finally:
        pool.close()


def test_one_touch_scan_stays_cold():
    """Admission: a single cold touch (no provable reuse) must NOT promote
    — scans stay cold (tier_admit_rejects counts them)."""
    from infinistore_tpu.tiering import TierPolicy, TierPolicyConfig

    t = [0.0]
    pool = _TierPool(policy=TierPolicy(
        TierPolicyConfig(demote_idle_s=5.0, admit_min_streak=2,
                         reuse_window_s=10.0),
        clock=lambda: t[0],
    ))
    try:
        tokens = pool.save_root(1)
        t[0] += 6.0  # idle past demote_idle_s
        assert pool.cluster.tiering.run_pass()["demoted"] == 1
        t[0] += 100.0  # far past the reuse window: the next touch is a scan
        assert pool.cluster.lookup(tokens) == 2  # served from cold...
        st = pool.cluster.tiering.status()
        assert st["tier_admit_rejects"] >= 1  # ...but NOT admitted back
        assert st["tier_promote_backlog"] == 0
        assert pool.cluster.tiering.run_pass()["promoted"] == 0
        assert pool.cluster.tier_location(tokens) == "cold"
        # A second touch inside the window proves reuse: now it promotes.
        t[0] += 1.0
        assert pool.cluster.lookup(tokens) == 2
        assert pool.cluster.tiering.status()["tier_promote_backlog"] == 1
        assert pool.cluster.tiering.run_pass()["promoted"] == 1
        assert pool.cluster.tier_location(tokens) == "hot"
    finally:
        pool.close()


def test_demotion_faulted_cold_member_routes_breakers_never_wedges():
    """A cold member erroring every write: demotion FAILS FAST through the
    breaker (counted, bounded time), data keeps serving from the serving
    members, and nothing wedges."""
    from infinistore_tpu.faults import FaultRule, FaultyConnection
    from infinistore_tpu.tiering import TierPolicy, TierPolicyConfig

    pool = _TierPool(
        policy=TierPolicy(TierPolicyConfig(demote_idle_s=0.0)),
        wrap_cold=lambda c: FaultyConnection(
            c, [FaultRule(op=("write_cache", "tcp_write_cache"),
                          action="error")],
        ),
    )
    try:
        tokens = pool.save_root(1)
        t0 = time.monotonic()
        for _ in range(4):  # enough passes to trip the breaker (threshold 3)
            res = pool.cluster.tiering.run_pass()
            assert res["demoted"] == 0
        assert time.monotonic() - t0 < 30.0, "faulted demotion wedged"
        st = pool.cluster.tiering.status()
        assert st["tier_demote_failures"] >= 3
        assert st["tier_demotions"] == 0
        # The breaker is OPEN: later passes fast-fail locally.
        h = pool.cluster._cold_health[0]
        assert h.breaker.state == "open"
        assert h.errors >= 3
        # The root never left the serving tier; reads stay byte-identical.
        assert pool.cluster.tier_location(tokens) == "hot"
        assert pool.load_and_verify(1) == 2
        assert pool.cluster.tiering.status()["tier_wrong_reads"] == 0
    finally:
        pool.close()


def test_zipf_workload_converges_hot_set_in_ram():
    """Under a Zipf access pattern the hot head stays (or returns)
    serving-side while the long tail demotes to the cold pool — the
    working set converges to RAM, capacity to cold."""
    from infinistore_tpu.tiering import TierPolicy, TierPolicyConfig

    t = [0.0]
    pool = _TierPool(policy=TierPolicy(
        TierPolicyConfig(demote_idle_s=5.0, admit_min_streak=2,
                         reuse_window_s=50.0, sketch_capacity=256),
        clock=lambda: t[0],
    ))
    try:
        n = 12
        tokens_of = {s: pool.save_root(s) for s in range(n)}
        hot = [0, 1, 2]
        rng = np.random.default_rng(7)
        # Zipf-ish rounds: the head is touched every round, the tail never.
        for _ in range(6):
            t[0] += 1.0
            for s in hot:
                assert pool.cluster.lookup(tokens_of[s]) == 2
            # one random mid-tail scan (one-touch; must not pin it hot)
            pool.cluster.lookup(tokens_of[int(rng.integers(3, n))])
        t[0] += 6.0  # now the tail (and the scans) are idle past threshold
        for s in hot:
            assert pool.cluster.lookup(tokens_of[s]) == 2  # head stays touched
        for _ in range(4):
            pool.cluster.tiering.run_pass()
        locs = {s: pool.cluster.tier_location(tokens_of[s]) for s in range(n)}
        assert all(locs[s] == "hot" for s in hot), locs
        tail_cold = sum(1 for s in range(3, n) if locs[s] == "cold")
        assert tail_cold >= (n - 3) - 2, locs  # the tail demoted
        # Every root still answers, byte-identical, wherever it lives.
        for s in range(n):
            assert pool.load_and_verify(s) == 2
        st = pool.cluster.tiering.status()
        assert st["tier_demotions"] >= tail_cold
        assert st["tier_wrong_reads"] == 0
    finally:
        pool.close()


def test_tiers_endpoint_and_metrics_families():
    """GET /tiers serves the TierManager status and /metrics carries the
    infinistore_tier_* families (the ITS-C007 lockstep surface)."""
    import json

    from infinistore_tpu.config import ServerConfig
    from infinistore_tpu.server import ManageServer
    from infinistore_tpu.tiering import TierPolicy, TierPolicyConfig

    pool = _TierPool(policy=TierPolicy(
        TierPolicyConfig(demote_idle_s=0.0)
    ))
    try:
        pool.save_root(1)
        pool.cluster.tiering.run_pass()

        async def drive():
            manage = ManageServer(
                ServerConfig(service_port=pool.servers[0].port, manage_port=0),
                cluster=pool.cluster,
            )
            server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = server.sockets[0].getsockname()[1]

            async def req(method, path):
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
                writer.write(
                    f"{method} {path} HTTP/1.1\r\nHost: t\r\n\r\n".encode()
                )
                await writer.drain()
                raw = await reader.read()
                writer.close()
                head, _, body = raw.partition(b"\r\n\r\n")
                return int(head.split()[1]), body

            status, body = await req("GET", "/tiers")
            doc = json.loads(body)
            assert status == 200 and doc["enabled"]
            assert doc["tier_demotions"] >= 1
            assert doc["tier_cold_members"] == 1
            assert doc["cold_members"][0]["breaker_state"] == "closed"

            status, body = await req("GET", "/metrics")
            assert status == 200
            assert b"infinistore_tier_demotions 1" in body
            assert b'infinistore_tier_hits{tier="ram"}' in body
            assert b"infinistore_tier_demote_backlog" in body
            assert b"infinistore_slo_cold_p99_us" in body

            status, _ = await req("DELETE", "/tiers")
            assert status == 405
            server.close()
            await server.wait_closed()

        asyncio.run(drive())
    finally:
        pool.close()


@pytest.mark.chaos
def test_kill_cold_member_mid_demotion_chaos():
    """Kill the cold member's transport outright: in-flight demotions fail
    typed and fast (breaker opens), serving data keeps serving, already-
    demoted roots degrade to a MISS (recompute — never wrong bytes, never
    a hang), and the half-open probe heals the transport so cold reads
    resume."""
    from infinistore_tpu.cluster import CircuitBreaker
    from infinistore_tpu.faults import kill_transport
    from infinistore_tpu.tiering import TierPolicy, TierPolicyConfig

    pool = _TierPool(policy=TierPolicy(
        TierPolicyConfig(demote_idle_s=0.0, admit_min_streak=2)
    ))
    # Fast probe windows so the heal happens inside the test budget.
    pool.cluster._cold_health[0].breaker = CircuitBreaker(
        fail_threshold=2, probe_backoff_s=0.05, max_backoff_s=0.2,
    )
    try:
        t_a = pool.save_root(1)
        assert pool.cluster.tiering.run_pass()["demoted"] == 1  # a is cold
        t_b = pool.save_root(2)  # still serving-side

        kill_transport(pool.conns[2])

        # Demotion of b fails typed + fast; b keeps serving.
        t0 = time.monotonic()
        for _ in range(3):
            assert pool.cluster.tiering.run_pass()["demoted"] == 0
        assert time.monotonic() - t0 < 30.0
        assert pool.cluster.tiering.status()["tier_demote_failures"] >= 1
        assert pool.load_and_verify(2) == 2
        # The demoted root degrades to a miss (its only copy is behind the
        # dead transport) — 0 blocks, never wrong bytes, never a hang.
        assert pool.load_and_verify(1) == 0
        assert pool.cluster._cold_health[0].breaker.state == "open"

        # Recovery: the probe window elapses, the next cold op heals the
        # connection (auto reconnect path) and cold reads resume.
        deadline = time.monotonic() + 20.0
        served = 0
        while time.monotonic() < deadline:
            time.sleep(0.1)
            if pool.cluster.lookup(t_a) == 2:
                served = 1
                break
        assert served, "cold member never healed through the probe"
        assert pool.load_and_verify(1) == 2  # byte-identical after the heal
        assert pool.cluster.tiering.status()["tier_wrong_reads"] == 0
        assert pool.load_and_verify(2) == 2
        del t_b
    finally:
        pool.close()
