"""Driver contract: entry() compiles single-chip; dryrun_multichip(8) runs a
sharded train step + ICI transfer on the virtual mesh."""

import sys

import jax
import pytest

sys.path.insert(0, ".")


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    logits, caches = jax.jit(fn)(*args)
    jax.block_until_ready(logits)
    assert logits.shape[-1] == 2048
    assert len(caches) == 4


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    assert len(jax.devices()) >= 8
    g.dryrun_multichip(8)
