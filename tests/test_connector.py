"""KVConnector: the LMCache-style engine glue (BASELINE.md config 4).

Covers the chain-hash key scheme (prefix property), cross-request prefix
reuse (lookup -> load skips recompute), save/load roundtrip through the real
loopback store, and drop().
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu import KVConnector, token_chain_hashes
from infinistore_tpu.tpu import PagedKVCacheSpec, gather_blocks

SPEC = PagedKVCacheSpec(
    num_layers=3, num_blocks=16, block_tokens=8, num_kv_heads=2, head_dim=32,
    dtype=jnp.bfloat16,
)


def _rand_caches(seed):
    out = []
    for layer in range(SPEC.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        out.append((k, v))
    return out


def test_chain_hashes_prefix_property():
    a = list(range(40))
    b = list(range(24)) + [99, 98, 97, 96, 95, 94, 93, 92] + list(range(8))
    ha, hb = token_chain_hashes(a, 8), token_chain_hashes(b, 8)
    assert len(ha) == 5
    assert ha[:3] == hb[:3]  # shared 24-token prefix -> same first 3 chains
    assert ha[3] != hb[3]  # divergence poisons every later chain
    assert ha[4] != hb[4]
    # Incomplete tail block is excluded.
    assert len(token_chain_hashes(list(range(15)), 8)) == 1
    assert token_chain_hashes([], 8) == []


@pytest.fixture()
def connector(conn):
    return KVConnector(conn, SPEC, model_id="demo-llama", max_blocks=8)


def test_lookup_miss_then_save_then_hit(connector):
    tokens = list(range(32))  # 4 complete blocks
    assert connector.lookup(tokens) == 0
    caches = _rand_caches(1)
    block_ids = np.array([3, 7, 1, 9], dtype=np.int32)
    written = asyncio.run(connector.save(tokens, caches, block_ids))
    assert written == 4 * 2 * SPEC.num_layers  # K+V per layer per block
    assert connector.lookup(tokens) == 4
    # A prompt sharing 2 blocks then diverging hits exactly 2.
    other = list(range(16)) + [500 + i for i in range(16)]
    assert connector.lookup(other) == 2


def test_save_load_roundtrip_scatters_correct_blocks(connector):
    tokens = list(range(24))  # 3 blocks
    caches = _rand_caches(2)
    src_ids = np.array([2, 11, 5], dtype=np.int32)
    asyncio.run(connector.save(tokens, caches, src_ids))

    fresh = SPEC.make_caches()
    dst_ids = np.array([8, 0, 14], dtype=np.int32)
    loaded, n = asyncio.run(connector.load(tokens, fresh, dst_ids))
    assert n == 3
    ids_src = jnp.asarray(src_ids)
    ids_dst = jnp.asarray(dst_ids)
    for layer in range(SPEC.num_layers):
        for side in (0, 1):
            want = np.asarray(gather_blocks(caches[layer][side], ids_src))
            got = np.asarray(gather_blocks(loaded[layer][side], ids_dst))
            np.testing.assert_array_equal(want, got)


def test_load_partial_prefix(connector):
    """Only the cached prefix is fetched; the divergent tail is untouched."""
    base = list(range(16))  # 2 blocks saved
    caches = _rand_caches(3)
    asyncio.run(connector.save(base, caches, np.array([1, 2], dtype=np.int32)))

    longer = base + [900 + i for i in range(16)]  # 4 blocks, 2 cached
    fresh = SPEC.make_caches()
    loaded, n = asyncio.run(
        connector.load(longer, fresh, np.array([4, 5, 6, 7], dtype=np.int32))
    )
    assert n == 2
    # Block 6/7 (would-be blocks 3/4) stay zero.
    for layer in range(SPEC.num_layers):
        assert float(jnp.abs(loaded[layer][0][6]).sum()) == 0.0
        assert float(jnp.abs(loaded[layer][0][7]).sum()) == 0.0


def test_load_mid_read_race_returns_partial_caches(connector, conn):
    """Blocks raced away between lookup and read, AFTER layer 0 scattered:
    load must report a miss but hand back the reader's PARTIAL cache list —
    layer 0's scatters donated their input buffers (deleted on TPU), so the
    caller's original arrays for that layer are unusable."""
    tokens = list(range(16))  # 2 blocks
    caches = _rand_caches(5)
    asyncio.run(connector.save(tokens, caches, np.array([1, 2], dtype=np.int32)))
    chains = token_chain_hashes(tokens, SPEC.block_tokens)
    # Delete a deeper layer's K keys: the layer-0 sentinel stays, so lookup
    # still hits and the read fails mid-pipeline at layer 1.
    assert conn.delete_keys([connector.block_key(1, "k", c) for c in chains]) == 2

    fresh = SPEC.make_caches()
    orig_last = fresh[-1][0]
    loaded, n = asyncio.run(
        connector.load(tokens, fresh, np.array([4, 5], dtype=np.int32))
    )
    assert n == 0
    # Layer 0 was scattered before the failure: new arrays, carrying the
    # fetched bytes; untouched layers are the caller's own arrays.
    assert loaded[-1][0] is orig_last
    got = np.asarray(
        gather_blocks(loaded[0][0], jnp.asarray([4, 5], jnp.int32)), np.float32
    )
    want = np.asarray(
        gather_blocks(caches[0][0], jnp.asarray([1, 2], jnp.int32)), np.float32
    )
    np.testing.assert_array_equal(got, want)


def test_writer_commits_layer0_last(connector, conn):
    """The lookup sentinel (layer-0 K key) must be written after all deeper
    layers, so a half-saved block reads as absent rather than a false hit."""
    order = []
    orig = conn.write_cache_async

    async def spy(blocks, block_size, ptr, **kw):
        order.extend(k for k, _ in blocks)
        return await orig(blocks, block_size, ptr, **kw)

    conn.write_cache_async = spy
    try:
        tokens = list(range(16))
        asyncio.run(
            connector.save(tokens, _rand_caches(9), np.array([0, 1], dtype=np.int32))
        )
    finally:
        conn.write_cache_async = orig
    layer0_positions = [i for i, k in enumerate(order) if "/L0/" in k]
    others = [i for i, k in enumerate(order) if "/L0/" not in k]
    assert layer0_positions and others
    assert min(layer0_positions) > max(others)


def test_stage_layer_save_validates_first_block(connector):
    """stage_layer_save applies the same first_block bounds contract as
    save()/load(): out of range raises instead of silently slicing to an
    empty chain list and returning a no-op ship (which would hide caller
    bugs save() fails loudly on)."""
    tokens = list(range(16))  # 2 complete blocks
    kv_pair = _rand_caches(7)[0]
    ids = np.array([0, 1], dtype=np.int32)
    with pytest.raises(ValueError, match="first_block"):
        connector.stage_layer_save(tokens, 0, kv_pair, ids, first_block=3)
    with pytest.raises(ValueError, match="first_block"):
        connector.stage_layer_save(tokens, 0, kv_pair, ids, first_block=-1)
    # The boundary value (== block count) is legal: an empty-span no-op.
    ship = connector.stage_layer_save(tokens, 0, kv_pair, ids, first_block=2)
    assert asyncio.run(ship()) == 0


def test_drop_removes_all_layers(connector, conn):
    tokens = list(range(16))
    caches = _rand_caches(4)
    asyncio.run(connector.save(tokens, caches, np.array([0, 1], dtype=np.int32)))
    assert connector.lookup(tokens) == 2
    deleted = connector.drop(tokens)
    assert deleted == 2 * 2 * SPEC.num_layers
    assert connector.lookup(tokens) == 0


def test_lookup_raises_when_store_down():
    """A dead store must NOT read as a cache miss: miss -> 0, failure ->
    exception (else the engine silently recomputes forever). Mirrors the
    reference's typed behavior (reference lib.py:575-577)."""
    import infinistore_tpu as its

    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    cfg = its.ClientConfig(
        host_addr="127.0.0.1",
        service_port=srv.port,
        connection_type=its.TYPE_RDMA,
        log_level="error",
    )
    c = its.InfinityConnection(cfg)
    try:
        c.connect()
        k = KVConnector(c, SPEC, model_id="demo-llama", max_blocks=8)
        tokens = list(range(16))
        assert k.lookup(tokens) == 0  # genuine miss -> 0, no exception
        srv.stop()  # kill the server out from under the connection
        with pytest.raises(its.InfiniStoreException) as ei:
            k.lookup(tokens)
        assert not isinstance(ei.value, its.InfiniStoreNoMatch)
    finally:
        c.close()
        srv.stop()  # no-op on the success path (stop() is idempotent)


def test_pure_ici_connector_typed_errors():
    """conn=None (pure-ICI): store-needing ops raise the typed misuse error,
    not a bare AttributeError / silent 0."""
    k = KVConnector(None, SPEC, model_id="demo", max_blocks=8, ici=object())
    tokens = list(range(16))
    with pytest.raises(ValueError, match="store connection"):
        k.lookup(tokens)
    with pytest.raises(ValueError, match="store connection"):
        k.drop(tokens)


def test_handoff_rejects_ici_layout_caches_on_dcn_path(connector):
    """An ICI-layout cache ([axis_size, num_blocks, *block]) falling through
    to the DCN path would be gathered along the DEVICE axis and ship wrong
    bytes under valid keys — it must raise instead."""
    tokens = list(range(16))
    ici_shaped = [
        (
            jnp.zeros((2, *SPEC.cache_shape), SPEC.dtype),
            jnp.zeros((2, *SPEC.cache_shape), SPEC.dtype),
        )
        for _ in range(SPEC.num_layers)
    ]
    ids = np.array([0, 1], dtype=np.int32)
    with pytest.raises(ValueError, match="ICI-layout"):
        asyncio.run(connector.handoff(tokens, ici_shaped, ids, ids))


def test_connector_save_load_over_striped_connection():
    """The engine-facing connector must work unchanged over a
    StripedConnection (cross-host deployments stripe the DCN link): save
    streams layer batches across stripes, lookup/load resolve through
    stripe 0's control plane, and the roundtrip is byte-exact."""
    import asyncio

    import jax
    import jax.numpy as jnp

    import infinistore_tpu as its
    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.tpu.paged import PagedKVCacheSpec

    spec = PagedKVCacheSpec(
        num_layers=2, num_blocks=16, block_tokens=4, num_kv_heads=2, head_dim=8,
        dtype=jnp.float32,
    )
    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
    conn = its.StripedConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error"),
        streams=3,
    )
    conn.connect()
    kvc = KVConnector(conn, spec, "striped-model", max_blocks=8)
    caches = [
        (
            jax.random.normal(jax.random.PRNGKey(2 * l), spec.cache_shape),
            jax.random.normal(jax.random.PRNGKey(2 * l + 1), spec.cache_shape),
        )
        for l in range(spec.num_layers)
    ]
    refs = [(np.asarray(k), np.asarray(v)) for k, v in caches]
    toks = list(range(8 * spec.block_tokens))
    ids = np.arange(8, dtype=np.int32)
    written = asyncio.run(kvc.save(toks, caches, ids))
    assert written == 2 * spec.num_layers * 8  # K+V x layers x blocks
    assert kvc.lookup(toks) == 8
    fresh = [(jnp.zeros(spec.cache_shape), jnp.zeros(spec.cache_shape))
             for _ in range(spec.num_layers)]
    out, loaded = asyncio.run(kvc.load(toks, fresh, ids))
    assert loaded == 8
    for l in range(spec.num_layers):
        for side in (0, 1):
            np.testing.assert_array_equal(
                np.asarray(out[l][side])[ids], refs[l][side][ids]
            )
    conn.close()
    srv.stop()


def test_chain_hash_cache_survives_buffer_reuse():
    """The connector's incremental chain-hash cache must copy ndarray token
    inputs: an engine reusing a preallocated token buffer for the next
    prompt would otherwise mutate the cached tokens into falsely matching
    it — returning the OLD prompt's hashes (another request's KV keys)."""
    from infinistore_tpu.connector import _ChainHashCache

    cache = _ChainHashCache()
    buf = np.arange(64, dtype=np.int64)
    assert cache.hashes(buf, 8) == token_chain_hashes(list(range(64)), 8)
    buf[:] = 999  # engine reuses the buffer for a different prompt
    assert cache.hashes(buf, 8) == token_chain_hashes([999] * 64, 8)


def test_chain_hash_cache_repeat_prefix_extension():
    """Cache paths (repeat / prefix / extension / divergence) must all be
    byte-identical to the uncached token_chain_hashes."""
    from infinistore_tpu.connector import _ChainHashCache

    rng = np.random.default_rng(7)
    cache = _ChainHashCache()
    base = rng.integers(0, 1000, size=100).tolist()
    for tokens in (base, base, base[:40], base + [1, 2] * 12, base[:16],
                   rng.integers(0, 1000, size=33).tolist(), [], [5]):
        assert cache.hashes(tokens, 8) == token_chain_hashes(tokens, 8)
