"""Sequence-parallel (ring-attention) prefill, end to end with the store.

The long-context flow: a prompt too big for one device prefills under "sp"
sharding (models/long_context.py), each shard's K/V chunk becomes paged
token blocks, and each "host" saves ITS OWN chunk through the connector —
then a decode-side connector loads the full context back and the bytes
match the dense single-device prefill exactly.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import infinistore_tpu as its
from infinistore_tpu import KVConnector
from infinistore_tpu.models import LlamaConfig, init_params
from infinistore_tpu.models.llama import _block, _kv_proj, _rms_norm
from infinistore_tpu.models.long_context import prefill_ring
from infinistore_tpu.tpu.paged import PagedKVCacheSpec

CFG = LlamaConfig(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    block_tokens=8, dtype=jnp.float32,
)
B, S, RING = 1, 64, 4  # 64-token prompt over a 4-way ring


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _dense_reference(params, tokens):
    positions = jnp.arange(S, dtype=jnp.int32)[None].repeat(B, axis=0)
    x = jnp.take(params["embed"], tokens, axis=0)
    mask = positions[:, :, None] >= positions[:, None, :]
    kvs = []
    for layer in range(CFG.n_layers):
        k, v = _kv_proj(params, layer, x, positions, CFG)
        kvs.append((np.asarray(k), np.asarray(v)))
        x = _block(params, layer, x, k, v, positions, mask, CFG)
    x = _rms_norm(x, params["final_norm"])
    return np.asarray(jnp.einsum("bsd,dv->bsv", x, params["lm_head"])), kvs


def test_sp_prefill_matches_dense(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, CFG.vocab)
    ref_logits, ref_kvs = _dense_reference(params, tokens)
    mesh = Mesh(np.array(jax.devices()[:RING]), ("sp",))
    logits, kvs = prefill_ring(params, tokens, CFG, mesh=mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(logits), ref_logits, atol=1e-5, rtol=1e-5)
    for l in range(CFG.n_layers):
        for side in (0, 1):
            np.testing.assert_allclose(
                np.asarray(kvs[l][side]), ref_kvs[l][side], atol=1e-5, rtol=1e-5
            )


def test_sp_prefill_streams_to_store_per_shard(params):
    """Each ring shard's K/V chunk is saved by its OWN connector (one per
    host, as in a real multi-host job — same model id, so chain keys line
    up); a decode connector then loads the full context and the bytes equal
    the dense prefill's K/V."""
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, CFG.vocab)
    _, ref_kvs = _dense_reference(params, tokens)
    mesh = Mesh(np.array(jax.devices()[:RING]), ("sp",))
    _, kvs = prefill_ring(params, tokens, CFG, mesh=mesh, axis="sp")

    spec = PagedKVCacheSpec(
        num_layers=CFG.n_layers, num_blocks=16, block_tokens=CFG.block_tokens,
        num_kv_heads=CFG.n_kv_heads, head_dim=CFG.head_dim, dtype=CFG.dtype,
    )
    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
    token_list = [int(t) for t in np.asarray(tokens)[0]]
    blocks_per_shard = (S // RING) // CFG.block_tokens
    s_loc = S // RING

    # Producer side: one connection + connector per "host" (ring shard).
    # Shard r owns global token blocks [r*bps, (r+1)*bps); save() gets the
    # full token list (chain hashes need the whole prefix) but only this
    # shard's cache blocks, placed at their global block positions.
    for r in range(RING):
        conn = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error"))
        conn.connect()
        kvc = KVConnector(conn, spec, "longctx", max_blocks=16)
        caches = []
        for l in range(CFG.n_layers):
            k_blocks = np.asarray(kvs[l][0])[0, r * s_loc : (r + 1) * s_loc].reshape(
                blocks_per_shard, *spec.block_shape
            )
            v_blocks = np.asarray(kvs[l][1])[0, r * s_loc : (r + 1) * s_loc].reshape(
                blocks_per_shard, *spec.block_shape
            )
            # Place this shard's blocks into a scratch paged cache at ids
            # matching their GLOBAL block positions.
            k_cache = np.zeros(spec.cache_shape, dtype=np.float32)
            v_cache = np.zeros(spec.cache_shape, dtype=np.float32)
            ids = np.arange(r * blocks_per_shard, (r + 1) * blocks_per_shard)
            k_cache[ids] = k_blocks
            v_cache[ids] = v_blocks
            caches.append((jnp.asarray(k_cache), jnp.asarray(v_cache)))
        # save() gets the FULL token list (chain hashes commit to the whole
        # prefix) but writes only this shard's logical span via first_block.
        n_written = asyncio.run(kvc.save(
            token_list, caches,
            np.arange(r * blocks_per_shard, (r + 1) * blocks_per_shard,
                      dtype=np.int32),
            first_block=r * blocks_per_shard,
        ))
        assert n_written == 2 * CFG.n_layers * blocks_per_shard
        conn.close()

    # Consumer side: a fresh connector sees the WHOLE prefix and loads it.
    conn = its.InfinityConnection(its.ClientConfig(
        host_addr="127.0.0.1", service_port=srv.port, log_level="error"))
    conn.connect()
    kvc = KVConnector(conn, spec, "longctx", max_blocks=16)
    assert kvc.lookup(token_list) == S // CFG.block_tokens
    fresh = [
        (jnp.zeros(spec.cache_shape), jnp.zeros(spec.cache_shape))
        for _ in range(CFG.n_layers)
    ]
    ids = np.arange(S // CFG.block_tokens, dtype=np.int32)
    out, loaded = asyncio.run(kvc.load(token_list, fresh, ids))
    assert loaded == S // CFG.block_tokens
    for l in range(CFG.n_layers):
        for side in (0, 1):
            got = np.asarray(out[l][side])[ids].reshape(S, CFG.n_kv_heads, CFG.head_dim)
            np.testing.assert_allclose(
                got, ref_kvs[l][side][0], atol=1e-5, rtol=1e-5
            )
    conn.close()
    srv.stop()
