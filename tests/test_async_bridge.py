"""Eventfd completion-ring bridge: lifecycle paths the data-plane tests
don't isolate — teardown with ops in flight, event-loop churn, multiple
loops sharing one connection, and the legacy-callback fallback staying
equivalent. (lib.py: _drain_ready/_dispatch_completions/_drain_ring_locked;
native: Connection::set_completion_fd/drain_completions.)"""

import asyncio
import threading

import numpy as np
import pytest

import infinistore_tpu as its

BLOCK = 64 << 10


@pytest.fixture()
def server():
    srv = its.start_local_server(prealloc_bytes=128 << 20, block_bytes=BLOCK)
    yield srv
    srv.stop()


def _conn(srv, **kw):
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error", **kw
        )
    )
    c.connect()
    return c


def test_ring_mode_active_and_roundtrip(server):
    c = _conn(server)
    try:
        assert c._efd is not None, "eventfd bridge should be on (Linux)"
        buf = c.alloc_shm_mr(8 * BLOCK)
        buf[:] = np.random.randint(0, 256, size=buf.nbytes, dtype=np.uint8)
        gold = buf.copy()
        pairs = [(f"rb-{i}", i * BLOCK) for i in range(8)]

        async def run():
            await c.write_cache_async(pairs, BLOCK, buf.ctypes.data)
            buf[:] = 0
            await c.read_cache_async(pairs, BLOCK, buf.ctypes.data)

        asyncio.run(run())
        assert np.array_equal(buf, gold)
    finally:
        c.close()


def test_loop_churn_prunes_semaphores(server):
    """asyncio.run per batch (the bench/example pattern) must not grow the
    per-loop registry without bound (r3 advisor + verdict item)."""
    c = _conn(server)
    try:
        buf = c.alloc_shm_mr(BLOCK)
        buf[:] = 1
        for i in range(25):
            asyncio.run(c.write_cache_async([(f"lc-{i}", 0)], BLOCK, buf.ctypes.data))
        # Every run() made a fresh loop; dead ones must have been pruned.
        assert len(c._semaphores) <= 2, len(c._semaphores)
    finally:
        c.close()


def test_two_loops_in_threads_share_connection(server):
    """Ops from two concurrent event loops (different threads) on ONE
    connection: each future resolves on its own loop."""
    c = _conn(server)
    try:
        buf = c.alloc_shm_mr(64 * BLOCK)
        buf[:] = 7
        errs = []

        def worker(base):
            async def run():
                pairs = [(f"tl-{base}-{i}", (base * 32 + i) * BLOCK) for i in range(32)]
                for _ in range(10):
                    await c.write_cache_async(pairs, BLOCK, buf.ctypes.data)
                    await c.read_cache_async(pairs, BLOCK, buf.ctypes.data)

            try:
                asyncio.run(run())
            except Exception as e:  # surface in the main thread
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(b,)) for b in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
    finally:
        c.close()


def test_server_death_fails_inflight_futures_typed(server):
    """Kill the server with async ops in flight: every pending future must
    resolve with a typed InfiniStoreException (fail_all -> ring -> loop
    drain), never hang."""
    c = _conn(server)
    buf = c.alloc_shm_mr(256 * BLOCK)
    buf[:] = 3
    pairs = [(f"sd-{i}", i * BLOCK) for i in range(256)]

    async def run():
        futs = [
            asyncio.ensure_future(
                c.write_cache_async(pairs, BLOCK, buf.ctypes.data)
            )
            for _ in range(8)
        ]
        await asyncio.sleep(0)  # let submits land
        server.stop()
        results = await asyncio.wait_for(
            asyncio.gather(*futs, return_exceptions=True), timeout=30
        )
        return results

    results = asyncio.run(run())
    for r in results:
        # Ops that raced the shutdown may have completed; the rest must be
        # typed errors, not hangs or bare cancellations.
        assert r == 200 or isinstance(r, its.InfiniStoreException), r
    c.close()


def test_close_with_pending_futures_resolves_them(server):
    """close() from another thread while a loop has ops pending: the final
    ring drain must resolve every future (typed error or success)."""
    c = _conn(server)
    buf = c.alloc_shm_mr(256 * BLOCK)
    buf[:] = 5
    pairs = [(f"cp-{i}", i * BLOCK) for i in range(256)]
    done = {}

    async def run():
        futs = [
            asyncio.ensure_future(c.write_cache_async(pairs, BLOCK, buf.ctypes.data))
            for _ in range(8)
        ]
        await asyncio.sleep(0)
        threading.Thread(target=c.close).start()
        done["res"] = await asyncio.wait_for(
            asyncio.gather(*futs, return_exceptions=True), timeout=30
        )

    asyncio.run(run())
    assert len(done["res"]) == 8
    for r in done["res"]:
        assert r == 200 or isinstance(r, its.InfiniStoreException), r


def test_legacy_callback_fallback_equivalent(server):
    """With the eventfd disabled (the non-Linux fallback), the async API
    must behave identically through the ctypes-callback path."""
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=server.port, log_level="error"
        )
    )
    c._efd = None  # force legacy path before connect
    c.connect()
    try:
        buf = c.alloc_shm_mr(8 * BLOCK)
        buf[:] = np.random.randint(0, 256, size=buf.nbytes, dtype=np.uint8)
        gold = buf.copy()
        pairs = [(f"lg-{i}", i * BLOCK) for i in range(8)]

        async def run():
            await c.write_cache_async(pairs, BLOCK, buf.ctypes.data)
            buf[:] = 0
            await c.read_cache_async(pairs, BLOCK, buf.ctypes.data)

        asyncio.run(run())
        assert np.array_equal(buf, gold)
        with pytest.raises(its.InfiniStoreKeyNotFound):
            asyncio.run(c.read_cache_async([("absent", 0)], BLOCK, buf.ctypes.data))
    finally:
        c.close()
