"""Wire protocol unit tests: Python encoders round-trip, and the server
understands hand-built frames (so the Python mirror and the C++ codec agree).
The reference has no protocol tests (SURVEY.md §4)."""

import socket
import struct

import pytest

from infinistore_tpu import wire


def test_req_header_roundtrip():
    hdr = wire.pack_req_header(wire.OP_PUT_BATCH, 1234)
    assert len(hdr) == 9
    op, body_size = wire.unpack_req_header(hdr)
    assert op == wire.OP_PUT_BATCH
    assert body_size == 1234


def test_req_header_bad_magic():
    bad = b"\x00" * 9
    with pytest.raises(ValueError):
        wire.unpack_req_header(bad)


def test_resp_header_roundtrip():
    hdr = wire.pack_resp_header(wire.STATUS_OK, 8, 1 << 40)
    assert len(hdr) == 16
    assert wire.unpack_resp_header(hdr) == (wire.STATUS_OK, 8, 1 << 40)


@pytest.mark.parametrize(
    "meta",
    [
        wire.BatchMeta(block_size=4096, keys=["a", "b" * 100, "unicode-ключ"]),
        wire.BatchMeta(block_size=1, keys=[]),
    ],
)
def test_batch_meta_roundtrip(meta):
    out = wire.BatchMeta.decode(meta.encode())
    assert out.block_size == meta.block_size
    assert out.keys == meta.keys


def test_tcp_put_meta_roundtrip():
    m = wire.TcpPutMeta(key="k1", value_length=7 << 30)
    out = wire.TcpPutMeta.decode(m.encode())
    assert (out.key, out.value_length) == ("k1", 7 << 30)


def test_key_list_roundtrip():
    m = wire.KeyListMeta(keys=[f"key-{i}" for i in range(1000)])
    assert wire.KeyListMeta.decode(m.encode()).keys == m.keys


def test_truncated_body_raises():
    body = wire.BatchMeta(block_size=64, keys=["abc"]).encode()
    with pytest.raises(ValueError):
        wire.BatchMeta.decode(body[:-1])


def test_server_speaks_python_wire(server):
    """Drive the C++ server with frames built by the Python mirror: proves the
    two codecs agree on the wire format, not just with themselves."""
    with socket.create_connection(("127.0.0.1", server["port"]), timeout=5) as s:
        # Single-key put via raw frames.
        payload = b"\xab" * 1000
        body = wire.TcpPutMeta(key="wire-key", value_length=len(payload)).encode()
        s.sendall(wire.pack_req_header(wire.OP_TCP_PUT, len(body)) + body + payload)
        resp = _recv_exact(s, 16)
        status, body_size, payload_size = wire.unpack_resp_header(resp)
        assert (status, body_size, payload_size) == (wire.STATUS_OK, 0, 0)

        # Existence probe.
        body = wire.KeyMeta(key="wire-key").encode()
        s.sendall(wire.pack_req_header(wire.OP_CHECK_EXIST, len(body)) + body)
        status, body_size, payload_size = wire.unpack_resp_header(_recv_exact(s, 16))
        assert status == wire.STATUS_OK
        assert _recv_exact(s, body_size) == b"\x01"

        # Get the value back.
        body = wire.KeyMeta(key="wire-key").encode()
        s.sendall(wire.pack_req_header(wire.OP_TCP_GET, len(body)) + body)
        status, body_size, payload_size = wire.unpack_resp_header(_recv_exact(s, 16))
        assert status == wire.STATUS_OK
        assert payload_size == len(payload)
        assert _recv_exact(s, payload_size) == payload


def test_server_closes_on_bad_magic(server):
    with socket.create_connection(("127.0.0.1", server["port"]), timeout=5) as s:
        s.sendall(struct.pack("<IBI", 0xDEADBEEF, 0, 0))
        # Server must close the connection (reference behavior,
        # reference src/infinistore.cpp:910-915).
        assert s.recv(1) == b""


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("server closed early")
        buf += chunk
    return buf


def test_chunk_desc_roundtrip():
    """ChunkDesc framing (the striped scheduler's work-stealing unit) must
    survive encode/decode byte-exactly, including 64-bit starts."""
    for desc in (
        wire.ChunkDesc(seq=0, start=0, count=1),
        wire.ChunkDesc(seq=125, start=992, count=8),
        wire.ChunkDesc(seq=2**32 - 1, start=2**40, count=2**32 - 1),
    ):
        out = wire.ChunkDesc.decode(desc.encode())
        assert out == desc
    with pytest.raises(ValueError):
        wire.ChunkDesc.decode(wire.ChunkDesc().encode()[:-1])


def test_chunk_spans_partition():
    """chunk_spans must tile [0, n) exactly: contiguous, ordered, bounded
    by the quantum, last descriptor short when n is not a multiple."""
    for n, q in ((0, 8), (1, 8), (8, 8), (1000, 8), (17, 4)):
        descs = wire.chunk_spans(n, q)
        assert sum(d.count for d in descs) == n
        pos = 0
        for i, d in enumerate(descs):
            assert d.seq == i and d.start == pos
            assert 1 <= d.count <= q
            pos += d.count
        assert pos == n
    with pytest.raises(ValueError):
        wire.chunk_spans(8, 0)
    with pytest.raises(ValueError):
        wire.chunk_spans(-1, 8)
