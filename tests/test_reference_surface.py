"""The reference-shaped module surface, exercised exactly as reference code
uses it: register_server(loop, ServerConfig) -> client traffic ->
get_kvmap_len / evict_cache / purge_kv_map -> unregister_server
(reference lib.py:177-249, server.py flow). A reference user's server script
should run against this package with only the import changed.
"""

import asyncio
import socket

import numpy as np
import pytest

import infinistore_tpu as its


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_reference_module_surface_end_to_end():
    port = _free_port()
    cfg = its.ServerConfig(
        host="127.0.0.1",
        service_port=port,
        manage_port=_free_port(),
        prealloc_size=1,  # GB-granular, like the reference
        minimal_allocate_size=64,
        pin_memory=False,
        log_level="error",
    )
    loop = asyncio.new_event_loop()  # accepted for drop-in compat, unused
    its.register_server(loop, cfg)
    try:
        # Double-registration is an error (one server per process, like the
        # reference's module-global kv_map).
        with pytest.raises(its.InfiniStoreException):
            its.register_server(loop, cfg)

        conn = its.InfinityConnection(
            its.ClientConfig(host_addr="127.0.0.1", service_port=port, log_level="error")
        )
        conn.connect()
        data = np.random.randint(0, 256, size=64 << 10, dtype=np.uint8)
        for i in range(5):
            conn.tcp_write_cache(f"ref-{i}", data.ctypes.data, data.nbytes)
        assert its.get_kvmap_len() == 5
        # Thresholds far above usage: nothing to evict.
        assert its.evict_cache(0.8, 0.95) == 0
        assert its.get_server_stats()["kvmap_len"] == 5
        assert its.purge_kv_map() == 5
        assert its.get_kvmap_len() == 0
        conn.close()
    finally:
        its.unregister_server()
        loop.close()
    with pytest.raises(its.InfiniStoreException):
        its.get_kvmap_len()  # no server registered anymore
