"""The overlapped admission pipeline: gate-free fetch / short install.

Covers the two-phase split of a load (KVConnector.start_fetch ->
LayerwisePrefetch.install), the staging-pool reservation accounting it
leans on (cancellation must return every slot), fetch coalescing across a
wave of admissions, and the engine-level payoffs the split exists for:
store I/O never holds the device gate, and a prefix HIT is no slower
end-to-end than recomputing (the whole point of the store).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu.connector import KVConnector
from infinistore_tpu.engine import ContinuousBatchingHarness, EngineKVAdapter
from infinistore_tpu.models import LlamaConfig, init_params
from infinistore_tpu.tpu.layerwise import PrefetchDiscarded
from infinistore_tpu.tpu.paged import PagedKVCacheSpec, gather_blocks
from infinistore_tpu.tpu.staging import HostStagingPool, StagingPoolExhausted

SPEC = PagedKVCacheSpec(
    num_layers=3, num_blocks=16, block_tokens=8, num_kv_heads=2, head_dim=32,
    dtype=jnp.float32,
)

CFG = LlamaConfig(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    block_tokens=8, dtype=jnp.float32,
)
NUM_BLOCKS = 32
MAX_REQ_BLOCKS = 4


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture()
def server():
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=64 << 10, enable_shm=True
    )
    yield srv
    srv.stop()


@pytest.fixture()
def conn(server):
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=server.port, log_level="error"
        )
    )
    c.connect()
    yield c
    c.close()


def _rand_caches(seed):
    out = []
    for layer in range(SPEC.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), SPEC.cache_shape, jnp.float32
        )
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), SPEC.cache_shape, jnp.float32
        )
        out.append((k, v))
    return out


async def _drain_pool(pool, timeout_s=3.0):
    """Wait for async region releases (install marks regions consumed from
    an executor thread) to land back in the pool."""
    for _ in range(int(timeout_s / 0.02)):
        if pool.slots_in_use == 0:
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"staging slots leaked: {pool.slots_in_use} in use")


# -- staging-pool reservation accounting -------------------------------------


def test_staging_pool_reserve_release_accounting():
    pool = HostStagingPool(16 * 1024, 1024)
    assert pool.slots_in_use == 0
    a = pool.reserve(6)
    b = pool.reserve(10)
    assert pool.slots_in_use == 16
    with pytest.raises(StagingPoolExhausted):
        pool.reserve(1)
    a.release()
    assert pool.slots_in_use == 10
    a.release()  # idempotent
    assert pool.slots_in_use == 10
    # Freed run is reusable, and contiguity is honored: 6 free in one run.
    c = pool.reserve(6)
    assert pool.slots_in_use == 16
    b.release()
    c.release()
    assert pool.slots_in_use == 0
    with pytest.raises(ValueError):
        pool.reserve(0)


def test_staging_pool_reserve_needs_contiguity():
    pool = HostStagingPool(8 * 1024, 1024)
    holds = [pool.reserve(2) for _ in range(4)]
    holds[0].release()
    holds[2].release()
    # 4 slots free but split 2+2: a 3-slot run must NOT fit, 2 must.
    with pytest.raises(StagingPoolExhausted):
        pool.reserve(3)
    lease = pool.reserve(2)
    assert lease.num_slots == 2
    for h in holds[1::2] + [lease]:
        h.release()
    assert pool.slots_in_use == 0


# -- connector-level fetch/install -------------------------------------------


def test_start_fetch_install_roundtrips_bytes(conn):
    kvc = KVConnector(conn, SPEC, "pf-rt", max_blocks=8)
    caches = _rand_caches(1)
    toks = list(range(32))
    src = np.array([3, 7, 1, 9], np.int32)
    dst = np.array([8, 0, 14, 2], np.int32)

    async def drive():
        await kvc.save(toks, caches, src)
        h = kvc.start_fetch(toks)
        assert h.hit_blocks == 4 and h.n_blocks == 4
        await h.primed()  # gate-free wait: the store I/O happens here
        out, n = await h.install(SPEC.make_caches(), dst)
        assert n == 4
        for layer in range(SPEC.num_layers):
            for side in (0, 1):
                want = np.asarray(gather_blocks(caches[layer][side], jnp.asarray(src)))
                got = np.asarray(
                    gather_blocks(out[layer][side], jnp.asarray(dst, jnp.int32))
                )
                np.testing.assert_array_equal(want, got)
        await _drain_pool(kvc._prefetch_pool)

    asyncio.run(drive())


def test_prefetch_wraps_regions_when_pool_is_shallow(conn):
    """regions < num_layers: the pipeline double-buffers — a region refills
    only after install consumed its occupant — and the bytes still land
    exactly (the non-fused, layer-streaming install path)."""
    kvc = KVConnector(conn, SPEC, "pf-wrap", max_blocks=8)
    caches = _rand_caches(2)
    toks = list(range(32))
    src = np.array([2, 11, 5, 6], np.int32)
    dst = np.array([1, 4, 9, 13], np.int32)
    n = 4
    # Room for exactly 2 regions of 2*n blocks: forces the wrap with L=3.
    tiny = HostStagingPool(2 * 2 * n * SPEC.block_nbytes, SPEC.block_nbytes, conn=conn)

    async def drive():
        await kvc.save(toks, caches, src)
        h = kvc.start_fetch(toks, prefetch_pool=tiny)
        assert h.regions == 2 < SPEC.num_layers
        out, loaded = await h.install(SPEC.make_caches(), dst)
        assert loaded == 4
        for layer in range(SPEC.num_layers):
            want = np.asarray(gather_blocks(caches[layer][0], jnp.asarray(src)))
            got = np.asarray(gather_blocks(out[layer][0], jnp.asarray(dst, jnp.int32)))
            np.testing.assert_array_equal(want, got)
        await _drain_pool(tiny)

    asyncio.run(drive())


def test_discard_returns_pool_to_baseline_and_counts_waste(conn):
    kvc = KVConnector(conn, SPEC, "pf-disc", max_blocks=8)
    caches = _rand_caches(3)
    toks = list(range(32))

    async def drive():
        await kvc.save(toks, caches, np.arange(4, dtype=np.int32))
        h = kvc.start_fetch(toks)
        await h.primed()  # let some layers actually stage (they become waste)
        await h.discard()
        assert kvc._prefetch_pool.slots_in_use == 0, "discard leaked staging slots"
        assert h.wasted_blocks == h.blocks_fetched > 0
        with pytest.raises(PrefetchDiscarded):
            await h.install(SPEC.make_caches(), np.arange(4, dtype=np.int32))
        # The pool is immediately reusable at full depth.
        h2 = kvc.start_fetch(toks)
        out, n = await h2.install(SPEC.make_caches(), np.arange(4, dtype=np.int32))
        assert n == 4
        await _drain_pool(kvc._prefetch_pool)

    asyncio.run(drive())


def test_raced_eviction_mid_fetch_reports_miss_and_releases(conn):
    kvc = KVConnector(conn, SPEC, "pf-race", max_blocks=8)
    caches = _rand_caches(4)
    toks = list(range(32))

    async def drive():
        await kvc.save(toks, caches, np.arange(4, dtype=np.int32))
        h = kvc.start_fetch(toks)  # lookup hits...
        kvc.drop(toks)  # ...but the blocks race away before the reads land
        out, n = await h.install(SPEC.make_caches(), np.arange(4, dtype=np.int32))
        assert n == 0, "raced-away blocks must read as a miss, never stale bytes"
        await _drain_pool(kvc._prefetch_pool)

    asyncio.run(drive())


def test_wave_of_fetches_coalesces_store_reads(conn):
    """Concurrent admissions' fetches merge into shared batched store calls
    (what a StripedConnection then splits across stripes) instead of one
    read per request per layer."""
    kvc = KVConnector(conn, SPEC, "pf-coal", max_blocks=8)
    caches = _rand_caches(5)
    toks_a = list(range(32))
    toks_b = list(range(500, 532))

    async def drive():
        await kvc.save(toks_a, caches, np.arange(4, dtype=np.int32))
        await kvc.save(toks_b, caches, np.arange(4, 8, dtype=np.int32))
        ha = kvc.start_fetch(toks_a)
        hb = kvc.start_fetch(toks_b)
        oa, na = await ha.install(SPEC.make_caches(), np.arange(4, dtype=np.int32))
        ob, nb = await hb.install(SPEC.make_caches(), np.arange(4, dtype=np.int32))
        assert na == 4 and nb == 4
        co = kvc._coalescer
        assert co.submissions == 2 * SPEC.num_layers
        assert co.calls < co.submissions, "wave reads never coalesced"
        assert co.max_batch >= 2
        await _drain_pool(kvc._prefetch_pool)

    asyncio.run(drive())


def test_exhausted_arena_raises_not_hangs(conn):
    kvc = KVConnector(conn, SPEC, "pf-full", max_blocks=8)
    caches = _rand_caches(6)
    toks = list(range(32))
    # An arena that cannot hold even one double-buffered pipeline.
    tiny = HostStagingPool(SPEC.block_nbytes, SPEC.block_nbytes, conn=conn)

    async def drive():
        await kvc.save(toks, caches, np.arange(4, dtype=np.int32))
        with pytest.raises(StagingPoolExhausted):
            kvc.start_fetch(toks, prefetch_pool=tiny)

    asyncio.run(drive())


# -- engine-level: the payoffs -----------------------------------------------


def _harness(conn, params, model_id, verify=True):
    kvc = KVConnector(conn, CFG.kv_spec(NUM_BLOCKS), model_id,
                      max_blocks=MAX_REQ_BLOCKS)
    return ContinuousBatchingHarness(
        EngineKVAdapter(kvc), params, CFG, NUM_BLOCKS, MAX_REQ_BLOCKS,
        verify=verify,
    )


def _prompt(seed, blocks=MAX_REQ_BLOCKS):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab, size=blocks * CFG.block_tokens).tolist()


def test_engine_prefetch_cancelled_by_alloc_wait_releases_staging(conn, params):
    """A request whose speculative fetch already ran but whose admission is
    cancelled while queued for device blocks must hand every staging slot
    back (accounting returns to baseline) and count the fetch as waste."""
    h = _harness(conn, params, "pf-eng-cancel", verify=False)
    p = _prompt(1)

    async def drive():
        await h.run_request(p)  # seed the store so the prefetch has a hit
        h.stats.clear()
        blockers = await h.pool.alloc(NUM_BLOCKS)  # exhaust the block pool
        task = asyncio.ensure_future(h.run_request(p))
        await asyncio.sleep(0.1)  # fetch staged; alloc still backpressured
        assert not task.done()
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        await h.pool.free(blockers)
        pool = h.adapter.connector._prefetch_pool
        assert pool is not None
        await _drain_pool(pool)
        m = h.metrics()
        assert m["prefetch_waste"] > 0, "cancelled prefetch not counted as waste"
        # The harness still serves the same prompt afterwards, correctly.
        s = await h.run_request(p)
        assert s.loaded_blocks == MAX_REQ_BLOCKS

    asyncio.run(asyncio.wait_for(drive(), 30))


def test_engine_raced_eviction_falls_back_to_recompute(conn, params):
    """Prefix evicted between the admission probe and the fetch: the
    request recomputes and its bytes still verify against the model's own
    prefill oracle — and the staging arena ends at baseline."""
    h = _harness(conn, params, "pf-eng-race", verify=True)
    p = _prompt(2)

    async def drive():
        await h.run_request(p)  # seed
        h.stats.clear()
        task = asyncio.ensure_future(h.run_request(p))
        await asyncio.sleep(0)  # lookup done, reads submitted, none landed
        h.adapter.evict_request(p)  # the race
        s = await task
        assert s.verified, "recompute after raced eviction delivered wrong bytes"
        assert s.computed_blocks == MAX_REQ_BLOCKS
        if s.raced_eviction:  # the drop won the race (timing-dependent)
            assert s.loaded_blocks == 0
        pool = h.adapter.connector._prefetch_pool
        await _drain_pool(pool)

    asyncio.run(asyncio.wait_for(drive(), 30))


def test_engine_hit_admission_not_slower_than_miss(conn):
    """THE regression the split exists for: with store I/O off the gate and
    overlapped, a prefix hit's end-to-end prefix residency (admission +
    install, no compute) must not be slower than a miss's (admission +
    full prefill) — a store that loses to recompute is pointless.

    Uses a model big enough that recompute has real cost (the toy 2-layer
    dim-64 config prefills in under a millisecond, below the store's
    fixed per-request cost — no store on earth wins that race)."""
    big = LlamaConfig(
        vocab=256, dim=256, n_layers=4, n_heads=4, n_kv_heads=2, ffn_dim=512,
        block_tokens=16, dtype=jnp.float32,
    )
    big_params = init_params(big, jax.random.PRNGKey(1))
    kvc = KVConnector(conn, big.kv_spec(NUM_BLOCKS), "pf-eng-hitmiss",
                      max_blocks=MAX_REQ_BLOCKS)
    h = ContinuousBatchingHarness(
        EngineKVAdapter(kvc), big_params, big, NUM_BLOCKS, MAX_REQ_BLOCKS,
        verify=False,
    )

    def prompt(seed):
        rng = np.random.default_rng(seed)
        return rng.integers(
            0, big.vocab, size=MAX_REQ_BLOCKS * big.block_tokens
        ).tolist()

    async def drive():
        seeds = [prompt(100 + i) for i in range(6)]
        for p in seeds:
            await h.run_request(p)  # seed + warm the jit caches
        h.stats.clear()
        for i, p in enumerate(seeds):
            await h.run_request(p)  # hit
            await h.run_request(prompt(200 + i))  # miss (cold prompt)
        return h.metrics()

    m = asyncio.run(drive())
    assert m["hit_rate"] > 0
    hit, miss = m["p50_prefix_ready_hit_us"], m["p50_prefix_ready_miss_us"]
    assert hit <= miss, (
        f"prefix hit ({hit:.0f}us) slower than recompute ({miss:.0f}us)"
    )


def test_engine_overlap_metrics_are_non_degenerate(conn, params):
    """The new bench metrics must be present and meaningful: installs hold
    the gate for a measurable, nonzero time; the fetch overlap fraction is
    a real fraction; waste is a ratio in [0, 1]."""
    h = _harness(conn, params, "pf-eng-metrics", verify=False)

    async def drive():
        fams = [_prompt(300 + i) for i in range(3)]
        for p in fams:
            await h.run_request(p)  # seed
        h.stats.clear()
        sched = []
        for i in range(6):
            sched.append(fams[i % 3])  # hits
            sched.append(_prompt(400 + i))  # misses
        return await h.run(sched, concurrency=4)

    m = asyncio.run(drive())
    for key in (
        "p50_gate_hold_us", "p99_gate_hold_us", "overlap_fraction",
        "prefetch_waste", "prefetch_fallbacks",
        "p50_prefix_ready_hit_us", "p50_prefix_ready_miss_us",
    ):
        assert key in m, f"metric {key} missing"
    assert m["p50_gate_hold_us"] > 0, "no install ever held the gate?"
    assert 0.0 < m["overlap_fraction"] <= 1.0, m["overlap_fraction"]
    assert 0.0 <= m["prefetch_waste"] <= 1.0
    # Store I/O no longer queues admissions at the gate: a MISS never
    # installs, so it holds the gate for store work exactly never (its
    # gate_stall still reports the COMPUTE phase's queue time).
    misses = [s for s in h.stats if not s.loaded_blocks]
    assert misses and all(s.gate_hold_us == 0.0 for s in misses)
    assert all(s.fetch_us == 0.0 for s in misses)
    # Every request's store fetch ran without holding the device gate:
    # overlap 1.0 means the fetch completed before the gate was even
    # acquired (the uncontended case); anything in (0, 1] is legal.
    per_req = [s.overlap_fraction for s in h.stats if s.overlap_fraction is not None]
    assert per_req and all(0.0 < f <= 1.0 for f in per_req)


def test_engine_fallback_when_arena_exhausted(conn, params):
    """StagingPoolExhausted at admission is backpressure: the request takes
    the one-phase gated load and still gets its blocks."""
    h = _harness(conn, params, "pf-eng-fallback", verify=True)
    p = _prompt(3)

    async def drive():
        await h.run_request(p)  # seed
        h.stats.clear()
        kvc = h.adapter.connector
        # Starve the arena: every slot reserved by someone else.
        arena = kvc._ensure_prefetch_pool()
        hog = arena.reserve(arena.num_slots)
        try:
            s = await h.run_request(p)
        finally:
            hog.release()
        assert h.prefetch_fallbacks == 1
        assert s.loaded_blocks == MAX_REQ_BLOCKS and s.verified
        m = h.metrics()
        assert m["prefetch_fallbacks"] == 1

    asyncio.run(asyncio.wait_for(drive(), 30))
