"""Trace-driven load generator proofs (docs/serving_load.md, ROADMAP-6):
the trace is deterministic per seed, its marginals have the advertised
shape (Zipf head mass, heavy length tail, diurnal/burst rate envelope),
it round-trips through JSON, and it replays through the real engine
harness with every request verified against the prefill oracle."""

import asyncio
import collections
import json
import math

import numpy as np
import pytest

from infinistore_tpu import loadgen
from infinistore_tpu.loadgen import Trace, TraceRequest, generate, preset
from infinistore_tpu.wire import PRIORITY_BACKGROUND, PRIORITY_FOREGROUND


# ---------------------------------------------------------------------------
# Determinism + schema
# ---------------------------------------------------------------------------

def test_same_seed_identical_trace():
    """The reproducibility contract: same seed + knobs => byte-identical
    JSON, including arrival times, lengths, priorities and bursts."""
    a = preset("skewed", seed=7, duration_s=1.0)
    b = preset("skewed", seed=7, duration_s=1.0)
    assert a.to_json() == b.to_json()
    assert len(a.requests) > 50


def test_different_seed_different_trace():
    a = preset("skewed", seed=1, duration_s=1.0)
    b = preset("skewed", seed=2, duration_s=1.0)
    assert a.to_json() != b.to_json()


def test_json_round_trip(tmp_path):
    tr = preset("skewed", seed=3, duration_s=0.5)
    path = str(tmp_path / "trace.json")
    tr.save(path)
    back = Trace.load(path)
    assert back.to_json() == tr.to_json()
    assert back.requests == tr.requests
    assert back.knobs == tr.knobs


def test_version_check_rejects_future_trace():
    tr = preset("uniform", seed=0, duration_s=0.2)
    doc = json.loads(tr.to_json())
    doc["version"] = 99
    with pytest.raises(ValueError, match="version"):
        Trace.from_json(json.dumps(doc))


def test_unknown_preset_raises():
    with pytest.raises(ValueError, match="unknown preset"):
        preset("nope")


def test_prompt_materialization_deterministic_and_prefix_shared():
    """prompts() is derived from the trace seed alone: two calls agree,
    and requests of the same family share the family prefix bytes —
    the prefix-cache hit surface replay depends on."""
    tr = preset("skewed", seed=5, duration_s=0.5)
    bt = 8
    p1 = tr.prompts(bt, vocab=128)
    p2 = tr.prompts(bt, vocab=128)
    assert p1 == p2
    by_family = collections.defaultdict(list)
    for req, toks in zip(tr.requests, p1):
        assert len(toks) == req.prompt_blocks * bt
        by_family[req.prefix_id].append((req, toks))
    shared = 0
    for fam, members in by_family.items():
        if len(members) < 2:
            continue
        (r0, t0), (r1, t1) = members[0], members[1]
        pre = min(r0.prefix_blocks, r1.prefix_blocks) * bt
        assert t0[:pre] == t1[:pre]
        shared += 1
    assert shared > 0, "no family had two requests — no prefix reuse to test"


def test_prompts_max_blocks_clamps():
    tr = preset("skewed", seed=5, duration_s=0.5)
    bt = 8
    for toks in tr.prompts(bt, vocab=128, max_blocks=4):
        assert 0 < len(toks) <= 4 * bt


# ---------------------------------------------------------------------------
# Distribution properties
# ---------------------------------------------------------------------------

def test_zipf_head_mass():
    """With zipf_s=1.2 over 64 families, the top-4 families must carry
    far more than their uniform share (4/64 ≈ 6%) of arrivals."""
    tr = preset("skewed", seed=11, duration_s=2.0, burst_prob_per_s=0.0)
    counts = collections.Counter(r.prefix_id for r in tr.requests)
    top4 = sum(c for _, c in counts.most_common(4))
    frac = top4 / len(tr.requests)
    assert frac > 0.35, f"top-4 family mass {frac:.2f} — Zipf head missing"


def test_uniform_preset_has_no_head():
    tr = preset("uniform", seed=11, duration_s=2.0)
    counts = collections.Counter(r.prefix_id for r in tr.requests)
    top4 = sum(c for _, c in counts.most_common(4))
    assert top4 / len(tr.requests) < 0.25


def test_length_heavy_tail_and_bg_tagging():
    """The outlier mechanism: the skewed preset's p99 prompt length well
    above its median, and exactly the >= bg_outlier_blocks requests ride
    BACKGROUND."""
    tr = preset("skewed", seed=13, duration_s=2.0)
    blocks = sorted(r.prompt_blocks for r in tr.requests)
    p50 = blocks[len(blocks) // 2]
    p99 = blocks[int(len(blocks) * 0.99)]
    assert p99 >= 2 * p50, f"p99 {p99} vs p50 {p50}: no heavy tail"
    bg = [r for r in tr.requests if r.priority == PRIORITY_BACKGROUND]
    bgk = tr.knobs["bg_outlier_blocks"]
    assert bg, "no BACKGROUND outliers in the skewed preset"
    assert all(r.prompt_blocks >= bgk for r in bg)
    assert all(
        r.prompt_blocks < bgk
        for r in tr.requests if r.priority == PRIORITY_FOREGROUND
    )
    assert len(bg) / len(tr.requests) < 0.5, "BACKGROUND must be the tail"


def test_burst_envelope():
    """Forcing a storm window every second: arrivals flagged burst=True
    exist, and the arrival rate inside storm windows beats the outside
    rate (the burst_mult mechanism)."""
    tr = preset(
        "skewed", seed=17, duration_s=2.0,
        burst_prob_per_s=1.0, burst_len_s=0.2, burst_mult=4.0,
        diurnal_amplitude=0.0,
    )
    inside = [r for r in tr.requests if r.burst]
    outside = [r for r in tr.requests if not r.burst]
    assert inside and outside
    # Every second opens one 0.2 s window => 0.4 s in-storm, 1.6 s out.
    rate_in = len(inside) / 0.4
    rate_out = len(outside) / 1.6
    assert rate_in > 2.0 * rate_out, (rate_in, rate_out)


def test_diurnal_envelope():
    """With amplitude 1.0 and a 1 s period over a 1 s trace, the rising
    half-period (sin > 0) must receive most arrivals."""
    tr = generate(
        seed=19, duration_s=1.0, base_rate_rps=400.0,
        diurnal_amplitude=1.0, diurnal_period_s=1.0,
        burst_prob_per_s=0.0, outlier_frac=0.0,
    )
    first_half = sum(1 for r in tr.requests if r.t_s < 0.5)
    second_half = len(tr.requests) - first_half
    assert first_half > 1.5 * second_half, (first_half, second_half)


def test_arrivals_sorted_and_capped():
    tr = generate(seed=23, duration_s=5.0, base_rate_rps=10_000.0,
                  max_requests=500)
    ts = [r.t_s for r in tr.requests]
    assert ts == sorted(ts)
    assert len(tr.requests) == 500  # the runaway-allocation cap


def test_prefill_only_fraction():
    tr = preset("skewed", seed=29, duration_s=2.0)
    frac = sum(1 for r in tr.requests if r.gen_tokens == 0) / len(tr.requests)
    assert 0.15 < frac < 0.45, frac  # knob is 0.3


# ---------------------------------------------------------------------------
# DisaggHarness consumption (docs/serving_load.md, docs/disaggregation.md)
# ---------------------------------------------------------------------------

def test_disagg_harness_trace_prompts():
    """DisaggHarness.trace_prompts clamps the trace's materialized
    prompts to the harness's own req_blocks limit and honors count —
    the one-workload-definition contract: the same trace that replays
    through the engine harness also feeds the disagg handoff. Only
    config/req_blocks are touched, so a bare skeleton suffices (no
    store, no jax params)."""
    pytest.importorskip("jax")
    from infinistore_tpu import disagg

    tr = preset("skewed", seed=37, duration_s=0.5)
    h = disagg.DisaggHarness.__new__(disagg.DisaggHarness)
    h.config = disagg.demo_config(n_layers=2)
    h.req_blocks = 3
    prompts = h.trace_prompts(tr)
    assert len(prompts) == len(tr.requests)
    bt = h.config.block_tokens
    for toks in prompts:
        assert 0 < len(toks) <= h.req_blocks * bt
        assert all(0 <= t < h.config.vocab for t in toks)
    # The clamp is the harness's, not the trace's: the raw trace has
    # prompts deeper than req_blocks (otherwise this test is vacuous).
    assert any(r.prompt_blocks > h.req_blocks for r in tr.requests)
    # count truncates; same seed => same prompts (determinism rides
    # Trace.prompts, already pinned above).
    assert h.trace_prompts(tr, count=5) == prompts[:5]


# ---------------------------------------------------------------------------
# Replay through the real engine harness
# ---------------------------------------------------------------------------

def test_replay_through_engine_harness():
    """The integration proof: a short skewed trace replays through the
    continuous-batching harness with the oracle verifier on — every
    request completes, none raises, all verify, and the harness metrics
    carry the trace's mixed prefill/decode shape."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    import infinistore_tpu as its
    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.engine import (
        ContinuousBatchingHarness, EngineKVAdapter, RequestStats,
    )
    from infinistore_tpu.models import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    tr = preset("skewed", seed=31, duration_s=0.12, base_rate_rps=250.0)
    assert len(tr.requests) >= 8
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=64 << 10, enable_shm=True
    )
    try:
        conn = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error"
        ))
        conn.connect()
        try:
            kvc = KVConnector(conn, cfg.kv_spec(64), "loadgen-replay",
                              max_blocks=8)
            h = ContinuousBatchingHarness(
                EngineKVAdapter(kvc), params, cfg, 64, 8, verify=True,
            )
            stats = asyncio.run(loadgen.replay(tr, h, concurrency=4))
        finally:
            conn.close()
    finally:
        srv.stop()
    assert len(stats) == len(tr.requests)
    errs = [s for s in stats if isinstance(s, Exception)]
    assert errs == [], f"replay surfaced failures: {errs[:3]}"
    assert all(isinstance(s, RequestStats) for s in stats)
    m = h.metrics()
    assert m["all_verified"], "a replayed request diverged from the oracle"
    assert m["requests"] == len(tr.requests)
    # The mixed shape reached the engine: some pure-prefill, some decoded.
    decoded = [s for r, s in zip(tr.requests, stats) if r.gen_tokens > 0]
    assert decoded and any(s.ttft_us > 0 for s in decoded)
