"""Crash-safe fleet coordination (docs/membership.md): durable catalog +
reshard journal, gossip epoch exchange, cold-client bootstrap.

Covers, in-process: the DurableLog record format's robustness properties
(torn tail discarded, checksum-bad skipped and counted, compaction
preserving holder levels + tombstones), the tombstone-aware gossip merge
lattice (commutative, idempotent, no resurrection, re-add via incarnation
stamps), journal replay/restart resume on a real cluster over loopback
servers, the POST /gossip + GET /bootstrap manage routes (real HTTP) with
structured error bodies, and ``ClusterKVConnector.bootstrap``.

Under the ``chaos`` marker (CI chaos + recovery jobs, hard timeout): a
REAL client subprocess (tools/fleet.py + infinistore_tpu.fleet_client)
kill -9s ITSELF mid-reshard via the faults ``crash`` capability, restarts
with the same argv, resumes from the journaled debt, and a cold
bootstrapped verify client proves 0 wrong reads.
"""

import asyncio
import json
import os
import struct
import subprocess
import sys
import tempfile
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import infinistore_tpu as its  # noqa: E402
from infinistore_tpu import telemetry  # noqa: E402
from infinistore_tpu.cluster import (  # noqa: E402
    CircuitBreaker,
    ClusterKVConnector,
)
from infinistore_tpu.membership import DurableLog, MemberState, Membership  # noqa: E402
from infinistore_tpu.tpu import PagedKVCacheSpec, gather_blocks  # noqa: E402

SPEC = PagedKVCacheSpec(
    num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2,
    head_dim=32, dtype=jnp.bfloat16,
)


def _start_server():
    return its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)


def _connect(port, **overrides):
    cfg = dict(
        host_addr="127.0.0.1", service_port=port, log_level="error",
        auto_reconnect=True, connect_timeout_ms=500, op_timeout_ms=2000,
    )
    cfg.update(overrides)
    conn = its.InfinityConnection(its.ClientConfig(**cfg))
    conn.connect()
    return conn


def _fast_breakers(i):
    return CircuitBreaker(
        fail_threshold=2, probe_backoff_s=0.05, max_backoff_s=0.4, seed=i
    )


def _mk_caches(seed):
    out = []
    for layer in range(SPEC.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), SPEC.cache_shape,
            jnp.float32,
        ).astype(SPEC.dtype)
        out.append((k, v))
    return out


# ---------------------------------------------------------------------------
# DurableLog: the record format's crash-robustness properties.
# ---------------------------------------------------------------------------


class TestDurableLog:
    def test_append_replay_roundtrip(self, tmp_path):
        p = str(tmp_path / "log")
        log = DurableLog(p)
        recs = [
            {"k": "root", "root": "r1", "tokens": [1, 2], "blocks": 2,
             "holders": {"a:1": 2}},
            {"k": "hadd", "root": "r1", "m": "b:2", "lv": 2},
            {"k": "drop", "root": "r1"},
        ]
        for r in recs:
            log.append(r)
        log.close()
        log2 = DurableLog(p)
        assert log2.replay() == recs
        assert log2.replay_torn == 0 and log2.replay_bad_checksum == 0
        st = log2.status()
        assert st["journal_replay_records"] == 3
        log2.close()

    def test_torn_tail_discarded_cleanly(self, tmp_path):
        """The record being written at the kill -9: truncated payload AND
        truncated header are both discarded, never parsed, and counted —
        earlier records replay whole."""
        p = str(tmp_path / "log")
        log = DurableLog(p)
        log.append({"k": "root", "root": "keep", "tokens": [1], "blocks": 1,
                    "holders": {}})
        log.append({"k": "root", "root": "keep2", "tokens": [2], "blocks": 1,
                    "holders": {}})
        log.close()
        whole = open(p, "rb").read()
        for cut in (whole[:-3], whole[:-(len(whole) // 3)], whole + b"\x20\x00"):
            with open(p, "wb") as f:
                f.write(cut)
            log2 = DurableLog(p)
            out = log2.replay()
            assert [r["root"] for r in out] in (["keep"], ["keep", "keep2"])
            if len(cut) != len(whole):
                assert log2.replay_torn == 1
            log2.close()

    def test_checksum_mismatch_skipped_and_counted(self, tmp_path):
        """A bit flipped inside one record's payload: that record is
        skipped (counted), the frames after it still replay — corruption
        never crashes recovery."""
        p = str(tmp_path / "log")
        log = DurableLog(p)
        for i in range(3):
            log.append({"k": "root", "root": f"r{i}", "tokens": [i],
                        "blocks": 1, "holders": {}})
        log.close()
        data = bytearray(open(p, "rb").read())
        # Flip a byte inside the SECOND record's payload (skip its header).
        hdr = struct.Struct("<II")
        ln0, _ = hdr.unpack_from(data, 0)
        second_payload_at = hdr.size + ln0 + hdr.size + 4
        data[second_payload_at] ^= 0xFF
        with open(p, "wb") as f:
            f.write(bytes(data))
        log2 = DurableLog(p)
        out = log2.replay()
        assert [r["root"] for r in out] == ["r0", "r2"]
        assert log2.replay_bad_checksum == 1
        assert log2.replay_torn == 0
        log2.close()

    def test_compact_rewrites_atomically(self, tmp_path):
        p = str(tmp_path / "log")
        log = DurableLog(p)
        for i in range(50):
            log.append({"k": "hadd", "root": "r", "m": f"m{i}", "lv": i})
        before = log.size_bytes()
        snap = [{"k": "root", "root": "r", "tokens": [1], "blocks": 1,
                 "holders": {"m49": 49}}]
        log.compact(snap)
        assert log.size_bytes() < before
        assert log.compactions == 1
        # Appends continue on the compacted file.
        log.append({"k": "drop", "root": "r"})
        log.close()
        log2 = DurableLog(p)
        assert log2.replay() == snap + [{"k": "drop", "root": "r"}]
        log2.close()


# ---------------------------------------------------------------------------
# The gossip merge lattice (pure Membership, no I/O).
# ---------------------------------------------------------------------------


class TestMergeLattice:
    def test_adopts_newer_epoch_and_entries(self):
        a = Membership(["m1", "m2"])
        a.add_member("m3")
        b = Membership(["m1", "m2"])
        payload = a.view().as_dict()
        changed, view = b.merge_apply(payload["members"], payload["epoch"])
        assert changed and view.epoch == a.view().epoch
        assert view.state_of("m3") == MemberState.JOINING
        assert b.view().since == a.view().since
        # A merge never takes transition ownership: the originator
        # finalizes, the adopter settles when that gossips back.
        assert a.owns_transition and not b.owns_transition

    def test_idempotent_and_commutative(self):
        a = Membership(["m1", "m2"])
        a.add_member("m3")
        a.mark_dead("m2")
        b = Membership(["m1", "m2"])
        b.remove_member("m1")
        pa, pb = a.view().as_dict(), b.view().as_dict()
        a.merge_apply(pb["members"], pb["epoch"])
        b.merge_apply(pa["members"], pa["epoch"])
        va, vb = a.view(), b.view()
        assert va.epoch == vb.epoch
        for mid in ("m1", "m2", "m3"):
            assert va.state_of(mid) == vb.state_of(mid)
        # Re-merging the same payloads changes nothing.
        assert a.merge_apply(pb["members"], pb["epoch"])[0] is False

    def test_tombstone_dominates_stale_liveness(self):
        a = Membership(["m1", "m2"])
        stale = a.view().as_dict()  # m2 alive at epoch 1
        a.mark_dead("m2")
        changed, _ = a.merge_apply(stale["members"], stale["epoch"])
        assert not changed
        assert a.view().state_of("m2") == MemberState.DEAD

    def test_readd_after_dead_wins_via_incarnation(self):
        a = Membership(["m1", "m2"])
        a.mark_dead("m2")
        a.add_member("m2")  # rejoin: NEW entry, higher since_epoch
        b = Membership(["m1", "m2"])
        b.mark_dead("m2")
        payload = a.view().as_dict()
        changed, view = b.merge_apply(payload["members"], payload["epoch"])
        assert changed
        assert view.state_of("m2") == MemberState.JOINING  # latest entry wins
        # The dead incarnation's tombstone entry is still present (index
        # stability): two entries for m2.
        assert list(view.member_ids).count("m2") == 2

    def test_unsettled_merge_installs_fallback_placement(self):
        a = Membership(["m1", "m2"])
        a.add_member("m3")
        payload = a.view().as_dict()
        b = Membership(["m1", "m2"])
        b.merge_apply(
            payload["members"], payload["epoch"],
            prev_placement=list(a.prev_placement),
        )
        assert not b.settled
        assert b.prev_placement == ("m1", "m2")
        # Finalized view gossips back: B settles and drops the fallback.
        a.finalize_transitions()
        payload = a.view().as_dict()
        b.merge_apply(payload["members"], payload["epoch"])
        assert b.settled and b.prev_placement is None


# ---------------------------------------------------------------------------
# Journal replay + restart resume on a real cluster (loopback servers).
# ---------------------------------------------------------------------------


class _Pool:
    def __init__(self, n, journal_path=None, **cluster_kw):
        self.servers = [_start_server() for _ in range(n)]
        self.conns = [_connect(s.port) for s in self.servers]
        kw = dict(
            degrade=True, replicas=2, breaker_factory=_fast_breakers,
            member_ids=[f"127.0.0.1:{s.port}" for s in self.servers],
            journal_path=journal_path,
        )
        kw.update(cluster_kw)
        self.cluster = ClusterKVConnector(
            self.conns, SPEC, "recovery-test", max_blocks=8, **kw
        )
        self.contents = {}
        self.prompts = []
        self.src = np.array([3, 9], np.int32)

    def seed_roots(self, n_roots, rng_seed=5):
        rng = np.random.default_rng(rng_seed)
        self.prompts = [
            rng.integers(0, 1000, size=2 * SPEC.block_tokens).tolist()
            for _ in range(n_roots)
        ]
        for i, p in enumerate(self.prompts):
            self.contents[i] = _mk_caches(i)
            asyncio.run(self.cluster.save(p, self.contents[i], self.src))

    def sweep(self):
        reads = misses = wrong = 0
        dst = np.array([6, 2], np.int32)
        for i, p in enumerate(self.prompts):
            reads += 1
            loaded, n = asyncio.run(self.cluster.load(p, SPEC.make_caches(), dst))
            if n == 0:
                misses += 1
                continue
            wrong += any(
                not np.array_equal(
                    np.asarray(
                        gather_blocks(loaded[layer][kind], jnp.asarray(dst)),
                        np.float32,
                    ),
                    np.asarray(
                        gather_blocks(
                            self.contents[i][layer][kind], jnp.asarray(self.src)
                        ),
                        np.float32,
                    ),
                )
                for layer in range(SPEC.num_layers)
                for kind in (0, 1)
            )
        return reads, misses, wrong

    def rebuild(self, journal_path):
        """Simulated restart: new connections + a new cluster over the
        SAME journal (the old cluster object is abandoned un-closed,
        like a crash — only its resharder/journal are stopped so the
        test process doesn't leak threads)."""
        self.cluster.resharder.stop()
        if self.cluster._journal_log is not None:
            self.cluster._journal_log.close()
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass
        self.conns = [_connect(s.port) for s in self.servers]
        self.cluster = ClusterKVConnector(
            self.conns, SPEC, "recovery-test", max_blocks=8,
            degrade=True, replicas=2, breaker_factory=_fast_breakers,
            member_ids=[f"127.0.0.1:{s.port}" for s in self.servers],
            journal_path=journal_path,
        )
        return self.cluster

    def close(self):
        self.cluster.close()
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass
        for s in self.servers:
            s.stop()


class TestJournalRecovery:
    def test_restart_recovers_catalog_and_reads(self, tmp_path):
        jp = str(tmp_path / "a.journal")
        pool = _Pool(2, journal_path=jp)
        try:
            pool.seed_roots(6)
            assert pool.cluster.membership_status()["reshard_catalog_roots"] == 6
            pool.rebuild(jp)
            rec = pool.cluster.recovered
            assert rec is not None and rec["roots"] == 6
            assert rec["replay_torn"] == 0 and rec["replay_bad_checksum"] == 0
            assert pool.cluster.membership_status()["reshard_catalog_roots"] == 6
            reads, misses, wrong = pool.sweep()
            assert (misses, wrong) == (0, 0)
            # The replay emitted the causal client_restart event.
            kinds = [e["kind"] for e in telemetry.get_journal().snapshot()]
            assert "client_restart" in kinds
        finally:
            pool.close()

    def test_drop_tombstone_never_resurrects(self, tmp_path):
        jp = str(tmp_path / "a.journal")
        pool = _Pool(2, journal_path=jp)
        try:
            pool.seed_roots(4)
            dropped = pool.prompts[0]
            pool.cluster.drop(dropped)
            pool.rebuild(jp)
            assert pool.cluster.recovered["roots"] == 3
            root = pool.cluster._root_of(dropped)
            with pool.cluster._cat_lock:
                assert root not in pool.cluster._catalog
        finally:
            pool.close()

    def test_corrupt_tail_and_checksum_never_crash_recovery(self, tmp_path):
        jp = str(tmp_path / "a.journal")
        pool = _Pool(2, journal_path=jp)
        try:
            pool.seed_roots(4)
            pool.cluster.resharder.stop()
            pool.cluster._journal_log.close()
            # Tear the tail AND flip a byte mid-file: recovery must come
            # up clean, count both, and keep every intact root.
            data = bytearray(open(jp, "rb").read())
            data[len(data) // 2] ^= 0xFF
            data += b"\x99\x00\x00\x00\x01"  # torn trailing frame
            with open(jp, "wb") as f:
                f.write(bytes(data))
            pool.rebuild(jp)
            rec = pool.cluster.recovered
            assert rec is not None
            assert rec["replay_torn"] >= 1 or rec["replay_bad_checksum"] >= 1
            # Whatever survived reads correctly (subset of the 4 roots).
            reads, misses, wrong = pool.sweep()
            assert wrong == 0
        finally:
            pool.close()

    def test_compaction_preserves_levels_and_tombstones(self, tmp_path):
        """Finalize compacts the journal to a snapshot; a restart from the
        COMPACTED file must reproduce holder block-levels and the DEAD
        tombstone entry (index stability across restarts)."""
        jp = str(tmp_path / "a.journal")
        pool = _Pool(3, journal_path=jp)
        extra_srv = extra_conn = None
        try:
            pool.seed_roots(6)
            extra_srv = _start_server()
            pool.servers.append(extra_srv)
            extra_conn = _connect(extra_srv.port)
            pool.conns.append(extra_conn)
            pool.cluster.add_member(
                extra_conn, member_id=f"127.0.0.1:{extra_srv.port}", wait=True
            )
            victim = pool.cluster.member_ids[0]
            pool.cluster.mark_dead(victim, wait=True)
            assert pool.cluster.membership.settled
            status = pool.cluster.membership_status()
            assert status["journal_compactions"] >= 1
            with pool.cluster._cat_lock:
                levels_before = {
                    root: dict(rec.holders)
                    for root, rec in pool.cluster._catalog.items()
                }
            view_before = pool.cluster.membership.view()
            pool.rebuild(jp)
            view = pool.cluster.membership.view()
            assert view.epoch == view_before.epoch
            assert view.member_ids == view_before.member_ids
            assert view.states == view_before.states
            assert view.state_of(victim) == MemberState.DEAD
            with pool.cluster._cat_lock:
                levels_after = {
                    root: dict(rec.holders)
                    for root, rec in pool.cluster._catalog.items()
                }
            assert levels_after == levels_before
            reads, misses, wrong = pool.sweep()
            assert (misses, wrong) == (0, 0)
        finally:
            pool.close()

    def test_interrupted_reshard_resumes_from_journaled_debt(self, tmp_path):
        """Stop the reshard at a DETERMINISTIC point (after exactly 2
        migrated roots the worker wedges — the in-process analogue of the
        fleet client's kill -9 hook) and rebuild: the recovered cluster
        must flag the resume, kick the reconciler on construction, and
        settle with zero debt — moving only the remainder."""
        jp = str(tmp_path / "a.journal")
        pool = _Pool(3, journal_path=jp)
        extra_srv = extra_conn = None
        try:
            pool.seed_roots(10)
            extra_srv = _start_server()
            pool.servers.append(extra_srv)
            extra_conn = _connect(extra_srv.port)
            pool.conns.append(extra_conn)
            cluster = pool.cluster
            orig_add = cluster.catalog_add_holder
            state = {"n": 0}
            crashed = threading.Event()

            def crash_point(root, member_id, blocks=0):
                if state["n"] >= 2:
                    # From here the incarnation does no further work —
                    # every later pass fails immediately (the journal
                    # keeps its open plan + exactly 2 progress records).
                    crashed.set()
                    raise RuntimeError("injected crash point")
                ok = orig_add(root, member_id, blocks)
                if ok:
                    state["n"] += 1
                return ok

            cluster.catalog_add_holder = crash_point
            cluster.add_member(
                extra_conn, member_id=f"127.0.0.1:{extra_srv.port}"
            )
            assert crashed.wait(timeout=20.0)
            moved_before = cluster.resharder.progress()["reshard_moved_roots"]
            assert moved_before >= 2
            pool.rebuild(jp)  # the "restart": un-finalized journal replay
            rec = pool.cluster.recovered
            assert rec is not None and rec["resume_reshard"]
            assert rec["roots"] == 10
            assert pool.cluster.resharder.wait_idle(timeout=30.0)
            assert pool.cluster.membership.settled
            assert pool.cluster.resharder.progress()["reshard_debt_roots"] == 0
            # Resume, not re-copy: the journaled progress means the new
            # incarnation's plan excluded the 2 already-migrated roots.
            resumed = pool.cluster.resharder.progress()["reshard_moved_roots"]
            with pool.cluster._cat_lock:
                joiner_id = f"127.0.0.1:{extra_srv.port}"
                joiner_holds = sum(
                    1 for r in pool.cluster._catalog.values()
                    if r.holders.get(joiner_id, 0) > 0
                )
            assert joiner_holds == 2 + resumed
            reads, misses, wrong = pool.sweep()
            assert (misses, wrong) == (0, 0)
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Gossip + bootstrap over real HTTP (two clusters, one process).
# ---------------------------------------------------------------------------


class TestGossipAndBootstrap:
    def _two_clusters(self):
        servers = [_start_server() for _ in range(3)]
        ids = [f"127.0.0.1:{s.port}" for s in servers]

        def build():
            conns = [_connect(s.port) for s in servers]
            return conns, ClusterKVConnector(
                conns, SPEC, "gossip-test", max_blocks=8, degrade=True,
                replicas=2, breaker_factory=_fast_breakers, member_ids=ids,
            )

        conns_a, a = build()
        conns_b, b = build()
        return servers, conns_a + conns_b, a, b

    def test_epoch_propagates_via_gossip_alone(self):
        servers, conns, a, b = self._two_clusters()
        extra_srv = None
        try:
            from infinistore_tpu.config import ServerConfig
            from infinistore_tpu.server import ManageServer

            extra_srv = _start_server()
            servers.append(extra_srv)
            journal = telemetry.get_journal()
            seq0 = journal.emitted

            async def drive():
                manage_b = ManageServer(
                    ServerConfig(manage_port=0), cluster=b
                )
                http_b = await asyncio.start_server(
                    manage_b._handle, host="127.0.0.1", port=0
                )
                port_b = http_b.sockets[0].getsockname()[1]
                agent = telemetry.GossipAgent(
                    a, peers=[(f"b:{port_b}", "127.0.0.1", port_b)],
                    interval_s=0.05,
                )
                # Transition on A ONLY (no POST to B, no agent on B).
                extra_conn = _connect(extra_srv.port)
                a.add_member(
                    extra_conn, member_id=f"127.0.0.1:{extra_srv.port}"
                )
                epoch_a = a.membership.view().epoch
                # Drive rounds deterministically (no thread timing).
                res = await asyncio.to_thread(agent.exchange_once)
                assert res["ok"] == 1
                assert b.membership.view().epoch >= epoch_a
                assert (
                    b.membership.view().state_of(
                        f"127.0.0.1:{extra_srv.port}"
                    ) is not None
                )
                # B dialed the gossiped member and can route reads to it.
                assert len(b.member_ids) == 4
                # A's reshard drains; the finalized epoch reaches B on the
                # next exchange — B settles with NO manage-plane help.
                assert a.resharder.wait_idle(timeout=30.0)
                await asyncio.to_thread(agent.exchange_once)
                assert b.membership.settled
                assert b.membership.view().epoch == a.membership.view().epoch
                st = agent.status()
                assert st["gossip_rounds"] == 2
                assert st["gossip_exchanges"] == 2
                assert st["gossip_merges_out"] >= 1
                http_b.close()
                await http_b.wait_closed()
                return extra_conn

            extra_conn = asyncio.run(drive())
            conns.append(extra_conn)
            kinds = [
                e["kind"] for e in journal.snapshot(since_seq=seq0)
            ]
            assert "gossip_round" in kinds
        finally:
            a.close()
            b.close()
            for c in conns:
                try:
                    c.close()
                except Exception:
                    pass
            for s in servers:
                s.stop()

    def test_gossip_bootstrap_routes_and_structured_errors(self):
        servers, conns, a, b = self._two_clusters()
        try:
            from infinistore_tpu.config import ServerConfig
            from infinistore_tpu.server import ManageServer

            rng = np.random.default_rng(5)
            prompts = [
                rng.integers(0, 1000, size=2 * SPEC.block_tokens).tolist()
                for _ in range(5)
            ]
            for i, p in enumerate(prompts):
                asyncio.run(a.save(p, _mk_caches(i), np.array([3, 9], np.int32)))

            async def drive():
                manage = ManageServer(ServerConfig(manage_port=0), cluster=a)
                http = await asyncio.start_server(
                    manage._handle, host="127.0.0.1", port=0
                )
                port = http.sockets[0].getsockname()[1]

                async def req(method, path, body=None, raw=None):
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", port
                    )
                    payload = (
                        raw if raw is not None
                        else json.dumps(body).encode() if body is not None
                        else b""
                    )
                    writer.write(
                        f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
                        f"Content-Length: {len(payload)}\r\n\r\n".encode()
                        + payload
                    )
                    await writer.drain()
                    raw_resp = await reader.read()
                    writer.close()
                    head, _, body_bytes = raw_resp.partition(b"\r\n\r\n")
                    return int(head.split()[1]), json.loads(body_bytes)

                # A valid push-pull exchange: B's payload merges into A,
                # the response carries A's post-merge view.
                status, doc = await req("POST", "/gossip", b.gossip_payload())
                assert status == 200 and doc["status"] == "ok"
                assert doc["epoch"] == a.membership.view().epoch
                assert {m["member_id"] for m in doc["members"]} == set(
                    a.member_ids
                )

                # Structured errors: reason + CURRENT epoch, never a bare
                # 400 — a stale peer self-corrects from the body.
                status, doc = await req("POST", "/gossip", raw=b"{nope")
                assert status == 400 and doc["reason"] == "bad_json"
                assert doc["epoch"] == a.membership.view().epoch
                status, doc = await req("POST", "/gossip", {"members": []})
                assert status == 400 and doc["reason"] == "bad_payload"
                status, doc = await req(
                    "POST", "/membership", {"action": "nope"}
                )
                assert status == 400 and doc["reason"] == "unknown_action"
                assert doc["epoch"] == a.membership.view().epoch
                status, doc = await req(
                    "POST", "/membership",
                    {"action": "remove", "member_id": "ghost"},
                )
                assert status == 400 and doc["reason"] == "invalid_transition"
                status, doc = await req("POST", "/membership", raw=b"}{")
                assert status == 400 and doc["reason"] == "bad_json"

                # The cold-client snapshot.
                status, boot = await req("GET", "/bootstrap")
                assert status == 200 and boot["enabled"]
                assert boot["catalog_total"] == 5
                assert len(boot["catalog"]) == 5
                status, doc = await req("GET", "/bootstrap?limit=2")
                assert status == 200 and len(doc["catalog"]) == 2
                assert doc["catalog_total"] == 5

                http.close()
                await http.wait_closed()
                return boot

            boot = asyncio.run(drive())

            # A cold client reconstructs view + catalog from the snapshot
            # and serves lookups immediately.
            cold = ClusterKVConnector.bootstrap(
                boot, SPEC, "gossip-test", max_blocks=8, degrade=True,
                replicas=2, breaker_factory=_fast_breakers,
            )
            try:
                assert cold.membership.view().epoch == a.membership.view().epoch
                assert set(cold.member_ids) == set(a.member_ids)
                assert cold.membership_status()["reshard_catalog_roots"] == 5
                assert cold.lookup(prompts[0]) == 2
            finally:
                cold.close()
        finally:
            a.close()
            b.close()
            for c in conns:
                try:
                    c.close()
                except Exception:
                    pass
            for s in servers:
                s.stop()

    def test_no_cluster_routes_answer_structured(self):
        from infinistore_tpu.config import ServerConfig
        from infinistore_tpu.server import ManageServer

        async def drive():
            manage = ManageServer(ServerConfig(manage_port=0))
            http = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = http.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(b"GET /bootstrap HTTP/1.1\r\n\r\n")
            await writer.drain()
            raw = await reader.read()
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            assert int(head.split()[1]) == 400
            doc = json.loads(body)
            assert doc["reason"] == "no_cluster" and doc["epoch"] == 0
            http.close()
            await http.wait_closed()

        asyncio.run(drive())


# ---------------------------------------------------------------------------
# The faults "crash" capability (process-level kill -9).
# ---------------------------------------------------------------------------


class TestCrashCapability:
    def test_crash_action_sigkills_the_process(self):
        """FaultRule(action="crash") hard-kills the process at the
        scripted op — proven in a SUBPROCESS (rc == -SIGKILL); nothing
        after the faulted op runs (no marker file)."""
        script = (
            "import sys\n"
            "from infinistore_tpu.faults import FaultRule, FaultyConnection\n"
            "class Dummy:\n"
            "    def check_exist(self, key):\n"
            "        return True\n"
            "fc = FaultyConnection(Dummy(), [FaultRule(op='check_exist',"
            " after=1, action='crash')])\n"
            "fc.check_exist('a')\n"
            "print('before', flush=True)\n"
            "fc.check_exist('b')\n"
            "print('after', flush=True)\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == -9
        assert b"before" in proc.stdout
        assert b"after" not in proc.stdout


# ---------------------------------------------------------------------------
# chaos: the full kill -9 / restart-with-same-argv / bootstrap-verify flow
# over REAL subprocesses (CI chaos + recovery jobs, hard timeout).
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow
class TestKillRestartSubprocess:
    def test_client_killed_mid_reshard_resumes_and_verifies(self):
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        from tools import fleet

        n_roots, crash_after = 12, 2
        tmp = tempfile.mkdtemp(prefix="its-recovery-test-")
        stores = fleet.spawn_fleet_servers(2)
        joiner = fleet.spawn_fleet_servers(1)[0]
        store_addrs = [f"127.0.0.1:{m['service_port']}" for m in stores]
        pa = fleet.free_port()
        A = fleet.spawn_fleet_client(
            manage_port=pa, stores=store_addrs,
            journal=f"{tmp}/a.journal", seed=11, roots=n_roots,
            crash_after_moved=crash_after, gossip_interval_s=0.1,
            wait_ready=False,
        )
        C = None
        try:
            fleet.wait_manage(
                pa, "/membership", 180, proc=A["proc"],
                predicate=lambda d: (
                    d.get("reshard_catalog_roots", 0) >= n_roots
                ),
            )
            resp = fleet.manage_post_json(pa, "/membership", {
                "action": "add", "host": "127.0.0.1",
                "service_port": joiner["service_port"],
            })
            assert resp.get("status") == "ok", resp
            # The scripted faults.crash_process fires at the 2nd migrated
            # root: a real SIGKILL mid-reshard.
            assert fleet.wait_member_exit(A, timeout_s=120) == -9
            fleet.restart_member(A, timeout_s=180)
            doc = fleet.wait_manage(
                pa, "/membership", 180, proc=A["proc"],
                predicate=lambda d: (
                    d.get("membership_settled") == 1
                    and d.get("reshard_debt_roots") == 0
                    and d.get("reshard_active") == 0
                ),
            )
            assert doc["membership_members"] == 3
            assert doc["journal_replay_records"] >= n_roots
            events = fleet.manage_json(pa, "/events")["events"]
            restart_ev = [
                e for e in events if e["kind"] == "client_restart"
            ]
            assert restart_ev
            assert restart_ev[0]["attrs"]["recovered_roots"] == n_roots
            assert restart_ev[0]["attrs"]["resume_reshard"] is True
            # Cold bootstrap + byte-verify: 0 wrong, 0 misses.
            C = fleet.spawn_fleet_client(
                peers=[f"127.0.0.1:{pa}"], seed=11, roots=n_roots,
                bootstrap=True, verify=True, wait_ready=False, capture=True,
            )
            out, _ = C["proc"].communicate(timeout=240)
            report = json.loads(out.decode().strip().splitlines()[-1])
            assert report["reads"] == n_roots
            assert report["wrong"] == 0
            assert report["misses"] == 0
            assert report["members"] == 3
        finally:
            members = [A] + stores + [joiner]
            if C is not None:
                members.append(C)
            fleet.stop_members(members)
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
