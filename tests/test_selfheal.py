"""Self-healing cluster layer: per-member circuit breakers, R=2 rendezvous
replication with read failover, attributable per-member health, and the
stage-time degrade fix (docs/robustness.md is the contract narrative).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu.cluster import (
    CircuitBreaker,
    ClusterKVConnector,
    rendezvous_owner,
    rendezvous_ranked,
)
from infinistore_tpu.tpu import PagedKVCacheSpec, gather_blocks

SPEC = PagedKVCacheSpec(
    num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2, head_dim=32,
    dtype=jnp.bfloat16,
)


# ---------------------------------------------------------------------------
# CircuitBreaker state machine (fake clock: every transition is exact).
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _breaker(clock, **kw):
    kw.setdefault("fail_threshold", 3)
    kw.setdefault("probe_backoff_s", 1.0)
    kw.setdefault("max_backoff_s", 4.0)
    kw.setdefault("jitter_frac", 0.0)  # exact windows for the clock tests
    return CircuitBreaker(clock=clock, seed=0, **kw)


def test_breaker_opens_only_on_consecutive_failures():
    clk = _Clock()
    br = _breaker(clk)
    for _ in range(2):
        br.record_failure()
    br.record_success()  # streak broken
    assert br.state == CircuitBreaker.CLOSED
    for _ in range(2):
        br.record_failure()
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    br.record_failure()  # third consecutive
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()


def test_breaker_half_open_probe_window_and_recovery():
    clk = _Clock()
    br = _breaker(clk)
    for _ in range(3):
        br.record_failure()
    assert not br.allow()  # window not elapsed
    clk.t = 1.0
    assert br.allow()  # THE probe
    assert br.state == CircuitBreaker.HALF_OPEN
    assert not br.allow()  # one probe in flight is enough
    assert br.record_success() is True  # recovery reported
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    assert br.record_success() is False  # steady-state success is not recovery


def test_breaker_failed_probe_doubles_backoff_to_cap():
    clk = _Clock()
    br = _breaker(clk)
    for _ in range(3):
        br.record_failure()
    for expect in (1.0, 2.0, 4.0, 4.0):  # capped at max_backoff_s
        clk.t += expect - 0.01
        assert not br.allow(), expect
        clk.t += 0.01
        assert br.allow()
        br.record_failure()  # probe fails -> reopen, doubled
        assert br.state == CircuitBreaker.OPEN


def test_breaker_jitter_is_seeded_and_bounded():
    clk = _Clock()
    spreads = set()
    for seed in range(4):
        br = CircuitBreaker(
            fail_threshold=1, probe_backoff_s=1.0, max_backoff_s=8.0,
            jitter_frac=0.5, seed=seed, clock=clk,
        )
        br.record_failure()
        spreads.add(br.next_probe_at)
        assert 1.0 <= br.next_probe_at <= 1.5
        # Same seed replays the same schedule.
        br2 = CircuitBreaker(
            fail_threshold=1, probe_backoff_s=1.0, max_backoff_s=8.0,
            jitter_frac=0.5, seed=seed, clock=clk,
        )
        br2.record_failure()
        assert br2.next_probe_at == br.next_probe_at
    assert len(spreads) > 1  # members decorrelate


# ---------------------------------------------------------------------------
# Rendezvous ranking (replica placement).
# ---------------------------------------------------------------------------


def test_rendezvous_ranked_head_is_owner_and_drain_preserves_pairings():
    members = ["a:1", "b:2", "c:3", "d:4"]
    roots = [f"r{i}" for i in range(200)]
    for r in roots:
        ranked = rendezvous_ranked(members, r)
        assert sorted(ranked) == [0, 1, 2, 3]
        assert ranked[0] == rendezvous_owner(members, r)
    # Removing one member must not reshuffle pairs it did not appear in:
    # every (owner, successor) pair not involving the drained member stays.
    survivors = members[:3]  # drain d:4
    for r in roots:
        before = [members[i] for i in rendezvous_ranked(members, r)[:2]]
        after = [survivors[i] for i in rendezvous_ranked(survivors, r)[:2]]
        if "d:4" not in before:
            assert after == before


# ---------------------------------------------------------------------------
# Cluster failover / replication / attributable health over live servers.
# ---------------------------------------------------------------------------


@pytest.fixture()
def trio():
    """Three live loopback servers + reconnect-capable connections."""
    servers, conns = [], []
    try:
        for _ in range(3):
            srv = its.start_local_server(
                prealloc_bytes=64 << 20, block_bytes=16 << 10
            )
            conn = its.InfinityConnection(
                its.ClientConfig(
                    host_addr="127.0.0.1", service_port=srv.port,
                    log_level="error", auto_reconnect=True,
                    connect_timeout_ms=500, op_timeout_ms=2000,
                )
            )
            conn.connect()
            servers.append(srv)
            conns.append(conn)
        yield servers, conns
    finally:
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        for s in servers:
            s.stop()


def _fast_breakers(i, clock=None):
    kw = {} if clock is None else {"clock": clock}
    return CircuitBreaker(
        fail_threshold=2, probe_backoff_s=0.05, max_backoff_s=0.4, seed=i, **kw
    )


def _cluster(conns, **kw):
    kw.setdefault("breaker_factory", _fast_breakers)
    return ClusterKVConnector(conns, SPEC, "heal", max_blocks=8, **kw)


def _rand_caches(seed):
    out = []
    for layer in range(SPEC.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), SPEC.cache_shape,
            jnp.float32,
        ).astype(SPEC.dtype)
        out.append((k, v))
    return out


def _prompt_with_chain(cluster, want_chain, vocab=1000, tries=400):
    """A 2-block prompt whose (owner, successor) replica chain matches."""
    rng = np.random.default_rng(sum(want_chain))
    for _ in range(tries):
        p = rng.integers(0, vocab, size=2 * SPEC.block_tokens).tolist()
        if cluster.replica_indices(p) == list(want_chain):
            return p
    raise AssertionError(f"no prompt found with chain {want_chain}")


def _kvmap_lens(servers):
    from infinistore_tpu._native import lib as native

    return [int(native.its_server_kvmap_len(s.handle)) for s in servers]


def test_r2_save_mirrors_to_owner_and_successor_only(trio):
    servers, conns = trio
    cluster = _cluster(conns, replicas=2)
    tokens = _prompt_with_chain(cluster, (1, 0))
    caches = _rand_caches(1)
    src = np.array([3, 9], np.int32)
    written = asyncio.run(cluster.save(tokens, caches, src))
    assert written == 2 * 2 * SPEC.num_layers
    lens = _kvmap_lens(servers)
    assert lens[0] > 0 and lens[1] > 0 and lens[2] == 0
    assert lens[0] == lens[1]  # full mirror, not a partial copy
    # drop removes from BOTH replicas.
    assert cluster.drop(tokens) == 2 * 2 * SPEC.num_layers
    assert _kvmap_lens(servers) == [0, 0, 0]


def test_owner_death_degrades_to_replica_reads_byte_correct(trio):
    servers, conns = trio
    cluster = _cluster(conns, replicas=2, degrade=True)
    tokens = _prompt_with_chain(cluster, (2, 0))
    caches = _rand_caches(2)
    src = np.array([1, 5], np.int32)
    asyncio.run(cluster.save(tokens, caches, src))

    servers[2].stop()  # kill the OWNER; successor (member 0) holds the mirror

    assert cluster.lookup(tokens) == 2  # served by the replica, not a miss
    fresh = SPEC.make_caches()
    dst = np.array([6, 2], np.int32)
    loaded, n = asyncio.run(cluster.load(tokens, fresh, dst))
    assert n == 2
    for layer in range(SPEC.num_layers):
        for kind in (0, 1):
            got = np.asarray(
                gather_blocks(loaded[layer][kind], jnp.asarray(dst)), np.float32
            )
            want = np.asarray(
                gather_blocks(caches[layer][kind], jnp.asarray(src)), np.float32
            )
            np.testing.assert_array_equal(got, want)
    health = cluster.health()
    owner, replica = health["members"][2], health["members"][0]
    assert owner["errors"] >= 1 and owner["last_error"] is not None
    assert replica["replica_serves"] >= 2  # lookup + load
    # Replica reads are SERVED ops, not degraded ones.
    assert health["degraded_ops"] == 0


def test_breaker_fast_fails_then_probe_recovers_after_restart(trio):
    servers, conns = trio
    cluster = _cluster(conns, replicas=1, degrade=True)
    victim = 1
    tokens = _prompt_with_chain(cluster, (victim,))
    port = servers[victim].port
    servers[victim].stop()

    # fail_threshold=2 transport errors open the breaker...
    for _ in range(2):
        assert cluster.lookup(tokens) == 0
    h = cluster.health()["members"][victim]
    assert h["breaker_state"] == "open" and h["errors"] == 2
    # ...after which ops fast-fail locally without touching the member.
    before = h["errors"]
    for _ in range(3):
        assert cluster.lookup(tokens) == 0
    h = cluster.health()["members"][victim]
    assert h["errors"] == before  # no new transport attempts
    assert h["fast_fails"] >= 1
    assert cluster.degraded_ops == 5
    assert cluster.health()["members"][victim]["degraded_ops"] == 5
    # Healthy members carry no blame.
    for i in (0, 2):
        m = cluster.health()["members"][i]
        assert m["errors"] == 0 and m["degraded_ops"] == 0

    # Restart on the same port: the next due probe heals the connection and
    # closes the breaker within one probe window.
    import time

    for _ in range(50):
        try:
            servers[victim] = its.start_local_server(
                host="127.0.0.1", service_port=port,
                prealloc_bytes=64 << 20, block_bytes=16 << 10,
            )
            break
        except its.InfiniStoreException:
            time.sleep(0.1)
    else:
        pytest.skip("could not rebind the chaos port")
    deadline = time.time() + 5
    while time.time() < deadline:
        cluster.lookup(tokens)
        h = cluster.health()["members"][victim]
        if h["breaker_state"] == "closed":
            break
        time.sleep(0.02)
    h = cluster.health()["members"][victim]
    assert h["breaker_state"] == "closed"
    assert h["probes"] >= 1 and h["recoveries"] >= 1
    # Fully functional again: a save lands on the restarted member.
    asyncio.run(
        cluster.save(tokens, _rand_caches(3), np.array([4, 7], np.int32))
    )
    assert cluster.lookup(tokens) == 2


def test_strict_mode_raises_only_when_no_replica_serves(trio):
    servers, conns = trio
    cluster = _cluster(conns, replicas=2, degrade=False)
    tokens = _prompt_with_chain(cluster, (0, 1))
    asyncio.run(cluster.save(tokens, _rand_caches(4), np.array([1, 2], np.int32)))
    servers[0].stop()
    # Reads fail over: strict mode stays AVAILABLE while a replica serves.
    assert cluster.lookup(tokens) == 2
    # Writes must not silently under-replicate in strict mode.
    with pytest.raises(its.InfiniStoreException):
        asyncio.run(
            cluster.save(tokens, _rand_caches(4), np.array([1, 2], np.int32))
        )
    servers[1].stop()
    # Exhaust retries until the breaker opens, then the fast-fail path must
    # still raise a TYPED error in strict mode (never return a fake miss).
    for _ in range(4):
        with pytest.raises(its.InfiniStoreException):
            cluster.lookup(tokens)
    stats = cluster.stats()
    assert stats[0].get("unreachable") is True
    assert stats[0]["breaker_state"] in ("open", "half_open")


def test_stage_layer_save_stage_time_error_obeys_degrade():
    """The satellite fix: an InfiniStoreException raised AT STAGE TIME
    (before ship() exists) used to bypass the failure policy and crash the
    engine even with degrade=True."""

    class BoomMember:
        spec = SPEC

        def stage_layer_save(self, *a, **kw):
            raise its.InfiniStoreException("stage-time boom")

        def get_stats(self):
            return {}

    class FakeConn:
        class config:
            host_addr = "x"
            service_port = 1

    # Single member so the boom member is unavoidably the owner.
    soft = ClusterKVConnector(
        [FakeConn()], SPEC, "m", max_blocks=8, degrade=True,
        member_factory=lambda c: BoomMember(),
        breaker_factory=_fast_breakers,
    )
    tokens = list(range(2 * SPEC.block_tokens))
    kv = (jnp.zeros(SPEC.cache_shape, SPEC.dtype),
          jnp.zeros(SPEC.cache_shape, SPEC.dtype))
    ship = soft.stage_layer_save(tokens, 0, kv, np.array([0, 1], np.int32))
    assert asyncio.run(ship()) == 0  # noop ship, engine survives
    assert soft.degraded_ops == 1
    assert soft.health()["members"][0]["errors"] == 1

    strict = ClusterKVConnector(
        [FakeConn()], SPEC, "m", max_blocks=8, degrade=False,
        member_factory=lambda c: BoomMember(),
        breaker_factory=_fast_breakers,
    )
    with pytest.raises(its.InfiniStoreException, match="stage-time boom"):
        strict.stage_layer_save(tokens, 0, kv, np.array([0, 1], np.int32))


def test_per_member_stats_carry_health_and_aggregate_persists(trio):
    _, conns = trio
    cluster = _cluster(conns, replicas=1, degrade=True)
    stats = cluster.stats()
    assert len(stats) == 3
    for s in stats:
        assert s["breaker_state"] == "closed"
        assert s["degraded_ops"] == 0 and s["errors"] == 0
        assert "member_id" in s and s["last_error"] is None
    assert cluster.degraded_ops == 0  # aggregate keeps its name and meaning


def test_non_store_exception_never_wedges_a_half_open_probe():
    """StagingPoolExhausted (backpressure) or any non-store exception
    escaping THE half-open probe must propagate — but still resolve the
    probe, or the breaker would stay HALF_OPEN and fast-fail the member
    forever."""

    class FlakyMember:
        spec = SPEC
        boom: Exception = None

        def lookup(self, token_ids):
            if self.boom is not None:
                raise self.boom
            return 2

    class FakeConn:
        class config:
            host_addr = "x"
            service_port = 1

    clk = _Clock()
    member = FlakyMember()
    cluster = ClusterKVConnector(
        [FakeConn()], SPEC, "m", max_blocks=8, degrade=True,
        member_factory=lambda c: member,
        breaker_factory=lambda i: CircuitBreaker(
            fail_threshold=1, probe_backoff_s=1.0, max_backoff_s=4.0,
            jitter_frac=0.0, seed=i, clock=clk,
        ),
    )
    tokens = list(range(2 * SPEC.block_tokens))
    member.boom = its.InfiniStoreException("down")
    assert cluster.lookup(tokens) == 0  # opens the breaker (threshold 1)
    assert cluster.health()["members"][0]["breaker_state"] == "open"
    clk.t = 1.0  # probe window elapsed; the next op is THE probe...
    member.boom = RuntimeError("backpressure-ish, not a store failure")
    with pytest.raises(RuntimeError):
        cluster.lookup(tokens)
    # ...and despite escaping, the probe resolved: not wedged HALF_OPEN.
    assert cluster.health()["members"][0]["breaker_state"] == "closed"
    member.boom = None
    assert cluster.lookup(tokens) == 2  # member serves again


def test_striped_sweep_rejoin_restores_shm_segment_aliases():
    """An externally-reconnected stripe lost its alias registrations of
    stripe 0's shm segments; the op-entry sweep's rejoin must restore them
    (and never double-register ones still held), or the stripe would fail
    its first segment-based chunk and flap straight back into quarantine."""
    from infinistore_tpu.faults import kill_transport

    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    sc = its.StripedConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error"
        ),
        streams=3,
    )
    sc.connect()
    seg = sc.alloc_shm_mr(64 << 10)
    assert seg is not None
    base = (seg.ctypes.data, seg.nbytes)
    assert base in sc.conns[1]._segment_aliases
    # External heal: transport dies, someone calls reconnect() directly —
    # the reconnect drops stripe 1's alias registrations.
    kill_transport(sc.conns[1])
    sc.conns[1].reconnect()
    assert base not in sc.conns[1]._segment_aliases
    sc._quarantined[1] = True  # as a failed batch would have left it
    sc._sweep_quarantine()
    assert not sc._quarantined[1]
    assert base in sc.conns[1]._segment_aliases  # re-aliased, not flapping
    # Stripe 2 never reconnected: its alias survived and was NOT duplicated.
    assert sc.conns[2]._segment_aliases.count(base) == 1
    sc.close()
    srv.stop()
