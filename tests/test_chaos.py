"""Chaos: repeated server restarts under a live mixed workload.

The invariants under churn are exactly the cache contract: an op either
succeeds with CORRECT bytes or raises a typed error — never wrong data,
never a crash, never a hang — and with auto_reconnect the client is
functional again once a server is back. Every read's content is verified
against what was last successfully written under that key.
"""

import time

import numpy as np
import pytest

import infinistore_tpu as its

BLOCK = 16 << 10
ROUNDS = 4
OPS_PER_ROUND = 60


@pytest.mark.parametrize("enable_shm", [False, True], ids=["socket", "shm"])
def test_ops_stay_correct_across_repeated_restarts(enable_shm):
    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=BLOCK)
    port = srv.port
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=port, log_level="error",
            enable_shm=enable_shm, auto_reconnect=True, op_timeout_ms=2000,
            connect_timeout_ms=1000,
        )
    )
    c.connect()
    src = np.zeros(BLOCK, dtype=np.uint8)
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(src)
    c.register_mr(dst)

    written = {}  # key -> fill byte of the last SUCCESSFUL write
    rng = np.random.default_rng(3)
    errors_seen = 0

    for rnd in range(ROUNDS):
        for i in range(OPS_PER_ROUND):
            key = f"ch-{int(rng.integers(0, 32))}"
            if rng.integers(0, 2) == 0:
                fill = int(rng.integers(0, 256))
                src[:] = fill
                try:
                    c.write_cache([(key, 0)], BLOCK, src.ctypes.data)
                    written[key] = fill
                except its.InfiniStoreException:
                    errors_seen += 1
                    # A timed-out write may still have committed server-side;
                    # its content is now unknown — stop verifying this key.
                    written.pop(key, None)
            else:
                dst[:] = 255
                try:
                    c.read_cache([(key, 0)], BLOCK, dst.ctypes.data)
                    # Success => the bytes must be SOME fill value; if we
                    # know the last write, they must match it exactly.
                    assert (dst == dst[0]).all(), "torn read"
                    if key in written:
                        assert dst[0] == written[key], (
                            f"round {rnd}: read {dst[0]} != last write "
                            f"{written[key]} for {key}"
                        )
                except its.InfiniStoreKeyNotFound:
                    pass  # restart wiped it: a miss is always legal
                except its.InfiniStoreException:
                    errors_seen += 1

        # Chaos: kill the server mid-stream, restart on the same port.
        srv.stop()
        written.clear()  # in-RAM store: a restart is a cold cache
        for _ in range(30):
            try:
                srv = its.start_local_server(
                    host="127.0.0.1", service_port=port,
                    prealloc_bytes=32 << 20, block_bytes=BLOCK,
                )
                break
            except its.InfiniStoreException:
                time.sleep(0.1)
        else:
            pytest.skip("could not rebind the chaos port")

    # After the final restart the client must be fully functional.
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            src[:] = 77
            c.write_cache([("final", 0)], BLOCK, src.ctypes.data)
            break
        except its.InfiniStoreException:
            time.sleep(0.2)
    dst[:] = 0
    c.read_cache([("final", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 77).all()
    # Proof the chaos actually hit: the client reconnected at least once
    # (auto-reconnect heals the first failing op transparently, so
    # exceptions may never surface — that is the feature working; the
    # parked dead handles are the footprint the restarts leave behind).
    assert len(c._dead_handles) >= 1, "no reconnect ever happened"
    c.close()
    srv.stop()
