"""Chaos: repeated server restarts under a live mixed workload.

The invariants under churn are exactly the cache contract: an op either
succeeds with CORRECT bytes or raises a typed error — never wrong data,
never a crash, never a hang — and with auto_reconnect the client is
functional again once a server is back. Every read's content is verified
against what was last successfully written under that key.
"""

import time

import numpy as np
import pytest

import infinistore_tpu as its

BLOCK = 16 << 10
ROUNDS = 4
OPS_PER_ROUND = 60


@pytest.mark.chaos
@pytest.mark.parametrize("enable_shm", [False, True], ids=["socket", "shm"])
def test_ops_stay_correct_across_repeated_restarts(enable_shm):
    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=BLOCK)
    port = srv.port
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=port, log_level="error",
            enable_shm=enable_shm, auto_reconnect=True, op_timeout_ms=2000,
            connect_timeout_ms=1000,
        )
    )
    c.connect()
    src = np.zeros(BLOCK, dtype=np.uint8)
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(src)
    c.register_mr(dst)

    written = {}  # key -> fill byte of the last SUCCESSFUL write
    rng = np.random.default_rng(3)
    errors_seen = 0

    for rnd in range(ROUNDS):
        for i in range(OPS_PER_ROUND):
            key = f"ch-{int(rng.integers(0, 32))}"
            if rng.integers(0, 2) == 0:
                fill = int(rng.integers(0, 256))
                src[:] = fill
                try:
                    c.write_cache([(key, 0)], BLOCK, src.ctypes.data)
                    written[key] = fill
                except its.InfiniStoreException:
                    errors_seen += 1
                    # A timed-out write may still have committed server-side;
                    # its content is now unknown — stop verifying this key.
                    written.pop(key, None)
            else:
                dst[:] = 255
                try:
                    c.read_cache([(key, 0)], BLOCK, dst.ctypes.data)
                    # Success => the bytes must be SOME fill value; if we
                    # know the last write, they must match it exactly.
                    assert (dst == dst[0]).all(), "torn read"
                    if key in written:
                        assert dst[0] == written[key], (
                            f"round {rnd}: read {dst[0]} != last write "
                            f"{written[key]} for {key}"
                        )
                except its.InfiniStoreKeyNotFound:
                    pass  # restart wiped it: a miss is always legal
                except its.InfiniStoreException:
                    errors_seen += 1

        # Chaos: kill the server mid-stream, restart on the same port.
        srv.stop()
        written.clear()  # in-RAM store: a restart is a cold cache
        for _ in range(30):
            try:
                srv = its.start_local_server(
                    host="127.0.0.1", service_port=port,
                    prealloc_bytes=32 << 20, block_bytes=BLOCK,
                )
                break
            except its.InfiniStoreException:
                time.sleep(0.1)
        else:
            pytest.skip("could not rebind the chaos port")

    # After the final restart the client must be fully functional.
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            src[:] = 77
            c.write_cache([("final", 0)], BLOCK, src.ctypes.data)
            break
        except its.InfiniStoreException:
            time.sleep(0.2)
    dst[:] = 0
    c.read_cache([("final", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 77).all()
    # Proof the chaos actually hit: the client reconnected at least once
    # (auto-reconnect heals the first failing op transparently, so
    # exceptions may never surface — that is the feature working; the
    # parked dead handles are the footprint the restarts leave behind).
    assert len(c._dead_handles) >= 1, "no reconnect ever happened"
    c.close()
    srv.stop()


# ---------------------------------------------------------------------------
# Cluster chaos: one member killed/restarted mid-workload (ISSUE 3).
# The invariant is unchanged from above, lifted to the pool: every read
# returns CORRECT bytes or a typed error/miss — never wrong data, never a
# hang — and the self-healing layer (breakers + R=2 replication) turns the
# outage into replica reads instead of recompute under degrade=True.
# ---------------------------------------------------------------------------


def _restart_on_port(port, tries=50):
    for _ in range(tries):
        try:
            return its.start_local_server(
                host="127.0.0.1", service_port=port,
                prealloc_bytes=64 << 20, block_bytes=BLOCK,
            )
        except its.InfiniStoreException:
            time.sleep(0.1)
    pytest.skip("could not rebind the chaos port")


@pytest.mark.chaos
@pytest.mark.parametrize("degrade", [False, True], ids=["strict", "degrade"])
def test_cluster_member_kill_restart_mid_workload(degrade):
    import asyncio

    import jax
    import jax.numpy as jnp

    from infinistore_tpu.cluster import CircuitBreaker, ClusterKVConnector
    from infinistore_tpu.tpu import PagedKVCacheSpec, gather_blocks

    spec = PagedKVCacheSpec(
        num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2,
        head_dim=32, dtype=jnp.bfloat16,
    )
    servers, conns = [], []
    try:
        for _ in range(3):
            srv = its.start_local_server(
                prealloc_bytes=64 << 20, block_bytes=BLOCK
            )
            conn = its.InfinityConnection(
                its.ClientConfig(
                    host_addr="127.0.0.1", service_port=srv.port,
                    log_level="error", auto_reconnect=True,
                    connect_timeout_ms=500, op_timeout_ms=2000,
                )
            )
            conn.connect()
            servers.append(srv)
            conns.append(conn)
        cluster = ClusterKVConnector(
            conns, spec, "chaos", max_blocks=8, degrade=degrade, replicas=2,
            breaker_factory=lambda i: CircuitBreaker(
                fail_threshold=2, probe_backoff_s=0.05, max_backoff_s=0.4,
                seed=i,
            ),
        )

        def mk_caches(seed):
            out = []
            for layer in range(spec.num_layers):
                k = jax.random.normal(
                    jax.random.PRNGKey(seed * 100 + layer), spec.cache_shape,
                    jnp.float32,
                ).astype(spec.dtype)
                v = jax.random.normal(
                    jax.random.PRNGKey(seed * 100 + 50 + layer),
                    spec.cache_shape, jnp.float32,
                ).astype(spec.dtype)
                out.append((k, v))
            return out

        rng = np.random.default_rng(5)
        prompts = [
            rng.integers(0, 1000, size=2 * spec.block_tokens).tolist()
            for _ in range(6)
        ]
        contents = {i: mk_caches(i) for i in range(len(prompts))}
        src = np.array([3, 9], np.int32)
        for i, p in enumerate(prompts):
            asyncio.run(cluster.save(p, contents[i], src))

        victim = cluster.owner_index(prompts[0])
        port = servers[victim].port
        servers[victim].stop()  # mid-workload node death

        def read_all(expect_full: bool):
            """One read pass over every prompt; verifies every delivered
            byte. Returns (served, misses)."""
            served = misses = 0
            for i, p in enumerate(prompts):
                dst = np.array([6, 2], np.int32)
                try:
                    hit = cluster.lookup(p)
                    loaded, n = asyncio.run(
                        cluster.load(p, spec.make_caches(), dst)
                    )
                except its.InfiniStoreException:
                    assert not degrade, "degrade mode must absorb, not raise"
                    misses += 1
                    continue
                assert n in (0, 2) and hit in (0, 2)
                if n == 0:
                    misses += 1
                    continue
                served += 1
                for layer in range(spec.num_layers):
                    for kind in (0, 1):
                        got = np.asarray(
                            gather_blocks(loaded[layer][kind], jnp.asarray(dst)),
                            np.float32,
                        )
                        want = np.asarray(
                            gather_blocks(
                                contents[i][layer][kind], jnp.asarray(src)
                            ),
                            np.float32,
                        )
                        np.testing.assert_array_equal(got, want)
            if expect_full:
                assert misses == 0, "R=2: one node death must not cost a read"
            return served, misses

        # During the outage: with replicas=2 EVERY prompt is still served
        # byte-correct — its surviving replica holds the mirror (3 members,
        # R=2: the victim is never both replicas). Two passes so the opened
        # breaker's fast-fail path serves reads too.
        for _ in range(2):
            served, _ = read_all(expect_full=True)
            assert served == len(prompts)

        # A save during the outage is under-replicated: typed error in
        # strict mode, absorbed + counted in degrade mode — never a crash.
        if degrade:
            before = cluster.degraded_ops
            assert asyncio.run(
                cluster.save(prompts[0], contents[0], src)
            ) == 2 * 2 * spec.num_layers  # surviving replica took it
            assert cluster.degraded_ops == before + 1
        else:
            with pytest.raises(its.InfiniStoreException):
                asyncio.run(cluster.save(prompts[0], contents[0], src))

        # Restart: the half-open probe must re-admit the member within one
        # probe window (asserted via per-member stats).
        servers[victim] = _restart_on_port(port)
        deadline = time.time() + 5
        while time.time() < deadline:
            cluster.lookup(prompts[0])
            if (
                cluster.health()["members"][victim]["breaker_state"]
                == "closed"
            ):
                break
            time.sleep(0.02)
        h = cluster.health()["members"][victim]
        assert h["breaker_state"] == "closed", h
        assert h["probes"] >= 1 and h["recoveries"] >= 1

        # Fully healed: saves mirror again and every read still verifies.
        for i, p in enumerate(prompts):
            asyncio.run(cluster.save(p, contents[i], src))
        read_all(expect_full=True)
    finally:
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# Striped chaos: one stripe dies mid-batch (ISSUE 3). All stripes speak to
# ONE server, so "this stripe's server died" is, as the client observes it,
# its transport dropping mid-op — injected deterministically with
# faults.FaultRule(action="reset"). The batch must complete byte-correct on
# the survivors, the dead stripe must be quarantined and then rejoin after
# its background reconnect (asserted via data_plane_stats()).
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_striped_one_stripe_killed_mid_batch_completes_and_rejoins():
    import asyncio

    from infinistore_tpu.faults import FaultRule, FaultyConnection

    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=BLOCK)
    cfg = its.ClientConfig(
        host_addr="127.0.0.1", service_port=srv.port, log_level="error",
        enable_shm=False,  # no same-host collapse: the fan-out must run
        connect_timeout_ms=1000, op_timeout_ms=5000,
    )
    victim = 2
    # Stripe 2's transport is severed on its SECOND pull: mid-batch, after
    # it already delivered one chunk.
    rules = [FaultRule(op_indices=[1], action="reset", max_fires=1)]

    def factory(config, i):
        c = its.InfinityConnection(config)
        return FaultyConnection(c, rules) if i == victim else c

    sc = its.StripedConnection(cfg, streams=4, conn_factory=factory)
    sc.connect()
    n_blocks = 128
    src = np.zeros(n_blocks * BLOCK, dtype=np.uint8)
    dst = np.zeros(n_blocks * BLOCK, dtype=np.uint8)
    rng = np.random.default_rng(11)
    src[:] = rng.integers(0, 256, size=src.size, dtype=np.uint8)
    sc.register_mr(src)
    sc.register_mr(dst)
    blocks = [(f"sq-{i}", i * BLOCK) for i in range(n_blocks)]

    async def drive():
        # The faulted batch: stripe 2 dies mid-op; survivors must drain the
        # requeued spans and complete the WHOLE write.
        await sc.write_cache_async(blocks, BLOCK, src.ctypes.data)
        st = sc.data_plane_stats()
        assert st["quarantines"] == 1
        assert st["stripe_errors"][victim] == 1
        assert st["requeued_blocks"] >= 1
        # Read it all back (survivors again, or post-rejoin — both legal).
        await sc.read_cache_async(blocks, BLOCK, dst.ctypes.data)
        # Quarantine exits via the background reconnect: wait for rejoin.
        deadline = time.time() + 5
        while time.time() < deadline:
            if not any(sc.data_plane_stats()["quarantined"]):
                break
            await asyncio.sleep(0.05)
        st = sc.data_plane_stats()
        assert st["quarantined"] == [False] * 4, st
        assert st["rejoins"] >= 1
        # A post-rejoin batch runs on all four stripes again.
        chunks_before = sc.data_plane_stats()["stripe_chunks"][victim]
        await sc.write_cache_async(blocks, BLOCK, src.ctypes.data)
        assert sc.data_plane_stats()["stripe_chunks"][victim] > chunks_before

    asyncio.run(drive())
    np.testing.assert_array_equal(dst, src)  # byte-correct despite the death
    assert sc.is_connected  # full capacity restored
    sc.close()
    srv.stop()


@pytest.mark.chaos
def test_striped_whole_server_death_is_typed_error_then_recovers():
    """Every stripe dying (the server itself is gone) must surface as ONE
    typed error — never a hang, never partial silent success presented as
    completion — and after a restart + reconnect the striped connection
    serves verified bytes again (cold cache)."""
    import asyncio

    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=BLOCK)
    port = srv.port
    cfg = its.ClientConfig(
        host_addr="127.0.0.1", service_port=port, log_level="error",
        enable_shm=False, connect_timeout_ms=500, op_timeout_ms=2000,
    )
    sc = its.StripedConnection(cfg, streams=4)
    sc.connect()
    n_blocks = 64
    src = np.zeros(n_blocks * BLOCK, dtype=np.uint8)
    src[:] = 123
    dst = np.zeros(n_blocks * BLOCK, dtype=np.uint8)
    sc.register_mr(src)
    sc.register_mr(dst)
    blocks = [(f"sd-{i}", i * BLOCK) for i in range(n_blocks)]

    async def doomed():
        await sc.write_cache_async(blocks, BLOCK, src.ctypes.data)
        srv.stop()
        with pytest.raises(its.InfiniStoreException):
            # Bounded: op timeouts cap every stripe's failure; quarantine
            # must conclude "batch incomplete", not spin.
            await asyncio.wait_for(
                sc.read_cache_async(blocks, BLOCK, dst.ctypes.data), timeout=30
            )

    asyncio.run(doomed())

    srv2 = _restart_on_port(port)
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            sc.reconnect()
            break
        except its.InfiniStoreException:
            time.sleep(0.2)

    async def healed():
        await sc.write_cache_async(blocks, BLOCK, src.ctypes.data)
        dst[:] = 0
        await sc.read_cache_async(blocks, BLOCK, dst.ctypes.data)

    asyncio.run(healed())
    np.testing.assert_array_equal(dst, src)
    sc.close()
    srv2.stop()
