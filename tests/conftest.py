"""Test harness config.

Tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI); the env vars must be set before jax is first imported.
The store's TCP/DCN paths need no accelerator at all — unlike the reference,
whose entire test suite is gated on real RDMA NICs + CUDA GPUs
(/root/reference/infinistore/test_infinistore.py:20-87, SURVEY.md §4).
"""

import os

# Force the CPU backend with 8 virtual devices. The environment pins
# JAX_PLATFORMS=axon (remote TPU tunnel) and its sitecustomize registers the
# plugin whenever PALLAS_AXON_POOL_IPS is set, so both must be overridden
# before jax is first imported.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon plugin's register() overrides the platform list via
# jax.config.update("jax_platforms", "axon,cpu") at interpreter start, which
# beats the env var — override it back before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import infinistore_tpu as its  # noqa: E402


@pytest.fixture()
def server():
    """An in-process store server on an ephemeral loopback port with a small
    unpinned pool (64MB, 16KB blocks)."""
    cfg = its.ServerConfig(
        host="127.0.0.1",
        service_port=0,
        manage_port=1,  # unused placeholder; verify() needs it distinct
        prealloc_size=1,
        minimal_allocate_size=16,
        pin_memory=False,
        log_level="error",
    )
    # Shrink below the dataclass's GB units for tests: build directly.
    from infinistore_tpu._native import lib

    handle = lib.its_server_create(
        b"127.0.0.1", 0, 64 << 20, 16 << 10, 0, 64 << 20, 0, 0.8, 0.95
    )
    assert handle
    assert lib.its_server_start(handle) == 0
    port = lib.its_server_port(handle)
    yield {"handle": handle, "port": port, "lib": lib, "config": cfg}
    lib.its_server_stop(handle)
    lib.its_server_destroy(handle)


@pytest.fixture()
def conn(server):
    cfg = its.ClientConfig(
        host_addr="127.0.0.1",
        service_port=server["port"],
        connection_type=its.TYPE_RDMA,
        log_level="error",
    )
    c = its.InfinityConnection(cfg)
    c.connect()
    yield c
    c.close()
