"""Test harness config.

Tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI); the env vars must be set before jax is first imported.
The store's TCP/DCN paths need no accelerator at all — unlike the reference,
whose entire test suite is gated on real RDMA NICs + CUDA GPUs
(reference infinistore/test_infinistore.py:20-87, SURVEY.md §4).
"""

from infinistore_tpu.hostmesh import force_cpu_devices

force_cpu_devices(8)

import pytest  # noqa: E402

import infinistore_tpu as its  # noqa: E402


@pytest.fixture()
def server():
    """An in-process store server on an ephemeral loopback port with a small
    unpinned pool (64MB, 16KB blocks)."""
    cfg = its.ServerConfig(
        host="127.0.0.1",
        service_port=0,
        manage_port=1,  # unused placeholder; verify() needs it distinct
        prealloc_size=1,
        minimal_allocate_size=16,
        pin_memory=False,
        log_level="error",
    )
    from infinistore_tpu._native import lib

    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=16 << 10, extend_bytes=64 << 20
    )
    yield {"handle": srv.handle, "port": srv.port, "lib": lib, "config": cfg}
    srv.stop()


@pytest.fixture(params=["shm", "socket"])
def conn(server, request):
    """Every integration test runs against both data planes: the same-host
    shm fast path and the socket (DCN) path."""
    cfg = its.ClientConfig(
        host_addr="127.0.0.1",
        service_port=server["port"],
        connection_type=its.TYPE_RDMA,
        log_level="error",
        enable_shm=request.param == "shm",
    )
    c = its.InfinityConnection(cfg)
    c.connect()
    assert c.shm_active == (request.param == "shm")
    yield c
    c.close()
