"""Striping under a rate-shaped link: the regime where stripes win.

On this box's unshaped loopback, striping hurts (single core, memcpy-bound —
docs/multistream.md). These tests build the cross-host regime the knob exists
for: ``pacing_rate_mbps`` caps each connection with SO_MAX_PACING_RATE (TCP
internal pacing), like a bandwidth-limited DCN stream, and striping must then
scale aggregate throughput with the stream count. The reference gets the same
effect from pipeline depth over one RC QP (8000 outstanding WRs,
reference src/protocol.h:22-26); multiple TCP streams are the socket-world
equivalent.
"""

import pytest

import infinistore_tpu as its
from infinistore_tpu.shaping import BLOCK, shaped_roundtrip_mbps

CAP_MBPS = 40
N = 64  # 4MB per direction: >=0.1s single-stream at the cap, fast at 4


@pytest.fixture(scope="module")
def paced_server():
    srv = its.start_local_server(
        prealloc_bytes=64 << 20,
        block_bytes=BLOCK,
        enable_shm=False,  # stripes split socket traffic; shm would bypass it
        pacing_rate_mbps=CAP_MBPS,
    )
    yield srv
    srv.stop()


def _roundtrip_mbps(port: int, streams: int) -> float:
    mbps, verified = shaped_roundtrip_mbps(
        port, CAP_MBPS, streams, nbytes=N * BLOCK, verify=True
    )
    assert verified, "shaped roundtrip corrupted data"
    return mbps


def test_single_stream_pins_at_the_cap(paced_server):
    """One paced connection must cap near pacing_rate_mbps — proof the
    shaping emulates a bandwidth-limited stream (not a no-op flag)."""
    mbps = _roundtrip_mbps(paced_server.port, 1)
    # Write and read legs are paced separately, so the aggregate cannot
    # meaningfully exceed the cap; generous floor for scheduler noise.
    assert mbps < CAP_MBPS * 1.5, f"pacing not applied: {mbps:.0f} MB/s"
    assert mbps > CAP_MBPS * 0.4, f"paced stream unreasonably slow: {mbps:.0f} MB/s"


def test_striping_scales_under_shaping(paced_server):
    """4 stripes must deliver >=2x one stripe when each stream is capped —
    the claim docs/multistream.md made and round 2 shipped unproven."""
    one = _roundtrip_mbps(paced_server.port, 1)
    four = _roundtrip_mbps(paced_server.port, 4)
    assert four >= 2.0 * one, (
        f"striping failed to scale under shaping: 1 stream {one:.0f} MB/s, "
        f"4 streams {four:.0f} MB/s"
    )
