"""Striping under a rate-shaped link: the regime where stripes win.

On this box's unshaped loopback, striping hurts (single core, memcpy-bound —
docs/multistream.md). These tests build the cross-host regime the knob exists
for: ``pacing_rate_mbps`` caps each connection with SO_MAX_PACING_RATE (TCP
internal pacing), like a bandwidth-limited DCN stream, and striping must then
scale aggregate throughput with the stream count. The reference gets the same
effect from pipeline depth over one RC QP (8000 outstanding WRs,
reference src/protocol.h:22-26); multiple TCP streams are the socket-world
equivalent.
"""

import pytest

import infinistore_tpu as its
from infinistore_tpu.shaping import BLOCK, shaped_roundtrip_mbps

CAP_MBPS = 40
N = 64  # 4MB per direction: >=0.1s single-stream at the cap, fast at 4


@pytest.fixture(scope="module")
def paced_server():
    srv = its.start_local_server(
        prealloc_bytes=64 << 20,
        block_bytes=BLOCK,
        enable_shm=False,  # stripes split socket traffic; shm would bypass it
        pacing_rate_mbps=CAP_MBPS,
    )
    yield srv
    srv.stop()


def _roundtrip_mbps(port: int, streams: int) -> float:
    mbps, verified = shaped_roundtrip_mbps(
        port, CAP_MBPS, streams, nbytes=N * BLOCK, verify=True
    )
    assert verified, "shaped roundtrip corrupted data"
    return mbps


def test_single_stream_pins_at_the_cap(paced_server):
    """One paced connection must cap near pacing_rate_mbps — proof the
    shaping emulates a bandwidth-limited stream (not a no-op flag)."""
    mbps = _roundtrip_mbps(paced_server.port, 1)
    # Write and read legs are paced separately, so the aggregate cannot
    # meaningfully exceed the cap; generous floor for scheduler noise.
    assert mbps < CAP_MBPS * 1.5, f"pacing not applied: {mbps:.0f} MB/s"
    assert mbps > CAP_MBPS * 0.4, f"paced stream unreasonably slow: {mbps:.0f} MB/s"


def test_striping_scales_under_shaping(paced_server):
    """4 stripes must deliver >=2x one stripe when each stream is capped —
    the claim docs/multistream.md made and round 2 shipped unproven. Under
    the adaptive scheduler this also proves pacing does not defeat the
    chunk sizing: capped stripes shrink their pulls instead of starving."""
    one = _roundtrip_mbps(paced_server.port, 1)
    stats: dict = {}
    four, verified = shaped_roundtrip_mbps(
        paced_server.port, CAP_MBPS, 4, nbytes=N * BLOCK, verify=True,
        stats_out=stats,
    )
    assert verified, "shaped roundtrip corrupted data"
    assert four >= 2.0 * one, (
        f"striping failed to scale under shaping: 1 stream {one:.0f} MB/s, "
        f"4 streams {four:.0f} MB/s"
    )
    # Scheduler receipt: shm is off, so the same-host detector must NOT
    # have collapsed, and every paced stripe must have pulled work.
    assert stats["collapsed_ops"] == 0, stats
    assert all(c > 0 for c in stats["stripe_chunks"]), stats
    # Each stripe's measured EWMA must sit around the per-stream cap, not
    # at memcpy rates: the proof pacing and adaptive chunks compose.
    cap_gbps = CAP_MBPS / 1024
    assert all(e < 4 * cap_gbps for e in stats["stripe_ewma_gbps"]), stats


def test_zero_cap_is_unshaped_not_a_stall():
    """cap 0/None must mean 'no pacing' (SO_MAX_PACING_RATE never set), not
    a zero-rate stall: the same socket-path roundtrip must complete fast
    and well above any plausible cap misread of 0 MB/s."""
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=BLOCK, enable_shm=False
    )
    try:
        for cap in (0, None):
            mbps, verified = shaped_roundtrip_mbps(
                srv.port, cap, 4, nbytes=16 * BLOCK,
                key_prefix=f"z{cap}", verify=True,
            )
            assert verified, "unshaped roundtrip corrupted data"
            assert mbps > CAP_MBPS, f"cap={cap!r} behaved like a real cap: {mbps:.0f} MB/s"
    finally:
        srv.stop()


def test_cap_smaller_than_one_chunk():
    """A cap so low that one descriptor quantum (8 x 64KB = 512KB) takes
    ~100ms to move: the scheduler's minimum pull is one quantum, so pacing
    must slow the transfer, never wedge it, and the bytes must verify."""
    cap = 4  # MB/s per stream; floor-pull per stripe ~= 0.125s at the cap
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=BLOCK, enable_shm=False,
        pacing_rate_mbps=cap,
    )
    try:
        stats: dict = {}
        mbps, verified = shaped_roundtrip_mbps(
            srv.port, cap, 4, nbytes=32 * BLOCK, key_prefix="tiny",
            verify=True, stats_out=stats,
        )
        assert verified, "tiny-cap roundtrip corrupted data"
        # The payload is deliberately tiny (the whole point is cap < one
        # chunk), so TCP's initial unpaced burst dominates and the aggregate
        # overshoots the 4 x 4 MB/s steady state; the invariants that must
        # hold are (a) pacing ENGAGED — orders of magnitude below the
        # unshaped socket rate (the zero-cap test above measures that well
        # over 40 MB/s) — and (b) the scheduler still split and completed.
        assert mbps < 100, f"pacing not applied: {mbps:.0f} MB/s"
        assert stats["chunks"] >= 4, stats  # the batch was still split
    finally:
        srv.stop()
