"""Contract tests for the vLLM v1 connector (infinistore_tpu/vllm_v1.py).

These drive the PUBLISHED KVConnectorBase_V1 call order exactly as vLLM's
scheduler and model runner do (vllm/distributed/kv_transfer/kv_connector/v1/
base.py; the reference's integration point, reference README.md:22):
scheduler-side probe -> alloc -> metadata build, worker-side bind ->
start_load_kv -> per-layer wait/save -> wait_for_save -> clear. The vLLM
objects (Request, NewRequestData, SchedulerOutput) are duck-typed stand-ins
carrying exactly the attributes the connector contract reads.
"""

from dataclasses import dataclass, field
from typing import List

import jax.numpy as jnp
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu.connector import KVConnector, token_chain_hashes
from infinistore_tpu.tpu.paged import PagedKVCacheSpec
from infinistore_tpu.vllm_v1 import (
    InfiniStoreConnectorMetadata,
    InfiniStoreKVConnectorV1,
    KVConnectorRole,
)

SPEC = PagedKVCacheSpec(
    num_layers=3, num_blocks=16, block_tokens=4, num_kv_heads=2, head_dim=8,
    dtype=jnp.float32,
)
MAX_BLOCKS = 4
LAYERS = [f"model.layers.{i}.self_attn" for i in range(SPEC.num_layers)]


# -- duck-typed vLLM objects (attribute surface the connector reads) --------


@dataclass
class Request:
    request_id: str
    prompt_token_ids: List[int]


@dataclass
class NewRequestData:
    req_id: str
    prompt_token_ids: List[int]
    block_ids: List[List[int]]  # vLLM nests per KV-cache group
    num_computed_tokens: int = 0


@dataclass
class SchedulerOutput:
    scheduled_new_reqs: List[NewRequestData] = field(default_factory=list)


# -- fixtures ---------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=64 << 10, enable_shm=True
    )
    yield srv
    srv.stop()


def _connect(server):
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=server.port, log_level="error"
        )
    )
    c.connect()
    return c


def _vllm_config(kv: KVConnector, **extra):
    """Duck-typed vllm_config: kv_transfer_config.kv_connector_extra_config."""

    class KTC:
        kv_connector_extra_config = {"kv_connector": kv, **extra}

    class Cfg:
        kv_transfer_config = KTC()

    return Cfg()


def _connector(server, model_id: str, role: KVConnectorRole, **extra):
    conn = _connect(server)
    kv = KVConnector(conn, SPEC, model_id, max_blocks=MAX_BLOCKS)
    c = InfiniStoreKVConnectorV1(_vllm_config(kv, **extra), role)
    return c, conn


def _block_bytes(layer: int, kind: int, chain_i: int, seed: int = 0) -> np.ndarray:
    """Deterministic content for one logical block."""
    rng = np.random.default_rng(1000 * seed + 100 * layer + 10 * kind + chain_i)
    return rng.standard_normal(
        (SPEC.block_tokens, SPEC.num_kv_heads, SPEC.head_dim)
    ).astype(np.float32)


def _filled_caches(phys_of_logical: List[int], n_logical: int, seed: int = 0):
    """Engine caches with logical block i's bytes at physical block
    phys_of_logical[i]; everything else zero."""
    out = []
    for layer in range(SPEC.num_layers):
        k = np.zeros((SPEC.num_blocks, *SPEC.block_shape), np.float32)
        v = np.zeros_like(k)
        for i in range(n_logical):
            k[phys_of_logical[i]] = _block_bytes(layer, 0, i, seed)
            v[phys_of_logical[i]] = _block_bytes(layer, 1, i, seed)
        out.append((jnp.asarray(k), jnp.asarray(v)))
    return out


def _worker_step(connector, meta, caches_dict, *, layers=LAYERS, save=True):
    """One runner step in the published order: bind -> start_load_kv ->
    per-layer [wait_for_layer_load; save_kv_layer] -> wait_for_save ->
    clear. Returns the post-step per-layer caches."""
    connector.register_kv_caches(caches_dict)
    connector.bind_connector_metadata(meta)
    connector.start_load_kv(forward_context=None)
    for name in layers:
        connector.wait_for_layer_load(name)
        if save:
            connector.save_kv_layer(name, None, attn_metadata=None)
    connector.wait_for_save()
    connector.clear_connector_metadata()
    return {name: connector.kv_cache(name) for name in layers}


def _produce(server, model_id, prompt, phys, seed=0):
    """Run a full producer step (miss -> compute -> layer-wise save) and
    return (scheduler, worker) connectors still open."""
    sched, _s = _connector(server, model_id, KVConnectorRole.SCHEDULER)
    worker, _w = _connector(server, model_id, KVConnectorRole.WORKER)
    n_blocks = len(prompt) // SPEC.block_tokens
    req = Request("r-prod", prompt)
    external, is_async = sched.get_num_new_matched_tokens(req, 0)
    assert external == 0 and is_async is False
    sched.update_state_after_alloc(req, [phys], 0)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("r-prod", prompt, [phys])])
    )
    assert len(meta.saves) == 1 and len(meta.loads) == 0
    assert meta.saves[0].first_block == 0
    caches = _filled_caches(phys, n_blocks, seed)
    _worker_step(worker, meta, dict(zip(LAYERS, caches)))
    return sched, worker


def test_published_call_order_roundtrip(server):
    """Producer saves via the layer-wise worker path; a consumer's
    scheduler probe sees the hit, its worker loads layer by layer, and
    every byte matches the producer's blocks."""
    prompt = list(range(14))  # 3 complete blocks + a 2-token tail
    phys_prod = [2, 5, 7]
    sched_p, worker_p = _produce(server, "v1-rt", prompt, phys_prod, seed=1)

    # consumer: separate connector pair (vLLM runs these in new processes)
    sched_c, _ = _connector(server, "v1-rt", KVConnectorRole.SCHEDULER)
    worker_c, _ = _connector(server, "v1-rt", KVConnectorRole.WORKER)
    req = Request("r-cons", prompt)
    external, _ = sched_c.get_num_new_matched_tokens(req, 0)
    assert external == 12, "store hit not reported to the scheduler"
    phys_cons = [[9, 3, 11]]
    sched_c.update_state_after_alloc(req, phys_cons, external)
    meta = sched_c.build_connector_meta(
        SchedulerOutput([NewRequestData("r-cons", prompt, phys_cons)])
    )
    assert len(meta.loads) == 1 and len(meta.saves) == 0, (
        "a full hit must not re-save the prefix"
    )
    zero = [
        (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
         jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for _ in range(SPEC.num_layers)
    ]
    out = _worker_step(worker_c, meta, dict(zip(LAYERS, zero)), save=False)
    assert worker_c.loaded_tokens("r-cons") == 12
    for layer, name in enumerate(LAYERS):
        k, v = out[name]
        for i, pb in enumerate(phys_cons[0]):
            np.testing.assert_array_equal(
                np.asarray(k)[pb], _block_bytes(layer, 0, i, seed=1)
            )
            np.testing.assert_array_equal(
                np.asarray(v)[pb], _block_bytes(layer, 1, i, seed=1)
            )
    for c in (sched_p, worker_p, sched_c, worker_c):
        c.kv.conn.close()


def test_bytes_correct_immediately_after_each_layer_wait(server):
    """wait_for_layer_load(L) must deliver L's bytes BEFORE later layers
    are waited on — the layer-streaming contract the runner relies on to
    overlap network with per-layer compute."""
    prompt = list(range(10))  # 2 complete blocks + tail
    sched_p, worker_p = _produce(server, "v1-layerwise", prompt, [1, 4], seed=2)

    sched_c, _ = _connector(server, "v1-layerwise", KVConnectorRole.SCHEDULER)
    worker_c, _ = _connector(server, "v1-layerwise", KVConnectorRole.WORKER)
    req = Request("rc", prompt)
    external, _ = sched_c.get_num_new_matched_tokens(req, 0)
    assert external == 8
    sched_c.update_state_after_alloc(req, [[6, 2]], external)
    meta = sched_c.build_connector_meta(
        SchedulerOutput([NewRequestData("rc", prompt, [[6, 2]])])
    )
    zero = {
        name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
               jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for name in LAYERS
    }
    worker_c.register_kv_caches(zero)
    worker_c.bind_connector_metadata(meta)
    worker_c.start_load_kv(forward_context=None)
    for layer, name in enumerate(LAYERS):
        worker_c.wait_for_layer_load(name)
        # Check THIS layer's bytes before any later wait.
        k, v = worker_c.kv_cache(name)
        for i, pb in enumerate([6, 2]):
            np.testing.assert_array_equal(
                np.asarray(k)[pb], _block_bytes(layer, 0, i, seed=2)
            )
            np.testing.assert_array_equal(
                np.asarray(v)[pb], _block_bytes(layer, 1, i, seed=2)
            )
    worker_c.wait_for_save()
    worker_c.clear_connector_metadata()
    # request_finished: saves completed within the step, so the engine may
    # free blocks immediately and no transfer params ride the response.
    assert sched_c.request_finished(req, [[6, 2]]) == (False, None)
    # get_finished: nothing is ever deferred across steps.
    assert worker_c.get_finished(set()) == (None, None)
    for c in (sched_p, worker_p, sched_c, worker_c):
        c.kv.conn.close()


def test_sentinel_commits_last(server):
    """Layer 0's keys are the whole-block presence sentinel: after every
    save_kv_layer call but BEFORE wait_for_save, deeper layers are durable
    while the sentinel is absent — a concurrent lookup must see a miss,
    never a half-saved hit."""
    prompt = list(range(8))
    sched, _ = _connector(server, "v1-sentinel", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(server, "v1-sentinel", KVConnectorRole.WORKER)
    probe = _connect(server)
    probe_kv = KVConnector(probe, SPEC, "v1-sentinel", max_blocks=MAX_BLOCKS)

    req = Request("rs", prompt)
    assert sched.get_num_new_matched_tokens(req, 0)[0] == 0
    sched.update_state_after_alloc(req, [[0, 1]], 0)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("rs", prompt, [[0, 1]])])
    )
    caches = _filled_caches([0, 1], 2, seed=3)
    worker.register_kv_caches(dict(zip(LAYERS, caches)))
    worker.bind_connector_metadata(meta)
    worker.start_load_kv(forward_context=None)
    for name in LAYERS:
        worker.wait_for_layer_load(name)
        worker.save_kv_layer(name, None, attn_metadata=None)
    # Drain the non-sentinel (layer >= 1) saves deterministically.
    for f in list(worker._save_futures):
        f.result()
    # Deeper layers durable, sentinel absent -> lookup is a MISS.
    chain0 = token_chain_hashes(prompt, SPEC.block_tokens)[0]
    assert probe.check_exist(worker.kv.block_key(1, "k", chain0)), (
        "layer-1 save did not commit"
    )
    assert probe_kv.lookup(prompt) == 0, (
        "half-saved block visible as a hit before wait_for_save"
    )
    worker.wait_for_save()
    assert probe_kv.lookup(prompt) == 2, "sentinel missing after wait_for_save"
    worker.clear_connector_metadata()
    for c in (sched, worker):
        c.kv.conn.close()
    probe.close()


def test_local_prefix_skips_load_and_save(server):
    """The engine's own prefix cache already computed block 0: the
    connector must promise only the EXTRA tokens, load only blocks [1, 3)
    into their physical slots, and (store hit == prompt) save nothing."""
    prompt = list(range(14))  # tail keeps the >=1-token-to-compute cap out of play
    sched_p, worker_p = _produce(server, "v1-local", prompt, [0, 1, 2], seed=4)

    sched, _ = _connector(server, "v1-local", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(server, "v1-local", KVConnectorRole.WORKER)
    req = Request("rl", prompt)
    external, _ = sched.get_num_new_matched_tokens(req, num_computed_tokens=4)
    assert external == 8, "must not promise tokens the engine already has"
    phys = [[8, 9, 10]]
    sched.update_state_after_alloc(req, phys, external)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("rl", prompt, phys, num_computed_tokens=4)])
    )
    assert len(meta.loads) == 1 and meta.loads[0].first_block == 1
    assert list(meta.loads[0].block_ids) == [9, 10]
    assert len(meta.saves) == 0
    zero = {
        name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
               jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for name in LAYERS
    }
    out = _worker_step(worker, meta, zero, save=False)
    assert worker.loaded_tokens("rl") == 8
    for layer, name in enumerate(LAYERS):
        k, _v = out[name]
        # physical 8 (locally computed block 0's slot) untouched; 9/10 hold
        # logical blocks 1/2.
        assert not np.asarray(k)[8].any()
        np.testing.assert_array_equal(
            np.asarray(k)[9], _block_bytes(layer, 0, 1, seed=4)
        )
        np.testing.assert_array_equal(
            np.asarray(k)[10], _block_bytes(layer, 0, 2, seed=4)
        )
    for c in (sched_p, worker_p, sched, worker):
        c.kv.conn.close()


def test_local_compute_beyond_store_hit_saves_the_difference(server):
    """Store holds 1 block; the engine locally computed 2. No load (store
    has nothing new), and the save must cover [store_hit, prompt) so the
    store learns the locally-computed blocks."""
    short = list(range(4))
    sched_p, worker_p = _produce(server, "v1-diff", short, [3], seed=5)

    prompt = short + list(range(100, 108))  # 3 blocks, store has block 0
    sched, _ = _connector(server, "v1-diff", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(server, "v1-diff", KVConnectorRole.WORKER)
    req = Request("rd", prompt)
    external, _ = sched.get_num_new_matched_tokens(req, num_computed_tokens=8)
    assert external == 0
    sched.update_state_after_alloc(req, [[4, 5, 6]], 0)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("rd", prompt, [[4, 5, 6]], 8)])
    )
    assert len(meta.loads) == 0
    assert len(meta.saves) == 1
    assert meta.saves[0].first_block == 1
    assert list(meta.saves[0].block_ids) == [5, 6]
    caches = _filled_caches([4, 5, 6], 3, seed=6)
    _worker_step(worker, meta, dict(zip(LAYERS, caches)))
    probe = _connect(server)
    probe_kv = KVConnector(probe, SPEC, "v1-diff", max_blocks=MAX_BLOCKS)
    assert probe_kv.lookup(prompt) == 3, "store never learned the local blocks"
    probe.close()
    for c in (sched_p, worker_p, sched, worker):
        c.kv.conn.close()


def test_full_aligned_hit_holds_back_one_block(server):
    """A block-aligned prompt fully cached in the store: the promise must
    leave >= 1 token for the engine to compute (vLLM's scheduler requires
    a non-empty local step), so one whole block is held back — and no save
    is built (the store already holds the held-back block)."""
    prompt = list(range(12))  # exactly 3 blocks, all cached
    sched_p, worker_p = _produce(server, "v1-cap", prompt, [0, 1, 2], seed=8)

    sched, _ = _connector(server, "v1-cap", KVConnectorRole.SCHEDULER)
    req = Request("rc", prompt)
    external, _ = sched.get_num_new_matched_tokens(req, 0)
    assert external == 8, "full-prompt promise would leave 0 tokens to compute"
    sched.update_state_after_alloc(req, [[4, 5, 6]], external)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("rc", prompt, [[4, 5, 6]])])
    )
    assert len(meta.loads) == 1
    assert meta.loads[0].first_block == 0
    assert list(meta.loads[0].block_ids) == [4, 5]
    assert len(meta.saves) == 0, "the held-back block is already stored"
    for c in (sched_p, worker_p, sched):
        c.kv.conn.close()


def test_chunked_prefill_saves_only_scheduled_blocks(server):
    """vLLM chunks long prefills: with num_scheduled_tokens bounding the
    step, only blocks COMPLETE by end of step may be saved — committing an
    unscheduled block would publish garbage under a valid chain key."""
    prompt = list(range(200, 212))  # 3 blocks, cold
    sched, _ = _connector(server, "v1-chunk", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(server, "v1-chunk", KVConnectorRole.WORKER)
    req = Request("rk", prompt)
    assert sched.get_num_new_matched_tokens(req, 0)[0] == 0
    sched.update_state_after_alloc(req, [[0, 1, 2]], 0)
    out = SchedulerOutput([NewRequestData("rk", prompt, [[0, 1, 2]])])
    out.num_scheduled_tokens = {"rk": 4}  # step computes 1 block of 3
    meta = sched.build_connector_meta(out)
    assert len(meta.saves) == 1
    assert meta.saves[0].first_block == 0
    assert list(meta.saves[0].block_ids) == [0], (
        "saved blocks the step never computed"
    )
    caches = _filled_caches([0, 1, 2], 3, seed=9)
    _worker_step(worker, meta, dict(zip(LAYERS, caches)))
    probe = _connect(server)
    probe_kv = KVConnector(probe, SPEC, "v1-chunk", max_blocks=MAX_BLOCKS)
    assert probe_kv.lookup(prompt) == 1, "exactly the scheduled block is visible"
    probe.close()
    for c in (sched, worker):
        c.kv.conn.close()


def test_call_order_is_enforced(server):
    """Worker entry points before bind_connector_metadata fail loudly (the
    runner contract), and an unknown layer name is a KeyError."""
    sched, _ = _connector(server, "v1-order", KVConnectorRole.WORKER)
    zero = {
        name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
               jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for name in LAYERS
    }
    sched.register_kv_caches(zero)
    with pytest.raises(RuntimeError, match="bind_connector_metadata"):
        sched.start_load_kv(forward_context=None)
    with pytest.raises(RuntimeError, match="bind_connector_metadata"):
        sched.save_kv_layer(LAYERS[0], None, attn_metadata=None)
    sched.bind_connector_metadata(InfiniStoreConnectorMetadata())
    sched.start_load_kv(forward_context=None)
    with pytest.raises(KeyError):
        sched.wait_for_layer_load("no.such.layer")
    sched.kv.conn.close()


def test_v1_composes_with_cluster_pool(server):
    """The duck-typed connector gate: a ClusterKVConnector drops into the
    vLLM v1 surface unchanged — layer-wise saves route to the prefix
    owner, a second engine's probe + load find them."""
    from infinistore_tpu.cluster import ClusterKVConnector

    srv2 = its.start_local_server(
        prealloc_bytes=16 << 20, block_bytes=64 << 10, enable_shm=True
    )
    conns = []
    try:
        def mk_cluster():
            cs = [_connect(server), _connect(srv2)]
            conns.extend(cs)
            return ClusterKVConnector(cs, SPEC, "v1-cluster", MAX_BLOCKS)

        prompt = list(range(300, 310))  # 2 complete blocks + tail
        sched_p = InfiniStoreKVConnectorV1(
            _vllm_config(mk_cluster()), KVConnectorRole.SCHEDULER
        )
        worker_p = InfiniStoreKVConnectorV1(
            _vllm_config(mk_cluster()), KVConnectorRole.WORKER
        )
        req = Request("rp", prompt)
        assert sched_p.get_num_new_matched_tokens(req, 0)[0] == 0
        sched_p.update_state_after_alloc(req, [[0, 1]], 0)
        meta = sched_p.build_connector_meta(
            SchedulerOutput([NewRequestData("rp", prompt, [[0, 1]])])
        )
        caches = _filled_caches([0, 1], 2, seed=11)
        _worker_step(worker_p, meta, dict(zip(LAYERS, caches)))

        sched_c = InfiniStoreKVConnectorV1(
            _vllm_config(mk_cluster()), KVConnectorRole.SCHEDULER
        )
        worker_c = InfiniStoreKVConnectorV1(
            _vllm_config(mk_cluster()), KVConnectorRole.WORKER
        )
        req2 = Request("rq", prompt)
        external, _ = sched_c.get_num_new_matched_tokens(req2, 0)
        assert external == 8, "cluster routing lost the saved prefix"
        sched_c.update_state_after_alloc(req2, [[7, 8]], external)
        meta2 = sched_c.build_connector_meta(
            SchedulerOutput([NewRequestData("rq", prompt, [[7, 8]])])
        )
        zero = {
            name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
                   jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
            for name in LAYERS
        }
        out = _worker_step(worker_c, meta2, zero, save=False)
        assert worker_c.loaded_tokens("rq") == 8
        for layer, name in enumerate(LAYERS):
            k, _v = out[name]
            for i, pb in enumerate([7, 8]):
                np.testing.assert_array_equal(
                    np.asarray(k)[pb], _block_bytes(layer, 0, i, seed=11)
                )
    finally:
        for c in conns:
            c.close()
        srv2.stop()


def test_raced_eviction_degrades_to_recompute(server):
    """Keys deleted between the scheduler's probe and the worker's load,
    with the engine OPTED INTO the loaded_tokens() recompute protocol: the
    load must settle every layer wait and report loaded_tokens == 0 —
    cache semantics (the engine recomputes), never a hang or stale bytes."""
    prompt = list(range(10))
    sched_p, worker_p = _produce(server, "v1-race", prompt, [0, 1], seed=7)

    sched, _ = _connector(server, "v1-race", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(
        server, "v1-race", KVConnectorRole.WORKER, allow_partial_delivery=True
    )
    req = Request("rr", prompt)
    external, _ = sched.get_num_new_matched_tokens(req, 0)
    assert external == 8
    sched.update_state_after_alloc(req, [[2, 3]], external)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("rr", prompt, [[2, 3]])])
    )
    # Race: drop the blocks before the worker loads.
    assert worker_p.kv.drop(prompt) > 0
    zero = {
        name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
               jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for name in LAYERS
    }
    out = _worker_step(worker, meta, zero, save=False)
    assert worker.loaded_tokens("rr") == 0
    for name in LAYERS:
        k, v = out[name]
        assert not np.asarray(k).any() and not np.asarray(v).any()
    for c in (sched_p, worker_p, sched, worker):
        c.kv.conn.close()


def test_under_delivery_raises_without_opt_in(server):
    """WITHOUT the loaded_tokens() opt-in, a load delivering less than the
    scheduler was promised must fail the step loudly — stock vLLM already
    counted the promise as computed and would silently attend over
    zero-filled blocks."""
    from infinistore_tpu.vllm_v1 import KVLoadUnderDelivery

    prompt = list(range(10))
    sched_p, worker_p = _produce(server, "v1-strict", prompt, [0, 1], seed=12)

    sched, _ = _connector(server, "v1-strict", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(server, "v1-strict", KVConnectorRole.WORKER)
    req = Request("ru", prompt)
    external, _ = sched.get_num_new_matched_tokens(req, 0)
    assert external == 8
    sched.update_state_after_alloc(req, [[2, 3]], external)
    meta = sched.build_connector_meta(
        SchedulerOutput([NewRequestData("ru", prompt, [[2, 3]])])
    )
    assert worker_p.kv.drop(prompt) > 0  # the race
    zero = {
        name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
               jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for name in LAYERS
    }
    worker.register_kv_caches(zero)
    worker.bind_connector_metadata(meta)
    worker.start_load_kv(forward_context=None)
    with pytest.raises(RuntimeError) as ei:
        for name in LAYERS:
            worker.wait_for_layer_load(name)
        worker.wait_for_save()
    assert isinstance(
        ei.value if isinstance(ei.value, KVLoadUnderDelivery) else ei.value.__cause__,
        KVLoadUnderDelivery,
    )
    worker.clear_connector_metadata()
    for c in (sched_p, worker_p, sched, worker):
        c.kv.conn.close()


@dataclass
class CachedRequestData:
    """Duck-typed vLLM CachedRequestData: a resumed request's step carries
    no prompt tokens — only ids, newly allocated blocks, and progress."""

    req_id: str
    new_block_ids: List[List[int]]
    num_computed_tokens: int
    resumed_from_preemption: bool = False


def test_chunked_prefill_resumed_chunks_are_saved(server):
    """A long prompt chunked over several steps: chunks after the first
    arrive via scheduled_cached_reqs (no prompt data). The per-request
    saved-block watermark must carry across steps so EVERY computed block
    reaches the store — and be cleared at request_finished."""
    prompt = list(range(400, 412))  # 3 blocks, cold
    sched, _ = _connector(server, "v1-resume", KVConnectorRole.SCHEDULER)
    worker, _ = _connector(server, "v1-resume", KVConnectorRole.WORKER)
    req = Request("rz", prompt)
    assert sched.get_num_new_matched_tokens(req, 0)[0] == 0
    sched.update_state_after_alloc(req, [[0, 1, 2]], 0)
    # Step 1: the new request computes 1 of 3 blocks.
    out1 = SchedulerOutput([NewRequestData("rz", prompt, [[0, 1, 2]])])
    out1.num_scheduled_tokens = {"rz": 4}
    meta1 = sched.build_connector_meta(out1)
    assert [list(s.block_ids) for s in meta1.saves] == [[0]]
    caches = _filled_caches([0, 1, 2], 3, seed=13)
    _worker_step(worker, meta1, dict(zip(LAYERS, caches)))
    # Step 2: the SAME request resumes via scheduled_cached_reqs — 8 more
    # tokens complete blocks 1 and 2. Without the watermark these blocks
    # would silently never be saved (the seed behavior).
    out2 = SchedulerOutput([])
    out2.scheduled_cached_reqs = [CachedRequestData("rz", [[]], 4)]
    out2.num_scheduled_tokens = {"rz": 8}
    meta2 = sched.build_connector_meta(out2)
    assert len(meta2.loads) == 0
    assert len(meta2.saves) == 1
    assert meta2.saves[0].first_block == 1
    assert list(meta2.saves[0].block_ids) == [1, 2]
    _worker_step(worker, meta2, dict(zip(LAYERS, caches)))
    probe = _connect(server)
    probe_kv = KVConnector(probe, SPEC, "v1-resume", max_blocks=MAX_BLOCKS)
    assert probe_kv.lookup(prompt) == 3, "resumed chunks never reached the store"
    probe.close()
    # request_finished clears the watermark (no unbounded growth, and a
    # reused request id starts fresh).
    assert sched.request_finished(req, [[0, 1, 2]]) == (False, None)
    assert "rz" not in sched._save_watermark
    # A third step for the (finished) request emits nothing.
    out3 = SchedulerOutput([])
    out3.scheduled_cached_reqs = [CachedRequestData("rz", [[]], 12)]
    meta3 = sched.build_connector_meta(out3)
    assert meta3.saves == []
    for c in (sched, worker):
        c.kv.conn.close()


def test_preemption_resume_replaces_block_list(server):
    """resumed_from_preemption=True means the old physical blocks were
    freed and new_block_ids is the FULL replacement list: the tracker must
    REPLACE, not append — appending would emit saves that gather other
    requests' data from the recycled blocks under this prompt's chain
    keys. The saved-block watermark survives (already-saved blocks are
    content-addressed by tokens, still valid)."""
    prompt = list(range(500, 512))  # 3 blocks, cold
    sched, _ = _connector(server, "v1-preempt", KVConnectorRole.SCHEDULER)
    req = Request("rp2", prompt)
    assert sched.get_num_new_matched_tokens(req, 0)[0] == 0
    sched.update_state_after_alloc(req, [[0, 1, 2]], 0)
    out1 = SchedulerOutput([NewRequestData("rp2", prompt, [[0, 1, 2]])])
    out1.num_scheduled_tokens = {"rp2": 4}  # step 1 computes block 0
    meta1 = sched.build_connector_meta(out1)
    assert [list(s.block_ids) for s in meta1.saves] == [[0]]
    # Preempted; resumed later with a completely new physical placement.
    out2 = SchedulerOutput([])
    out2.scheduled_cached_reqs = [
        CachedRequestData("rp2", [[5, 6, 7]], 4, resumed_from_preemption=True)
    ]
    out2.num_scheduled_tokens = {"rp2": 8}  # completes blocks 1 and 2
    meta2 = sched.build_connector_meta(out2)
    assert len(meta2.saves) == 1
    assert meta2.saves[0].first_block == 1
    assert list(meta2.saves[0].block_ids) == [6, 7], (
        "resume must save from the REPLACEMENT block list, not the stale one"
    )
    sched.request_finished(req, [[5, 6, 7]])
    sched.kv.conn.close()


def test_hookless_donating_load_installs_returned_caches(server):
    """A connector whose load DONATES the cache buffers but fires no
    on_layer hooks (the quantized connector's scales-race degrade path
    returns 0 after donating every layer): the worker must install the
    returned per-layer arrays — dropping them leaves _kv_caches pointing
    at deleted TPU buffers for the rest of the step."""

    class DonatingKV:
        """KVConnector-shaped; load replaces every layer, fires no hooks,
        reports 0 loaded (degrade path). No start_fetch: exercises the
        one-phase branch the degrade path actually takes."""

        spec = SPEC

        def lookup(self, token_ids):
            return 2

        async def load(self, token_ids, caches, block_ids, first_block=0,
                       on_layer=None):
            replaced = [
                (k + jnp.float32(1.0), v + jnp.float32(1.0)) for k, v in caches
            ]
            return replaced, 0

        def stage_layer_save(self, *a, **kw):
            async def noop():
                return 0

            return noop

    kv = DonatingKV()
    worker = InfiniStoreKVConnectorV1(
        _vllm_config(kv, allow_partial_delivery=True), KVConnectorRole.WORKER
    )
    zero = {
        name: (jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32),
               jnp.zeros((SPEC.num_blocks, *SPEC.block_shape), jnp.float32))
        for name in LAYERS
    }
    originals = {name: zero[name] for name in LAYERS}
    worker.register_kv_caches(zero)
    from infinistore_tpu.vllm_v1 import _LoadSpec

    meta = InfiniStoreConnectorMetadata(
        loads=[
            _LoadSpec(
                req_id="dq",
                token_ids=list(range(8)),
                block_ids=np.array([1, 2], np.int32),
                num_tokens=8,
                first_block=0,
            )
        ]
    )
    worker.bind_connector_metadata(meta)
    worker.start_load_kv(forward_context=None)
    for name in LAYERS:
        worker.wait_for_layer_load(name)
        k, v = worker.kv_cache(name)
        # The donated replacements (all-ones) were installed, not the
        # stale originals.
        assert k is not originals[name][0]
        assert float(np.asarray(k)[0, 0, 0, 0]) == 1.0
    worker.wait_for_save()
    assert worker.loaded_tokens("dq") == 0
    worker.clear_connector_metadata()
    worker.close()
