"""ClusterKVConnector: one KV pool over several independent servers with
prefix-affine rendezvous routing (the multi-node shape of the reference's
"extra-large KV-cache pool / cross-node reuse" scenario, reference
README.md:13-16 — which the reference itself serves with a single process).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import ClusterKVConnector, rendezvous_owner, token_chain_hashes
from infinistore_tpu.tpu import PagedKVCacheSpec, gather_blocks

SPEC = PagedKVCacheSpec(
    num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2, head_dim=32,
    dtype=jnp.bfloat16,
)


def _rand_caches(seed):
    out = []
    for layer in range(SPEC.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        out.append((k, v))
    return out


@pytest.fixture()
def cluster3():
    """Three live loopback servers + connections, torn down in order."""
    servers, conns = [], []
    try:
        for _ in range(3):
            srv = its.start_local_server(
                prealloc_bytes=64 << 20, block_bytes=16 << 10
            )
            conn = its.InfinityConnection(
                its.ClientConfig(
                    host_addr="127.0.0.1", service_port=srv.port, log_level="error"
                )
            )
            conn.connect()
            servers.append(srv)
            conns.append(conn)
        yield servers, conns
    finally:
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        for s in servers:
            s.stop()


def _prompt_owned_by(cluster, want_idx, vocab=1000, tries=200):
    """A 2-block prompt whose chain root rendezvous-hashes to member want_idx."""
    rng = np.random.default_rng(want_idx)
    for _ in range(tries):
        p = rng.integers(0, vocab, size=2 * SPEC.block_tokens).tolist()
        if cluster.owner_index(p) == want_idx:
            return p
    raise AssertionError(f"no prompt found for member {want_idx}")


def test_rendezvous_membership_change_only_remaps_removed_owner():
    """The property that makes draining a cache node cheap: removing one
    member remaps ONLY the roots it owned."""
    members = ["a:1", "b:2", "c:3"]
    roots = [f"root-{i}" for i in range(300)]
    before = {r: rendezvous_owner(members, r) for r in roots}
    survivors = ["a:1", "c:3"]  # drain b:2
    moved = stayed = 0
    for r in roots:
        after = survivors[rendezvous_owner(survivors, r)]
        if members[before[r]] == "b:2":
            moved += 1
            assert after in survivors
        else:
            stayed += 1
            assert after == members[before[r]]
    # All three got meaningful shares (sha256 balance at n=300).
    assert moved > 50 and stayed > 100


def test_prefix_tree_colocates_and_prompts_distribute(cluster3):
    _, conns = cluster3
    cluster = ClusterKVConnector(conns, SPEC, "demo", max_blocks=8)
    # Same first block => same owner, regardless of what follows.
    base = list(range(SPEC.block_tokens))
    a = base + [11] * SPEC.block_tokens
    b = base + [22] * SPEC.block_tokens
    assert cluster.owner_index(a) == cluster.owner_index(b)
    # Distinct roots spread over members (300 roots, 3 members).
    owners = {
        cluster.owner_index([seed] + base[1:]) for seed in range(300)
    }
    assert owners == {0, 1, 2}
    # Sub-block prompt: nothing to route.
    assert cluster.owner_index(base[:4]) is None
    assert cluster.lookup(base[:4]) == 0


def test_cluster_roundtrip_lands_on_owner_only(cluster3):
    servers, conns = cluster3
    cluster = ClusterKVConnector(conns, SPEC, "demo", max_blocks=8)
    tokens = _prompt_owned_by(cluster, 1)
    caches = _rand_caches(1)
    src_ids = np.array([3, 9], dtype=np.int32)
    written = asyncio.run(cluster.save(tokens, caches, src_ids))
    assert written == 2 * 2 * SPEC.num_layers
    # Keys exist only on the owner.
    from infinistore_tpu._native import lib as native

    lens = [int(native.its_server_kvmap_len(s.handle)) for s in servers]
    assert lens[1] > 0 and lens[0] == 0 and lens[2] == 0

    assert cluster.lookup(tokens) == 2
    fresh = SPEC.make_caches()
    dst_ids = np.array([5, 0], dtype=np.int32)
    loaded, n = asyncio.run(cluster.load(tokens, fresh, dst_ids))
    assert n == 2
    for layer in range(SPEC.num_layers):
        for kind in (0, 1):
            got = np.asarray(
                gather_blocks(loaded[layer][kind], jnp.asarray(dst_ids)), np.float32
            )
            want = np.asarray(
                gather_blocks(caches[layer][kind], jnp.asarray(src_ids)), np.float32
            )
            np.testing.assert_array_equal(got, want)

    assert cluster.drop(tokens) == 2 * 2 * SPEC.num_layers
    assert cluster.lookup(tokens) == 0


def test_down_member_strict_raises_degrade_misses(cluster3):
    servers, conns = cluster3
    strict = ClusterKVConnector(conns, SPEC, "demo", max_blocks=8)
    soft = ClusterKVConnector(conns, SPEC, "demo", max_blocks=8, degrade=True)
    victim_tokens = _prompt_owned_by(strict, 2)
    healthy_tokens = _prompt_owned_by(strict, 0)
    # Seed the healthy member before the outage.
    asyncio.run(soft.save(healthy_tokens, _rand_caches(2), np.array([1, 2], np.int32)))

    servers[2].stop()  # the outage

    with pytest.raises(its.InfiniStoreException):
        strict.lookup(victim_tokens)
    assert soft.lookup(victim_tokens) == 0
    assert asyncio.run(
        soft.save(victim_tokens, _rand_caches(3), np.array([4, 5], np.int32))
    ) == 0
    fresh = SPEC.make_caches()
    _, n = asyncio.run(soft.load(victim_tokens, fresh, np.array([6, 7], np.int32)))
    assert n == 0
    assert soft.degraded_ops == 3
    # The healthy member keeps serving through the same cluster object.
    assert soft.lookup(healthy_tokens) == 2
    stats = soft.stats()
    assert stats[2].get("unreachable") is True
    assert "member_id" in stats[0]


def test_quantized_members_compose_with_routing(cluster3):
    """member_factory swaps each member for a QuantizedKVConnector: the
    pool stores int8 + scales per member while prefix-affine routing and
    the degrade policy stay the cluster's."""
    from infinistore_tpu.tpu.kv_quant import (
        QuantizedKVConnector, dequantize_kv, quantize_kv,
    )

    _, conns = cluster3
    cluster = ClusterKVConnector(
        conns, SPEC, "demo-q8", max_blocks=8,
        member_factory=lambda c: QuantizedKVConnector(c, SPEC, "demo-q8", 8),
    )
    tokens = _prompt_owned_by(cluster, 0)
    rng = np.random.default_rng(8)
    float_caches = [
        (jnp.asarray(rng.standard_normal(SPEC.cache_shape), jnp.float32),
         jnp.asarray(rng.standard_normal(SPEC.cache_shape), jnp.float32))
        for _ in range(SPEC.num_layers)
    ]
    quant = [(quantize_kv(k), quantize_kv(v)) for k, v in float_caches]
    src = np.array([1, 2], np.int32)
    assert asyncio.run(cluster.save(tokens, quant, src)) == 2 * 2 * SPEC.num_layers
    assert cluster.lookup(tokens) == 2

    fresh = [
        (
            (jnp.zeros(SPEC.cache_shape, jnp.int8),
             jnp.zeros(SPEC.cache_shape[:-1], jnp.float32)),
            (jnp.zeros(SPEC.cache_shape, jnp.int8),
             jnp.zeros(SPEC.cache_shape[:-1], jnp.float32)),
        )
        for _ in range(SPEC.num_layers)
    ]
    dst = np.array([4, 6], np.int32)
    loaded, n = asyncio.run(cluster.load(tokens, fresh, dst))
    assert n == 2
    got = np.asarray(dequantize_kv(*loaded[0][0]))[dst]
    want = np.asarray(dequantize_kv(*quant[0][0]))[src]
    np.testing.assert_array_equal(got, want)
    assert "member_id" in cluster.stats()[0]


def test_engine_harness_runs_over_cluster(cluster3):
    """The continuous-batching harness (BASELINE config 4 shape) over a
    2-member cluster pool: concurrent requests, full verification against
    the model's prefill oracle, prefix hits on the second wave."""
    from infinistore_tpu.engine import ContinuousBatchingHarness, EngineKVAdapter
    from infinistore_tpu.models import LlamaConfig, init_params

    _, conns = cluster3
    cfg = LlamaConfig(
        vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
        block_tokens=8, dtype=jnp.float32,
    )
    cluster = ClusterKVConnector(
        conns[:2], cfg.kv_spec(1), "engine-demo", max_blocks=4
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    h = ContinuousBatchingHarness(
        EngineKVAdapter(cluster), params, cfg, num_blocks=16, max_req_blocks=4,
        verify=True,
    )
    rng = np.random.default_rng(9)
    prompts = [
        rng.integers(0, cfg.vocab, size=4 * cfg.block_tokens).tolist()
        for _ in range(3)
    ]
    # ONE event loop for both waves: the harness's asyncio primitives bind
    # to the loop that first awaits them (engine.py docstring).
    async def drive():
        m1 = await h.run(prompts, concurrency=3)
        h.stats.clear()
        m2 = await h.run(prompts, concurrency=3)
        return m1, m2

    m1, m2 = asyncio.run(drive())
    assert m1["all_verified"]
    assert m2["all_verified"]
    assert m2["hit_rate"] == 1.0  # second wave fully served from the pool
    # Both members hold keys iff the roots actually split; at minimum the
    # cluster routed every request somewhere real.
    owners = {cluster.owner_index(p) for p in prompts}
    assert owners <= {0, 1} and len(owners) >= 1
