"""Flash (blocked online-softmax) prefill attention: the Pallas kernel in
interpret mode against a float64 numpy oracle — causal and full, GQA ratios,
block-size boundaries — plus the dispatcher contract the model's prefill
relies on (mask=None routes causal attention through it)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from infinistore_tpu.tpu.flash_prefill import (
    _flash_prefill_pallas,
    flash_prefill_attention,
    flash_prefill_xla,
)


def _oracle(q, k, v, causal):
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    b, s, h, d = q.shape
    groups = h // k.shape[2]
    k = np.repeat(k, groups, axis=2)
    v = np.repeat(v, groups, axis=2)
    logits = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(d)
    if causal:
        t = k.shape[1]
        cm = np.arange(s)[:, None] >= np.arange(t)[None, :]
        logits = np.where(cm[None, None], logits, -np.inf)
    p = np.exp(logits - logits.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhst,bthd->bshd", p, v)


CASES = [
    # (B, S, H, KVH, D, block_q, block_k)
    (1, 32, 4, 2, 16, 8, 8),  # GQA x2, several blocks
    (2, 64, 8, 8, 32, 16, 32),  # MHA, batch 2, uneven bq/bk
    (1, 16, 4, 1, 64, 16, 16),  # MQA, single block each way
    (1, 48, 2, 2, 16, 8, 24),  # bk > bq
]


@pytest.mark.parametrize("case", CASES)
@pytest.mark.parametrize("causal", [True, False])
def test_kernel_matches_oracle(case, causal):
    b, s, h, kvh, d, bq, bk = case
    rng = np.random.default_rng(abs(hash((case, causal))) % 2**32)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kvh, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kvh, d)), jnp.float32)
    want = _oracle(q, k, v, causal)
    got = _flash_prefill_pallas(
        q, k, v, causal=causal, block_q=bq, block_k=bk, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float64), want, rtol=1e-5, atol=1e-5
    )
    got_xla = flash_prefill_xla(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got_xla, np.float64), want, rtol=1e-5, atol=1e-5
    )


def test_awkward_lengths_pick_dividing_blocks():
    """Lengths that don't divide the requested block size must still work
    (the kernel clamps to the largest dividing block) — a 264-token prompt
    is valid under the model's S % block_tokens contract and must not
    trace-error on TPU."""
    from infinistore_tpu.tpu.flash_prefill import _dividing_block

    assert _dividing_block(264, 256) == 132
    assert _dividing_block(20, 8) == 5
    assert _dividing_block(17, 8) == 1  # prime tail: slow but correct
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((1, 20, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 20, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 20, 2, 16)), jnp.float32)
    got = _flash_prefill_pallas(
        q, k, v, causal=True, block_q=8, block_k=8, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float64), _oracle(q, k, v, True), rtol=1e-5, atol=1e-5
    )


def test_dispatcher_is_dense_off_tpu():
    """On non-TPU backends the dispatcher must be the XLA dense path (the
    model's prefill routes mask=None through it, and CPU tests rely on the
    dense numerics)."""
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.standard_normal((1, 16, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 16, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 16, 2, 16)), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(flash_prefill_attention(q, k, v)),
        np.asarray(flash_prefill_xla(q, k, v)),
    )


def test_prefill_still_matches_decode_through_flash_route():
    """The model's prefill now routes causal attention through the flash
    dispatcher; the paged-decode == full-prefill invariant must hold."""
    from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill

    cfg = LlamaConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=64,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(2))
    full = jax.random.randint(jax.random.PRNGKey(3), (24,), 0, cfg.vocab)
    table = jnp.asarray([0, 1, 2, 3], jnp.int32)
    caches = cfg.kv_spec(8).make_caches()
    ref_logits, _ = prefill(
        params, full, cfg.kv_spec(8).make_caches(), table[:3], cfg
    )
    logits, caches = prefill(params, full[:16], caches, table[:2], cfg)
    for pos in range(16, 24):
        logits, caches = decode_step(
            params, full[pos], jnp.int32(pos), caches, table, cfg, 4
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
