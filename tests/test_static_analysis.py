"""Tests for the in-repo static-analysis suite (tools/analysis).

Two layers of guarantee:

1. **Seeded violations**: for every checker, fixtures carrying a deliberate
   violation of each drift/violation class must FIRE. The wire-drift
   fixtures are mutated copies of the REAL protocol.h / wire.py (changed
   field width, reordered field, missing Priority value, drifted opcode,
   missing struct, header-layout drift), so the parser is exercised
   against production text, not toy grammars.
2. **Clean tree**: `python -m tools.analysis --all` exits 0 on the
   repository as committed — the acceptance gate CI's `analysis` job runs.

Plus the framework mechanics: inline `# its: allow[ID]` suppressions,
the committed-baseline flow, and machine-readable JSON output.
"""

import dataclasses
import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.analysis import (  # noqa: E402
    core,
    counters,
    loop_block,
    modelcheck,
    policy,
    races,
    trace_stages,
    wire_drift,
)
from tools.analysis import specs as mspecs  # noqa: E402
from tools.analysis.specs import membership_spec, ring_spec  # noqa: E402


def make_tree(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    return core.Context(str(tmp_path))


# ---------------------------------------------------------------------------
# wire_drift (ITS-W*)
# ---------------------------------------------------------------------------

def drifted_ctx(tmp_path, header_sub=None, wire_sub=None, wire_append=""):
    """Context over copies of the real protocol.h / wire.py with one
    targeted mutation applied (asserting the anchor text exists, so a
    refactor that moves it fails loudly here instead of silently testing
    nothing)."""
    hdr = (REPO / wire_drift.HEADER_REL).read_text()
    wr = (REPO / wire_drift.WIRE_REL).read_text()
    if header_sub is not None:
        old, new = header_sub
        assert old in hdr, f"fixture anchor missing from protocol.h: {old!r}"
        hdr = hdr.replace(old, new, 1)
    if wire_sub is not None:
        old, new = wire_sub
        assert old in wr, f"fixture anchor missing from wire.py: {old!r}"
        wr = wr.replace(old, new, 1)
    wr += wire_append
    return make_tree(tmp_path, {wire_drift.HEADER_REL: hdr, wire_drift.WIRE_REL: wr})


class TestWireDrift:
    def test_real_tree_is_clean(self):
        assert wire_drift.compare(core.Context(str(REPO))) == []

    def test_parser_inventory(self):
        """The parsers must see the full protocol surface — a parser that
        silently skips half the header would also 'find no drift'."""
        ctx = core.Context(str(REPO))
        cpp = wire_drift.parse_header(ctx)
        py = wire_drift.parse_wire(ctx)
        ops = [k for k in cpp.constants if k.startswith("OP_")]
        assert len(ops) == 18
        assert len([k for k in cpp.constants if k.startswith("STATUS_")]) == 10
        assert cpp.constants["STATUS_COLD_TIER"] == 512
        assert cpp.constants["PRIORITY_BACKGROUND"] == 1
        assert cpp.header_asserts == {
            "ReqHeader": 9, "RespHeader": 16,
            "RingCtrl": 72, "RingSlot": 24, "RingCqe": 32,
            "RingBatchHdr": 4, "RingBatchEntry": 8,
        }
        for name in ("BatchMeta", "SegBatchMeta", "ShmLocResp", "SegMeta",
                     "RingMeta", "TcpPutMeta", "TicketMeta", "KeyMeta",
                     "KeyListMeta"):
            assert name in cpp.structs and name in py.structs
        # The mapped ring structs are parsed on BOTH representations: packed
        # width sequences (W004) and named-field layouts (W005).
        for name in ("RingCtrl", "RingSlot", "RingCqe", "RingBatchHdr",
                     "RingBatchEntry"):
            assert name in cpp.headers and name in py.headers
            assert name in py.ring_layouts
            assert py.ring_layouts[name] == [
                (f, {1: "u8", 2: "u16", 4: "u32", 8: "u64"}[w])
                for f, w in cpp.headers[name]
            ]
        # The QoS tag is an OPTIONAL trailing byte on both batch metas,
        # followed by the OPTIONAL trace-context pair (trace id + parent).
        assert cpp.structs["BatchMeta"][-3:] == ["u8?", "u64?", "u64?"]
        assert py.structs["BatchMeta"][-3:] == ["u8?", "u64?", "u64?"]
        assert cpp.structs["SegBatchMeta"][-3:] == ["u8?", "u64?", "u64?"]

    def test_changed_field_width_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, header_sub=(
            "w.u32(block_size);\n        w.str_list(keys);",
            "w.u16(block_size);\n        w.str_list(keys);",
        ))
        rules = {(f.rule, "BatchMeta" in f.message) for f in wire_drift.compare(ctx)}
        assert ("ITS-W002", True) in rules

    def test_reordered_field_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, header_sub=(
            "w.u32(block_size);\n        w.u16(seg_id);",
            "w.u16(seg_id);\n        w.u32(block_size);",
        ))
        found = [f for f in wire_drift.compare(ctx) if f.rule == "ITS-W002"]
        assert any("SegBatchMeta" in f.message for f in found)

    def test_missing_priority_value_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, header_sub=(
            "kPriorityBackground = 1,", "",
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W001" and "PRIORITY_BACKGROUND" in f.message
            for f in found
        )

    def test_opcode_value_drift_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, wire_sub=(
            'OP_STAT = ord("S")', 'OP_STAT = ord("T")',
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W001" and "OP_STAT" in f.message for f in found
        )

    def test_missing_struct_mirror_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, wire_sub=(
            "class TicketMeta:", "class TicketMetaRenamed:",
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W003" and "TicketMeta" in f.message for f in found
        )

    def test_fixed_header_drift_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, wire_sub=(
            '_REQ_HEADER = struct.Struct("<IBI")',
            '_REQ_HEADER = struct.Struct("<IBH")',
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W004" and "ReqHeader" in f.message for f in found
        )

    def test_header_static_assert_drift_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, header_sub=(
            "uint32_t body_size;\n};\nstruct RespHeader",
            "uint16_t body_size;\n};\nstruct RespHeader",
        ))
        found = wire_drift.compare(ctx)
        assert any(f.rule == "ITS-W004" for f in found)

    def test_python_only_struct_is_caught(self, tmp_path):
        """The diff is bidirectional: a wire-encoding dataclass added only
        to wire.py (not registered as client-side framing) must fire —
        the native server could never parse its bytes."""
        ctx = drifted_ctx(tmp_path, wire_append=(
            "\n\n@dataclass\nclass RogueMeta:\n"
            "    n: int = 0\n\n"
            "    def encode(self) -> bytes:\n"
            '        return struct.pack("<I", self.n)\n'
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W003" and "RogueMeta" in f.message for f in found
        )

    def test_python_only_header_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, wire_append=(
            '\n_ROGUE_HEADER = struct.Struct("<IQ")\n'
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W004" and "_ROGUE_HEADER" in f.message for f in found
        )

    def test_ring_same_width_field_swap_is_caught(self, tmp_path):
        """THE gap ITS-W005 exists for: swapping sq_tail/sq_head is
        invisible to the width diff (both u64) but misroutes every cursor
        access in mapped memory."""
        ctx = drifted_ctx(tmp_path, header_sub=(
            "uint64_t sq_tail;",
            "uint64_t sq_head_x;",
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W005" and "RingCtrl" in f.message for f in found
        )
        # And the width diff alone would indeed have stayed silent.
        assert not any(
            f.rule == "ITS-W004" and "RingCtrl" in f.message for f in found
        )

    def test_batch_entry_same_width_field_swap_is_caught(self, tmp_path):
        """Same gap, new struct: swapping the two u8s of a batch-slot entry
        (op <-> flags) keeps the width sequence AND the static_assert sum
        identical — only the named-field layout diff (W005) can see the
        server decoding every batched op's opcode from the flags byte."""
        ctx = drifted_ctx(tmp_path, header_sub=(
            # Anchored through RingBatchEntry's unique meta_len comment —
            # RingSlot carries byte-identical op/flags lines.
            "    uint32_t meta_len;  // SegBatchMeta bytes following this entry\n"
            "    uint8_t op;         // kOpPutFrom or kOpGetInto\n"
            "    uint8_t flags;      // reserved (0)",
            "    uint32_t meta_len;  // SegBatchMeta bytes following this entry\n"
            "    uint8_t flags;      // reserved (0)\n"
            "    uint8_t op;         // kOpPutFrom or kOpGetInto",
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W005" and "RingBatchEntry" in f.message for f in found
        )
        assert not any(
            f.rule == "ITS-W004" and "RingBatchEntry" in f.message for f in found
        )

    def test_ring_width_change_is_caught_by_both(self, tmp_path):
        ctx = drifted_ctx(tmp_path, header_sub=(
            "uint32_t meta_len;",
            "uint16_t meta_len;",
        ))
        rules = {f.rule for f in wire_drift.compare(ctx) if "RingSlot" in f.message}
        assert "ITS-W005" in rules
        assert "ITS-W004" in rules  # width sequence AND static_assert sum

    def test_ring_layout_removed_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, wire_sub=(
            '"RingCqe": (',
            '"RingCqeX": (',
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W005" and "RingCqe has no named-field" in f.message
            for f in found
        )
        assert any(
            f.rule == "ITS-W005" and "RingCqeX has no packed struct" in f.message
            for f in found
        )

    def test_ring_python_field_rename_is_caught(self, tmp_path):
        ctx = drifted_ctx(tmp_path, wire_sub=(
            '("token", "u64"),\n        ("meta_len", "u32"),',
            '("tok", "u64"),\n        ("meta_len", "u32"),',
        ))
        found = wire_drift.compare(ctx)
        assert any(
            f.rule == "ITS-W005" and "RingSlot" in f.message and "drifted" in f.message
            for f in found
        )

    def test_block_comment_preserves_line_anchors(self, tmp_path):
        """/* */ comments must not shift finding lines: suppression markers
        index into the ORIGINAL file."""
        ctx = drifted_ctx(tmp_path, header_sub=(
            "#pragma once",
            "/* a\n block\n comment\n */\n#pragma once",
        ))
        base = {
            k: v for k, v in wire_drift.parse_header(
                core.Context(str(REPO))).const_lines.items()
        }
        shifted = wire_drift.parse_header(ctx, wire_drift.HEADER_REL).const_lines
        # Original file line 14 is `#pragma once`; the fixture adds exactly
        # 4 lines before it, so every constant's anchor shifts by exactly 4.
        assert shifted["MAGIC"] == base["MAGIC"] + 4


# ---------------------------------------------------------------------------
# loop_block (ITS-L*)
# ---------------------------------------------------------------------------

LOOP_FIXTURE = '''\
import asyncio
import threading
import time


def helper():
    time.sleep(2)


async def direct():
    time.sleep(1)


async def transitive():
    helper()


async def escaped():
    await asyncio.to_thread(helper)


async def allowed():
    time.sleep(3)  # its: allow[ITS-L002]


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.conn = None

    async def locked(self):
        with self._lock:
            pass

    async def native(self):
        lib.its_conn_connect(None)

    async def store(self):
        self.conn.read_cache([], 0, 0)
'''


class TestLoopBlock:
    @pytest.fixture()
    def fixture_ctx(self, tmp_path):
        return make_tree(tmp_path, {"pkg/mod.py": LOOP_FIXTURE})

    def test_seeded_violations_fire(self, fixture_ctx):
        found = loop_block.scan(fixture_ctx, package_rel="pkg", audited={})
        by_slug = {(f.rule, f.key.rsplit(":", 2)[-2:][0]) for f in found}
        # direct sleep in async body
        assert any(f.rule == "ITS-L002" and ":direct:" in f.key for f in found)
        # transitive through a sync helper, with the path in the message
        trans = [f for f in found if ":helper:" in f.key]
        assert trans and "transitive -> helper" in trans[0].message
        # lock acquire, native call, blocking store method
        assert any(f.rule == "ITS-L003" and "C.locked" in f.key for f in found)
        assert any(f.rule == "ITS-L001" and "its_conn_connect" in f.key for f in found)
        assert any(f.rule == "ITS-L001" and "read_cache" in f.key for f in found)
        del by_slug  # documented-above assertions are the contract

    def test_executor_hop_escapes(self, fixture_ctx):
        found = loop_block.scan(fixture_ctx, package_rel="pkg", audited={})
        # helper IS flagged via transitive(); the to_thread reference in
        # escaped() must not add an entry of its own (same site dedup) nor
        # flag escaped() itself.
        assert not any("escaped" in f.message for f in found)

    def test_inline_allow_suppresses(self, fixture_ctx):
        found = loop_block.scan(fixture_ctx, package_rel="pkg", audited={})
        allowed = [f for f in found if ":allowed:" in f.key]
        assert allowed  # the checker still SEES it...
        assert fixture_ctx.suppressed(allowed[0])  # ...but the marker wins

    def test_same_basename_modules_all_scanned(self, tmp_path):
        """Modules are keyed by path: two __init__.py (or same-named)
        files in different subpackages must BOTH be scanned."""
        bad = "import time\n\n\nasync def tick():\n    time.sleep(1)\n"
        ctx = make_tree(tmp_path, {
            "pkg/a/__init__.py": bad,
            "pkg/b/__init__.py": bad,
        })
        found = loop_block.scan(ctx, package_rel="pkg", audited={})
        files = {f.file for f in found}
        assert files == {"pkg/a/__init__.py", "pkg/b/__init__.py"}

    def test_start_fetch_is_a_blocking_name(self, tmp_path):
        """start_fetch embeds a probe RTT; an un-hopped call in an async
        body must fire (the vllm phase-1 regression class)."""
        ctx = make_tree(tmp_path, {"pkg/m.py": (
            "async def wave(kv):\n"
            "    return kv.start_fetch([1, 2])\n"
        )})
        found = loop_block.scan(ctx, package_rel="pkg", audited={})
        assert any(
            f.rule == "ITS-L001" and "start_fetch" in f.key for f in found
        )

    def test_audited_fg_gate_seed_is_active(self):
        """The committed allowlist must cover exactly the audited QoS
        foreground gate in lib.py: with the seed the real tree is clean,
        without it the gate's condition-variable ops surface."""
        ctx = core.Context(str(REPO))
        with_seed = loop_block.scan(ctx)
        assert not [f for f in with_seed if not ctx.suppressed(f)]
        bare = loop_block.scan(ctx, audited={})
        gate = [f for f in bare if "_fg_gate_" in f.key]
        assert gate, "fg gate sites should surface without the audit seed"


# ---------------------------------------------------------------------------
# counters (ITS-C*)
# ---------------------------------------------------------------------------

FIXTURE_CPP = '''
#include <string>
std::string Server::stats_json() {
    std::string out;
    out = "{\\"alpha\\":" + std::to_string(a_) +
          ",\\"grp\\":{\\"beta\\":" + std::to_string(b_) + "}" +
          ",\\"ops\\":{";
    for (const auto& [op, s] : stats_) {
        out += "\\"" + std::string(1, op) + "\\":{" +
               "\\"count\\":" + std::to_string(s.count) + "}";
    }
    out += "}}";
    return out;
}
'''

FIXTURE_MANAGE = '''
def _prometheus_text(stats):
    lines = [f"alpha {stats['alpha']}", f"gamma {stats['gamma']}"]
    for op, s in sorted(stats.get("ops", {}).items()):
        lines.append(f"count {s['count']}")
    return "\\n".join(lines)


def route(path):
    if path == "/stats":
        return get_server_stats()
'''


class TestCounters:
    @pytest.fixture()
    def fixture_ctx(self, tmp_path):
        return make_tree(tmp_path, {
            "native/server.cpp": FIXTURE_CPP,
            "manage.py": FIXTURE_MANAGE,
            "docs.md": "documented: alpha, count, gamma.\n",
        })

    def run_scan(self, ctx):
        return counters.scan(
            ctx, server_cpp_rel="native/server.cpp", manage_rel="manage.py",
            docs_rel="docs.md", ledgers=[],
        )

    def test_native_key_tree(self, fixture_ctx):
        keys = counters.native_stats_keys(fixture_ctx, "native/server.cpp")
        assert keys == {"alpha", "grp.beta", "ops.*.count"}

    def test_unexported_and_stale_keys_fire(self, fixture_ctx):
        found = self.run_scan(fixture_ctx)
        rules = {(f.rule, f.key.rsplit(":", 1)[-1]) for f in found}
        assert ("ITS-C001", "grp.beta") in rules      # native, not exported
        assert ("ITS-C002", "gamma") in rules         # exported, not native
        assert any(r == "ITS-C003" and k == "grp.beta" for r, k in rules)

    def test_missing_stats_route_fires(self, tmp_path):
        ctx = make_tree(tmp_path, {
            "native/server.cpp": FIXTURE_CPP,
            "manage.py": FIXTURE_MANAGE.replace('"/stats"', '"/nope"'),
            "docs.md": "alpha beta count gamma",
        })
        found = counters.scan(
            ctx, server_cpp_rel="native/server.cpp", manage_rel="manage.py",
            docs_rel="docs.md", ledgers=[],
        )
        assert any(f.rule == "ITS-C004" for f in found)

    def test_ledger_keys_doc_checked(self, tmp_path):
        ctx = make_tree(tmp_path, {
            "native/server.cpp": FIXTURE_CPP,
            "manage.py": FIXTURE_MANAGE,
            "docs.md": "alpha count gamma grp beta documented_key",
            "led.py": (
                "class K:\n"
                "    def stats(self):\n"
                "        return {'documented_key': 1, 'mystery_key': 2}\n"
            ),
        })
        found = counters.scan(
            ctx, server_cpp_rel="native/server.cpp", manage_rel="manage.py",
            docs_rel="docs.md", ledgers=[("led.py", "K.stats")],
        )
        ledger = [f for f in found if "K.stats" in f.key]
        assert any("mystery_key" in f.key for f in ledger)
        assert not any("documented_key" in f.key for f in ledger)

    def test_real_tree_is_clean(self):
        assert counters.scan(core.Context(str(REPO))) == []

    def test_real_native_inventory(self):
        """Pin the shape of the real stats_json parse: qos + spill + ops
        subtrees must all be seen (a parser regression that drops a subtree
        would otherwise pass 'clean')."""
        keys = counters.native_stats_keys(core.Context(str(REPO)))
        assert "qos.fg_ops" in keys and "spill.dropped" in keys
        assert "ops.*.p99_us" in keys and "conns_accepted" in keys


# ---------------------------------------------------------------------------
# policy (ITS-P*)
# ---------------------------------------------------------------------------

POLICY_FIXTURE = '''\
class InfiniStoreException(Exception):
    pass


def swallowed(conn):
    try:
        conn.op()
    except InfiniStoreException:
        pass


def routed(self, conn):
    try:
        conn.op()
    except InfiniStoreException as e:
        self._degrade([0], e)


def rethrown(conn):
    try:
        conn.op()
    except InfiniStoreException:
        raise


def semantic_ok(conn):
    try:
        conn.op()
    except InfiniStoreKeyNotFound:
        return 0


async def untagged(conn):
    await conn.write_cache_async([], 0, 0)


async def tagged(conn):
    await conn.write_cache_async([], 0, 0, priority=1)


async def splatted(conn, kw):
    await conn.read_cache_async([], 0, 0, **kw)
'''


class TestPolicy:
    @pytest.fixture()
    def fixture_ctx(self, tmp_path):
        return make_tree(tmp_path, {"pkg/mod.py": POLICY_FIXTURE})

    def test_seeded_violations_fire(self, fixture_ctx):
        found = policy.scan(fixture_ctx, package_rel="pkg",
                            p001_exempt=set(), p002_exempt=set())
        p1 = [f for f in found if f.rule == "ITS-P001"]
        p2 = [f for f in found if f.rule == "ITS-P002"]
        assert len(p1) == 1 and p1[0].line == POLICY_FIXTURE.splitlines().index(
            "    except InfiniStoreException:"
        ) + 1
        assert len(p2) == 1 and "write_cache_async" in p2[0].message

    def test_real_tree_is_clean_after_suppressions(self):
        ctx = core.Context(str(REPO))
        found = policy.scan(ctx)
        assert not [f for f in found if not ctx.suppressed(f)]


# ---------------------------------------------------------------------------
# framework: baseline, suppression classification, CLI, JSON
# ---------------------------------------------------------------------------

class TestFramework:
    def test_baseline_marks_known_findings(self, tmp_path):
        ctx = make_tree(tmp_path, {"pkg/mod.py": POLICY_FIXTURE})
        raw = policy.scan(ctx, package_rel="pkg",
                          p001_exempt=set(), p002_exempt=set())
        assert raw

        # A run() over a checker stub: everything baselined -> not failing.
        def stub(c):
            return policy.scan(c, package_rel="pkg",
                               p001_exempt=set(), p002_exempt=set())

        core.CHECKERS["_stub"] = core.Checker("_stub", "test stub", stub)
        try:
            baseline = {f.key: "audited in test" for f in raw}
            res = core.run(["_stub"], ctx=ctx, baseline=baseline)
            assert not res.failed and len(res.baselined) == len(raw)
            res2 = core.run(["_stub"], ctx=ctx, baseline={})
            assert res2.failed
        finally:
            del core.CHECKERS["_stub"]

    def test_stable_keys_do_not_move_with_unrelated_edits(self, tmp_path):
        ctx1 = make_tree(tmp_path / "a", {"pkg/mod.py": POLICY_FIXTURE})
        ctx2 = make_tree(
            tmp_path / "b",
            {"pkg/mod.py": "# unrelated leading comment\n\n" + POLICY_FIXTURE},
        )
        k1 = {f.key for f in policy.scan(ctx1, package_rel="pkg",
                                         p001_exempt=set(), p002_exempt=set())}
        k2 = {f.key for f in policy.scan(ctx2, package_rel="pkg",
                                         p001_exempt=set(), p002_exempt=set())}
        assert k1 == k2

    def test_cli_all_green_with_json(self, tmp_path):
        out = tmp_path / "analysis.json"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis", "--all", "--json", str(out)],
            cwd=str(REPO), capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(out.read_text())
        assert payload["failed"] is False
        assert set(payload["per_checker"]) == {
            "counters", "loop_block", "modelcheck", "policy", "races",
            "trace_stages", "wire_drift",
        }
        assert payload["counts"]["new"] == 0
        # Per-rule-family drift rows: every checker reports its finding
        # counts AND wall-clock, so the CI receipt shows which family is
        # growing (the bench-receipt pattern).
        for name, row in payload["per_checker"].items():
            assert set(row) == {"new", "baselined", "suppressed", "ms"}, name
            assert row["ms"] >= 0.0
        # The receipt carries modelcheck's per-spec exploration stats
        # (state counts + wall-time), so budget regressions show in CI.
        spec_rows = payload["stats"]["modelcheck"]["specs"]
        assert len(spec_rows) == 4
        for name, row in spec_rows.items():
            assert row["states"] > 0 and row["complete"], name
            assert row["ms"] >= 0.0

    def test_cli_rejects_unknown_checker(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis", "nonsense"],
            cwd=str(REPO), capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 2

    def test_committed_baseline_is_loadable(self):
        baseline = core.load_baseline()
        assert isinstance(baseline, dict)

    def test_write_baseline_preserves_other_checkers_entries(self, tmp_path):
        """Baselining one checker's findings must not drop another
        checker's audited entries (prune is scoped to the ran checkers'
        rule prefixes)."""
        path = str(tmp_path / "baseline.json")
        core.write_baseline(
            [core.Finding(rule="ITS-L001", file="a.py", line=1,
                          message="m", key="ITS-L001:a.py:f")],
            path=path, prune_prefixes=None,
        )
        # A policy-only rewrite: the loop_block entry must survive.
        core.write_baseline(
            [core.Finding(rule="ITS-P001", file="b.py", line=1,
                          message="m", key="ITS-P001:b.py:g")],
            path=path, prune_prefixes=["ITS-P"],
        )
        entries = core.load_baseline(path)
        assert "ITS-L001:a.py:f" in entries and "ITS-P001:b.py:g" in entries
        # A full rewrite (prune everything) drops stale entries.
        core.write_baseline([], path=path, prune_prefixes=None)
        assert core.load_baseline(path) == {}

    def test_baseline_path_follows_root(self, tmp_path):
        """--root runs must use THAT tree's baseline, not this repo's."""
        ctx = core.Context(str(tmp_path))
        assert ctx.baseline_path.startswith(str(tmp_path))

    def test_policy_keys_anchor_on_enclosing_scope(self, tmp_path):
        """Adding a violation in one function must not re-key another
        function's baseline entry (the unsound-baseline failure mode)."""
        ctx1 = make_tree(tmp_path / "a", {"pkg/mod.py": POLICY_FIXTURE})
        extra = POLICY_FIXTURE.replace(
            "def swallowed(conn):",
            "def earlier(conn):\n"
            "    try:\n"
            "        conn.op()\n"
            "    except InfiniStoreException:\n"
            "        pass\n\n\n"
            "def swallowed(conn):",
        )
        ctx2 = make_tree(tmp_path / "b", {"pkg/mod.py": extra})
        k1 = {f.key for f in policy.scan(ctx1, package_rel="pkg",
                                         p001_exempt=set(), p002_exempt=set())}
        k2 = {f.key for f in policy.scan(ctx2, package_rel="pkg",
                                         p001_exempt=set(), p002_exempt=set())}
        assert k1 <= k2  # old keys intact; the new function adds its own
        assert any("earlier" in k for k in k2 - k1)

    def test_cli_write_baseline_also_writes_json(self, tmp_path):
        out = tmp_path / "analysis.json"
        baseline_file = REPO / "tools" / "analysis" / "baseline.json"
        snapshot = baseline_file.read_text()
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "tools.analysis", "--all",
                 "--json", str(out), "--write-baseline"],
                cwd=str(REPO), capture_output=True, text=True, timeout=120,
            )
            assert proc.returncode == 0, proc.stdout + proc.stderr
            assert json.loads(out.read_text())["counts"]["new"] == 0
        finally:
            baseline_file.write_text(snapshot)  # the test must not mutate the repo


# ---------------------------------------------------------------------------
# policy ITS-P003: migration traffic is BACKGROUND (membership subsystem)
# ---------------------------------------------------------------------------

P003_FIXTURE = '''\
from .wire import PRIORITY_BACKGROUND, PRIORITY_FOREGROUND
import wire


def copy_ok(src, dst, blocks, size, ptr):
    src.read_cache(blocks, size, ptr, priority=PRIORITY_BACKGROUND)
    dst.write_cache(blocks, size, ptr, priority=wire.PRIORITY_BACKGROUND)
    dst.write_cache(blocks, size, ptr, **wire.qos_kwargs(dst, PRIORITY_BACKGROUND))
    src.tcp_read_cache("k", priority=PRIORITY_BACKGROUND)


def copy_untagged(src, blocks, size, ptr):
    src.read_cache(blocks, size, ptr)


def copy_foreground(dst, blocks, size, ptr):
    dst.write_cache(blocks, size, ptr, priority=PRIORITY_FOREGROUND)


def copy_tcp_untagged(src, dst):
    data = src.tcp_read_cache("k")
    dst.tcp_write_cache("k", 0, 16)
'''


class TestPolicyP003:
    def scan(self, tmp_path):
        ctx = make_tree(tmp_path, {"pkg/membership.py": P003_FIXTURE})
        return policy.scan(
            ctx, package_rel="pkg", p001_exempt=set(), p002_exempt=set(),
            p003_files={"pkg/membership.py"},
        )

    def test_untagged_and_foreground_migration_ops_fire(self, tmp_path):
        p3 = [f for f in self.scan(tmp_path) if f.rule == "ITS-P003"]
        ops = sorted(f.message.split("(")[0].split(".")[1].split("()")[0] for f in p3)
        # The three violations: an untagged batched read, a FOREGROUND-tagged
        # batched write, and BOTH untagged single-key tcp ops.
        assert ops == [
            "read_cache", "tcp_read_cache", "tcp_write_cache", "write_cache",
        ]

    def test_background_tagged_calls_pass(self, tmp_path):
        p3 = [f for f in self.scan(tmp_path) if f.rule == "ITS-P003"]
        # Nothing from copy_ok: kwarg, attribute form, and qos_kwargs splat
        # all count as a BACKGROUND tag.
        assert not [f for f in p3 if "copy_ok" in f.key]

    def test_scope_is_membership_only(self, tmp_path):
        ctx = make_tree(tmp_path, {"pkg/other.py": P003_FIXTURE})
        found = policy.scan(
            ctx, package_rel="pkg", p001_exempt=set(), p002_exempt=set(),
            p003_files={"pkg/membership.py"},
        )
        assert not [f for f in found if f.rule == "ITS-P003"]

    def test_real_membership_is_background_tagged(self):
        ctx = core.Context(str(REPO))
        found = [f for f in policy.scan(ctx) if f.rule == "ITS-P003"]
        assert found == []

    def test_tiering_is_in_p003_scope(self, tmp_path):
        # The tiered capacity plane's copy engine (docs/tiering.md) is
        # migration traffic too: an untagged op in tiering.py fires.
        ctx = make_tree(tmp_path, {"pkg/tiering.py": P003_FIXTURE})
        found = policy.scan(
            ctx, package_rel="pkg", p001_exempt=set(), p002_exempt=set(),
            p003_files=policy.P003_FILES | {"pkg/tiering.py"},
        )
        assert [f for f in found if f.rule == "ITS-P003"]
        assert "infinistore_tpu/tiering.py" in policy.P003_FILES


# ---------------------------------------------------------------------------
# counters ITS-C005: membership status keys reach the /metrics exporter
# ---------------------------------------------------------------------------

C005_MEMBERSHIP = '''\
class Membership:
    def status(self):
        return {"membership_epoch": 1, "membership_settled": 1}


class Resharder:
    def __init__(self):
        self._c = {"reshard_moved_roots": 0, "reshard_debt_roots": 0}

    def progress(self):
        out = dict(self._c)
        out["reshard_active"] = 0
        return out


class DurableLog:
    def status(self):
        return {"journal_records": 0, "journal_replay_torn": 0}
'''

C005_MANAGE_OK = '''\
def _membership_prometheus_lines(ms):
    return [
        f"a {ms['membership_epoch']}",
        f"b {ms['membership_settled']}",
        f"c {ms['reshard_moved_roots']}",
        f"d {ms['reshard_debt_roots']}",
        f"e {ms['reshard_active']}",
        f"f {ms.get('journal_records', 0)}",
        f"g {ms.get('journal_replay_torn', 0)}",
    ]

route = "/membership"   # served from membership_status
route2 = "/bootstrap"   # served from bootstrap_payload
'''


class TestCountersMembership:
    def scan(self, tmp_path, manage_src, membership_src=C005_MEMBERSHIP):
        ctx = make_tree(tmp_path, {
            "manage.py": manage_src, "membership.py": membership_src,
        })
        return counters._scan_membership(ctx, "manage.py", "membership.py")

    def test_complete_exporter_is_clean(self, tmp_path):
        assert self.scan(tmp_path, C005_MANAGE_OK) == []

    def test_unexported_status_key_fires(self, tmp_path):
        manage = C005_MANAGE_OK.replace(
            "        f\"d {ms['reshard_debt_roots']}\",\n", "")
        found = self.scan(tmp_path, manage)
        assert any(
            f.rule == "ITS-C005" and f.key.endswith("reshard_debt_roots")
            for f in found
        )

    def test_stale_exporter_key_fires(self, tmp_path):
        manage = C005_MANAGE_OK.replace(
            "reshard_debt_roots", "reshard_gone_key")
        found = self.scan(tmp_path, manage)
        keys = {f.key for f in found}
        assert any(k.endswith("stale:reshard_gone_key") for k in keys)
        assert any(k.endswith(":reshard_debt_roots") for k in keys)

    def test_missing_membership_route_fires(self, tmp_path):
        manage = C005_MANAGE_OK.replace('"/membership"', '"/nope"')
        found = self.scan(tmp_path, manage)
        assert any(f.key.endswith("membership-route") for f in found)

    def test_unexported_journal_key_fires(self, tmp_path):
        manage = C005_MANAGE_OK.replace(
            "        f\"g {ms.get('journal_replay_torn', 0)}\",\n", "")
        found = self.scan(tmp_path, manage)
        assert any(
            f.rule == "ITS-C005" and f.key.endswith("journal_replay_torn")
            for f in found
        )

    def test_missing_bootstrap_route_fires(self, tmp_path):
        manage = C005_MANAGE_OK.replace("bootstrap_payload", "nothing")
        found = self.scan(tmp_path, manage)
        assert any(f.key.endswith("bootstrap-route") for f in found)

    def test_real_membership_counters_are_clean(self):
        ctx = core.Context(str(REPO))
        found = [f for f in counters.scan(ctx) if f.rule == "ITS-C005"]
        assert found == []


# ---------------------------------------------------------------------------
# counters ITS-C006: fleet-telemetry vocabulary lockstep
# ---------------------------------------------------------------------------

C006_TELEMETRY = '''\
EVENT_KINDS = (
    "breaker_open",
    "membership_epoch",
)


class SloEngine:
    def status(self):
        return {
            "slo_availability": 1.0,
            "slo_burn_rate_max": 0.0,
            "verdict": "ok",
        }


class GossipAgent:
    def status(self):
        return {"gossip_rounds": 0, "gossip_merges_in": 0}


def emit(kind, **attrs):
    pass


emit("membership_epoch")
'''

C006_PRODUCER = '''\
from . import telemetry

telemetry.emit("breaker_open", member="m0")
'''

C006_MANAGE_OK = '''\
def _slo_prometheus_lines(slo):
    return [
        f"a {slo['slo_availability']}",
        f"b {slo['slo_burn_rate_max']}",
    ]


def _gossip_prometheus_lines(gs):
    return [
        f"a {gs['gossip_rounds']}",
        f"b {gs['gossip_merges_in']}",
    ]

route_a = "/slo"      # served from telemetry.slo_engine
route_b = "/events"   # served from telemetry.get_journal
route_c = "/gossip"   # served through cluster.merge_remote_view
served = (slo_engine, get_journal, merge_remote_view)
'''

C006_DOCS = (
    "table: breaker_open membership_epoch slo_availability "
    "slo_burn_rate_max gossip_rounds gossip_merges_in\n"
)


class TestCountersTelemetry:
    def scan(self, tmp_path, manage_src=C006_MANAGE_OK,
             telemetry_src=C006_TELEMETRY, producer_src=C006_PRODUCER,
             docs=C006_DOCS):
        ctx = make_tree(tmp_path, {
            "manage.py": manage_src,
            "pkg/telemetry.py": telemetry_src,
            "pkg/producer.py": producer_src,
            "docs/obs.md": docs,
        })
        return counters._scan_telemetry(
            ctx, "manage.py", telemetry_rel="pkg/telemetry.py",
            docs_rel="docs/obs.md", package_rel="pkg",
        )

    def test_complete_vocabulary_is_clean(self, tmp_path):
        assert self.scan(tmp_path) == []

    def test_unexported_slo_key_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace(
            "        f\"b {slo['slo_burn_rate_max']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.rule == "ITS-C006" and f.key.endswith("slo_burn_rate_max")
            for f in found
        )

    def test_stale_slo_exporter_key_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace(
            "slo_burn_rate_max", "slo_gone_key")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("stale:slo_gone_key") for k in keys)
        assert any(k.endswith(":slo_burn_rate_max") for k in keys)

    def test_undocumented_slo_key_fires(self, tmp_path):
        docs = C006_DOCS.replace("slo_availability", "")
        found = self.scan(tmp_path, docs=docs)
        assert any(
            f.key.endswith("undocumented:slo_availability") for f in found
        )

    def test_unknown_event_kind_fires_at_producer(self, tmp_path):
        producer = C006_PRODUCER.replace("breaker_open", "made_up_kind")
        found = self.scan(tmp_path, producer_src=producer)
        hits = [f for f in found if "unknown-kind:made_up_kind" in f.key]
        assert hits and hits[0].file == "pkg/producer.py"
        # ...and breaker_open is now dead vocabulary (no producer left).
        assert any(f.key.endswith("dead:breaker_open") for f in found)

    def test_undocumented_event_kind_fires(self, tmp_path):
        docs = C006_DOCS.replace("membership_epoch", "")
        found = self.scan(tmp_path, docs=docs)
        assert any(
            f.key.endswith("undocumented:membership_epoch") for f in found
        )

    def test_missing_slo_route_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace('"/slo"', '"/nope"')
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("slo-route") for f in found)

    def test_missing_events_route_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace("get_journal", "no_journal")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("events-route") for f in found)

    def test_unexported_gossip_key_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace(
            "        f\"b {gs['gossip_merges_in']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.rule == "ITS-C006" and f.key.endswith("gossip:gossip_merges_in")
            for f in found
        )

    def test_stale_gossip_exporter_key_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace("gossip_merges_in", "gossip_gone")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("gossip-stale:gossip_gone") for k in keys)
        assert any(k.endswith("gossip:gossip_merges_in") for k in keys)

    def test_undocumented_gossip_key_fires(self, tmp_path):
        docs = C006_DOCS.replace("gossip_rounds", "")
        found = self.scan(tmp_path, docs=docs)
        assert any(
            f.key.endswith("undocumented:gossip_rounds") for f in found
        )

    def test_missing_gossip_route_fires(self, tmp_path):
        manage = C006_MANAGE_OK.replace("merge_remote_view", "nothing")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("gossip-route") for f in found)

    def test_real_telemetry_vocabulary_is_clean(self):
        ctx = core.Context(str(REPO))
        found = [f for f in counters.scan(ctx) if f.rule == "ITS-C006"]
        assert found == []


# ---------------------------------------------------------------------------
# counters ITS-C007: tiered-capacity-plane vocabulary lockstep
# ---------------------------------------------------------------------------

C007_TIERING = '''\
class TierManager:
    def __init__(self):
        self._c = {"tier_demotions": 0, "tier_cold_hits": 0}

    def status(self):
        return {**self._c, "tier_cold_members": 1, "tier_promote_backlog": 0}
'''

C007_MANAGE_OK = '''\
def _tier_prometheus_lines(ts):
    return [
        f"a {ts['tier_demotions']}",
        f"b {ts['tier_cold_hits']}",
        f"c {ts['tier_cold_members']}",
        f"d {ts['tier_promote_backlog']}",
    ]

route = "/tiers"   # served from the cluster's tiering status
'''

C007_DOCS = (
    "| tier_demotions | tier_cold_hits | tier_cold_members | "
    "tier_promote_backlog |\n"
)


class TestCountersTiering:
    def scan(self, tmp_path, manage_src=C007_MANAGE_OK,
             tiering_src=C007_TIERING, docs=C007_DOCS):
        ctx = make_tree(tmp_path, {
            "manage.py": manage_src,
            "tiering.py": tiering_src,
            "docs/tiering.md": docs,
        })
        return counters._scan_tiering(
            ctx, "manage.py", tiering_rel="tiering.py",
            docs_rel="docs/tiering.md",
        )

    def test_complete_vocabulary_is_clean(self, tmp_path):
        assert self.scan(tmp_path) == []

    def test_unexported_tier_key_fires(self, tmp_path):
        manage = C007_MANAGE_OK.replace(
            "        f\"b {ts['tier_cold_hits']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.rule == "ITS-C007" and f.key.endswith(":tier_cold_hits")
            for f in found
        )

    def test_unexported_init_ledger_key_fires(self, tmp_path):
        # Keys living only in the __init__ counter dict (not the status
        # literal) are vocabulary too — the C005 Resharder.__init__ rule.
        manage = C007_MANAGE_OK.replace(
            "        f\"a {ts['tier_demotions']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith(":tier_demotions") for f in found)

    def test_stale_exporter_key_fires(self, tmp_path):
        manage = C007_MANAGE_OK.replace("tier_cold_hits", "tier_gone_key")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("stale:tier_gone_key") for k in keys)
        assert any(k.endswith(":tier_cold_hits") for k in keys)

    def test_undocumented_tier_key_fires(self, tmp_path):
        docs = C007_DOCS.replace("tier_cold_members", "")
        found = self.scan(tmp_path, docs=docs)
        assert any(
            f.key.endswith("undocumented:tier_cold_members") for f in found
        )

    def test_missing_tiers_route_fires(self, tmp_path):
        manage = C007_MANAGE_OK.replace('"/tiers"', '"/nope"').replace(
            "tiering", "nothing")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("tiers-route") for f in found)

    def test_real_tiering_vocabulary_is_clean(self):
        ctx = core.Context(str(REPO))
        found = [f for f in counters.scan(ctx) if f.rule == "ITS-C007"]
        assert found == []


# ---------------------------------------------------------------------------
# counters ITS-C008: continuous-profiling / metrics-history lockstep
# ---------------------------------------------------------------------------

C008_PROFILING = '''\
class SamplingProfiler:
    def status(self):
        return {"prof_samples": 0, "prof_tagged_samples": 0, "prof_hz": 101.0}
'''

C008_TELEMETRY = '''\
class MetricsHistory:
    def status(self):
        return {"timeseries_series": 0, "timeseries_anomalies": 0}
'''

C008_MANAGE_OK = '''\
def _prof_prometheus_lines(ps):
    return [
        f"a {ps['prof_samples']}",
        f"b {ps['prof_tagged_samples']}",
        f"c {ps['prof_hz']}",
    ]


def _timeseries_prometheus_lines(ts):
    return [
        f"a {ts['timeseries_series']}",
        f"b {ts['timeseries_anomalies']}",
    ]

routes = ("/profile", "/timeseries")   # profiling + history surfaces
'''

C008_DOCS = (
    "| prof_samples | prof_tagged_samples | prof_hz | "
    "timeseries_series | timeseries_anomalies |\n"
)


class TestCountersProfiling:
    def scan(self, tmp_path, manage_src=C008_MANAGE_OK,
             profiling_src=C008_PROFILING, telemetry_src=C008_TELEMETRY,
             docs=C008_DOCS):
        ctx = make_tree(tmp_path, {
            "manage.py": manage_src,
            "profiling.py": profiling_src,
            "telemetry.py": telemetry_src,
            "docs/observability.md": docs,
        })
        return counters._scan_profiling(
            ctx, "manage.py", profiling_rel="profiling.py",
            telemetry_rel="telemetry.py", docs_rel="docs/observability.md",
        )

    def test_complete_vocabulary_is_clean(self, tmp_path):
        assert self.scan(tmp_path) == []

    def test_unexported_prof_key_fires(self, tmp_path):
        manage = C008_MANAGE_OK.replace(
            "        f\"b {ps['prof_tagged_samples']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.rule == "ITS-C008"
            and f.key.endswith("prof:prof_tagged_samples")
            for f in found
        )

    def test_stale_prof_exporter_key_fires(self, tmp_path):
        manage = C008_MANAGE_OK.replace("prof_tagged_samples", "prof_gone")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("prof-stale:prof_gone") for k in keys)
        assert any(k.endswith("prof:prof_tagged_samples") for k in keys)

    def test_unexported_timeseries_key_fires(self, tmp_path):
        manage = C008_MANAGE_OK.replace(
            "        f\"b {ts['timeseries_anomalies']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.key.endswith("timeseries:timeseries_anomalies") for f in found
        )

    def test_stale_timeseries_exporter_key_fires(self, tmp_path):
        manage = C008_MANAGE_OK.replace("timeseries_anomalies",
                                        "timeseries_gone")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("timeseries-stale:timeseries_gone")
                   for k in keys)

    def test_undocumented_keys_fire(self, tmp_path):
        docs = C008_DOCS.replace("prof_hz", "").replace(
            "timeseries_series", "")
        keys = {f.key for f in self.scan(tmp_path, docs=docs)}
        assert any(k.endswith("undocumented:prof_hz") for k in keys)
        assert any(k.endswith("undocumented:timeseries_series") for k in keys)

    def test_missing_profile_route_fires(self, tmp_path):
        manage = C008_MANAGE_OK.replace('"/profile"', '"/nope"')
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("profile-route") for f in found)

    def test_missing_timeseries_route_fires(self, tmp_path):
        manage = C008_MANAGE_OK.replace('"/timeseries"', '"/nope"').replace(
            "history", "nothing")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("timeseries-route") for f in found)

    def test_real_profiling_vocabulary_is_clean(self):
        ctx = core.Context(str(REPO))
        found = [f for f in counters.scan(ctx) if f.rule == "ITS-C008"]
        assert found == []


# ---------------------------------------------------------------------------
# trace_stages (ITS-T*)
# ---------------------------------------------------------------------------

T_TRACING = '''\
STAGES = (
    "enqueue",
    "submit",
    "server_recv",
)

SERVER_TICK_STAGES = {
    "recv_us": "server_recv",
}
'''

T_PRODUCER = '''\
def run(span, tracing):
    span.stage("enqueue")
    with tracing.trace_op("op", stage="submit"):
        pass
'''

T_MANAGE = '''\
def _trace_payload(stats):
    return {"stages": list(STAGES)}


def route(path):
    if path == "/trace":
        return _trace_payload({})
'''

T_CPP = '''\
void Server::stats_json() {
    out += ",\\"recv_us\\":" + std::to_string(t.recv_us);
}
'''


class TestTraceStages:
    def _tree(self, tmp_path, **overrides):
        files = {
            "infinistore_tpu/tracing.py": T_TRACING,
            "infinistore_tpu/prod.py": T_PRODUCER,
            "infinistore_tpu/server.py": T_MANAGE,
            "docs/observability.md": "stages: enqueue submit server_recv\n",
            "native/src/server.cpp": T_CPP,
        }
        files.update(overrides)
        return make_tree(tmp_path, files)

    def test_clean_fixture(self, tmp_path):
        assert trace_stages.scan(self._tree(tmp_path)) == []

    def test_unknown_producer_stage_fires(self, tmp_path):
        ctx = self._tree(tmp_path, **{
            "infinistore_tpu/prod.py":
                T_PRODUCER.replace('"enqueue"', '"mystery_stage"'),
        })
        found = trace_stages.scan(ctx)
        assert any(
            f.rule == "ITS-T001" and "mystery_stage" in f.key for f in found
        )

    def test_trace_op_stage_kwarg_is_scanned(self, tmp_path):
        ctx = self._tree(tmp_path, **{
            "infinistore_tpu/prod.py":
                T_PRODUCER.replace('stage="submit"', 'stage="kw_rogue"'),
        })
        found = trace_stages.scan(ctx)
        assert any(
            f.rule == "ITS-T001" and "kw_rogue" in f.key for f in found
        )

    def test_undocumented_stage_fires(self, tmp_path):
        ctx = self._tree(
            tmp_path, **{"docs/observability.md": "stages: enqueue submit\n"}
        )
        found = trace_stages.scan(ctx)
        assert any(
            f.rule == "ITS-T002" and f.key.endswith("server_recv")
            for f in found
        )

    def test_missing_trace_route_fires(self, tmp_path):
        ctx = self._tree(tmp_path, **{
            "infinistore_tpu/server.py": T_MANAGE.replace('"/trace"', '"/nope"'),
        })
        found = trace_stages.scan(ctx)
        assert any(f.key.endswith("trace-route") for f in found)

    def test_tick_map_outside_vocabulary_fires(self, tmp_path):
        ctx = self._tree(tmp_path, **{
            "infinistore_tpu/tracing.py":
                T_TRACING.replace('"recv_us": "server_recv"',
                                  '"recv_us": "not_a_stage"'),
        })
        found = trace_stages.scan(ctx)
        assert any(
            f.rule == "ITS-T003" and f.key.endswith("tick:recv_us")
            for f in found
        )

    def test_native_tick_field_missing_fires(self, tmp_path):
        ctx = self._tree(
            tmp_path, **{"native/src/server.cpp": "void nothing() {}\n"}
        )
        found = trace_stages.scan(ctx)
        assert any(
            f.rule == "ITS-T003" and f.key.endswith("native:recv_us")
            for f in found
        )

    def test_dead_vocabulary_fires(self, tmp_path):
        ctx = self._tree(tmp_path, **{
            "infinistore_tpu/tracing.py": T_TRACING.replace(
                '"submit",', '"submit",\n    "never_stamped",'
            ),
            "docs/observability.md":
                "stages: enqueue submit server_recv never_stamped\n",
        })
        found = trace_stages.scan(ctx)
        assert any(
            f.rule == "ITS-T004" and f.key.endswith("dead:never_stamped")
            for f in found
        )

    def test_real_tree_is_clean_modulo_docs(self):
        """The real repo's producers, tick map, /trace schema and native
        emitter are in lockstep (T002 pends only on docs/observability.md
        existing — covered by the clean-suite acceptance test)."""
        found = [
            f for f in trace_stages.scan(core.Context(str(REPO)))
            if f.rule != "ITS-T002"
        ]
        assert found == []

    def test_real_vocabulary_inventory(self):
        stages, tick_map = trace_stages.recorder_stages(core.Context(str(REPO)))
        assert stages[0] == "enqueue" and "stripe_claim" in stages
        assert set(tick_map.values()) == {
            "server_recv", "first_slice", "last_slice",
        }


# ---------------------------------------------------------------------------
# races (ITS-R*): cross-thread shared-state discipline
# ---------------------------------------------------------------------------

def mutated_pkg(tmp_path, rel, sub=None, append=""):
    """Fixture tree holding a copy of ONE real package module with a
    targeted mutation (the wire-drift pattern: anchors must exist, so a
    refactor that moves them fails loudly instead of testing nothing)."""
    src = (REPO / rel).read_text()
    if sub is not None:
        old, new = sub
        assert old in src, f"fixture anchor missing from {rel}: {old!r}"
        src = src.replace(old, new, 1)
    src += append
    return make_tree(tmp_path, {rel: src})


class TestRaces:
    def test_real_tree_is_clean_after_suppressions(self):
        ctx = core.Context(str(REPO))
        found = races.scan(ctx)
        assert not [f for f in found if not ctx.suppressed(f)]

    def test_registry_classifies_the_daemon_owners(self):
        """The shared-state registry must see the known worker-thread
        owners (a regression that stops classifying them would also stop
        finding anything)."""
        ctx = core.Context(str(REPO))
        names = {sc.cls.name for sc in races.build_registry(ctx)}
        for expected in ("TierManager", "Resharder", "FleetScraper",
                         "GossipAgent", "Membership", "ClusterKVConnector",
                         "EventJournal", "DurableLog"):
            assert expected in names, expected

    # -- R001: guard discipline over mutated REAL sources -------------------

    def test_removed_guard_annotation_fires(self, tmp_path):
        """Deleting the `guard[_c: _stats_lock]` declaration re-exposes
        the confirmed PR 13 race: TierManager._c is written on both sides
        with no declared guard."""
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/tiering.py",
            sub=("# its: guard[_c: _stats_lock]", "#"),
        )
        found = races.scan(ctx, docs=False)
        assert any(
            f.rule == "ITS-R001" and f.key.endswith("TierManager._c")
            for f in found
        )

    def test_access_outside_declared_guard_fires(self, tmp_path):
        """Stripping the lock out of _bump (the declared guard stays)
        must fire the dominance check on the bare write."""
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/tiering.py",
            sub=(
                "        with self._stats_lock:\n            self._c[key] += n",
                "        if True:\n            self._c[key] += n",
            ),
        )
        found = races.scan(ctx, docs=False)
        hits = [
            f for f in found
            if f.rule == "ITS-R001" and "TierManager._c" in f.key
            and "_bump" in f.key
        ]
        assert hits and "outside its declared guard" in hits[0].message

    def test_single_writer_violation_fires(self, tmp_path):
        """A single_writer ledger written from BOTH sides is a lie: seed a
        loop-side write into Resharder (declared single_writer) and the
        checker must fire."""
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/membership.py",
            sub=(
                "    def kick(self):\n        \"\"\"Wake the reconciler",
                "    def kick(self):\n"
                "        self._c[\"reshard_passes\"] += 0  # seeded\n"
                "        \"\"\"Wake the reconciler",
            ),
        )
        found = races.scan(ctx, docs=False)
        assert any(
            f.rule == "ITS-R001" and "Resharder._c" in f.key
            and "single-writer" in f.key
            for f in found
        )

    # -- R002: lock-order cycles --------------------------------------------

    def test_inverted_lock_order_fires(self, tmp_path):
        """add_member nests _cat_lock under _admin_lock; appending one
        function taking them in the OPPOSITE order closes a deadlock
        cycle the graph must report."""
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/cluster.py",
            append=(
                "\n\ndef _seeded_inversion(self):\n"
                "    with self._cat_lock:\n"
                "        with self._admin_lock:\n"
                "            pass\n"
            ),
        )
        found = races.scan(ctx, docs=False)
        cycles = [f for f in found if f.rule == "ITS-R002" and "cycle" in f.key]
        assert cycles and any(
            "_admin_lock" in f.message and "_cat_lock" in f.message
            for f in cycles
        )

    def test_reacquiring_a_plain_lock_fires(self, tmp_path):
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/cluster.py",
            append=(
                "\n\ndef _seeded_reacquire(self):\n"
                "    with self._cat_lock:\n"
                "        with self._cat_lock:\n"
                "            pass\n"
            ),
        )
        found = races.scan(ctx, docs=False)
        assert any(
            f.rule == "ITS-R002" and "reacquire" in f.key for f in found
        )

    def test_real_lock_order_graph_is_acyclic(self):
        idx = races.PackageIndex(core.Context(str(REPO)))
        edges = races.lock_order_edges(idx)
        assert races.find_cycles(edges) == []
        # The blessed journal-compaction direction is in the graph (the
        # `its: acquires[...]` summary; the tracer validates it live).
        assert ("DurableLog._lock", "ClusterKVConnector._cat_lock") in edges

    # -- R003: journal/emit outside engine locks -----------------------------

    def test_journal_under_catalog_lock_fires(self, tmp_path):
        """Moving catalog_add_holder's journal append INSIDE the catalog
        lock breaks the emit-outside-lock discipline structurally."""
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/cluster.py",
            sub=(
                "            rec.holders[member_id] = "
                "max(rec.holders.get(member_id, 0), blocks)\n",
                "            rec.holders[member_id] = "
                "max(rec.holders.get(member_id, 0), blocks)\n"
                "            self._journal_append({\"k\": \"seeded\"})\n",
            ),
        )
        found = races.scan(ctx, docs=False)
        hits = [
            f for f in found
            if f.rule == "ITS-R003" and "catalog_add_holder" in f.key
        ]
        assert hits and "_cat_lock" in hits[0].message

    def test_real_tree_honors_emit_discipline(self):
        ctx = core.Context(str(REPO))
        idx = races.PackageIndex(ctx)
        assert races.check_r003(ctx, idx) == []

    # -- R004: predicate-looped condition waits ------------------------------

    def test_bare_if_gated_wait_fires(self, tmp_path):
        """Regressing TierManager._run to its pre-PR-13 `if`-gated wait
        (acting on a possibly-spurious wake) must fire."""
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/tiering.py",
            sub=(
                "                while not self._dirty and not self._stop:\n"
                "                    if not self._cv.wait(timeout=self.interval_s):\n"
                "                        break",
                "                if not self._dirty and not self._stop:\n"
                "                    self._cv.wait(timeout=self.interval_s)",
            ),
        )
        found = races.scan(ctx, docs=False)
        assert any(
            f.rule == "ITS-R004" and "TierManager._cv" in f.message
            for f in found
        )

    def test_wait_for_and_event_waits_are_exempt(self, tmp_path):
        ctx = make_tree(tmp_path, {"infinistore_tpu/m.py": (
            "import threading\n\n\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "        self._ev = threading.Event()\n"
            "        self._thread = None\n\n"
            "    def start(self):\n"
            "        self._thread = threading.Thread(target=self._run)\n\n"
            "    def _run(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait_for(lambda: True)\n"
            "        self._ev.wait(1.0)\n"
        )})
        found = races.scan(ctx, docs=False)
        assert not [f for f in found if f.rule == "ITS-R004"]

    # -- R005: concurrency-model docs lockstep -------------------------------

    def test_real_docs_table_is_in_lockstep(self):
        ctx = core.Context(str(REPO))
        idx = races.PackageIndex(ctx)
        assert races.check_r005(ctx, idx) == []

    def test_missing_docs_row_fires(self, tmp_path):
        src = (REPO / "infinistore_tpu/tiering.py").read_text()
        ctx = make_tree(tmp_path, {
            "infinistore_tpu/tiering.py": src,
            "docs/design.md": "# design\n\nno table here\n",
        })
        found = races.check_r005(ctx, races.PackageIndex(ctx))
        assert any(
            f.rule == "ITS-R005" and "TierManager._c" in f.key for f in found
        )

    def test_stale_docs_row_fires(self, tmp_path):
        ctx = core.Context(str(REPO))
        doc = (REPO / "docs/design.md").read_text() + (
            "\n| `GhostClass._gone` | `_lock` | all accesses | "
            "`infinistore_tpu/nope.py` |\n"
        )
        ctx2 = make_tree(tmp_path, {"docs/design.md": doc})
        # Same package, doctored docs: copy the package reference files in.
        import shutil
        shutil.copytree(
            REPO / "infinistore_tpu", tmp_path / "infinistore_tpu",
            ignore=shutil.ignore_patterns("__pycache__", "_native", "*.so"),
        )
        found = races.check_r005(ctx2, races.PackageIndex(ctx2))
        assert any(
            f.rule == "ITS-R005" and "stale" in f.key and "GhostClass" in f.key
            for f in found
        )
        del ctx

    # -- framework plumbing ---------------------------------------------------

    def test_requires_contract_is_honored(self, tmp_path):
        """`# its: requires[lock]` marks a caller-holds contract: the
        method's accesses count as guarded."""
        ctx = make_tree(tmp_path, {"infinistore_tpu/m.py": (
            "import threading\n\n\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        # its: guard[state: _lock]\n"
            "        self.state = 0\n"
            "        self._thread = None\n\n"
            "    def start(self):\n"
            "        self._thread = threading.Thread(target=self._run)\n\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._step()\n\n"
            "    def _step(self):  # its: requires[_lock]\n"
            "        self.state += 1\n\n"
            "    def read(self):\n"
            "        with self._lock:\n"
            "            return self.state\n"
        )})
        found = races.scan(ctx, docs=False)
        assert not [f for f in found if f.rule == "ITS-R001"]

    def test_inline_allow_suppresses_races_findings(self, tmp_path):
        ctx = mutated_pkg(
            tmp_path, "infinistore_tpu/tiering.py",
            sub=(
                "                while not self._dirty and not self._stop:\n"
                "                    if not self._cv.wait(timeout=self.interval_s):\n"
                "                        break",
                "                if not self._dirty and not self._stop:\n"
                "                    self._cv.wait(timeout=self.interval_s)"
                "  # its: allow[ITS-R004]",
            ),
        )
        found = races.scan(ctx, docs=False)
        hits = [f for f in found if f.rule == "ITS-R004"]
        assert hits and ctx.suppressed(hits[0])


# ---------------------------------------------------------------------------
# policy ITS-P004: layer-streaming saves name their QoS class at the source
# ---------------------------------------------------------------------------

P004_FIXTURE = '''\
from .wire import PRIORITY_FOREGROUND
import wire


def ship_named(conn, prompt, layer, kv, ids):
    conn.stage_layer_save(prompt, layer, kv, ids,
                          priority=PRIORITY_FOREGROUND)
    conn.stage_layer_save(prompt, layer, kv, ids,
                          priority=wire.PRIORITY_BACKGROUND)


def ship_default(conn, prompt, layer, kv, ids):
    conn.stage_layer_save(prompt, layer, kv, ids)


def ship_opaque(conn, prompt, layer, kv, ids, prio):
    conn.stage_layer_save(prompt, layer, kv, ids, priority=prio)
'''


class TestPolicyP004:
    def scan(self, tmp_path, rel="pkg/disagg.py"):
        ctx = make_tree(tmp_path, {rel: P004_FIXTURE})
        return policy.scan(
            ctx, package_rel="pkg", p001_exempt=set(), p002_exempt=set(),
            p003_files=set(), p004_files={"pkg/disagg.py", "pkg/vllm_v1.py"},
        )

    def test_default_and_opaque_priority_fire(self, tmp_path):
        p4 = [f for f in self.scan(tmp_path) if f.rule == "ITS-P004"]
        # The inherited-default call AND the opaque-variable call fire;
        # ITS-P002's "any explicit kwarg" is not enough here.
        scopes = sorted(f.key.split(":")[2] for f in p4)
        assert scopes == ["ship_default", "ship_opaque"]

    def test_literal_class_names_pass(self, tmp_path):
        p4 = [f for f in self.scan(tmp_path) if f.rule == "ITS-P004"]
        assert not [f for f in p4 if "ship_named" in f.key]

    def test_scope_is_producer_files_only(self, tmp_path):
        # Connector-layer forwards (priority=priority) live outside the
        # producer files and must not fire.
        ctx = make_tree(tmp_path, {"pkg/connector.py": P004_FIXTURE})
        found = policy.scan(
            ctx, package_rel="pkg", p001_exempt=set(), p002_exempt=set(),
            p003_files=set(), p004_files={"pkg/disagg.py"},
        )
        assert not [f for f in found if f.rule == "ITS-P004"]

    def test_vllm_is_in_p004_scope(self, tmp_path):
        found = self.scan(tmp_path, rel="pkg/vllm_v1.py")
        assert [f for f in found if f.rule == "ITS-P004"]
        assert "infinistore_tpu/vllm_v1.py" in policy.P004_FILES
        assert "infinistore_tpu/disagg.py" in policy.P004_FILES

    def test_real_producers_name_their_class(self):
        ctx = core.Context(str(REPO))
        found = [f for f in policy.scan(ctx) if f.rule == "ITS-P004"]
        assert found == []


# ---------------------------------------------------------------------------
# counters ITS-C009: disaggregated-handoff vocabulary lockstep
# ---------------------------------------------------------------------------

C009_DISAGG = '''\
class DisaggCounters:
    def __init__(self):
        self._c = {"disagg_handoffs": 0, "disagg_wrong_bytes": 0}

    def status(self):
        c = self._c
        return {**c, "disagg_overlap_layers": 1, "disagg_watermark_stalls": 0}
'''

C009_MANAGE_OK = '''\
def _disagg_prometheus_lines(ds):
    return [
        f"a {ds['disagg_handoffs']}",
        f"b {ds['disagg_wrong_bytes']}",
        f"c {ds['disagg_overlap_layers']}",
        f"d {ds['disagg_watermark_stalls']}",
    ]

route = "/disagg"   # served from _disagg_status()
'''

C009_DOCS = (
    "| disagg_handoffs | disagg_wrong_bytes | disagg_overlap_layers | "
    "disagg_watermark_stalls |\n"
)


class TestCountersDisagg:
    def scan(self, tmp_path, manage_src=C009_MANAGE_OK,
             disagg_src=C009_DISAGG, docs=C009_DOCS):
        ctx = make_tree(tmp_path, {
            "manage.py": manage_src,
            "disagg.py": disagg_src,
            "docs/disaggregation.md": docs,
        })
        return counters._scan_disagg(
            ctx, "manage.py", disagg_rel="disagg.py",
            docs_rel="docs/disaggregation.md",
        )

    def test_complete_vocabulary_is_clean(self, tmp_path):
        assert self.scan(tmp_path) == []

    def test_unexported_status_key_fires(self, tmp_path):
        manage = C009_MANAGE_OK.replace(
            "        f\"c {ds['disagg_overlap_layers']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.rule == "ITS-C009" and f.key.endswith(":disagg_overlap_layers")
            for f in found
        )

    def test_unexported_init_ledger_key_fires(self, tmp_path):
        # Keys living only in the __init__ counter dict are vocabulary too.
        manage = C009_MANAGE_OK.replace(
            "        f\"a {ds['disagg_handoffs']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith(":disagg_handoffs") for f in found)

    def test_stale_exporter_key_fires(self, tmp_path):
        manage = C009_MANAGE_OK.replace("disagg_wrong_bytes",
                                        "disagg_gone_key")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("stale:disagg_gone_key") for k in keys)
        assert any(k.endswith(":disagg_wrong_bytes") for k in keys)

    def test_undocumented_disagg_key_fires(self, tmp_path):
        docs = C009_DOCS.replace("disagg_watermark_stalls", "")
        found = self.scan(tmp_path, docs=docs)
        assert any(
            f.key.endswith("undocumented:disagg_watermark_stalls")
            for f in found
        )

    def test_missing_disagg_route_fires(self, tmp_path):
        manage = C009_MANAGE_OK.replace('"/disagg"', '"/nope"').replace(
            "_disagg_status", "nothing")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("disagg-route") for f in found)

    def test_real_disagg_vocabulary_is_clean(self):
        ctx = core.Context(str(REPO))
        found = [f for f in counters.scan(ctx) if f.rule == "ITS-C009"]
        assert found == []


# ---------------------------------------------------------------------------
# counters ITS-C010: skew-aware wave-policy vocabulary lockstep
# ---------------------------------------------------------------------------

C010_ENGINE = '''\
class WaveCounters:
    def __init__(self):
        self._c = {"engine_wave_deferrals": 0, "engine_wave_policy_waves": 0}

    def status(self):
        c = self._c
        return {**c, "engine_wave_aging_escapes": 1,
                "engine_wave_defer_age_us_p99": 0.0}
'''

C010_MANAGE_OK = '''\
def _engine_wave_prometheus_lines(ws):
    return [
        f"a {ws['engine_wave_deferrals']}",
        f"b {ws['engine_wave_policy_waves']}",
        f"c {ws['engine_wave_aging_escapes']}",
        f"d {ws['engine_wave_defer_age_us_p99']}",
    ]

route = "/wave"   # served from _engine_wave_status()
'''

C010_DOCS = (
    "| engine_wave_deferrals | engine_wave_policy_waves | "
    "engine_wave_aging_escapes | engine_wave_defer_age_us_p99 |\n"
)


class TestCountersEngineWave:
    def scan(self, tmp_path, manage_src=C010_MANAGE_OK,
             engine_src=C010_ENGINE, docs=C010_DOCS):
        ctx = make_tree(tmp_path, {
            "manage.py": manage_src,
            "engine.py": engine_src,
            "docs/serving_load.md": docs,
        })
        return counters._scan_engine_wave(
            ctx, "manage.py", engine_rel="engine.py",
            docs_rel="docs/serving_load.md",
        )

    def test_complete_vocabulary_is_clean(self, tmp_path):
        assert self.scan(tmp_path) == []

    def test_unexported_status_key_fires(self, tmp_path):
        manage = C010_MANAGE_OK.replace(
            "        f\"c {ws['engine_wave_aging_escapes']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(
            f.rule == "ITS-C010"
            and f.key.endswith(":engine_wave_aging_escapes")
            for f in found
        )

    def test_unexported_init_ledger_key_fires(self, tmp_path):
        # Keys living only in the __init__ counter dict are vocabulary too.
        manage = C010_MANAGE_OK.replace(
            "        f\"a {ws['engine_wave_deferrals']}\",\n", "")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith(":engine_wave_deferrals") for f in found)

    def test_stale_exporter_key_fires(self, tmp_path):
        manage = C010_MANAGE_OK.replace("engine_wave_policy_waves",
                                        "engine_wave_gone_key")
        keys = {f.key for f in self.scan(tmp_path, manage_src=manage)}
        assert any(k.endswith("stale:engine_wave_gone_key") for k in keys)
        assert any(k.endswith(":engine_wave_policy_waves") for k in keys)

    def test_undocumented_wave_key_fires(self, tmp_path):
        docs = C010_DOCS.replace("engine_wave_defer_age_us_p99", "")
        found = self.scan(tmp_path, docs=docs)
        assert any(
            f.key.endswith("undocumented:engine_wave_defer_age_us_p99")
            for f in found
        )

    def test_missing_wave_route_fires(self, tmp_path):
        manage = C010_MANAGE_OK.replace('"/wave"', '"/nope"').replace(
            "_engine_wave_status", "nothing")
        found = self.scan(tmp_path, manage_src=manage)
        assert any(f.key.endswith("wave-route") for f in found)

    def test_real_wave_vocabulary_is_clean(self):
        ctx = core.Context(str(REPO))
        found = [f for f in counters.scan(ctx) if f.rule == "ITS-C010"]
        assert found == []


# ---------------------------------------------------------------------------
# modelcheck (ITS-M*)
# ---------------------------------------------------------------------------

def mini_spec(name="mini", **overrides):
    """A one-state spec that explores cleanly (complete, invariant held) —
    the neutral carrier for targeting ONE seeded defect per test."""
    kw = dict(
        name=name, doc="test fixture", initial_states=lambda: [(0,)],
        actions=(), invariants=(("true", lambda s: True),),
    )
    kw.update(overrides)
    return mspecs.Spec(**kw)


def ring_variant(**replacements):
    """The real ring spec with named actions swapped for mutants."""
    acts = tuple(replacements.get(a.name, a) for a in ring_spec.ACTIONS)
    return dataclasses.replace(ring_spec.SPEC, actions=acts)


def schedule_from(finding):
    """Parse the serialized counterexample out of an ITS-M finding."""
    m = re.search(r"counterexample schedule (\[.*?\]) \(replay",
                  finding.message)
    assert m, finding.message
    sched = json.loads(m.group(1))
    assert sched and all(isinstance(step, str) for step in sched)
    return sched


class TestModelcheck:
    def test_real_tree_is_clean_with_full_exploration(self):
        """The acceptance gate: every shipped spec explores its complete
        bounded state space at HEAD with zero findings, and the per-spec
        stats rows (states/edges/ms) land in Context.stats for --json."""
        ctx = core.Context(str(REPO))
        assert modelcheck.scan(ctx) == []
        rows = ctx.stats["modelcheck"]["specs"]
        assert set(rows) == {
            "membership_merge", "durable_log", "ring_sq_cq", "qos_aging",
        }
        for row in rows.values():
            assert row["states"] > 0 and row["edges"] > 0
            assert row["complete"] is True
            assert row["violations"] == []
            assert isinstance(row["ms"], float)

    # -- ITS-M001: stale action list vs the real class ----------------------

    def test_stale_action_list_vs_real_class_fires(self, tmp_path):
        ctx = make_tree(tmp_path, {"pkg/fake.py": (
            "class Membership:\n"
            "    def poke_method(self):\n"
            "        pass\n"
            "    def extra(self):\n"
            "        pass\n"
        )})
        spec = mini_spec(actions=(
            mspecs.Action("poke", lambda s: False, lambda s: s),
            mspecs.Action("mystery@0", lambda s: False, lambda s: s),
        ))
        mirrors = {
            "kind": "py_class", "file": "pkg/fake.py", "cls": "Membership",
            "actions": {"poke": "poke_method", "stale": "vanished"},
            "exempt": {"gone": "was audited once"},
        }
        found = modelcheck.scan(ctx, specs=[(spec, mirrors)])
        # All four drift directions, and nothing else (the carrier spec
        # itself explores cleanly).
        assert {f.key for f in found} == {
            "ITS-M001:pkg/fake.py:mini:unmapped:mystery",
            "ITS-M001:pkg/fake.py:mini:stale-covered:vanished",
            "ITS-M001:pkg/fake.py:mini:stale-exempt:gone",
            "ITS-M001:pkg/fake.py:mini:unmodeled:extra",
        }

    def test_mirrored_class_vanishing_fires(self, tmp_path):
        ctx = make_tree(tmp_path, {"pkg/fake.py": "class Other:\n    pass\n"})
        mirrors = {"kind": "py_class", "file": "pkg/fake.py",
                   "cls": "Membership", "actions": {}, "exempt": {}}
        found = modelcheck.scan(ctx, specs=[(mini_spec(), mirrors)])
        assert any(f.key.endswith(":missing-class") for f in found)

    def test_cpp_surface_strips_comments(self, tmp_path):
        """Prose like "bg_cooldown_us (hysteresis ...)" in a header comment
        must not read as a surface name the model has to cover."""
        ctx = make_tree(tmp_path, {"h.h": (
            "// bg_ghost (prose about a knob)\n"
            "/* ring_phantom ( multi-line\n   prose */\n"
            "static inline void bg_real(int x);\n"
        )})
        pattern = r"\b(bg_[a-z_]+|ring_[a-z_]+)\s*\("
        assert modelcheck._cpp_surface(ctx, "h.h", pattern) == {"bg_real"}

    # -- seeded protocol defects: the mutations MUST be caught ---------------

    def test_dropped_dekker_recheck_is_caught(self):
        """Mutate the ring model so the server parks WITHOUT the Dekker
        tail re-check (sleep straight after flag-set). Exploration must
        refute it — this is the lost-wakeup bug the discipline exists to
        prevent — and the finding must carry a replayable schedule."""
        sleepy = mspecs.Action(
            name="s_park_recheck",
            guard=lambda s: s[ring_spec.PC_S] == ring_spec.PARKING,
            apply=lambda s: ring_spec._set(
                s, s_parked=True, pc_s=ring_spec.IDLE),
        )
        spec = ring_variant(s_park_recheck=sleepy)
        ctx = core.Context(str(REPO))
        found = modelcheck.scan(ctx, specs=[(spec, ring_spec.MIRRORS)])
        assert found
        # Exploration findings only: the mutant's action names still match
        # the real ring.h surface, so M001 stays quiet.
        assert {f.rule for f in found} <= {"ITS-M002", "ITS-M003"}
        sched = schedule_from(found[0])
        assert any(step.startswith("s_park") for step in sched)

    def test_nonsticky_doorbell_strands_the_parker(self):
        """Drop the doorbell's socket-frame stickiness (and the re-check's
        insta-wake drain): a stale doorbell for an already-consumed publish
        takes the freshly-set park flag before the consumer sleeps, and the
        consumer then parks with its flag down — undoorbellable. The
        parked-flag-consistent invariant must find that exact schedule."""
        forgetful = mspecs.Action(
            name="p_doorbell",
            guard=lambda s: s[ring_spec.PC_P] == ring_spec.PUBLISHED,
            apply=lambda s: ring_spec._set(
                s, pc_p=ring_spec.IDLE,
                **({"sq_flag": 0, "s_parked": False}
                   if s[ring_spec.SQ_FLAG] else {}),
            ),
        )
        amnesiac = mspecs.Action(
            name="s_park_recheck",
            guard=lambda s: s[ring_spec.PC_S] == ring_spec.PARKING,
            apply=lambda s: (
                ring_spec._set(s, sq_flag=0, pc_s=ring_spec.IDLE)
                if s[ring_spec.SQ_TAIL] > s[ring_spec.SQ_HEAD]
                else ring_spec._set(s, s_parked=True, pc_s=ring_spec.IDLE)
            ),
        )
        spec = ring_variant(p_doorbell=forgetful, s_park_recheck=amnesiac)
        res = mspecs.explore(spec)
        bad = [v for v in res.violations
               if v.prop == "parked-flag-consistent"]
        assert bad
        # The shortest counterexample ends at the fatal sleep, with the
        # stale doorbell landing inside the park window.
        assert bad[0].schedule[-1] == "s_park_recheck"
        assert "p_doorbell" in bad[0].schedule

    def test_weakened_invariant_yields_replayable_counterexample(self):
        """Swap the membership no-resurrection step invariant for a
        WRONG/over-strict variant that also rejects the legal within-
        incarnation DEAD -> REMOVED terminal rank advance. Exploration
        must produce an ITS-M002 finding whose schedule ends in the
        offending exchange — the counterexample-to-test workflow's input
        (tests/test_modelcheck.py replays exactly this class of schedule
        against the real Membership)."""
        def too_strict(prev, action, nxt):
            if not action.startswith("exchange"):
                return True
            for i in range(membership_spec.N_PEERS):
                a = membership_spec._entry(prev, i)
                b = membership_spec._entry(nxt, i)
                if a == b:
                    continue
                if not membership_spec.beats(a, b):
                    return False
                if (a is not None and a[0] in membership_spec.TERMINAL
                        and b[1] <= a[1]):
                    return False  # no terminal-to-terminal carve-out
            return True

        spec = dataclasses.replace(
            membership_spec.SPEC,
            step_invariants=(
                ("no-resurrection", too_strict),
                ("epoch-monotone", membership_spec.step_epoch_monotone),
            ),
        )
        ctx = core.Context(str(REPO))
        found = modelcheck.scan(
            ctx, specs=[(spec, membership_spec.MIRRORS)])
        rows = [f for f in found
                if f.key == "ITS-M002:membership_merge:no-resurrection"]
        assert rows
        sched = schedule_from(rows[0])
        assert sched[-1].startswith("exchange@")
        # With violations present, the incomplete exploration is NOT
        # additionally reported as an M005 health finding.
        assert not any(f.rule == "ITS-M005" for f in found)

    # -- ITS-M005: exploration health ----------------------------------------

    def test_exploration_health_rules_fire(self, tmp_path):
        ctx = make_tree(tmp_path, {"h.h": "void zz_x(int);\n"})
        mirrors = {"kind": "cpp_functions", "file": "h.h",
                   "pattern": r"\b(zz_[a-z_]+)\s*\(",
                   "actions": {}, "exempt": {"zz_x": "fixture"}}
        runaway = mini_spec(
            name="runaway",
            actions=(mspecs.Action("inc", lambda s: True,
                                   lambda s: (s[0] + 1,)),),
            state_cap=8,
        )
        keys = {f.key for f in modelcheck.scan(ctx, specs=[
            (mini_spec(name="hollow", initial_states=lambda: []), mirrors),
            (mini_spec(name="blind", invariants=()), mirrors),
            (runaway, mirrors),
        ])}
        assert "ITS-M005:hollow:empty" in keys
        assert "ITS-M005:blind:no-invariants" in keys
        assert "ITS-M005:runaway:incomplete" in keys
