"""Descriptor-ring zero-copy data plane (docs/descriptor_ring.md).

Python-level coverage of the shared-memory submission/completion rings:
activation and automatic degradation, the client/server counter ledgers and
their /metrics rendering, ring-full backpressure as a COUNTED fallback,
torn-descriptor rejection via the generation tag (tampering through the
``wire`` geometry helpers, exactly how a buggy second writer would corrupt
the ring), trace ticks on ring-posted ops, and the off-path wire-identity
gate — a ring-disabled or ring-incapable connection must leave the socket
protocol surface untouched (the QoS/trace extension pattern).

The native half (cursor wrap, doorbell coalescing, QoS ordering inside the
copy engine) lives in native/tests/test_core.cpp and runs under
ASAN/TSAN — the ring header is genuinely cross-thread shared state there.
"""

import asyncio
import mmap
import struct
import time

import pytest

import infinistore_tpu as its
from infinistore_tpu import wire

pytestmark = pytest.mark.ring

BLOCK = 16 << 10


@pytest.fixture
def server():
    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=BLOCK)
    yield srv
    srv.stop()


def _connect(port, **kw):
    conn = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=port,
                         log_level="error", **kw)
    )
    conn.connect()
    return conn


def _seg_blocks(conn, n):
    arr = conn.alloc_shm_mr(n * BLOCK)
    assert arr is not None
    blocks = [(f"rk{i}", i * BLOCK) for i in range(n)]
    return arr, arr.ctypes.data, blocks


# ---------------------------------------------------------------------------
# Activation / degradation
# ---------------------------------------------------------------------------


def test_ring_active_on_loopback_and_counters_flow(server):
    conn = _connect(server.port)
    try:
        assert conn.shm_active
        assert conn.ring_active
        assert conn.ring_name().startswith("/its.")
        arr, ptr, blocks = _seg_blocks(conn, 8)
        arr[:] = 0x5A
        conn.write_cache(blocks, BLOCK, ptr)
        arr[:] = 0
        conn.read_cache(blocks, BLOCK, ptr)
        assert (arr == 0x5A).all()

        cs = conn.ring_stats()
        assert cs["ring_posted"] == 2
        assert cs["ring_completions"] == 2
        assert cs["ring_full_fallbacks"] == 0
        assert cs["ring_meta_fallbacks"] == 0
        assert cs["ring_doorbells"] >= 1
        assert cs["ring_doorbell_ratio"] >= 1.0

        ring = conn.get_stats()["ring"]
        assert ring["attached"] == 1
        assert ring["conns"] == 1
        assert ring["descriptors"] == 2
        assert ring["completions"] == 2
        assert ring["bad_descriptors"] == 0
        assert ring["torn_descriptors"] == 0
        # Drained at rest.
        assert ring["sq_depth"] == 0
        assert ring["pending"] == 0
    finally:
        conn.close()


def test_ring_disabled_degrades_to_socket_path(server):
    conn = _connect(server.port, enable_ring=False)
    try:
        assert conn.shm_active  # shm fast path unaffected
        assert not conn.ring_active
        assert conn.ring_name() == ""
        arr, ptr, blocks = _seg_blocks(conn, 4)
        arr[:] = 0x21
        conn.write_cache(blocks, BLOCK, ptr)
        arr[:] = 0
        conn.read_cache(blocks, BLOCK, ptr)
        assert (arr == 0x21).all()
        cs = conn.ring_stats()
        assert all(v == 0 for k, v in cs.items())
        # The batch/poll ledger keys exist (pinned 0) even with the ring
        # off — dashboards never see the vocabulary appear mid-flight.
        assert {
            "ring_batch_slots", "ring_batch_ops", "ring_batch_ops_per_slot",
            "ring_poll_hits", "ring_poll_arms", "ring_batch_windows",
        } <= set(cs)
    finally:
        conn.close()


def test_ring_unavailable_without_shm():
    srv = its.start_local_server(
        prealloc_bytes=16 << 20, block_bytes=BLOCK, enable_shm=False
    )
    try:
        conn = _connect(srv.port)
        try:
            assert not conn.shm_active
            assert not conn.ring_active  # ring requires the shm fast path
        finally:
            conn.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Off-path wire identity (the QoS/trace extension gate)
# ---------------------------------------------------------------------------


def test_ring_off_leaves_socket_protocol_untouched(server):
    """With the ring disabled, the connection must speak EXACTLY the
    pre-ring protocol: no attach frame, no doorbell frames, no ring
    completions — every server-side ring counter stays zero while the ops
    flow over the ordinary segment path."""
    conn = _connect(server.port, enable_ring=False)
    try:
        arr, ptr, blocks = _seg_blocks(conn, 4)
        arr[:] = 7
        conn.write_cache(blocks, BLOCK, ptr)
        conn.read_cache(blocks, BLOCK, ptr)
        st = conn.get_stats()
        assert st["ring"] == {
            "attached": 0, "conns": 0, "descriptors": 0, "doorbells_rx": 0,
            "cq_doorbells_tx": 0, "completions": 0, "bad_descriptors": 0,
            "torn_descriptors": 0, "batch_slots": 0, "batch_ops": 0,
            "poll_hits": 0, "poll_arms": 0, "doorbell_elided": 0,
            "sq_depth": 0, "pending": 0,
        }
        # The ops really ran — over the segment opcodes, not the ring.
        ops = st["ops"]
        assert ops.get("F", {}).get("count", 0) >= 1  # PutFrom
        assert ops.get("I", {}).get("count", 0) >= 1  # GetInto
    finally:
        conn.close()


def test_wire_encodings_byte_stable():
    """The ring rides OUT-OF-BAND of the socket bodies: SegBatchMeta (and
    friends) must encode the exact pre-ring bytes, and the only new body —
    RingMeta, spoken solely inside the attach handshake — is pinned here
    so a drive-by edit fails loudly."""
    m = wire.SegBatchMeta(block_size=4096, seg_id=7, keys=["k"], offsets=[65536])
    assert m.encode().hex() == (
        "0010000007000100000001006b010000000000010000000000"
    )
    r = wire.RingMeta(name="/its.1.ring", size=4096)
    assert r.encode().hex() == "0b002f6974732e312e72696e670010000000000000"
    d = wire.RingMeta.decode(r.encode())
    assert d.name == "/its.1.ring" and d.size == 4096


def test_ring_batch_layout_byte_stable():
    """The batch-slot frame (RingBatchHdr + per-op RingBatchEntry +
    SegBatchMeta) is shared memory the native server decodes raw — pin the
    exact bytes ``ring_batch_encode`` (the reference encoding the native
    client's ring_group_end mirrors) produces so a drive-by field edit
    fails loudly, plus the op-count bounds."""
    m1 = wire.SegBatchMeta(block_size=4096, seg_id=7, keys=["k"], offsets=[65536])
    m2 = wire.SegBatchMeta(block_size=4096, seg_id=7, keys=["k2"], offsets=[0])
    b = wire.ring_batch_encode(
        [(wire.OP_PUT_FROM, m1.encode()), (wire.OP_GET_INTO, m2.encode())]
    )
    assert b.hex() == (
        "0200000019000000460000000010000007000100000001006b0100000000000100"
        "000000001a000000490000000010000007000100000002006b3201000000000000"
        "0000000000"
    )
    # hdr.count little-endian up front; each entry leads with meta_len.
    assert b[:2] == (2).to_bytes(2, "little")
    assert b[4:8] == len(m1.encode()).to_bytes(4, "little")
    with pytest.raises(ValueError):
        wire.ring_batch_encode([])
    with pytest.raises(ValueError):
        wire.ring_batch_encode(
            [(wire.OP_PUT_FROM, b"")] * (wire.RING_BATCH_MAX_OPS + 1)
        )


def test_ring_geometry_helpers_match_native_layout():
    """wire.py's geometry mirror must agree with native ring.h: struct
    sizes via the packed formats, offsets via the 64-byte-aligned walk."""
    assert wire._RING_CTRL.size == 72
    assert wire._RING_SLOT.size == 24
    assert wire._RING_CQE.size == 32
    assert wire._RING_BATCH_HDR.size == 4
    assert wire._RING_BATCH_ENTRY.size == 8
    assert wire.ring_sq_off() == wire.RING_CTRL_SPAN
    assert wire.ring_cq_off(64) == 4096 + 64 * 24
    assert wire.ring_meta_off(64, 64) == 4096 + 64 * 24 + 64 * 32
    assert wire.ring_segment_bytes(64, 64, wire.RING_META_STRIDE) == (
        wire.ring_meta_off(64, 64) + 64 * wire.RING_META_STRIDE
    )
    # Layout-derived field offsets (the tamper hook): cursors sit after the
    # eight u32 geometry fields.
    assert wire.ring_ctrl_offset("sq_tail") == 32
    assert wire.ring_ctrl_offset("sq_head") == 40
    assert wire.ring_ctrl_offset("cq_tail") == 48
    assert wire.ring_ctrl_offset("cli_waiting") == 68
    with pytest.raises(KeyError):
        wire.ring_ctrl_offset("nope")


# ---------------------------------------------------------------------------
# Backpressure, tamper rejection, trace ticks
# ---------------------------------------------------------------------------


def test_ring_full_backpressure_is_counted_fallback(server):
    """A 2-slot ring under a 12-op async burst: the in-flight bound forces
    overflow onto the socket path — counted, never an error, all bytes
    land."""
    conn = _connect(server.port, ring_slots=2)
    try:
        assert conn.ring_active
        n = 12
        arr = conn.alloc_shm_mr(n * BLOCK)
        ptr = arr.ctypes.data
        arr[:] = 0x33

        async def burst():
            await asyncio.gather(*[
                conn.write_cache_async([(f"bp{i}", i * BLOCK)], BLOCK, ptr)
                for i in range(n)
            ])

        asyncio.run(burst())
        cs = conn.ring_stats()
        assert cs["ring_posted"] + cs["ring_full_fallbacks"] == n
        assert cs["ring_completions"] == cs["ring_posted"]
        # Every op committed regardless of which path carried it.
        arr[:] = 0
        conn.read_cache([(f"bp{i}", i * BLOCK) for i in range(n)], BLOCK, ptr)
        assert (arr == 0x33).all()
    finally:
        conn.close()


def test_batch_window_packs_flush_into_one_slot(server):
    """The flush-coalescing contract end-to-end: every async op submitted
    in one event-loop tick rides ONE multi-op batch slot — K-op flush, one
    descriptor, one doorbell per doze — and the eager
    ``ring_batch_window()`` hint (what FetchCoalescer._flush calls) is
    counted."""
    conn = _connect(server.port)
    try:
        assert conn.ring_active
        n = 8
        arr, ptr, blocks = _seg_blocks(conn, n)
        arr[:] = 0x44

        async def flush():
            conn.ring_batch_window()  # the coalescer's eager hint
            await asyncio.gather(*[
                conn.write_cache_async([blk], BLOCK, ptr) for blk in blocks
            ])

        asyncio.run(flush())
        cs = conn.ring_stats()
        assert cs["ring_posted"] == n
        assert cs["ring_batch_slots"] == 1
        assert cs["ring_batch_ops"] == n
        assert cs["ring_batch_ops_per_slot"] == float(n)
        assert cs["ring_batch_windows"] == 1
        assert cs["ring_full_fallbacks"] == 0
        ring = conn.get_stats()["ring"]
        assert ring["batch_slots"] == 1
        assert ring["batch_ops"] == n
        assert ring["descriptors"] == n
        # The bytes all landed (one sync read — a plain, non-batch slot).
        arr[:] = 0
        conn.read_cache(blocks, BLOCK, ptr)
        assert (arr == 0x44).all()
        assert conn.ring_stats()["ring_batch_slots"] == 1  # sync never joins
    finally:
        conn.close()


def test_batch_arena_overflow_matrix():
    """Oversized descriptor bodies degrade exactly like the single-op path
    promised: a pair of ops too big to SHARE a slot splits the flush (the
    lone one posts as a plain slot, the rest still batch), and a single op
    whose body exceeds the whole 128KB arena stride rides the socket as a
    counted meta fallback — never an error."""
    srv = its.start_local_server(prealloc_bytes=96 << 20, block_bytes=4096)
    conn = _connect(srv.port)
    try:
        assert conn.ring_active
        stride = wire.RING_META_STRIDE

        def body_len(nkeys):
            keys = [f"m{j:05d}" for j in range(nkeys)]
            m = wire.SegBatchMeta(
                block_size=512, seg_id=0, keys=keys, offsets=[0] * nkeys
            )
            return len(m.encode())

        # Two "big" ops: each fits a slot alone, two never share one.
        nbig = 4600
        assert 12 + body_len(nbig) <= stride
        assert 4 + 2 * (8 + body_len(nbig)) > stride
        arr = conn.alloc_shm_mr(4096)
        ptr = arr.ctypes.data

        def blks(tag, nkeys):
            # Puts read from the segment: offsets may overlap, so one page
            # backs arbitrarily many keys.
            return [(f"{tag}{j:05d}", 0) for j in range(nkeys)]

        async def mixed():
            await asyncio.gather(
                conn.write_cache_async(blks("b1_", nbig), 512, ptr),
                conn.write_cache_async(blks("b2_", nbig), 512, ptr),
                conn.write_cache_async([("s1", 0)], 512, ptr),
                conn.write_cache_async([("s2", 0)], 512, ptr),
            )

        asyncio.run(mixed())
        cs = conn.ring_stats()
        assert cs["ring_posted"] == 4          # every op still rode the ring
        assert cs["ring_batch_slots"] == 1     # big2 + s1 + s2
        assert cs["ring_batch_ops"] == 3       # big1 split off as a plain slot
        assert cs["ring_meta_fallbacks"] == 0
        assert cs["ring_full_fallbacks"] == 0

        # One op whose body alone exceeds the arena stride: counted meta
        # fallback onto the socket path, op succeeds.
        nhuge = 9100
        assert body_len(nhuge) > stride
        conn.write_cache(blks("h", nhuge), 512, ptr)
        cs = conn.ring_stats()
        assert cs["ring_meta_fallbacks"] == 1
        assert cs["ring_posted"] == 4          # unchanged — socket carried it
        assert conn.check_exist(f"h{nhuge - 1:05d}")
    finally:
        conn.close()
        srv.stop()


def test_torn_descriptor_poisons_connection(server):
    """Generation-tag validation end-to-end from Python: advance sq_tail in
    the mapped segment without publishing a slot gen — the server must
    count a torn descriptor and close the connection rather than decode
    garbage."""
    conn = _connect(server.port, op_timeout_ms=2000)
    try:
        assert conn.ring_active
        name = conn.ring_name()
        with open(f"/dev/shm{name}", "r+b") as f:
            mm = mmap.mmap(f.fileno(), 0)
            try:
                off = wire.ring_ctrl_offset("sq_tail")
                (tail,) = struct.unpack_from("<Q", mm, off)
                struct.pack_into("<Q", mm, off, tail + 1)
            finally:
                mm.close()
        deadline = time.time() + 5.0
        dead = False
        while time.time() < deadline and not dead:
            try:
                conn.check_exist("poke")  # generates events; outcome moot
            except Exception:
                pass
            dead = not conn.is_connected
            time.sleep(0.01)
        assert dead
        st = server_stats(server)
        assert st["ring"]["torn_descriptors"] == 1
        assert st["ring"]["conns"] == 0
    finally:
        conn.close()


def server_stats(srv) -> dict:
    """Server stats via a fresh (ring-less, to not disturb counters)
    connection — the tampered conn above is already dead."""
    probe = _connect(srv.port, enable_ring=False)
    try:
        return probe.get_stats()
    finally:
        probe.close()


def test_trace_ticks_present_for_ring_posted_ops(server):
    """A traced batched op that rides the ring must stamp the same ordered
    server ticks as the socket path (recv <= first <= last <= done) with
    its trace id joinable in the tick ring."""
    from infinistore_tpu import tracing

    tracing.configure(enabled=True, capacity=64, slow_op_us=0)
    conn = _connect(server.port)
    try:
        assert conn.ring_active
        arr, ptr, blocks = _seg_blocks(conn, 8)
        arr[:] = 1
        with tracing.trace_op("ring_put", stage="enqueue") as span:
            conn.write_cache(blocks, BLOCK, ptr)
        assert conn.ring_stats()["ring_posted"] == 1  # it WAS the ring path
        st = conn.get_stats()
        entries = st["trace"]["entries"]
        mine = [e for e in entries if e["trace_id"] == span.trace_id]
        assert len(mine) == 1
        e = mine[0]
        assert 0 < e["recv_us"] <= e["first_slice_us"]
        assert e["first_slice_us"] <= e["last_slice_us"] <= e["done_us"]
        assert e["bytes"] == len(blocks) * BLOCK
    finally:
        conn.close()
        tracing.configure(enabled=False)


# ---------------------------------------------------------------------------
# Observability surfaces
# ---------------------------------------------------------------------------


def test_metrics_renders_ring_family(server):
    from infinistore_tpu.server import _prometheus_text

    conn = _connect(server.port)
    try:
        arr, ptr, blocks = _seg_blocks(conn, 4)
        conn.write_cache(blocks, BLOCK, ptr)
        text = _prometheus_text(conn.get_stats()).decode()
        assert "infinistore_ring_conns 1" in text
        assert "infinistore_ring_attached 1" in text
        assert "infinistore_ring_descriptors 1" in text
        assert 'infinistore_ring_doorbells{dir="rx"}' in text
        assert 'infinistore_ring_doorbells{dir="tx"}' in text
        assert "infinistore_ring_completions 1" in text
        assert "infinistore_ring_bad_descriptors 0" in text
        assert "infinistore_ring_torn_descriptors 0" in text
        assert "infinistore_ring_sq_depth 0" in text
        assert "infinistore_ring_pending 0" in text
        # Batch + adaptive-poll mechanism families (values are
        # timing-dependent; one sync op batches nothing).
        assert "infinistore_ring_batch_slots 0" in text
        assert "infinistore_ring_batch_ops 0" in text
        assert "infinistore_ring_poll_hits" in text
        assert "infinistore_ring_poll_arms" in text
        assert "infinistore_ring_doorbell_elided" in text
    finally:
        conn.close()


def test_top_renders_ring_row():
    from tools.top import render

    frame = {
        "t": "00:00:00", "base": "x", "error": None, "slo": {},
        "events": {}, "membership": {},
        "metrics": {
            "infinistore_ring_conns": 2.0,
            "infinistore_ring_sq_depth": 3.0,
            "infinistore_ring_pending": 1.0,
            "infinistore_ring_descriptors": 640.0,
            'infinistore_ring_doorbells{dir="rx"}': 16.0,
            'infinistore_ring_doorbells{dir="tx"}': 8.0,
            "infinistore_ring_bad_descriptors": 0.0,
            "infinistore_ring_torn_descriptors": 0.0,
            "infinistore_ring_batch_slots": 64.0,
            "infinistore_ring_batch_ops": 512.0,
            "infinistore_ring_poll_hits": 100.0,
            "infinistore_ring_poll_arms": 4.0,
            "infinistore_ring_doorbell_elided": 600.0,
        },
    }
    lines = render(frame)
    ring_rows = [ln for ln in lines if ln.startswith("ring ")]
    assert len(ring_rows) == 1
    row = ring_rows[0]
    assert "conns=2" in row and "sq_depth=3" in row
    assert "descs=640" in row and "rx=16" in row and "tx=8" in row
    assert "descs/db=40.0" in row  # the coalescing ratio

    # The batch/poll mechanism line rides directly under the ring row.
    batch_rows = [ln for ln in lines if "batch slots=" in ln]
    assert len(batch_rows) == 1
    brow = batch_rows[0]
    assert "slots=64" in brow and "ops=512" in brow
    assert "ops/slot=8.0" in brow  # the flush-coalescing ratio
    assert "poll hit=100" in brow and "arm=4" in brow
    assert "db_elided=600" in brow

    # No ring conns -> no rows (a socket-only fleet stays uncluttered).
    frame["metrics"] = {"infinistore_ring_conns": 0.0}
    quiet = render(frame)
    assert not [ln for ln in quiet if ln.startswith("ring ")]
    assert not [ln for ln in quiet if "batch slots=" in ln]


def test_striped_connection_aggregates_ring_stats(server):
    conn = its.StripedConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=server.port,
                         log_level="error"),
        streams=2,
    )
    conn.connect()
    try:
        assert conn.ring_active  # stripe 0 owns the segment + ring
        arr = conn.alloc_shm_mr(4 * BLOCK)
        ptr = arr.ctypes.data
        arr[:] = 9
        conn.write_cache([(f"sk{i}", i * BLOCK) for i in range(4)], BLOCK, ptr)
        st = conn.ring_stats()
        assert st["ring_posted"] >= 1
        assert st["ring_completions"] == st["ring_posted"]
        # The batch/poll ledger aggregates across stripes too.
        assert {
            "ring_batch_slots", "ring_batch_ops", "ring_batch_ops_per_slot",
            "ring_poll_hits", "ring_poll_arms", "ring_batch_windows",
        } <= set(st)
    finally:
        conn.close()
