"""Leak soak: sustained op churn must not grow process memory.

ASAN covers native leaks in unit tests; this guards the Python bridge —
the completion registry, per-loop semaphores, MR tracking lists, and the
native request/response buffers — across tens of thousands of real ops.
"""

import asyncio
import gc
import os

import numpy as np

import infinistore_tpu as its


def _rss_mb() -> float:
    with open(f"/proc/{os.getpid()}/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024
    return 0.0


def test_sustained_ops_do_not_leak():
    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    block = 16 << 10
    buf = np.random.randint(0, 256, size=4 * block, dtype=np.uint8)
    c.register_mr(buf)
    pairs = [(f"soak-{i}", i * block) for i in range(4)]

    async def batch(n):
        for _ in range(n):
            await c.write_cache_async(pairs, block, buf.ctypes.data)
            await c.read_cache_async(pairs, block, buf.ctypes.data)

    # Warm up allocators/caches, then measure growth across sustained churn.
    asyncio.run(batch(200))
    for _ in range(5):
        c.tcp_read_cache("soak-0")  # exercises the malloc'd tcp_get path too
    gc.collect()
    base = _rss_mb()
    for _ in range(4):
        asyncio.run(batch(500))  # fresh event loop each round (semaphore map)
        for _ in range(200):
            c.read_cache(pairs, block, buf.ctypes.data)
        for _ in range(100):
            c.tcp_read_cache("soak-1")
    gc.collect()
    grown = _rss_mb() - base
    # 4000 batched async ops + 800 sync + 400 tcp gets: a real leak of even
    # one response body per op would show tens of MB; allow arena noise.
    assert grown < 20, f"RSS grew {grown:.1f} MB over sustained ops"
    c.close()
    srv.stop()


def test_spill_churn_does_not_leak():
    """Sustained demote/promote churn through the budget-sliced segment
    ops: continuations (SegCont allocations, banked pins, cont_queue
    entries) and spill-slot bookkeeping must not accumulate."""
    block = 16 << 10
    srv = its.start_local_server(
        prealloc_bytes=1 << 20, block_bytes=block,  # RAM holds 64 blocks
        spill_dir="/tmp", spill_bytes=16 << 20,
    )
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    n = 192  # 3x RAM -> constant churn
    buf = c.alloc_shm_mr(n * block)
    if buf is None:
        buf = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
        c.register_mr(buf)
    else:
        buf[:] = 7
    pairs = [(f"sc-{i}", i * block) for i in range(n)]

    async def churn(rounds):
        for _ in range(rounds):
            for s in range(0, n, 32):
                await c.write_cache_async(pairs[s : s + 32], block, buf.ctypes.data)
            for s in range(0, n, 32):
                await c.read_cache_async(pairs[s : s + 32], block, buf.ctypes.data)

    asyncio.run(churn(3))  # warm allocators, spill file pages
    gc.collect()
    base = _rss_mb()
    asyncio.run(churn(12))
    gc.collect()
    grown = _rss_mb() - base
    stats = c.get_stats()["spill"]
    assert stats["promotions"] > 500, "churn did not actually exercise spill"
    assert grown < 20, f"RSS grew {grown:.1f} MB under spill churn"
    c.close()
    srv.stop()
