"""Model + disaggregation tests: the flagship E2E — prefill on one engine,
KV blocks through the store, decode resumes on a second engine (the
single-host shape of BASELINE.md config 5 / the reference's
prefill->decode-disaggregation scenario, README.md:13-16)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill, train_step
from infinistore_tpu.tpu import (
    HostStagingPool,
    LayerwiseKVReader,
    LayerwiseKVWriter,
    kv_block_key,
)

CFG = LlamaConfig(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    block_tokens=8, dtype=jnp.float32,  # float32 for exact comparisons
)
NUM_BLOCKS = 16
MAX_BLOCKS = 4  # 32-token max context in these tests


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _fresh_caches():
    return CFG.kv_spec(NUM_BLOCKS).make_caches()


def test_prefill_shapes(params):
    tokens = jnp.arange(16, dtype=jnp.int32) % CFG.vocab
    table = jnp.array([3, 7], dtype=jnp.int32)
    logits, caches = prefill(params, tokens, _fresh_caches(), table, CFG)
    assert logits.shape == (CFG.vocab,)
    assert len(caches) == CFG.n_layers
    # Written blocks are non-zero, untouched blocks stay zero.
    k0 = np.asarray(caches[0][0])
    assert np.abs(k0[3]).sum() > 0 and np.abs(k0[7]).sum() > 0
    assert np.abs(k0[0]).sum() == 0


def test_decode_matches_prefill(params):
    """Paged incremental decode must reproduce full-prefill logits."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, CFG.vocab)
    table = jnp.array([0, 1, 2, 3], dtype=jnp.int32)

    # Ground truth: prefill over 24 tokens.
    full = jax.random.randint(jax.random.PRNGKey(2), (24,), 0, CFG.vocab)
    full = full.at[:16].set(prompt)
    ref_logits, _ = prefill(params, full, _fresh_caches(), table[:3], CFG)

    # Incremental: prefill 16, then decode tokens 16..23 one at a time.
    logits, caches = prefill(params, prompt, _fresh_caches(), table[:2], CFG)
    for pos in range(16, 24):
        logits, caches = decode_step(
            params, full[pos], jnp.int32(pos), caches, table, CFG, MAX_BLOCKS
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )


def test_disagg_prefill_store_decode(conn, params):
    """Prefill engine -> store -> fresh decode engine, logits must match the
    non-disaggregated continuation."""
    prompt = jax.random.randint(jax.random.PRNGKey(3), (16,), 0, CFG.vocab)
    next_tok = jnp.int32(42)
    table = jnp.array([5, 9], dtype=jnp.int32)  # prefill engine's blocks

    # --- prefill engine ---
    _, prefill_caches = prefill(params, prompt, _fresh_caches(), table, CFG)
    spec = CFG.kv_spec(NUM_BLOCKS)
    pool = HostStagingPool(
        nbytes=4 * 2 * spec.block_nbytes * 2, block_size=spec.block_nbytes, conn=conn
    )
    writer = LayerwiseKVWriter(conn, pool, spec, max_blocks=2)
    key_fn = lambda l, k, i: kv_block_key("demo", "prompt-hash", l, k, i)
    asyncio.run(writer.write(prefill_caches, np.asarray(table), key_fn))

    # --- decode engine (different block layout!) ---
    decode_table = jnp.array([1, 2, 14, 3], dtype=jnp.int32)
    reader = LayerwiseKVReader(conn, pool, spec, max_blocks=2)
    decode_caches = asyncio.run(
        reader.read(_fresh_caches(), np.asarray(decode_table[:2]), key_fn)
    )
    logits_disagg, _ = decode_step(
        params, next_tok, jnp.int32(16), decode_caches, decode_table, CFG, MAX_BLOCKS
    )

    # --- reference: continue on the prefill engine directly ---
    ref_table = jnp.array([5, 9, 12, 13], dtype=jnp.int32)
    logits_ref, _ = decode_step(
        params, next_tok, jnp.int32(16), prefill_caches, ref_table, CFG, MAX_BLOCKS
    )
    np.testing.assert_allclose(
        np.asarray(logits_disagg), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )


def test_prefill_continue_matches_decode_loop_logits(params):
    """Chunked continuation must reproduce the decode loop's logits at EVERY
    chunk row (not just leave equal cache bytes)."""
    from infinistore_tpu.models import prefill_continue

    full = jax.random.randint(jax.random.PRNGKey(9), (32,), 0, CFG.vocab)
    table = jnp.asarray([0, 1, 2, 3], jnp.int32)
    _, caches = prefill(params, full[:16], _fresh_caches(), table[:2], CFG)
    cont_logits, cont_caches = prefill_continue(
        params, full[16:], jnp.int32(16), caches, table, CFG, MAX_BLOCKS
    )

    _, loop_caches = prefill(params, full[:16], _fresh_caches(), table[:2], CFG)
    for i, pos in enumerate(range(16, 32)):
        step_logits, loop_caches = decode_step(
            params, full[pos], jnp.int32(pos), loop_caches, table, CFG, MAX_BLOCKS
        )
        np.testing.assert_allclose(
            np.asarray(cont_logits[i]), np.asarray(step_logits),
            rtol=2e-5, atol=2e-5, err_msg=f"row {i}",
        )
    for layer in range(CFG.n_layers):
        for kind in (0, 1):
            np.testing.assert_allclose(
                np.asarray(cont_caches[layer][kind]),
                np.asarray(loop_caches[layer][kind]),
                rtol=2e-5, atol=2e-5,
            )


def test_speculative_verify_accepts_greedy_prefix(params):
    """The accepted draft prefix + emitted token must exactly reproduce
    token-by-token greedy decoding; a corrupted draft tail is rejected at
    the first divergence, and continuing from the accepted point (stale
    slots beyond it never attended) still matches greedy."""
    from infinistore_tpu.models import speculative_verify

    prompt = jax.random.randint(jax.random.PRNGKey(13), (16,), 0, CFG.vocab)
    table = jnp.asarray([0, 1, 2, 3], jnp.int32)

    # Greedy oracle: greedy[i] = token at position 16 + i.
    logits, oracle_caches = prefill(params, prompt, _fresh_caches(), table[:2], CFG)
    greedy = []
    tok = int(jnp.argmax(logits))  # token at position 16
    pos = 16
    for _ in range(9):
        greedy.append(tok)
        logits, oracle_caches = decode_step(
            params, jnp.int32(tok), jnp.int32(pos), oracle_caches, table, CFG,
            MAX_BLOCKS,
        )
        tok = int(jnp.argmax(logits))
        pos += 1

    # A PERFECT draft (the greedy continuation itself) is fully accepted
    # and the emitted next_token continues it.
    _, caches = prefill(params, prompt, _fresh_caches(), table[:2], CFG)
    draft = jnp.asarray(greedy[:6], jnp.int32)
    n, nxt, caches = speculative_verify(
        params, draft, 16, caches, table, CFG, MAX_BLOCKS
    )
    assert n == 6, f"perfect draft should fully accept, got {n}"
    assert nxt == greedy[6]

    # A draft corrupted at index 3 accepts exactly 3 and emits the greedy
    # token for that position instead.
    _, caches2 = prefill(params, prompt, _fresh_caches(), table[:2], CFG)
    bad = list(greedy[:6])
    bad[3] = (bad[3] + 1) % CFG.vocab
    n2, nxt2, caches2 = speculative_verify(
        params, jnp.asarray(bad, jnp.int32), 16, caches2, table, CFG, MAX_BLOCKS
    )
    assert n2 == 3, f"should reject at the corruption, got {n2}"
    assert nxt2 == greedy[3]

    # Continue from the accepted point over the same caches (stale slots
    # beyond position 16+3 are present but masked): next greedy step
    # matches the oracle.
    logits3, _ = decode_step(
        params, jnp.int32(nxt2), jnp.int32(16 + n2), caches2, table, CFG,
        MAX_BLOCKS,
    )
    assert int(jnp.argmax(logits3)) == greedy[4]

    # pad_to: one compiled shape for variable-length drafts — results equal
    # the unpadded call, and a span past the table's capacity fails loudly
    # (jnp.take would otherwise clip and corrupt the last block).
    _, caches3 = prefill(params, prompt, _fresh_caches(), table[:2], CFG)
    n3, nxt3, _ = speculative_verify(
        params, bad, 16, caches3, table, CFG, MAX_BLOCKS, pad_to=12
    )
    assert (n3, nxt3) == (n2, nxt2)
    with pytest.raises(ValueError, match="capacity"):
        speculative_verify(
            params, bad, 16, _fresh_caches(), table, CFG, MAX_BLOCKS, pad_to=20
        )


def test_train_step_runs(params):
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, CFG.vocab)
    import copy

    p = jax.tree.map(jnp.copy, params)
    p2, loss = train_step(p, tokens, CFG)
    assert np.isfinite(float(loss))
    # Params actually moved.
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2))
    )
    assert moved


MOE_CFG = LlamaConfig(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    n_experts=4, block_tokens=8, dtype=jnp.float32,
)


def test_moe_decode_matches_prefill():
    """The mixture-of-experts variant (expert-parallel FFN in the dryrun)
    must keep the paged-decode == full-prefill invariant."""
    params = init_params(MOE_CFG, jax.random.PRNGKey(5))
    table = jnp.array([0, 1, 2, 3], dtype=jnp.int32)
    caches = MOE_CFG.kv_spec(NUM_BLOCKS).make_caches()
    full = jax.random.randint(jax.random.PRNGKey(6), (24,), 0, MOE_CFG.vocab)
    ref_logits, _ = prefill(
        params, full, MOE_CFG.kv_spec(NUM_BLOCKS).make_caches(), table[:3], MOE_CFG
    )
    logits, caches = prefill(params, full[:16], caches, table[:2], MOE_CFG)
    for pos in range(16, 24):
        logits, caches = decode_step(
            params, full[pos], jnp.int32(pos), caches, table, MOE_CFG, MAX_BLOCKS
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )


def test_moe_expert_parallel_train_step():
    """One training step with expert weights sharded over an 'ep' mesh axis
    (the dryrun's EP configuration, on the virtual 8-device mesh)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    assert len(devices) == 8
    mesh = Mesh(np.array(devices).reshape(2, 2, 2), ("dp", "tp", "ep"))
    params = init_params(MOE_CFG, jax.random.PRNGKey(7))

    def spec(name):
        if name.endswith("w_gate_up_moe"):
            return P("ep", None, None, "tp")
        if name.endswith("w_down_moe"):
            return P("ep", "tp", None)
        if name.endswith("router"):
            return P(None, "ep")
        return P()

    sharded = {
        k: jax.device_put(v, NamedSharding(mesh, spec(k))) for k, v in params.items()
    }
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 16), 0, MOE_CFG.vocab)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    with mesh:
        new_params, loss = train_step(sharded, tokens, MOE_CFG)
    assert np.isfinite(float(loss))
    # Expert weights stay ep-sharded after the step (no silent gather).
    out_sharding = new_params["l0.w_gate_up_moe"].sharding
    assert out_sharding.is_equivalent_to(
        NamedSharding(mesh, spec("l0.w_gate_up_moe")),
        new_params["l0.w_gate_up_moe"].ndim,
    )


def test_pipeline_parallel_matches_dense_and_trains():
    """GPipe-style 2-stage pipeline over a 'pp' mesh axis: the pipelined
    loss must EQUAL the dense loss_fn (same params, same tokens), and one
    SGD step through the inter-stage permutes must reduce it."""
    from jax.sharding import Mesh

    from infinistore_tpu.models.pipeline import make_pp_train_step, stack_stage_params

    cfg = LlamaConfig(
        vocab=128, dim=64, n_layers=4, n_heads=4, n_kv_heads=2, ffn_dim=128,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(11))
    tokens = jax.random.randint(jax.random.PRNGKey(12), (8, 16), 0, cfg.vocab)
    from infinistore_tpu.models import loss_fn

    dense = float(loss_fn(params, tokens, cfg))
    mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
    step, shard = make_pp_train_step(mesh, cfg, stages=2, microbatches=4)
    stacked = shard(stack_stage_params(params, cfg, stages=2))
    new, loss = step(stacked, tokens)
    assert abs(dense - float(loss)) < 1e-5, (dense, float(loss))
    _, loss2 = step(new, tokens)
    assert float(loss2) < float(loss)


def test_pipeline_stacking_validates_inputs():
    from infinistore_tpu.models.pipeline import stack_stage_params

    cfg = LlamaConfig(n_layers=3)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="divisible"):
        stack_stage_params(params, cfg, stages=2)
    moe = LlamaConfig(n_layers=2, n_experts=2)
    with pytest.raises(ValueError, match="dense"):
        stack_stage_params(init_params(moe, jax.random.PRNGKey(0)), moe, stages=2)
