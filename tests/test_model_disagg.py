"""Model + disaggregation tests: the flagship E2E — prefill on one engine,
KV blocks through the store, decode resumes on a second engine (the
single-host shape of BASELINE.md config 5 / the reference's
prefill->decode-disaggregation scenario, README.md:13-16)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill, train_step
from infinistore_tpu.tpu import (
    HostStagingPool,
    LayerwiseKVReader,
    LayerwiseKVWriter,
    kv_block_key,
)

CFG = LlamaConfig(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    block_tokens=8, dtype=jnp.float32,  # float32 for exact comparisons
)
NUM_BLOCKS = 16
MAX_BLOCKS = 4  # 32-token max context in these tests


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _fresh_caches():
    return CFG.kv_spec(NUM_BLOCKS).make_caches()


def test_prefill_shapes(params):
    tokens = jnp.arange(16, dtype=jnp.int32) % CFG.vocab
    table = jnp.array([3, 7], dtype=jnp.int32)
    logits, caches = prefill(params, tokens, _fresh_caches(), table, CFG)
    assert logits.shape == (CFG.vocab,)
    assert len(caches) == CFG.n_layers
    # Written blocks are non-zero, untouched blocks stay zero.
    k0 = np.asarray(caches[0][0])
    assert np.abs(k0[3]).sum() > 0 and np.abs(k0[7]).sum() > 0
    assert np.abs(k0[0]).sum() == 0


def test_decode_matches_prefill(params):
    """Paged incremental decode must reproduce full-prefill logits."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, CFG.vocab)
    table = jnp.array([0, 1, 2, 3], dtype=jnp.int32)

    # Ground truth: prefill over 24 tokens.
    full = jax.random.randint(jax.random.PRNGKey(2), (24,), 0, CFG.vocab)
    full = full.at[:16].set(prompt)
    ref_logits, _ = prefill(params, full, _fresh_caches(), table[:3], CFG)

    # Incremental: prefill 16, then decode tokens 16..23 one at a time.
    logits, caches = prefill(params, prompt, _fresh_caches(), table[:2], CFG)
    for pos in range(16, 24):
        logits, caches = decode_step(
            params, full[pos], jnp.int32(pos), caches, table, CFG, MAX_BLOCKS
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )


def test_disagg_prefill_store_decode(conn, params):
    """Prefill engine -> store -> fresh decode engine, logits must match the
    non-disaggregated continuation."""
    prompt = jax.random.randint(jax.random.PRNGKey(3), (16,), 0, CFG.vocab)
    next_tok = jnp.int32(42)
    table = jnp.array([5, 9], dtype=jnp.int32)  # prefill engine's blocks

    # --- prefill engine ---
    _, prefill_caches = prefill(params, prompt, _fresh_caches(), table, CFG)
    spec = CFG.kv_spec(NUM_BLOCKS)
    pool = HostStagingPool(
        nbytes=4 * 2 * spec.block_nbytes * 2, block_size=spec.block_nbytes, conn=conn
    )
    writer = LayerwiseKVWriter(conn, pool, spec, max_blocks=2)
    key_fn = lambda l, k, i: kv_block_key("demo", "prompt-hash", l, k, i)
    asyncio.run(writer.write(prefill_caches, np.asarray(table), key_fn))

    # --- decode engine (different block layout!) ---
    decode_table = jnp.array([1, 2, 14, 3], dtype=jnp.int32)
    reader = LayerwiseKVReader(conn, pool, spec, max_blocks=2)
    decode_caches = asyncio.run(
        reader.read(_fresh_caches(), np.asarray(decode_table[:2]), key_fn)
    )
    logits_disagg, _ = decode_step(
        params, next_tok, jnp.int32(16), decode_caches, decode_table, CFG, MAX_BLOCKS
    )

    # --- reference: continue on the prefill engine directly ---
    ref_table = jnp.array([5, 9, 12, 13], dtype=jnp.int32)
    logits_ref, _ = decode_step(
        params, next_tok, jnp.int32(16), prefill_caches, ref_table, CFG, MAX_BLOCKS
    )
    np.testing.assert_allclose(
        np.asarray(logits_disagg), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )


def test_train_step_runs(params):
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, CFG.vocab)
    import copy

    p = jax.tree.map(jnp.copy, params)
    p2, loss = train_step(p, tokens, CFG)
    assert np.isfinite(float(loss))
    # Params actually moved.
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2))
    )
    assert moved
