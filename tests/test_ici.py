"""ICI fast-path tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.tpu.ici import IciBlockTransfer, mesh_from_devices


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return mesh_from_devices(axis_name="store")


def test_transfer_point_to_point(mesh):
    n_dev = 8
    tr = IciBlockTransfer(mesh, "store", perm=[(2, 5)])
    blocks = jnp.arange(n_dev * 4 * 8, dtype=jnp.float32).reshape(n_dev, 4, 8)
    out = np.asarray(tr.transfer(blocks))
    # dst row 5 received src row 2's payload; non-destination rows zeroed.
    assert np.array_equal(out[5], np.asarray(blocks)[2])
    assert out[0].sum() == 0


def test_transfer_pairwise_exchange(mesh):
    tr = IciBlockTransfer(mesh, "store", perm=[(0, 1), (1, 0)])
    blocks = jnp.stack([jnp.full((2, 4), i, dtype=jnp.float32) for i in range(8)])
    out = np.asarray(tr.transfer(blocks))
    assert (out[0] == 1).all() and (out[1] == 0).all()


def test_send_blocks_gather_and_deliver(mesh):
    """Prefill shard 1 sends selected paged blocks to decode shard 6."""
    n_dev, num_blocks = 8, 16
    block_shape = (4, 2, 8)
    cache = jax.random.normal(
        jax.random.PRNGKey(0), (n_dev, num_blocks, *block_shape), dtype=jnp.float32
    )
    ids = np.array([3, 11, 7], dtype=np.int32)
    tr = IciBlockTransfer(mesh, "store", perm=[(1, 6)])
    out = np.asarray(tr.send_blocks(cache, ids, src=1, dst=6))
    expect = np.asarray(cache)[1][ids]
    assert np.array_equal(out[6], expect)
    assert out[0].sum() == 0
