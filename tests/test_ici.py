"""ICI fast-path tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.tpu.ici import IciBlockTransfer, mesh_from_devices


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return mesh_from_devices(axis_name="store")


def test_transfer_point_to_point(mesh):
    n_dev = 8
    tr = IciBlockTransfer(mesh, "store", perm=[(2, 5)])
    blocks = jnp.arange(n_dev * 4 * 8, dtype=jnp.float32).reshape(n_dev, 4, 8)
    out = np.asarray(tr.transfer(blocks))
    # dst row 5 received src row 2's payload; non-destination rows zeroed.
    assert np.array_equal(out[5], np.asarray(blocks)[2])
    assert out[0].sum() == 0


def test_transfer_pairwise_exchange(mesh):
    tr = IciBlockTransfer(mesh, "store", perm=[(0, 1), (1, 0)])
    blocks = jnp.stack([jnp.full((2, 4), i, dtype=jnp.float32) for i in range(8)])
    out = np.asarray(tr.transfer(blocks))
    assert (out[0] == 1).all() and (out[1] == 0).all()


def test_send_blocks_gather_and_deliver(mesh):
    """Prefill shard 1 sends selected paged blocks to decode shard 6."""
    n_dev, num_blocks = 8, 16
    block_shape = (4, 2, 8)
    cache = jax.random.normal(
        jax.random.PRNGKey(0), (n_dev, num_blocks, *block_shape), dtype=jnp.float32
    )
    ids = np.array([3, 11, 7], dtype=np.int32)
    tr = IciBlockTransfer(mesh, "store", perm=[(1, 6)])
    out = np.asarray(tr.send_blocks(cache, ids, src=1, dst=6))
    expect = np.asarray(cache)[1][ids]
    assert np.array_equal(out[6], expect)
    assert out[0].sum() == 0


def test_handoff_blocks_single_program(mesh):
    """gather + ppermute + scatter fused into one SPMD program: src shard's
    selected pages land at the dst shard's chosen page slots; every other
    page on every shard keeps its bytes."""
    n_dev, num_blocks = 8, 16
    block_shape = (4, 2, 8)
    cache = jax.random.normal(
        jax.random.PRNGKey(3), (n_dev, num_blocks, *block_shape), dtype=jnp.float32
    )
    ref = np.asarray(cache)
    src_ids = np.array([2, 9], dtype=np.int32)
    dst_ids = np.array([14, 0], dtype=np.int32)
    tr = IciBlockTransfer(mesh, "store", perm=[(1, 6)])
    out = np.asarray(tr.handoff_blocks(cache, src_ids, dst_ids, src=1, dst=6))
    # dst shard 6 received src shard 1's pages at the dst slots.
    assert np.array_equal(out[6][14], ref[1][2])
    assert np.array_equal(out[6][0], ref[1][9])
    # all other pages everywhere untouched.
    mask = np.ones((n_dev, num_blocks), dtype=bool)
    mask[6][14] = mask[6][0] = False
    assert np.array_equal(out[mask], ref[mask])


def test_transfer_jit_is_cached(mesh):
    """The jitted transfer program is built once per (op, src, dst) — the
    round-1 version rebuilt shard_map+jit on every call (VERDICT weak #5)."""
    tr = IciBlockTransfer(mesh, "store", perm=[(0, 3)])
    cache = jnp.zeros((8, 4, 2, 2), dtype=jnp.float32)
    ids = np.array([1], dtype=np.int32)
    tr.send_blocks(cache, ids, 0, 3)
    fn_first = tr._jit_cache[("send", 0, 3)]
    tr.send_blocks(cache, ids, 0, 3)
    assert tr._jit_cache[("send", 0, 3)] is fn_first
    assert len(tr._jit_cache) == 1
    # Pre-sharded input is NOT resharded (device_put would copy): the
    # output of one call feeds the next without a layout round trip.
    shaped = jax.device_put(cache, tr.sharding)
    assert tr._ensure_sharded(shaped) is shaped


def test_handoff_layers_single_launch(mesh):
    """An 8-layer full-cache handoff is ONE compiled-program dispatch (and
    one collective over the stacked blocks), not L sequential launches —
    VERDICT r2 weak #6. Results must match the per-layer path exactly."""
    n_dev, num_blocks, L = 8, 12, 8
    block_shape = (4, 2, 8)
    keys = jax.random.split(jax.random.PRNGKey(7), 2 * L)
    caches = [
        (
            jax.random.normal(keys[2 * l], (n_dev, num_blocks, *block_shape)),
            jax.random.normal(keys[2 * l + 1], (n_dev, num_blocks, *block_shape)),
        )
        for l in range(L)
    ]
    refs = [(np.asarray(k), np.asarray(v)) for k, v in caches]
    src_ids = np.array([2, 9, 5], dtype=np.int32)
    dst_ids = np.array([11, 0, 7], dtype=np.int32)

    tr = IciBlockTransfer(mesh, "store", perm=[(1, 6)])
    out = tr.handoff_layers(caches, src_ids, dst_ids, src=1, dst=6)
    assert tr.launches == 1, f"expected 1 launch for {L} layers, got {tr.launches}"
    assert len(tr._jit_cache) == 1

    # Per-layer reference on untouched copies (handoff_layers donated `caches`).
    tr2 = IciBlockTransfer(mesh, "store", perm=[(1, 6)])
    for l in range(L):
        k2, v2 = tr2.handoff_kv(
            jnp.asarray(refs[l][0]), jnp.asarray(refs[l][1]),
            src_ids, dst_ids, src=1, dst=6,
        )
        assert np.array_equal(np.asarray(out[l][0]), np.asarray(k2))
        assert np.array_equal(np.asarray(out[l][1]), np.asarray(v2))
    assert tr2.launches == L  # the loop path really is L dispatches

    # Second call with same shapes reuses the cached program.
    caches2 = [
        (jnp.asarray(refs[l][0]), jnp.asarray(refs[l][1])) for l in range(L)
    ]
    tr.handoff_layers(caches2, src_ids, dst_ids, src=1, dst=6)
    assert tr.launches == 2 and len(tr._jit_cache) == 1


def test_handoff_layers_rejects_ragged_caches(mesh):
    tr = IciBlockTransfer(mesh, "store", perm=[(0, 1)])
    a = jnp.zeros((8, 4, 2, 2))
    b = jnp.zeros((8, 6, 2, 2))  # different num_blocks
    with pytest.raises(ValueError, match="uniform"):
        tr.handoff_layers([(a, a), (b, b)], [0], [1], src=0, dst=1)


def test_connector_handoff_routes_ici_without_store(mesh):
    """Connector-level route: with an IciBlockTransfer bound, handoff moves
    blocks HBM->HBM and the store is never contacted (conn=None proves it)."""
    import asyncio

    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.tpu.paged import PagedKVCacheSpec

    spec = PagedKVCacheSpec(
        num_layers=2, num_blocks=8, block_tokens=4, num_kv_heads=2, head_dim=8,
        dtype=jnp.float32,
    )
    tr = IciBlockTransfer(mesh, "store", perm=[(0, 5)])
    kvc = KVConnector(None, spec, "ici-model", max_blocks=4, ici=tr)
    caches = [
        (
            jax.random.normal(jax.random.PRNGKey(10 + l), (8, *spec.cache_shape)),
            jax.random.normal(jax.random.PRNGKey(20 + l), (8, *spec.cache_shape)),
        )
        for l in range(spec.num_layers)
    ]
    refs = [(np.asarray(k), np.asarray(v)) for k, v in caches]
    src_ids = np.array([1, 6], dtype=np.int32)
    dst_ids = np.array([3, 0], dtype=np.int32)
    out, n = asyncio.run(
        kvc.handoff(list(range(8)), caches, src_ids, dst_ids, src=0, dst=5)
    )
    assert n == 2
    assert tr.launches == 1  # connector route fuses all layers into one launch
    for l in range(spec.num_layers):
        for side in (0, 1):
            got = np.asarray(out[l][side])
            ref = refs[l][side]
            assert np.array_equal(got[5][3], ref[0][1])
            assert np.array_equal(got[5][0], ref[0][6])


def test_connector_handoff_ragged_layers_fall_back_per_layer(mesh):
    """Hybrid architectures (e.g. sliding-window layers with fewer blocks)
    cannot stack into one collective: the connector must fall back to one
    fused K+V launch per layer instead of raising."""
    import asyncio

    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.tpu.paged import PagedKVCacheSpec

    spec = PagedKVCacheSpec(
        num_layers=2, num_blocks=8, block_tokens=4, num_kv_heads=2, head_dim=8,
        dtype=jnp.float32,
    )
    tr = IciBlockTransfer(mesh, "store", perm=[(0, 3)])
    kvc = KVConnector(None, spec, "ragged", max_blocks=4, ici=tr)
    # Layer 1 has twice the blocks of layer 0 (ragged).
    caches = [
        (jnp.ones((8, 8, 4, 2, 8)), jnp.ones((8, 8, 4, 2, 8)) * 2),
        (jnp.ones((8, 16, 4, 2, 8)) * 3, jnp.ones((8, 16, 4, 2, 8)) * 4),
    ]
    out, n = asyncio.run(
        kvc.handoff(list(range(8)), caches, np.array([1, 2]), np.array([5, 0]),
                    src=0, dst=3)
    )
    assert n == 2
    assert tr.launches == 2  # one fused K+V launch per ragged layer
    for l, scale in ((0, 1), (1, 3)):
        got_k = np.asarray(out[l][0])
        assert got_k[3][5].flatten()[0] == scale  # src shard 0's block 1 content


def test_connector_handoff_degrades_to_dcn():
    """Without a bound mesh the same handoff call rides the DCN store."""
    import asyncio

    import infinistore_tpu as its
    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.tpu.paged import PagedKVCacheSpec

    spec = PagedKVCacheSpec(
        num_layers=2, num_blocks=16, block_tokens=4, num_kv_heads=2, head_dim=8,
        dtype=jnp.bfloat16,
    )
    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    conn = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    conn.connect()
    kvc = KVConnector(conn, spec, "dcn-model", max_blocks=4)  # no ici
    caches = [
        (
            jax.random.normal(jax.random.PRNGKey(l), spec.cache_shape).astype(spec.dtype),
            jax.random.normal(jax.random.PRNGKey(9 + l), spec.cache_shape).astype(spec.dtype),
        )
        for l in range(spec.num_layers)
    ]
    refs = [(np.asarray(k, np.float32), np.asarray(v, np.float32)) for k, v in caches]
    toks = list(range(2 * spec.block_tokens))
    src_ids = np.array([5, 11], dtype=np.int32)
    dst_ids = np.array([0, 3], dtype=np.int32)
    out, n = asyncio.run(kvc.handoff(toks, caches, src_ids, dst_ids))
    assert n == 2
    for l in range(spec.num_layers):
        for side in (0, 1):
            got = np.asarray(out[l][side], np.float32)
            assert np.array_equal(got[dst_ids[0]], refs[l][side][src_ids[0]])
            assert np.array_equal(got[dst_ids[1]], refs[l][side][src_ids[1]])
    conn.close()
    srv.stop()
