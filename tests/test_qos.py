"""Two-class QoS data plane (docs/qos.md): wire tag, scheduler behavior,
starvation-proofing, and byte-correctness under preemption.

The contract under test, end to end:
- FOREGROUND (untagged) is byte-identical to the pre-QoS wire format and
  runs the pre-QoS FIFO scheduling — tagging is strictly additive.
- A BACKGROUND-tagged op yields to foreground work in every queue it
  crosses (client sub-batch gate, stripe scheduler, server slice
  scheduler) but can never starve: time-based aging guarantees progress
  under a permanent foreground flood.
- Preemption/deferral never costs bytes: everything a background op wrote
  reads back exactly.
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import lib as libmod
from infinistore_tpu import wire

pytestmark = pytest.mark.qos

BLOCK = 64 << 10


@pytest.fixture
def server():
    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=BLOCK)
    yield srv
    srv.stop()


def _connect(port, **kw):
    conn = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=port,
                         log_level="error", **kw)
    )
    conn.connect()
    return conn


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


def test_priority_tag_is_optional_trailing_byte():
    m0 = wire.BatchMeta(block_size=4096, keys=["a", "b"])
    m1 = wire.BatchMeta(block_size=4096, keys=["a", "b"],
                        priority=wire.PRIORITY_BACKGROUND)
    assert m1.encode() == m0.encode() + b"\x01"
    assert wire.BatchMeta.decode(m0.encode()).priority == wire.PRIORITY_FOREGROUND
    assert wire.BatchMeta.decode(m1.encode()).priority == wire.PRIORITY_BACKGROUND

    s0 = wire.SegBatchMeta(block_size=4096, seg_id=7, keys=["k"], offsets=[65536])
    s1 = wire.SegBatchMeta(block_size=4096, seg_id=7, keys=["k"], offsets=[65536],
                           priority=wire.PRIORITY_BACKGROUND)
    assert s1.encode() == s0.encode() + b"\x01"
    d = wire.SegBatchMeta.decode(s1.encode())
    assert d.priority == wire.PRIORITY_BACKGROUND and d.offsets == [65536]
    # Round-trips through the tagged encoding preserve every other field.
    assert d.keys == ["k"] and d.seg_id == 7 and d.block_size == 4096


def test_qos_kwargs_gates_on_awareness():
    class Aware:
        QOS_AWARE = True

    class Naive:
        pass

    assert wire.qos_kwargs(Aware(), wire.PRIORITY_BACKGROUND) == {"priority": 1}
    assert wire.qos_kwargs(Aware(), wire.PRIORITY_FOREGROUND) == {}
    assert wire.qos_kwargs(Naive(), wire.PRIORITY_BACKGROUND) == {}


# ---------------------------------------------------------------------------
# Single connection: tagged ops, counters, byte-correctness
# ---------------------------------------------------------------------------


def test_tagged_ops_roundtrip_and_count(server):
    conn = _connect(server.port)
    try:
        buf = conn.alloc_shm_mr(32 * BLOCK)
        if buf is None:
            buf = np.zeros(32 * BLOCK, dtype=np.uint8)
            conn.register_mr(buf)
        rng = np.random.default_rng(7)
        buf[:] = rng.integers(0, 256, size=buf.size, dtype=np.uint8)
        want = buf.copy()
        pairs = [(f"q{i}", i * BLOCK) for i in range(32)]

        async def go():
            await conn.write_cache_async(
                pairs, BLOCK, buf.ctypes.data, priority=wire.PRIORITY_BACKGROUND
            )
            buf[:] = 0
            await conn.read_cache_async(pairs, BLOCK, buf.ctypes.data)

        asyncio.run(go())
        assert np.array_equal(buf, want)

        qs = conn.qos_stats()
        assert qs["bg_ops"] == 1 and qs["fg_ops"] == 1
        srv_qos = conn.get_stats()["qos"]
        # The 2MB background write rides sub-batches; every one is tagged.
        assert srv_qos["bg_ops"] >= 1
        assert srv_qos["fg_ops"] >= 1
    finally:
        conn.close()


def test_sync_tagged_ops(server):
    conn = _connect(server.port)
    try:
        buf = conn.alloc_shm_mr(4096)
        if buf is None:
            buf = np.zeros(4096, dtype=np.uint8)
            conn.register_mr(buf)
        buf[:] = 9
        conn.write_cache([("sk", 0)], 4096, buf.ctypes.data,
                         priority=wire.PRIORITY_BACKGROUND)
        buf[:] = 0
        conn.read_cache([("sk", 0)], 4096, buf.ctypes.data)
        assert (np.asarray(buf) == 9).all()
        assert conn.qos_stats()["bg_ops"] == 1
    finally:
        conn.close()


def test_bg_subbatch_split_bounds_inflight_bytes(server):
    conn = _connect(server.port)
    try:
        per = max(1, conn.BG_SUBBATCH_BYTES // 2 // BLOCK)
        blocks = [(f"s{i}", i * BLOCK) for i in range(3 * per + 1)]
        subs = conn._bg_subbatches(blocks, BLOCK)
        assert sum(len(s) for s in subs) == len(blocks)
        assert all(len(s) * BLOCK <= conn.BG_SUBBATCH_BYTES // 2 for s in subs)
        # Order-preserving, contiguous split.
        assert [b for s in subs for b in s] == blocks
        # Under half the budget: no split at all (and foreground never splits).
        assert conn._bg_subbatches(blocks[:per], BLOCK) == [blocks[:per]]
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Starvation-proofing: a background batch completes under a permanent
# foreground flood (acceptance criterion: impossible by construction).
# ---------------------------------------------------------------------------


def test_bg_completes_under_permanent_fg_flood(server):
    bg = _connect(server.port)
    fg = _connect(server.port)
    try:
        n = 64
        bgbuf = bg.alloc_shm_mr(n * BLOCK)
        if bgbuf is None:
            bgbuf = np.zeros(n * BLOCK, dtype=np.uint8)
            bg.register_mr(bgbuf)
        bgbuf[:] = 5
        fgbuf = fg.alloc_shm_mr(4096)
        if fgbuf is None:
            fgbuf = np.zeros(4096, dtype=np.uint8)
            fg.register_mr(fgbuf)
        fgbuf[:] = 1
        fg.write_cache([("hot", 0)], 4096, fgbuf.ctypes.data)

        stop = []

        def flood():
            while not stop:
                fg.read_cache([("hot", 0)], 4096, fgbuf.ctypes.data)

        th = threading.Thread(target=flood)
        th.start()
        try:
            pairs = [(f"fl{i}", i * BLOCK) for i in range(n)]
            t0 = time.monotonic()

            async def put():
                await bg.write_cache_async(
                    pairs, BLOCK, bgbuf.ctypes.data,
                    priority=wire.PRIORITY_BACKGROUND,
                )

            asyncio.run(put())  # must return while the flood still runs
            assert time.monotonic() - t0 < 30.0
        finally:
            stop.append(1)
            th.join()
        # Bytes survived the aged/preempted slices.
        bgbuf[:] = 0
        asyncio.run(bg.read_cache_async(pairs, BLOCK, bgbuf.ctypes.data))
        assert (np.asarray(bgbuf) == 5).all()
        srv_qos = bg.get_stats()["qos"]
        assert srv_qos["bg_preempted_slices"] + srv_qos["bg_aged_slices"] > 0
    finally:
        bg.close()
        fg.close()


def test_client_gate_ages_out():
    """The process-wide foreground gate must release a background waiter
    within _BG_AGING_S even if foreground never goes idle."""

    class C:
        _bg_deferred = 0
        _bg_aged = 0

    conn = C()
    libmod._fg_gate_enter()
    try:
        t0 = time.monotonic()
        libmod._bg_gate_wait_sync(conn)
        waited = time.monotonic() - t0
        assert conn._bg_deferred == 1 and conn._bg_aged == 1
        assert waited >= libmod._BG_AGING_S * 0.5
        assert waited < libmod._BG_AGING_S * 10
    finally:
        libmod._fg_gate_exit()
    # Gate open (after cooldown): no deferral at all.
    time.sleep(libmod._BG_COOLDOWN_S * 2)
    t0 = time.monotonic()
    libmod._bg_gate_wait_sync(conn)
    assert time.monotonic() - t0 < libmod._BG_AGING_S / 2
    assert conn._bg_deferred == 1


# ---------------------------------------------------------------------------
# Striped connection: foreground jumps the stripe queue, background ages,
# bytes stay correct — over a shaped (paced, shm-off) connection so the
# adaptive scheduler really stripes.
# ---------------------------------------------------------------------------


def _shaped_striped(port, streams=2, cap_mbps=200):
    from infinistore_tpu.shaping import shaped_config

    conn = its.StripedConnection(shaped_config(port, cap_mbps), streams=streams)
    conn.connect()
    return conn


def test_striped_mixed_priority_shaped(server):
    conn = _shaped_striped(server.port)
    try:
        n = 64
        rng = np.random.default_rng(3)
        src = rng.integers(0, 256, size=n * BLOCK, dtype=np.uint8)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)
        bg_pairs = [(f"bgq{i}", i * BLOCK) for i in range(n)]
        fg_pairs = [(f"fgq{i}", i * BLOCK) for i in range(8)]

        async def go():
            # Seed foreground keys first (untagged).
            await conn.write_cache_async(fg_pairs, BLOCK, src.ctypes.data)
            # Launch a background write and, while it runs, a foreground
            # read — the fg op must jump the stripe queue (bg pulls defer).
            bg_task = asyncio.ensure_future(conn.write_cache_async(
                bg_pairs, BLOCK, src.ctypes.data,
                priority=wire.PRIORITY_BACKGROUND,
            ))
            await asyncio.sleep(0.002)  # bg is mid-flight
            await conn.read_cache_async(fg_pairs, BLOCK, dst.ctypes.data)
            await bg_task
            # Read everything back (untagged) and verify bytes.
            dst[:] = 0
            await conn.read_cache_async(bg_pairs, BLOCK, dst.ctypes.data)

        asyncio.run(go())
        assert np.array_equal(dst[: n * BLOCK], src[: n * BLOCK])
        stats = conn.data_plane_stats()
        assert stats["qos"]["bg_ops"] == 1
        assert stats["qos"]["fg_ops"] == 3
        # The background op really deferred to the concurrent foreground op
        # at least once (it was mid-flight when the fg read arrived).
        assert (
            stats["qos"]["bg_deferred_pulls"] + stats["qos"]["bg_subbatches"] > 0
        )
    finally:
        conn.close()


def test_striped_bg_aging_under_fg_flood(server):
    """Background batch over a striped connection completes while a
    foreground flood holds the class gate — the BG_AGING_S escape."""
    conn = _shaped_striped(server.port)
    try:
        n = 48
        src = np.full(n * BLOCK, 7, dtype=np.uint8)
        conn.register_mr(src)
        pairs = [(f"ag{i}", i * BLOCK) for i in range(n)]

        async def go():
            stop = []

            async def fg_flood():
                while not stop:
                    await conn.write_cache_async(pairs[:2], BLOCK, src.ctypes.data)

            flood = asyncio.ensure_future(fg_flood())
            try:
                await asyncio.wait_for(
                    conn.write_cache_async(
                        pairs, BLOCK, src.ctypes.data,
                        priority=wire.PRIORITY_BACKGROUND,
                    ),
                    timeout=30.0,
                )
            finally:
                stop.append(1)
                await flood

        asyncio.run(go())
        dst = np.zeros_like(src)
        conn.register_mr(dst)
        asyncio.run(conn.read_cache_async(pairs, BLOCK, dst.ctypes.data))
        assert (dst == 7).all()
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Scheduler counters surface everywhere the ISSUE promises.
# ---------------------------------------------------------------------------


def test_server_stats_and_prometheus_export(server):
    conn = _connect(server.port)
    try:
        buf = conn.alloc_shm_mr(BLOCK)
        if buf is None:
            buf = np.zeros(BLOCK, dtype=np.uint8)
            conn.register_mr(buf)
        buf[:] = 3
        conn.write_cache([("m", 0)], BLOCK, buf.ctypes.data,
                         priority=wire.PRIORITY_BACKGROUND)
        st = conn.get_stats()
        qos = st["qos"]
        for key in (
            "fg_ops", "bg_ops", "fg_slices", "bg_slices",
            "bg_preempted_slices", "bg_aged_slices", "fg_queued", "bg_queued",
        ):
            assert key in qos, key
        assert qos["bg_ops"] >= 1
        assert "suspended_ops" in st

        from infinistore_tpu.server import _prometheus_text

        text = _prometheus_text(st).decode()
        assert 'infinistore_qos_ops{class="bg"}' in text
        assert "infinistore_qos_bg_preempted_slices" in text
        assert "infinistore_dataplane_suspended_ops" in text
    finally:
        conn.close()


def test_start_fetch_promote_upgrades_class(server):
    """A background-tagged speculative prefetch must upgrade to foreground
    the moment the engine admits its request (promote()) — including on the
    coalescer path, whose submit closure reads the live class cell."""
    import jax.numpy as jnp

    from infinistore_tpu.connector import KVConnector
    from infinistore_tpu.tpu.paged import PagedKVCacheSpec

    spec = PagedKVCacheSpec(
        num_layers=2, num_blocks=8, block_tokens=4, num_kv_heads=1,
        head_dim=8, dtype=jnp.float32,
    )
    conn = _connect(server.port)
    try:
        kvc = KVConnector(conn, spec, "qospf", max_blocks=4)

        async def go():
            h = kvc.start_fetch(
                list(range(8)), priority=wire.PRIORITY_BACKGROUND
            )
            assert h._pri_cell["value"] == wire.PRIORITY_BACKGROUND
            h.promote()
            assert h._pri_cell["value"] == wire.PRIORITY_FOREGROUND
            h.promote()  # idempotent
            assert h._pri_cell["value"] == wire.PRIORITY_FOREGROUND
            await h.discard()

        asyncio.run(go())
    finally:
        conn.close()


def test_fetch_coalescer_partitions_classes(server):
    """Same-tick submissions merge within a class but never across
    classes — a background speculative prefetch must not drag a foreground
    admission fetch into its service class (or vice versa)."""
    from infinistore_tpu.connector import FetchCoalescer

    conn = _connect(server.port)
    try:
        buf = conn.alloc_shm_mr(16 * BLOCK)
        if buf is None:
            buf = np.zeros(16 * BLOCK, dtype=np.uint8)
            conn.register_mr(buf)
        buf[:] = 8
        pairs = [(f"c{i}", i * BLOCK) for i in range(4)]
        asyncio.run(conn.write_cache_async(pairs, BLOCK, buf.ctypes.data))

        co = FetchCoalescer(conn, BLOCK, buf.ctypes.data)

        async def go():
            futs = [
                co.submit([pairs[0]]),
                co.submit([pairs[1]]),
                co.submit([pairs[2]], priority=wire.PRIORITY_BACKGROUND),
                co.submit([pairs[3]], priority=wire.PRIORITY_BACKGROUND),
            ]
            await asyncio.gather(*futs)

        asyncio.run(go())
        assert co.submissions == 4
        assert co.calls == 2  # one merged call per class, never across
    finally:
        conn.close()
