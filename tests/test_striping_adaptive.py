"""Adaptive work-stealing stripe scheduler (lib.StripedConnection).

The static 1/N split let one slow stripe gate every batched op (the
BENCH_r05 4-vs-1 inversion); the scheduler replaces it with bounded chunk
descriptors on a shared queue that stripes pull as they finish prior ones,
per-stripe EWMA-adaptive pull sizes, and a same-host detector that
collapses to stripe 0 when the data plane is a memcpy. These tests pin the
scheduler's correctness properties (data integrity through arbitrary chunk
interleavings, typed errors, settle-before-raise) and its observable
scheduling behavior (participation, stealing, collapse, pull sizing).
"""

import asyncio

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu.lib import StripedConnection

BLOCK = 64 << 10


@pytest.fixture(scope="module")
def socket_server():
    """Shm OFF: batched bytes ride the sockets, so the fan-out is real and
    the same-host detector must NOT collapse."""
    srv = its.start_local_server(
        prealloc_bytes=256 << 20, block_bytes=BLOCK, enable_shm=False
    )
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def shm_server():
    srv = its.start_local_server(prealloc_bytes=256 << 20, block_bytes=BLOCK)
    yield srv
    srv.stop()


def _cfg(port, **kw):
    return its.ClientConfig(
        host_addr="127.0.0.1", service_port=port, log_level="error", **kw
    )


def test_adaptive_roundtrip_and_participation(socket_server):
    """A 64-block batch over 4 stripes: bytes survive the work-stealing
    interleave, every stripe pulls work, and the op was actually chunked
    (more chunks than stripes -> at least one stripe came back for more)."""
    conn = StripedConnection(
        _cfg(socket_server.port, enable_shm=False), streams=4
    )
    conn.connect()
    try:
        n = 64
        src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)
        pairs = [(f"ad-{i}", i * BLOCK) for i in range(n)]

        async def go():
            await conn.write_cache_async(pairs, BLOCK, src.ctypes.data)
            await conn.read_cache_async(pairs, BLOCK, dst.ctypes.data)

        asyncio.run(go())
        assert np.array_equal(src, dst)
        stats = conn.data_plane_stats()
        assert stats["collapsed_ops"] == 0, "no shm -> no same-host collapse"
        assert stats["chunks"] > stats["streams"], stats
        assert all(c > 0 for c in stats["stripe_chunks"]), stats
        assert sum(stats["stripe_blocks"]) == 2 * n, stats
        assert stats["steals"] > 0, "nobody pulled a second chunk"
        # The measured EWMA feeds the next batch's pull sizing.
        assert all(e > 0 for e in stats["stripe_ewma_gbps"]), stats
    finally:
        conn.close()


def test_same_host_shm_collapses_to_one_stripe(shm_server):
    """With the shm fast path active the data plane is a memcpy: batched
    ops must ride stripe 0 whole (striping can only lose here), and the
    bytes must still verify."""
    conn = StripedConnection(_cfg(shm_server.port), streams=4)
    conn.connect()
    try:
        assert conn.shm_active and conn.memcpy_bound()
        n = 32
        buf = conn.alloc_shm_mr(n * BLOCK)
        buf[:] = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
        gold = buf.copy()
        pairs = [(f"co-{i}", i * BLOCK) for i in range(n)]

        async def go():
            await conn.write_cache_async(pairs, BLOCK, buf.ctypes.data)
            buf[:] = 0
            await conn.read_cache_async(pairs, BLOCK, buf.ctypes.data)

        asyncio.run(go())
        assert np.array_equal(buf, gold)
        stats = conn.data_plane_stats()
        assert stats["collapsed_ops"] == 2, stats
        assert stats["chunks"] == 0, "collapsed ops must not be chunked"
    finally:
        conn.close()


def test_missing_key_raises_typed_after_settle(socket_server):
    """KeyNotFound on one stolen chunk propagates as the typed exception,
    and only after every stripe's in-flight op settled (no pending native
    ops scatter/gathering into caller memory once the caller sees the
    error — the settle-before-raise contract the static split had)."""
    conn = StripedConnection(
        _cfg(socket_server.port, enable_shm=False), streams=4
    )
    conn.connect()
    try:
        n = 32
        buf = np.zeros(n * BLOCK, dtype=np.uint8)
        conn.register_mr(buf)
        pairs = [(f"miss-{i}", i * BLOCK) for i in range(n)]

        with pytest.raises(its.InfiniStoreKeyNotFound):
            asyncio.run(conn.read_cache_async(pairs, BLOCK, buf.ctypes.data))
        # The connection must remain fully usable (nothing wedged).
        buf[:] = 7
        asyncio.run(conn.write_cache_async(pairs, BLOCK, buf.ctypes.data))
        buf[:] = 0
        asyncio.run(conn.read_cache_async(pairs, BLOCK, buf.ctypes.data))
        assert (buf == 7).all()
    finally:
        conn.close()


def test_small_batches_skip_the_scheduler(socket_server):
    """Below 2*streams blocks, fan-out would only add round trips: the op
    rides stripe 0 and is counted as small, not chunked."""
    conn = StripedConnection(
        _cfg(socket_server.port, enable_shm=False), streams=4
    )
    conn.connect()
    try:
        buf = np.ones(4 * BLOCK, dtype=np.uint8)
        conn.register_mr(buf)
        pairs = [(f"sm-{i}", i * BLOCK) for i in range(4)]
        asyncio.run(conn.write_cache_async(pairs, BLOCK, buf.ctypes.data))
        stats = conn.data_plane_stats()
        assert stats["small_ops"] == 1 and stats["chunks"] == 0, stats
    finally:
        conn.close()


def test_pull_sizing_tracks_ewma_and_tail():
    """Pure sizing-policy unit test (no server): unmeasured stripes start
    at one quantum; a fast stripe's pull grows toward its EWMA x target
    time (whole quanta, capped); the remaining-work fair share splits the
    batch tail finely no matter how fast a stripe claims to be."""
    conn = StripedConnection.__new__(StripedConnection)
    conn.conns = [None] * 4
    conn._ewma_bps = [0.0] * 4
    q = StripedConnection.CHUNK_QUANTUM_BLOCKS
    # Unmeasured: exactly one quantum.
    assert conn._pull_blocks(0, 1000, BLOCK) == q
    # 2 GB/s EWMA at a 4ms target = ~8MB = 128 x 64KB blocks.
    conn._ewma_bps[1] = 2 * (1 << 30)
    take = conn._pull_blocks(1, 1000, BLOCK)
    assert take == 128 and take % q == 0
    # Absurd EWMA: capped at MAX_CHUNK_BLOCKS (remaining big enough that
    # the fair-share cap is not the binding one).
    conn._ewma_bps[2] = 1 << 40
    assert conn._pull_blocks(2, 4000, BLOCK) == StripedConnection.MAX_CHUNK_BLOCKS
    # Tail: with 32 blocks left, even the fastest stripe takes only a fair
    # share (ceil(32/4) = 8), so the end of the batch stays finely split.
    assert conn._pull_blocks(2, 32, BLOCK) == 8
    # Last blocks: never zero, never more than remain.
    assert conn._pull_blocks(2, 3, BLOCK) == 3
    # Paced stripe (50 MB/s): EWMA x 4ms is under one quantum -> floor at q.
    conn._ewma_bps[3] = 50 * (1 << 20)
    assert conn._pull_blocks(3, 1000, BLOCK) == q


def test_preferred_fanout_blocks_hint():
    conn = StripedConnection.__new__(StripedConnection)
    conn.conns = [None] * 4
    assert conn.preferred_fanout_blocks() == 4 * StripedConnection.MAX_CHUNK_BLOCKS


def test_completion_coalescing_counters(shm_server):
    """A burst of concurrent single-block reads must retire on fewer
    eventfd signals than completions (the native ring writes the fd only on
    empty->non-empty transitions), and the loop must drain every completion
    it was signalled for."""
    conn = its.InfinityConnection(_cfg(shm_server.port))
    conn.connect()
    try:
        n = 32
        block = 4 << 10
        buf = conn.alloc_shm_mr(n * block)
        buf[:] = 1
        pairs = [(f"cc-{i}", i * block) for i in range(n)]
        asyncio.run(conn.write_cache_async(pairs, block, buf.ctypes.data))

        async def burst():
            await asyncio.gather(*(
                conn.read_cache_async([p], block, buf.ctypes.data) for p in pairs
            ))

        for _ in range(3):
            asyncio.run(burst())
        st = conn.completion_stats()
        # Completions retire through TWO drains since the adaptive bridge
        # poll (PR 16): the add_reader loop drain and _ring_await's
        # poll-then-park window. Every completion must land in exactly one.
        assert st["completions"] == st["loop_drained"] + st["bridge_poll_drained"], st
        assert st["wakeups_signalled"] <= st["completions"], st
        assert st["completion_batch_size"] >= 1.0, st
        # 3 bursts of 32 concurrent ops: if every op still paid its own
        # wakeup the batch size would be exactly 1.0; coalescing must show.
        assert st["completion_batch_size"] > 1.2, st
    finally:
        conn.close()


def test_static_split_mode_still_works(socket_server):
    """adaptive=False keeps the legacy contiguous 1/N split (the
    benchmark's A/B baseline) byte-correct."""
    conn = StripedConnection(
        _cfg(socket_server.port, enable_shm=False), streams=4, adaptive=False
    )
    conn.connect()
    try:
        n = 32
        src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)
        pairs = [(f"st-{i}", i * BLOCK) for i in range(n)]

        async def go():
            await conn.write_cache_async(pairs, BLOCK, src.ctypes.data)
            await conn.read_cache_async(pairs, BLOCK, dst.ctypes.data)

        asyncio.run(go())
        assert np.array_equal(src, dst)
        assert conn.data_plane_stats()["chunks"] == 0
    finally:
        conn.close()
