"""TPU data plane tests (CPU backend: pure-XLA fallbacks + real staging +
real loopback store). The full pipeline — paged cache -> gather -> staging ->
DCN -> server pool and back — runs end-to-end with no TPU hardware."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.tpu import (
    HostStagingPool,
    LayerwiseKVReader,
    LayerwiseKVWriter,
    PagedKVCacheSpec,
    gather_blocks,
    gather_blocks_xla,
    kv_block_key,
    scatter_blocks,
    scatter_blocks_xla,
)

SPEC = PagedKVCacheSpec(
    num_layers=4, num_blocks=32, block_tokens=8, num_kv_heads=2, head_dim=64,
    dtype=jnp.bfloat16,
)


def _rand_cache(seed):
    return jax.random.normal(
        jax.random.PRNGKey(seed), SPEC.cache_shape, dtype=jnp.float32
    ).astype(SPEC.dtype)


def test_gather_scatter_xla_roundtrip():
    cache = _rand_cache(0)
    ids = jnp.array([5, 1, 30], dtype=jnp.int32)
    blocks = gather_blocks_xla(cache, ids)
    assert blocks.shape == (3, *SPEC.block_shape)
    # Scatter into an empty cache and gather again.
    empty = jnp.zeros_like(cache)
    updated = scatter_blocks_xla(empty, ids, blocks)
    again = gather_blocks_xla(updated, ids)
    assert np.array_equal(
        np.asarray(again, dtype=np.float32), np.asarray(blocks, dtype=np.float32)
    )
    # Non-targeted blocks untouched.
    assert np.asarray(updated, dtype=np.float32)[0].sum() == 0


def test_gather_scatter_dispatch_matches_xla():
    # On CPU the dispatchers use the XLA path; equality is trivial there but
    # this pins the public API contract either way.
    cache = _rand_cache(1)
    ids = jnp.array([7, 3], dtype=jnp.int32)
    assert np.array_equal(
        np.asarray(gather_blocks(cache, ids), dtype=np.float32),
        np.asarray(gather_blocks_xla(cache, ids), dtype=np.float32),
    )
    blocks = gather_blocks_xla(cache, ids)
    assert np.array_equal(
        np.asarray(scatter_blocks(jnp.zeros_like(cache), ids, blocks), np.float32),
        np.asarray(scatter_blocks_xla(jnp.zeros_like(cache), ids, blocks), np.float32),
    )


def test_staging_pool_roundtrip():
    from infinistore_tpu.tpu.staging import StagedTransfer

    pool = HostStagingPool(nbytes=1 << 20, block_size=SPEC.block_nbytes)
    arr = jax.random.normal(jax.random.PRNGKey(2), (4, *SPEC.block_shape)).astype(
        SPEC.dtype
    )
    # Zero-copy D2H: the host view is jax's own transfer buffer.
    views = StagedTransfer([arr]).wait()
    assert views[0].nbytes == arr.size * arr.dtype.itemsize
    assert np.array_equal(views[0].astype(np.float32), np.asarray(arr, np.float32))
    # Pool slots round-trip through stage_in.
    host = views[0].reshape(-1).view(np.uint8)
    pool.slot_view(0, host.nbytes)[:] = host
    back = pool.stage_in([0], arr.shape, SPEC.dtype)[0]
    assert np.array_equal(
        np.asarray(back, dtype=np.float32), np.asarray(arr, dtype=np.float32)
    )


def test_staging_pool_alignment_and_bounds():
    pool = HostStagingPool(nbytes=64 << 10, block_size=16 << 10)
    assert pool.base_ptr % 4096 == 0
    assert pool.num_slots == 4
    with pytest.raises(IndexError):
        pool.slot_offset(4)


def test_layerwise_writer_reader_e2e(conn):
    """Full pipeline: per-layer paged caches -> store -> fresh caches."""
    n_blocks = 6
    ids = np.array([3, 9, 0, 17, 31, 12], dtype=np.int32)
    caches = [( _rand_cache(10 + l), _rand_cache(100 + l)) for l in range(SPEC.num_layers)]

    pool = HostStagingPool(
        nbytes=4 * n_blocks * SPEC.block_nbytes * 2,
        block_size=SPEC.block_nbytes,
        conn=conn,
    )
    writer = LayerwiseKVWriter(conn, pool, SPEC, max_blocks=n_blocks)
    reader = LayerwiseKVReader(conn, pool, SPEC, max_blocks=n_blocks)

    def key_fn(layer, kind, i):
        return kv_block_key("llama-test", "chainhash42", layer, kind, i)

    total = asyncio.run(writer.write(caches, ids, key_fn))
    assert total == 2 * SPEC.num_layers * n_blocks  # K+V per layer

    # Restore into zeroed caches and compare only the targeted blocks.
    zero = [(jnp.zeros_like(k), jnp.zeros_like(v)) for k, v in caches]
    restored = asyncio.run(reader.read(zero, ids, key_fn))
    ids_dev = jnp.asarray(ids)
    for layer in range(SPEC.num_layers):
        for orig, got in zip(caches[layer], restored[layer]):
            assert np.array_equal(
                np.asarray(gather_blocks_xla(got, ids_dev), dtype=np.float32),
                np.asarray(gather_blocks_xla(orig, ids_dev), dtype=np.float32),
            ), f"layer {layer} mismatch"


def test_layerwise_prefix_reuse(conn):
    """The key scheme supports longest-prefix matching across requests."""
    n_blocks = 4
    ids = np.arange(n_blocks, dtype=np.int32)
    caches = [(_rand_cache(20), _rand_cache(21))]
    spec1 = PagedKVCacheSpec(1, 32, 8, 2, 64, jnp.bfloat16)
    pool = HostStagingPool(
        nbytes=4 * n_blocks * spec1.block_nbytes * 2,
        block_size=spec1.block_nbytes,
        conn=conn,
    )
    writer = LayerwiseKVWriter(conn, pool, spec1, max_blocks=n_blocks)
    asyncio.run(
        writer.write(caches, ids, lambda l, k, i: kv_block_key("m", "h1", l, k, i))
    )
    # A new request with a longer chain: first 4 blocks hit, rest miss.
    chain = [kv_block_key("m", "h1", 0, "k", i) for i in range(8)]
    assert conn.get_match_last_index(chain) == 3


def test_writer_capacity_check(conn):
    spec1 = PagedKVCacheSpec(1, 8, 8, 2, 64, jnp.bfloat16)
    pool = HostStagingPool(nbytes=8 * spec1.block_nbytes, block_size=spec1.block_nbytes)
    # The writer ships from jax D2H buffers (no pool slots), so a small pool
    # is fine — but a batch beyond max_blocks must be rejected.
    writer = LayerwiseKVWriter(conn, pool, spec1, max_blocks=2)
    cache = _rand_cache(5)
    with pytest.raises(ValueError):
        asyncio.run(
            writer.write([(cache, cache)], np.arange(3, dtype=np.int32), lambda *a: "x")
        )
    # The reader does stage through the pool: 8 slots < 4*max_blocks.
    with pytest.raises(ValueError):
        LayerwiseKVReader(conn, pool, spec1, max_blocks=8)


def test_pallas_kernels_interpret_mode_match_xla():
    """Run the actual Pallas kernels (interpret=True) on CPU and compare with
    the XLA reference — the kernels themselves get CI coverage, not just the
    dispatch wrapper (tpu/paged.py:114-156)."""
    from infinistore_tpu.tpu.paged import (
        _gather_blocks_pallas,
        _scatter_blocks_pallas,
    )

    cache = _rand_cache(3)
    ids = jnp.array([7, 0, 13, 2], dtype=jnp.int32)
    got = _gather_blocks_pallas(cache, ids, interpret=True)
    want = gather_blocks_xla(cache, ids)
    np.testing.assert_array_equal(
        np.asarray(got, np.float32), np.asarray(want, np.float32)
    )

    blocks = _rand_cache(4)[:4]
    base = _rand_cache(5)
    got_scatter = _scatter_blocks_pallas(base + 0, ids, blocks, interpret=True)
    want_scatter = scatter_blocks_xla(base, ids, blocks)
    np.testing.assert_array_equal(
        np.asarray(got_scatter, np.float32), np.asarray(want_scatter, np.float32)
    )


def test_pallas_scatter_aliasing_regression():
    """The donation-aliasing regression (real-TPU bug masked by CPU runs):
    scatter donates + aliases its cache argument, so untouched blocks must
    keep their bytes and each K/V cache must be a distinct buffer. Run the
    Pallas kernel in interpret mode to exercise the alias index mapping."""
    from infinistore_tpu.tpu.paged import _scatter_blocks_pallas

    spec1 = PagedKVCacheSpec(2, 16, 8, 2, 64, jnp.bfloat16)
    caches = spec1.make_caches()
    # make_caches must hand out distinct buffers (scatter donates them).
    seen = set()
    for k, v in caches:
        for arr in (k, v):
            if hasattr(arr, "unsafe_buffer_pointer"):
                ptr = arr.unsafe_buffer_pointer()
            else:
                # CPU jax zero-copies into numpy, so the data address is a
                # faithful aliasing probe (id(arr) would be vacuous).
                ptr = np.asarray(arr).__array_interface__["data"][0]
            assert ptr not in seen, "aliased zeros buffer across K/V caches"
            seen.add(ptr)

    cache = _rand_cache(11)
    ids = jnp.array([5, 9], dtype=jnp.int32)
    blocks = _rand_cache(12)[:2]
    out = _scatter_blocks_pallas(cache + 0, ids, blocks, interpret=True)
    ref = np.asarray(cache, np.float32)
    got = np.asarray(out, np.float32)
    # Targeted blocks replaced...
    np.testing.assert_array_equal(got[np.asarray(ids)], np.asarray(blocks, np.float32))
    # ...every other block byte-identical (the alias actually carried through).
    untouched = [i for i in range(cache.shape[0]) if i not in (5, 9)]
    np.testing.assert_array_equal(got[untouched], ref[untouched])
