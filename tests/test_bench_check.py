"""tools/bench_check.py: the data-plane regression gate.

The gate exists so the BENCH_r05 striping inversion (striped_4 < striped_1)
can never silently return; these tests pin its verdicts against the real
historical receipt and synthetic ones, including the driver's truncated
``tail`` format (the receipt's head is routinely clipped mid-JSON).
"""

import importlib.util
import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench_check():
    path = os.path.join(_REPO, "tools", "bench_check.py")
    spec = importlib.util.spec_from_file_location("bench_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_check = _load_bench_check()


def test_fails_on_the_r05_inversion_receipt():
    """The founding requirement: the real BENCH_r05.json (striped_4 3.14 <
    striped_1 5.03) must fail the gate."""
    path = os.path.join(_REPO, "BENCH_r05.json")
    if not os.path.exists(path):
        pytest.skip("historical receipt not present")
    assert bench_check.main([path]) == 1


def test_passes_on_a_healthy_receipt(tmp_path):
    doc = {
        "metric": "kv_batched_write_read_throughput",
        "value": 5.5,
        "extra": {
            "striped_1_gbps": 5.4,
            "striped_4_gbps": 5.5,
            "shaped_striped_1_mbps": 51.0,
            "shaped_striped_4_mbps": 205.0,
            "p50_fetch_4k_us": 28.0,
            "sync_p50_fetch_4k_us": 23.0,
        },
    }
    p = tmp_path / "good.json"
    p.write_text(json.dumps(doc))
    assert bench_check.main([str(p)]) == 0


def test_fails_on_inverted_striping(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"striped_1_gbps": 5.0, "striped_4_gbps": 3.0}))
    assert bench_check.main([str(p)]) == 1


def test_fails_on_pathological_async_bridge(tmp_path):
    """The async gate is calibrated for pathological bridges (a per-op
    call_soon_threadsafe hop lands 3-5x over sync), not host weather
    (honest history swings 1.27-2.64x). The measured asyncio eventfd wake
    floor is subtracted first — that cost is asyncio's, not the bridge's,
    and billing it to the bridge made the gate trip whenever the SYNC path
    got faster."""
    p = tmp_path / "slow_bridge.json"
    p.write_text(json.dumps({
        "p50_fetch_4k_us": 100.0,
        "sync_p50_fetch_4k_us": 20.0,
        "asyncio_efd_floor_us": 18.0,
    }))
    assert bench_check.main([str(p)]) == 1
    p.write_text(json.dumps({
        "p50_fetch_4k_us": 47.0,
        "sync_p50_fetch_4k_us": 14.0,
        "asyncio_efd_floor_us": 18.0,
    }))
    assert bench_check.main([str(p)]) == 0


def _ring_receipt(**over):
    """A healthy descriptor-ring receipt slice; override keys to break it."""
    doc = {
        "ring_ceiling_fraction": 0.93,
        "ring_vs_socket_speedup": 1.01,
        "ring_posted": 84,
        "ring_completions": 84,
        "ring_full_fallbacks": 0,
        "ring_meta_fallbacks": 0,
        "ring_doorbell_ratio": 10.5,
        "trace_frac_first_slice_to_last_slice": 0.764,
    }
    doc.update(over)
    return doc


def test_ring_gates_pass_on_healthy_receipt(tmp_path):
    p = tmp_path / "ring_ok.json"
    p.write_text(json.dumps(_ring_receipt()))
    assert bench_check.main([str(p)]) == 0


def test_ring_ceiling_fraction_gate(tmp_path):
    """The ROADMAP-2 target: the ring-backed batched leg must reach 0.75
    of the paired memcpy ceiling — 0.54 is the pre-ring r05 state."""
    p = tmp_path / "ring_slow.json"
    p.write_text(json.dumps(_ring_receipt(ring_ceiling_fraction=0.54)))
    assert bench_check.main([str(p)]) == 1


def test_ring_never_loses_to_socket(tmp_path):
    p = tmp_path / "ring_loses.json"
    p.write_text(json.dumps(_ring_receipt(ring_vs_socket_speedup=0.80)))
    assert bench_check.main([str(p)]) == 1


def test_ring_mechanism_gate(tmp_path):
    """Silent fallbacks would A/B the socket against itself; a 1.0
    doorbell ratio means every post paid the syscall the ring removes; a
    completion deficit means ring ops vanished."""
    for over in (
        {"ring_full_fallbacks": 3},
        {"ring_meta_fallbacks": 1},
        {"ring_doorbell_ratio": 1.0},
        {"ring_completions": 80},
        {"ring_posted": 0, "ring_completions": 0},
    ):
        p = tmp_path / "ring_mech.json"
        p.write_text(json.dumps(_ring_receipt(**over)))
        assert bench_check.main([str(p)]) == 1, over


def test_ring_stage_shift_gate(tmp_path):
    """first_slice->last_slice must stay visibly below the PR 7 receipt's
    ~0.80 — and the check binds only on ring-era receipts (a PR 7 receipt
    without ring keys skips instead of failing retroactively)."""
    p = tmp_path / "ring_frac.json"
    p.write_text(json.dumps(
        _ring_receipt(trace_frac_first_slice_to_last_slice=0.81)
    ))
    assert bench_check.main([str(p)]) == 1
    # Pre-ring receipt: same fraction, no ring keys -> not applicable.
    p.write_text(json.dumps({
        "trace_frac_first_slice_to_last_slice": 0.81,
        "striped_1_gbps": 5.0, "striped_4_gbps": 5.1,
    }))
    assert bench_check.main([str(p)]) == 0


def test_parses_truncated_driver_tail(tmp_path):
    """Driver receipts wrap the bench line and clip its head; metrics must
    still be recovered by key-value scan from the tail string."""
    # The way the driver writes it: a JSON wrapper whose "tail" value is a
    # string holding the CLIPPED bench line (starts mid-object; its quotes
    # are escaped inside the wrapper file, so only the tail-aware path can
    # recover the metrics).
    tail = (
        'extra": {"striped_1_gbps": 5.031, "striped_4_gbps": 3.138, '
        '"shaped_striped_1_mbps": 51.5}}'
    )
    doc = {"n": 5, "cmd": "python bench.py", "rc": 0, "tail": tail,
           "parsed": None}
    p = tmp_path / "driver.json"
    p.write_text(json.dumps(doc))
    m = bench_check.extract_metrics(p.read_text())
    assert m["striped_1_gbps"] == 5.031 and m["striped_4_gbps"] == 3.138
    assert bench_check.main([str(p)]) == 1  # the inversion is in the tail


def test_empty_receipt_is_not_a_pass(tmp_path):
    p = tmp_path / "empty.json"
    p.write_text(json.dumps({"rc": 0, "tail": "no metrics here"}))
    assert bench_check.main([str(p)]) == 2


def test_tiering_gates_pass_on_healthy_receipt(tmp_path):
    doc = {
        "tiering_hot_p99_ratio": 1.01,
        "tiering_cold_vs_spill_floor": 2.1,
        "tiering_demotions": 120,
        "tiering_promotions": 4,
        "tiering_admit_rejects": 32,
        "tiering_wrong_reads": 0,
        "tiering_misses": 0,
    }
    p = tmp_path / "tier.json"
    p.write_text(json.dumps(doc))
    assert bench_check.main([str(p)]) == 0


def test_tiering_hot_isolation_gate(tmp_path):
    # A tier plane stalling the hot path (policy hooks / fall-through
    # probing on serving hits) fails the paired-ratio gate.
    p = tmp_path / "tier.json"
    p.write_text(json.dumps({"tiering_hot_p99_ratio": 1.6}))
    assert bench_check.main([str(p)]) == 1


def test_tiering_cold_floor_and_mechanism_gates(tmp_path):
    # Cold reads far below the spill floor (a per-key fallback storm).
    p = tmp_path / "tier.json"
    p.write_text(json.dumps({"tiering_cold_vs_spill_floor": 0.2}))
    assert bench_check.main([str(p)]) == 1
    # Movement must run BOTH directions; one wrong read fails outright.
    p.write_text(json.dumps({
        "tiering_hot_p99_ratio": 1.0,
        "tiering_cold_vs_spill_floor": 2.0,
        "tiering_demotions": 120,
        "tiering_promotions": 0,
        "tiering_admit_rejects": 32,
        "tiering_wrong_reads": 0,
        "tiering_misses": 0,
    }))
    assert bench_check.main([str(p)]) == 1
    p.write_text(json.dumps({
        "tiering_hot_p99_ratio": 1.0,
        "tiering_cold_vs_spill_floor": 2.0,
        "tiering_demotions": 120,
        "tiering_promotions": 4,
        "tiering_admit_rejects": 32,
        "tiering_wrong_reads": 1,
        "tiering_misses": 0,
    }))
    assert bench_check.main([str(p)]) == 1


def _prof_receipt(**over):
    """A healthy profiling/timeseries receipt slice; override to break."""
    doc = {
        "prof_overhead_cost": 0.004,
        "prof_stage_tag_fraction": 0.97,
        "prof_completion_ring_samples": 41,
        "timeseries_anomaly_faulty": 1,
        "timeseries_anomaly_clean": 0,
    }
    doc.update(over)
    return doc


def test_profiling_gates_pass_on_healthy_receipt(tmp_path):
    p = tmp_path / "prof.json"
    p.write_text(json.dumps(_prof_receipt()))
    assert bench_check.main([str(p)]) == 0


def test_prof_overhead_gate(tmp_path):
    # A sampler whose frame walks eat >3% of op wall time is too heavy
    # for an always-on production instrument.
    p = tmp_path / "prof.json"
    p.write_text(json.dumps(_prof_receipt(prof_overhead_cost=0.06)))
    assert bench_check.main([str(p)]) == 1


def test_prof_stage_attribution_gate(tmp_path):
    # Untagged samples mean the thread->span feed broke; a completion_ring
    # interval with no samples means the ROADMAP-5 receipt is empty.
    p = tmp_path / "prof.json"
    p.write_text(json.dumps(_prof_receipt(prof_stage_tag_fraction=0.5)))
    assert bench_check.main([str(p)]) == 1
    p.write_text(json.dumps(_prof_receipt(prof_completion_ring_samples=0)))
    assert bench_check.main([str(p)]) == 1


def test_timeseries_anomaly_gate(tmp_path):
    # The step must fire exactly once (edge-triggering) and never on the
    # clean run (a false positive teaches operators to delete the alert).
    p = tmp_path / "prof.json"
    p.write_text(json.dumps(_prof_receipt(timeseries_anomaly_faulty=0)))
    assert bench_check.main([str(p)]) == 1
    p.write_text(json.dumps(_prof_receipt(timeseries_anomaly_faulty=3)))
    assert bench_check.main([str(p)]) == 1
    p.write_text(json.dumps(_prof_receipt(timeseries_anomaly_clean=1)))
    assert bench_check.main([str(p)]) == 1
