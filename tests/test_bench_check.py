"""tools/bench_check.py: the data-plane regression gate.

The gate exists so the BENCH_r05 striping inversion (striped_4 < striped_1)
can never silently return; these tests pin its verdicts against the real
historical receipt and synthetic ones, including the driver's truncated
``tail`` format (the receipt's head is routinely clipped mid-JSON).
"""

import importlib.util
import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench_check():
    path = os.path.join(_REPO, "tools", "bench_check.py")
    spec = importlib.util.spec_from_file_location("bench_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_check = _load_bench_check()


def test_fails_on_the_r05_inversion_receipt():
    """The founding requirement: the real BENCH_r05.json (striped_4 3.14 <
    striped_1 5.03) must fail the gate."""
    path = os.path.join(_REPO, "BENCH_r05.json")
    if not os.path.exists(path):
        pytest.skip("historical receipt not present")
    assert bench_check.main([path]) == 1


def test_passes_on_a_healthy_receipt(tmp_path):
    doc = {
        "metric": "kv_batched_write_read_throughput",
        "value": 5.5,
        "extra": {
            "striped_1_gbps": 5.4,
            "striped_4_gbps": 5.5,
            "shaped_striped_1_mbps": 51.0,
            "shaped_striped_4_mbps": 205.0,
            "p50_fetch_4k_us": 28.0,
            "sync_p50_fetch_4k_us": 23.0,
        },
    }
    p = tmp_path / "good.json"
    p.write_text(json.dumps(doc))
    assert bench_check.main([str(p)]) == 0


def test_fails_on_inverted_striping(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"striped_1_gbps": 5.0, "striped_4_gbps": 3.0}))
    assert bench_check.main([str(p)]) == 1


def test_fails_on_pathological_async_bridge(tmp_path):
    """The async gate is calibrated for pathological bridges (a per-op
    call_soon_threadsafe hop lands 3-5x over sync), not host weather
    (honest history swings 1.27-2.64x)."""
    p = tmp_path / "slow_bridge.json"
    p.write_text(json.dumps(
        {"p50_fetch_4k_us": 100.0, "sync_p50_fetch_4k_us": 20.0}
    ))
    assert bench_check.main([str(p)]) == 1
    p.write_text(json.dumps(
        {"p50_fetch_4k_us": 47.0, "sync_p50_fetch_4k_us": 22.0}
    ))
    assert bench_check.main([str(p)]) == 0


def test_parses_truncated_driver_tail(tmp_path):
    """Driver receipts wrap the bench line and clip its head; metrics must
    still be recovered by key-value scan from the tail string."""
    # The way the driver writes it: a JSON wrapper whose "tail" value is a
    # string holding the CLIPPED bench line (starts mid-object; its quotes
    # are escaped inside the wrapper file, so only the tail-aware path can
    # recover the metrics).
    tail = (
        'extra": {"striped_1_gbps": 5.031, "striped_4_gbps": 3.138, '
        '"shaped_striped_1_mbps": 51.5}}'
    )
    doc = {"n": 5, "cmd": "python bench.py", "rc": 0, "tail": tail,
           "parsed": None}
    p = tmp_path / "driver.json"
    p.write_text(json.dumps(doc))
    m = bench_check.extract_metrics(p.read_text())
    assert m["striped_1_gbps"] == 5.031 and m["striped_4_gbps"] == 3.138
    assert bench_check.main([str(p)]) == 1  # the inversion is in the tail


def test_empty_receipt_is_not_a_pass(tmp_path):
    p = tmp_path / "empty.json"
    p.write_text(json.dumps({"rc": 0, "tail": "no metrics here"}))
    assert bench_check.main([str(p)]) == 2
