"""Server CLI subprocess + HTTP management plane + benchmark CLI (reference
launches the server as a subprocess the same way,
reference infinistore/test_infinistore.py:29-54, and exercises
/purge + /kvmap_len; /selftest is new — advertised in the reference README but
never implemented there)."""

import json
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import infinistore_tpu as its


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cli_server():
    service_port, manage_port = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--host", "127.0.0.1",
            "--service-port", str(service_port),
            "--manage-port", str(manage_port),
            # dataclass units: GB / KB; keep the test pool tiny
            "--prealloc-size", "1",
            "--minimal-allocate-size", "16",
            "--no-pin-memory",
            "--evict-enabled",
            "--evict-interval", "0.2",
            "--log-level", "error",
        ],
    )
    # Wait for both planes to come up.
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", service_port), timeout=0.3):
                pass
            urllib.request.urlopen(
                f"http://127.0.0.1:{manage_port}/health", timeout=0.5
            )
            break
        except OSError:
            time.sleep(0.1)
    else:
        proc.terminate()
        pytest.fail("CLI server did not come up")
    yield {"service_port": service_port, "manage_port": manage_port, "proc": proc}
    proc.send_signal(2)  # SIGINT, as the reference fixture does
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


@pytest.fixture()
def cli_conn(cli_server):
    conn = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1",
            service_port=cli_server["service_port"],
            log_level="error",
        )
    )
    conn.connect()
    yield conn
    conn.close()


def _manage(cli_server, path, method="GET"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{cli_server['manage_port']}{path}", method=method
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def test_roundtrip_via_cli_server(cli_conn):
    data = np.random.randint(0, 256, size=64 << 10, dtype=np.uint8)
    cli_conn.tcp_write_cache("cli-key", data.ctypes.data, data.nbytes)
    assert np.array_equal(cli_conn.tcp_read_cache("cli-key"), data)


def test_manage_kvmap_len_and_purge(cli_server, cli_conn):
    data = np.zeros(1024, dtype=np.uint8)
    for i in range(3):
        cli_conn.tcp_write_cache(f"mg-{i}", data.ctypes.data, data.nbytes)
    status, body = _manage(cli_server, "/kvmap_len")
    assert status == 200 and body["len"] >= 3
    status, body = _manage(cli_server, "/purge", method="POST")
    assert status == 200 and body["status"] == "ok"
    status, body = _manage(cli_server, "/kvmap_len")
    assert body["len"] == 0


def test_manage_selftest(cli_server):
    status, body = _manage(cli_server, "/selftest")
    assert status == 200
    assert body["status"] == "ok"


def test_manage_stats(cli_server, cli_conn):
    data = np.zeros(1024, dtype=np.uint8)
    cli_conn.tcp_write_cache("stats-probe", data.ctypes.data, data.nbytes)
    status, body = _manage(cli_server, "/stats")
    assert status == 200
    assert "ops" in body and body["total_bytes"] > 0


def test_manage_prometheus_metrics(cli_server, cli_conn):
    data = np.zeros(1024, dtype=np.uint8)
    cli_conn.tcp_write_cache("metrics-probe", data.ctypes.data, data.nbytes)
    req = urllib.request.Request(
        f"http://127.0.0.1:{cli_server['manage_port']}/metrics"
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
    assert "infinistore_kvmap_entries" in text
    assert "infinistore_pool_usage_ratio" in text
    assert 'infinistore_op_count{op="P",result="ok"}' in text


def test_manage_unknown_and_wrong_method(cli_server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _manage(cli_server, "/nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _manage(cli_server, "/purge", method="GET")
    assert e.value.code == 405


def test_benchmark_cli_rdma(cli_server):
    out = subprocess.run(
        [
            sys.executable, "-m", "infinistore_tpu.benchmark",
            "--service-port", str(cli_server["service_port"]),
            "--size", "16", "--block-size", "64", "--steps", "4", "--json",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["verified"] is True
    assert result["write_mb_s"] > 0 and result["read_mb_s"] > 0


def test_benchmark_cli_tcp(cli_server):
    out = subprocess.run(
        [
            sys.executable, "-m", "infinistore_tpu.benchmark",
            "--service-port", str(cli_server["service_port"]),
            "--size", "4", "--block-size", "64", "--type", "tcp", "--json",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["verified"] is True
