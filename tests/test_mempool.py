"""Allocator unit tests — coverage the reference lacks entirely (SURVEY.md §4:
"no C++ unit tests at all"; the bitmap allocator under test mirrors
reference src/mempool.cpp:55-156 behavior)."""

import ctypes

import pytest

from infinistore_tpu._native import lib

KB = 1 << 10
MB = 1 << 20


@pytest.fixture()
def mm():
    handle = lib.its_mm_create(1 * MB, 16 * KB, 0)
    assert handle
    yield handle
    lib.its_mm_destroy(handle)


def _alloc(mm, size, n=1):
    ptrs = (ctypes.c_void_p * n)()
    rc = lib.its_mm_allocate(mm, size, n, ptrs)
    if rc != 0:
        return None
    return [ptrs[i] for i in range(n)]


def test_basic_alloc_free(mm):
    ptrs = _alloc(mm, 16 * KB)
    assert ptrs is not None
    assert lib.its_mm_used_bytes(mm) == 16 * KB
    lib.its_mm_deallocate(mm, ptrs[0], 16 * KB)
    assert lib.its_mm_used_bytes(mm) == 0


def test_multi_block_contiguous(mm):
    # 40KB rounds up to 3 x 16KB contiguous blocks.
    ptrs = _alloc(mm, 40 * KB)
    assert ptrs is not None
    assert lib.its_mm_used_bytes(mm) == 48 * KB
    lib.its_mm_deallocate(mm, ptrs[0], 40 * KB)
    assert lib.its_mm_used_bytes(mm) == 0


def test_batched_n_way(mm):
    ptrs = _alloc(mm, 16 * KB, n=10)
    assert ptrs is not None
    assert len(set(p for p in ptrs)) == 10
    assert lib.its_mm_used_bytes(mm) == 160 * KB
    for p in ptrs:
        lib.its_mm_deallocate(mm, p, 16 * KB)
    assert lib.its_mm_used_bytes(mm) == 0


def test_exhaustion_and_all_or_nothing(mm):
    # Pool holds 64 blocks of 16KB.
    ptrs = _alloc(mm, 16 * KB, n=64)
    assert ptrs is not None
    assert lib.its_mm_usage(mm) == 1.0
    assert _alloc(mm, 16 * KB) is None
    # Free one block: a 2-block batch must fail atomically (nothing leaked).
    lib.its_mm_deallocate(mm, ptrs[0], 16 * KB)
    assert _alloc(mm, 16 * KB, n=2) is None
    assert lib.its_mm_used_bytes(mm) == 63 * 16 * KB
    # And a 1-block alloc reuses the freed slot.
    again = _alloc(mm, 16 * KB)
    assert again is not None
    assert again[0] == ptrs[0]


def test_fragmentation_contiguous_run(mm):
    # Allocate all, free alternating blocks: a 2-block request must fail even
    # though 32 blocks are free (no contiguous run).
    ptrs = _alloc(mm, 16 * KB, n=64)
    for i in range(0, 64, 2):
        lib.its_mm_deallocate(mm, ptrs[i], 16 * KB)
    assert _alloc(mm, 32 * KB) is None
    # Free one neighbor -> a contiguous pair exists.
    lib.its_mm_deallocate(mm, ptrs[1], 16 * KB)
    assert _alloc(mm, 32 * KB) is not None


def test_extend(mm):
    assert lib.its_mm_total_bytes(mm) == 1 * MB
    assert lib.its_mm_extend(mm, 1 * MB) == 0
    assert lib.its_mm_total_bytes(mm) == 2 * MB
    # New capacity is usable.
    ptrs = _alloc(mm, 16 * KB, n=128)
    assert ptrs is not None
    assert lib.its_mm_usage(mm) == 1.0


def test_usage_ratio(mm):
    assert lib.its_mm_usage(mm) == 0.0
    ptrs = _alloc(mm, 16 * KB, n=32)
    assert lib.its_mm_usage(mm) == 0.5
    for p in ptrs:
        lib.its_mm_deallocate(mm, p, 16 * KB)


def test_data_integrity(mm):
    ptrs = _alloc(mm, 16 * KB, n=4)
    bufs = [(ctypes.c_char * (16 * KB)).from_address(p) for p in ptrs]
    for i, b in enumerate(bufs):
        b.raw = bytes([i]) * (16 * KB)
    for i, b in enumerate(bufs):
        assert b.raw == bytes([i]) * (16 * KB)
