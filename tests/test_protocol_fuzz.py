"""Protocol robustness: the server must survive arbitrary client bytes.

The reference closes the connection on a bad magic (reference
infinistore.cpp:910-915) and otherwise trusts the frame. Here the server is
fed (a) pure garbage, (b) valid headers with hostile body sizes, and
(c) bit-mutated versions of real frames — after every volley it must still
serve a well-behaved client. Deterministic seed: failures reproduce.
"""

import socket
import struct

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import wire


@pytest.fixture(scope="module")
def server():
    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    yield srv
    srv.stop()


def _healthy(server) -> bool:
    """A fresh client can do a full put/get roundtrip."""
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=server.port, log_level="error",
            enable_shm=False, op_timeout_ms=5000,
        )
    )
    c.connect()
    try:
        data = np.arange(4096, dtype=np.uint8) % 250
        c.tcp_write_cache("fuzz-health", data.ctypes.data, data.nbytes)
        out = c.tcp_read_cache("fuzz-health")
        return bool(np.array_equal(out, data))
    finally:
        c.close()


def _blast(port: int, payload: bytes):
    s = socket.socket()
    s.settimeout(0.3)  # server either answers or closes fast; don't linger
    try:
        s.connect(("127.0.0.1", port))
        s.sendall(payload)
        try:
            s.recv(4096)  # server may answer or close; either is fine
        except (TimeoutError, socket.timeout, ConnectionError):
            pass
    finally:
        s.close()


def test_survives_garbage_bytes(server):
    rng = np.random.default_rng(7)
    for size in (1, 8, 9, 64, 4096, 1 << 16):
        _blast(server.port, rng.integers(0, 256, size=size, dtype=np.uint8).tobytes())
    assert _healthy(server)


def test_survives_hostile_header_sizes(server):
    # Valid magic, EVERY op code — including the shm two-phase and one-RTT
    # segment ops whose handlers park budget-sliced continuations — with
    # body_size from 0 to 4GB-ish: the server must bound allocations,
    # reject before suspending, and drain or drop without dying.
    all_ops = (
        wire.OP_PUT_BATCH, wire.OP_GET_BATCH, wire.OP_TCP_PUT, wire.OP_TCP_GET,
        wire.OP_CHECK_EXIST, wire.OP_MATCH_LAST_IDX, wire.OP_DELETE_KEYS,
        wire.OP_STAT, wire.OP_SHM_HELLO, wire.OP_PUT_ALLOC, wire.OP_PUT_COMMIT,
        wire.OP_GET_LOC, wire.OP_RELEASE, wire.OP_REG_SEGMENT,
        wire.OP_PUT_FROM, wire.OP_GET_INTO, 0xFF,
    )
    for op in all_ops:
        for body_size in (0, 1, 0xFFFF, 0x00FFFFFF, 0xFFFFFFFF):
            hdr = wire.pack_req_header(op, body_size & 0xFFFFFFFF)
            _blast(server.port, hdr + b"A" * min(body_size, 1 << 16))
    assert _healthy(server)


def test_survives_mutated_segment_frames(server):
    """Bit-flipped SegBatchMeta frames (the one-RTT PutFrom/GetInto path):
    the server must reject hostile seg ids/offsets/counts BEFORE any
    continuation suspends, and stay healthy."""
    rng = np.random.default_rng(23)
    meta = wire.SegBatchMeta(
        block_size=4096, seg_id=1, keys=["sg-a", "sg-b"], offsets=[0, 4096]
    ).encode()
    hdr_len = 9  # flips stay in the META region: header-field hostility is
    # test_survives_hostile_header_sizes's job, and an inflated body_size
    # would just make the server wait out the recv timeout (pure idle time).
    for op in (wire.OP_PUT_FROM, wire.OP_GET_INTO):
        base = wire.pack_req_header(op, len(meta)) + meta
        for _ in range(200):
            buf = bytearray(base)
            for _ in range(rng.integers(1, 4)):
                buf[rng.integers(hdr_len, len(buf))] ^= 1 << rng.integers(0, 8)
            _blast(server.port, bytes(buf))
    assert _healthy(server)


def test_survives_mutated_real_frames(server):
    # Take a real put frame and flip bytes at every position of the header
    # and metadata; the payload region is size-driven so mutations there
    # mostly test the drain path.
    meta = wire.BatchMeta(block_size=4096, keys=["fz-a", "fz-b"]).encode()
    frame = wire.pack_req_header(wire.OP_PUT_BATCH, len(meta)) + meta + b"B" * 8192
    rng = np.random.default_rng(11)
    for pos in range(0, min(len(frame), 9 + len(meta))):
        mutated = bytearray(frame)
        mutated[pos] ^= int(rng.integers(1, 256))
        _blast(server.port, bytes(mutated))
    assert _healthy(server)


def _hostile_server(make_response):
    """A listener that reads one request and answers with whatever
    make_response(op, body) returns (bytes), then closes."""
    import threading

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)

    def serve():
        while True:
            try:
                s, _ = lst.accept()
            except OSError:
                return
            try:
                s.settimeout(1)
                hdr = b""
                while len(hdr) < 9:
                    hdr += s.recv(9 - len(hdr))
                _, op, bs = struct.unpack("<IBI", hdr)
                body = b""
                while len(body) < bs:
                    body += s.recv(bs - len(body))
                s.sendall(make_response(op, body))
            except OSError:
                pass
            finally:
                s.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return lst


@pytest.mark.parametrize(
    "response",
    [
        b"\x00" * 64,  # garbage where a response header should be
        wire.pack_resp_header(wire.STATUS_OK, 0xFFFFFFFF, 0),  # absurd body size
        wire.pack_resp_header(wire.STATUS_OK, 4, 1 << 40),  # absurd payload size
        wire.pack_resp_header(9999, 0, 0),  # status outside the HTTP range
        wire.pack_resp_header(0, 0, 0),  # status 0 must not read as success
    ],
    ids=["garbage", "huge-body", "huge-payload", "odd-status", "zero-status"],
)
def test_client_survives_hostile_server_responses(response):
    """The client parses server bytes too: a hostile/buggy server must
    produce a typed error (or a clean connection failure), never a crash, a
    hang past the op deadline, or a bogus status masquerading as success
    (the reactor validates the HTTP-like status range)."""
    lst = _hostile_server(lambda op, body: response)
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=lst.getsockname()[1],
            log_level="error", enable_shm=False, op_timeout_ms=1000,
        )
    )
    c.connect()
    import time

    t0 = time.time()
    with pytest.raises(its.InfiniStoreException):
        c.check_exist("k")
    assert time.time() - t0 < 5
    # The process survived; a fresh connection to a REAL server still works.
    c.close()
    lst.close()
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    ok = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    ok.connect()
    assert ok.check_exist("nope") is False
    ok.close()
    srv.stop()


def test_survives_truncated_frames_and_slow_trickle(server):
    meta = wire.BatchMeta(block_size=4096, keys=["fz-c"]).encode()
    frame = wire.pack_req_header(wire.OP_PUT_BATCH, len(meta)) + meta + b"C" * 4096
    # Truncations at every boundary region: header, body, payload.
    for cut in (1, 5, 9, 9 + len(meta) // 2, 9 + len(meta), len(frame) - 1):
        _blast(server.port, frame[:cut])
    assert _healthy(server)
