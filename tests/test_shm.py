"""Same-host shm fast-path behavior: activation/fallback, server-side ticket
lifetime (pending blocks freed on disconnect), clean OOM (no payload drain
needed), and on-demand mapping of auto-extended pools.

The reference gets its zero-copy local path from GPUDirect RDMA (ibv_reg_mr on
CUDA pointers, reference infinistore/test_infinistore.py:120-122); on TPU
hosts the analogue is named-shm pools mapped into the client, and these are
the behaviors that differ from the socket path.
"""

import asyncio
import socket
import struct
import time

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import wire


def _connect_raw(port: int) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", port))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _roundtrip(sock: socket.socket, op: int, body: bytes):
    sock.sendall(wire.pack_req_header(op, len(body)) + body)
    hdr = b""
    while len(hdr) < 16:
        hdr += sock.recv(16 - len(hdr))
    status, body_size, payload_size = wire.unpack_resp_header(hdr)
    resp = b""
    while len(resp) < body_size:
        resp += sock.recv(body_size - len(resp))
    return status, resp, payload_size


def test_shm_active_matches_server_capability():
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    assert c.shm_active is True
    c.close()
    srv.stop()

    # Server with shm disabled -> client degrades to the socket path.
    srv2 = its.start_local_server(
        prealloc_bytes=16 << 20, block_bytes=16 << 10, enable_shm=False
    )
    c2 = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv2.port, log_level="error")
    )
    c2.connect()
    assert c2.shm_active is False
    data = (np.arange(16 << 10) % 256).astype(np.uint8)
    dst = np.zeros_like(data)
    c2.register_mr(data)
    c2.register_mr(dst)
    asyncio.run(c2.write_cache_async([("sk", 0)], data.nbytes, data.ctypes.data))
    asyncio.run(c2.read_cache_async([("sk", 0)], data.nbytes, dst.ctypes.data))
    assert np.array_equal(data, dst)
    c2.close()
    srv2.stop()


def test_pending_put_blocks_freed_on_disconnect():
    """PutAlloc without commit pins pool blocks in the connection's ticket
    table; dropping the connection must free them (the reference analogue:
    inflight RDMA state dies with the Client struct, infinistore.cpp:967-988)."""
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    from infinistore_tpu._native import lib

    assert lib.its_server_usage(srv.handle) == 0.0
    s = _connect_raw(srv.port)
    body = wire.BatchMeta(block_size=16 << 10, keys=[f"pend-{i}" for i in range(64)]).encode()
    status, resp, _ = _roundtrip(s, wire.OP_PUT_ALLOC, body)
    assert status == wire.STATUS_OK
    parsed = wire.ShmLocResp.decode(resp)
    assert len(parsed.locs) == 64
    assert len(parsed.pools) >= 1
    assert parsed.ticket != 0
    # 64 x 16KB pinned by the ticket, never committed.
    assert lib.its_server_usage(srv.handle) > 0.0
    assert lib.its_server_kvmap_len(srv.handle) == 0
    s.close()
    deadline = time.time() + 5
    while time.time() < deadline and lib.its_server_usage(srv.handle) > 0.0:
        time.sleep(0.05)
    assert lib.its_server_usage(srv.handle) == 0.0
    srv.stop()


def test_shm_oom_is_immediate_507():
    """On the shm path OOM needs no payload drain: the 507 comes back before
    any data moves, and the connection stays usable."""
    srv = its.start_local_server(prealloc_bytes=8 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    assert c.shm_active
    big = np.zeros(16 << 20, dtype=np.uint8)
    c.register_mr(big)
    with pytest.raises(its.InfiniStoreException):
        asyncio.run(c.write_cache_async([("big", 0)], big.nbytes, big.ctypes.data))
    small = np.ones(4096, dtype=np.uint8)
    dst = np.zeros_like(small)
    c.register_mr(small)
    c.register_mr(dst)
    asyncio.run(c.write_cache_async([("ok", 0)], 4096, small.ctypes.data))
    asyncio.run(c.read_cache_async([("ok", 0)], 4096, dst.ctypes.data))
    assert np.array_equal(small, dst)
    c.close()
    srv.stop()


def test_stale_segment_sweep_spares_live_pools():
    """Startup sweep unlinks orphaned its.* segments (flock released = owner
    dead) but must not touch a running server's pools."""
    import os

    # Plant a fake orphan: nobody holds a lock on it.
    orphan = f"/its.999999.deadbeef.0"
    path = "/dev/shm" + orphan
    with open(path, "wb") as f:
        f.write(b"\0" * 4096)
    live = its.start_local_server(prealloc_bytes=8 << 20, block_bytes=16 << 10)
    try:
        # A second server's MM constructor runs the sweep.
        other = its.start_local_server(prealloc_bytes=8 << 20, block_bytes=16 << 10)
        other.stop()
        assert not os.path.exists(path), "orphan segment not swept"
        # The live server's pools survived: a client can still use them.
        c = its.InfinityConnection(
            its.ClientConfig(host_addr="127.0.0.1", service_port=live.port, log_level="error")
        )
        c.connect()
        assert c.shm_active
        data = np.ones(4096, dtype=np.uint8)
        dst = np.zeros_like(data)
        c.register_mr(data)
        c.register_mr(dst)
        asyncio.run(c.write_cache_async([("live", 0)], 4096, data.ctypes.data))
        asyncio.run(c.read_cache_async([("live", 0)], 4096, dst.ctypes.data))
        assert np.array_equal(data, dst)
        c.close()
    finally:
        live.stop()
        if os.path.exists(path):
            os.unlink(path)


def test_auto_extend_pool_mapped_on_demand():
    """Writes spilling into an auto-extended pool must reach the client via
    the directory embedded in responses — no re-handshake."""
    srv = its.start_local_server(
        prealloc_bytes=8 << 20,
        block_bytes=16 << 10,
        auto_increase=True,
        extend_bytes=16 << 20,
    )
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    assert c.shm_active
    n, block = 512, 16 << 10  # 8MB of data on an 8MB pool -> must extend
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    dst = np.zeros_like(src)
    c.register_mr(src)
    c.register_mr(dst)
    pairs = [(f"x-{i}", i * block) for i in range(n)]
    asyncio.run(c.write_cache_async(pairs, block, src.ctypes.data))
    asyncio.run(c.read_cache_async(pairs, block, dst.ctypes.data))
    assert np.array_equal(src, dst)
    c.close()
    srv.stop()


def test_alloc_shm_mr_one_rtt_roundtrip():
    """alloc_shm_mr returns a server-mapped staging buffer, and batched ops on
    it ride the one-RTT PutFrom/GetInto path (the shm analogue of the
    reference's one-sided RDMA against registered client memory,
    reference src/infinistore.cpp:558-595) — verified via op counters."""
    srv = its.start_local_server(prealloc_bytes=32 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    assert c.shm_active
    n, block = 16, 16 << 10
    buf = c.alloc_shm_mr(n * block)
    assert buf is not None and buf.nbytes == n * block
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    buf[:] = src
    pairs = [(f"seg-{i}", i * block) for i in range(n)]
    asyncio.run(c.write_cache_async(pairs, block, buf.ctypes.data))
    buf[:] = 0
    asyncio.run(c.read_cache_async(pairs, block, buf.ctypes.data))
    assert np.array_equal(buf, src)
    ops = c.get_stats()["ops"]
    assert ops.get("F", {}).get("count", 0) >= 1  # PutFrom
    assert ops.get("I", {}).get("count", 0) >= 1  # GetInto
    c.close()
    srv.stop()


def test_alloc_shm_mr_declined_falls_back():
    """A shm-less server declines RegSegment; the buffer stays usable as a
    plain registered region and batched ops ride the socket path ('W'/'R'
    op counters, not 'F'/'I')."""
    srv = its.start_local_server(
        prealloc_bytes=16 << 20, block_bytes=16 << 10, enable_shm=False
    )
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    assert not c.shm_active
    block = 16 << 10
    buf = c.alloc_shm_mr(2 * block)
    assert buf is not None
    src = np.random.randint(0, 256, size=2 * block, dtype=np.uint8)
    buf[:] = src
    pairs = [("d-0", 0), ("d-1", block)]
    asyncio.run(c.write_cache_async(pairs, block, buf.ctypes.data))
    buf[:] = 0
    asyncio.run(c.read_cache_async(pairs, block, buf.ctypes.data))
    assert np.array_equal(buf, src)
    ops = c.get_stats()["ops"]
    assert ops.get("W", {}).get("count", 0) >= 1
    assert "F" not in ops and "I" not in ops
    c.close()
    srv.stop()


def test_reg_segment_rejects_undersized_shm(tmp_path):
    """The server must fstat a client-declared segment and refuse to map past
    tmpfs EOF — an undersized segment would SIGBUS the reactor on first use."""
    import os

    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    name = f"/its.{os.getpid()}.feedf00d.t"
    path = "/dev/shm" + name
    with open(path, "wb") as f:
        f.truncate(4096)  # claims 1MB below but backs only 4KB
    try:
        s = _connect_raw(srv.port)
        body = wire.SegMeta(seg_id=7, name=name, size=1 << 20).encode()
        status, _, _ = _roundtrip(s, wire.OP_REG_SEGMENT, body)
        assert status != wire.STATUS_OK
        # A non-its-prefixed name must be refused outright.
        with open("/dev/shm/evil.seg", "wb") as f:
            f.truncate(1 << 20)
        body = wire.SegMeta(seg_id=8, name="/evil.seg", size=1 << 20).encode()
        status, _, _ = _roundtrip(s, wire.OP_REG_SEGMENT, body)
        assert status != wire.STATUS_OK
        s.close()
    finally:
        for p in (path, "/dev/shm/evil.seg"):
            if os.path.exists(p):
                os.unlink(p)
        srv.stop()


@pytest.mark.parametrize("shm", [True, False], ids=["shm", "socket"])
def test_get_with_smaller_block_size_errors_cleanly(shm):
    """Reading a key back with a block_size smaller than the stored block
    must fail with a typed error — never scatter past the caller's slot —
    and leave the connection usable (both data planes)."""
    srv = its.start_local_server(
        prealloc_bytes=16 << 20, block_bytes=32 << 10, enable_shm=shm
    )
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    big = np.random.randint(0, 256, size=32 << 10, dtype=np.uint8)
    c.register_mr(big)
    asyncio.run(c.write_cache_async([("over", 0)], big.nbytes, big.ctypes.data))
    # Guard pages: canary after the undersized slot must survive the get.
    dst = np.zeros(32 << 10, dtype=np.uint8)
    dst[16 << 10 :] = 0xAB
    c.register_mr(dst)
    with pytest.raises(its.InfiniStoreException):
        asyncio.run(c.read_cache_async([("over", 0)], 16 << 10, dst.ctypes.data))
    assert np.all(dst[16 << 10 :] == 0xAB)
    # Connection stays usable.
    full = np.zeros(32 << 10, dtype=np.uint8)
    c.register_mr(full)
    asyncio.run(c.read_cache_async([("over", 0)], 32 << 10, full.ctypes.data))
    assert np.array_equal(full, big)
    c.close()
    srv.stop()
