"""Multi-process clients against one server — the reference's concurrency
test shape (two `multiprocessing.Process` clients, reference
infinistore/test_infinistore.py:217-268) plus the cross-process handoff the
disaggregation story depends on: a producer process writes over the shm fast
path, a separate consumer process reads the same keys over the DCN socket
path (the cross-host transport), so the test proves the two data planes see
one consistent store."""

import subprocess
import sys

import infinistore_tpu as its

_CLIENT = r"""
import asyncio, sys
import numpy as np
import infinistore_tpu as its

port, tag, mode, use_shm = int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4] == "1"
conn = its.InfinityConnection(its.ClientConfig(
    host_addr="127.0.0.1", service_port=port, log_level="error", enable_shm=use_shm))
conn.connect()
assert conn.shm_active == use_shm, f"shm_active={conn.shm_active} want={use_shm}"
n, block = 32, 16 << 10
buf = np.full(n * block, (ord(tag[0]) + 7) % 256, dtype=np.uint8)
pairs = [(f"{tag}-{i}", i * block) for i in range(n)]
conn.register_mr(buf)
if mode in ("write", "both"):
    asyncio.run(conn.write_cache_async(pairs, block, buf.ctypes.data))
if mode in ("read", "both"):
    dst = np.zeros(n * block, dtype=np.uint8)
    conn.register_mr(dst)
    asyncio.run(conn.read_cache_async(pairs, block, dst.ctypes.data))
    expect = np.full(n * block, (ord(tag[0]) + 7) % 256, dtype=np.uint8)
    assert np.array_equal(dst, expect), "cross-process data mismatch"
conn.close()
print("ok")
"""


def _run_client(port, tag, mode, use_shm):
    return subprocess.run(
        [sys.executable, "-c", _CLIENT, str(port), tag, mode, "1" if use_shm else "0"],
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_two_concurrent_client_processes(server):
    """Two separate OS processes writing+reading disjoint keysets."""
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CLIENT, str(server["port"]), tag, "both", "1"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for tag in ("alpha", "beta")
    ]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, f"client failed: {err}"
        assert "ok" in out


def test_cross_process_shm_write_dcn_read(server):
    """Producer writes via shm fast path; a different process reads the same
    keys via the socket path (what a remote decode host would use)."""
    r = _run_client(server["port"], "handoff", "write", use_shm=True)
    assert r.returncode == 0, r.stderr
    r = _run_client(server["port"], "handoff", "read", use_shm=False)
    assert r.returncode == 0, r.stderr


def test_cross_process_dcn_write_shm_read(server):
    """And the reverse direction."""
    r = _run_client(server["port"], "ffodnah", "write", use_shm=False)
    assert r.returncode == 0, r.stderr
    r = _run_client(server["port"], "ffodnah", "read", use_shm=True)
    assert r.returncode == 0, r.stderr
