"""Elastic membership subsystem (infinistore_tpu/membership.py +
ClusterKVConnector's elastic surface): epoch-stamped views, the
JOINING/ACTIVE/LEAVING/DEAD state machine, rendezvous-delta properties,
live online resharding with epoch-aware read failover, the /membership
manage endpoints — and, under the ``chaos`` marker, a member killed
DURING an in-flight reshard and a join while another member's breaker is
OPEN (docs/membership.md).
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import ClusterKVConnector, rendezvous_ranked
from infinistore_tpu.cluster import CircuitBreaker
from infinistore_tpu.membership import Membership, MemberState
from infinistore_tpu.tpu import PagedKVCacheSpec, gather_blocks

SPEC = PagedKVCacheSpec(
    num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2, head_dim=32,
    dtype=jnp.bfloat16,
)


# ---------------------------------------------------------------------------
# rendezvous_ranked delta properties (pure; the math elasticity rests on)
# ---------------------------------------------------------------------------

ROOTS = [f"root-{i}" for i in range(2000)]


def _owner(ids, root):
    return ids[rendezvous_ranked(ids, root)[0]]


class TestRendezvousDelta:
    def test_join_moves_at_most_its_fair_share(self):
        """Adding one member to N moves ownership of ~1/(N+1) of roots
        (binomial slack), and every moved root moves TO the joiner."""
        members = [f"m{i}:0" for i in range(4)]
        grown = members + ["joiner:9"]
        moved = 0
        for r in ROOTS:
            before, after = _owner(members, r), _owner(grown, r)
            if before != after:
                moved += 1
                assert after == "joiner:9"  # delta moves toward the joiner only
        expect = len(ROOTS) / len(grown)
        assert moved <= expect + 4 * (expect * (1 - 1 / len(grown))) ** 0.5
        assert moved > 0.5 * expect  # and the joiner really takes a share

    def test_removal_moves_only_owned_roots(self):
        members = [f"m{i}:0" for i in range(5)]
        survivors = [m for m in members if m != "m2:0"]
        for r in ROOTS:
            before = _owner(members, r)
            after = _owner(survivors, r)
            if before == "m2:0":
                assert after in survivors
            else:
                assert after == before  # unowned roots never move

    def test_removal_preserves_surviving_rank_order(self):
        """Owner->successor promotion: removing a member promotes the ranks
        below it and NEVER reorders the survivors — so R=2 replica sets
        survive drains with only the promoted successor changing."""
        members = [f"m{i}:0" for i in range(5)]
        survivors = [m for m in members if m != "m2:0"]
        for r in ROOTS[:500]:
            full = [members[i] for i in rendezvous_ranked(members, r)]
            pruned = [m for m in full if m != "m2:0"]
            got = [survivors[i] for i in rendezvous_ranked(survivors, r)]
            assert got == pruned


# ---------------------------------------------------------------------------
# Membership state machine (pure)
# ---------------------------------------------------------------------------

class TestMembershipStateMachine:
    def test_transitions_bump_epochs_and_settle(self):
        m = Membership(["a:1", "b:2"])
        assert m.view().epoch == 1 and m.settled
        v = m.add_member("c:3")
        assert v.epoch == 2 and v.state_of("c:3") == MemberState.JOINING
        assert not m.settled and m.prev_placement == ("a:1", "b:2")
        assert set(v.placement_ids()) == {"a:1", "b:2", "c:3"}
        v = m.finalize_transitions()
        assert v.epoch == 3 and v.state_of("c:3") == MemberState.ACTIVE
        assert m.settled and m.prev_placement is None

    def test_leave_stays_readable_until_finalized(self):
        m = Membership(["a:1", "b:2", "c:3"])
        v = m.remove_member("b:2")
        assert v.state_of("b:2") == MemberState.LEAVING
        assert "b:2" not in v.placement_ids()  # no new writes
        assert "b:2" in v.readable_ids()  # still serves reads
        v = m.finalize_transitions()
        assert v.state_of("b:2") == MemberState.REMOVED
        assert "b:2" not in v.readable_ids()

    def test_dead_is_unreadable_immediately(self):
        m = Membership(["a:1", "b:2", "c:3"])
        v = m.mark_dead("b:2")
        assert v.state_of("b:2") == MemberState.DEAD
        assert "b:2" not in v.readable_ids()

    def test_invalid_transitions_raise(self):
        m = Membership(["a:1", "b:2"])
        with pytest.raises(ValueError):
            m.add_member("a:1")  # live id collision
        m.mark_dead("b:2")
        with pytest.raises(ValueError):
            m.remove_member("b:2")  # DEAD is terminal
        with pytest.raises(ValueError):
            m.mark_dead("b:2")
        with pytest.raises(ValueError):
            Membership([])
        with pytest.raises(ValueError):
            Membership(["x", "x"])

    def test_dead_id_may_rejoin_as_new_entry(self):
        m = Membership(["a:1", "b:2"])
        m.mark_dead("b:2")
        v = m.add_member("b:2")  # a restarted node rejoins under its old id
        assert v.state_of("b:2") == MemberState.JOINING  # latest entry wins
        assert len(v.member_ids) == 3  # tombstone retained: indices stable
        assert m.index_of("b:2") == 2

    def test_finalize_without_pending_is_a_noop(self):
        m = Membership(["a:1"])
        assert m.finalize_transitions() is None
        assert m.view().epoch == 1

    def test_last_placement_member_cannot_be_removed(self):
        """A graceful drain promises the data survives — with nowhere to
        re-mirror it, the transition must be refused (mark_dead remains
        for recording a real crash)."""
        m = Membership(["a:1", "b:2"])
        m.remove_member("a:1")
        with pytest.raises(ValueError):
            m.remove_member("b:2")
        m.mark_dead("b:2")  # recording a crash is still allowed

    def test_finalize_refuses_a_stale_epoch(self):
        """The resharder finalizes with the epoch it PLANNED at: a
        transition landing in between must be re-planned, never
        rubber-stamped to REMOVED with zero migration done."""
        m = Membership(["a:1", "b:2", "c:3"])
        m.add_member("d:4")
        planned = m.view().epoch
        m.remove_member("b:2")  # lands between plan and finalize
        assert m.finalize_transitions(expected_epoch=planned) is None
        assert m.view().state_of("b:2") == MemberState.LEAVING  # untouched
        v = m.finalize_transitions(expected_epoch=m.view().epoch)
        assert v.state_of("b:2") == MemberState.REMOVED

    def test_status_counters(self):
        m = Membership(["a:1", "b:2", "c:3"])
        m.add_member("d:4")
        m.mark_dead("a:1")
        s = m.status()
        assert s["membership_epoch"] == 3
        assert s["membership_members"] == 3  # b, c + joining d
        assert s["membership_joining"] == 1 and s["membership_dead"] == 1
        assert s["membership_settled"] == 0


# ---------------------------------------------------------------------------
# live clusters
# ---------------------------------------------------------------------------

def _start_server():
    return its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)


def _connect(port, **overrides):
    cfg = dict(
        host_addr="127.0.0.1", service_port=port, log_level="error",
        auto_reconnect=True, connect_timeout_ms=500, op_timeout_ms=2000,
    )
    cfg.update(overrides)
    conn = its.InfinityConnection(its.ClientConfig(**cfg))
    conn.connect()
    return conn


def _fast_breakers(i):
    return CircuitBreaker(
        fail_threshold=2, probe_backoff_s=0.05, max_backoff_s=0.4, seed=i
    )


def _mk_caches(seed):
    out = []
    for layer in range(SPEC.num_layers):
        k = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + layer), SPEC.cache_shape, jnp.float32
        ).astype(SPEC.dtype)
        v = jax.random.normal(
            jax.random.PRNGKey(seed * 100 + 50 + layer), SPEC.cache_shape,
            jnp.float32,
        ).astype(SPEC.dtype)
        out.append((k, v))
    return out


class _Pool:
    """N live loopback servers + a replicated elastic cluster over them,
    with saved roots and a correctness sweep."""

    def __init__(self, n, conn_wrap=None, **cluster_kw):
        self.servers = [_start_server() for _ in range(n)]
        self.conns = [_connect(s.port) for s in self.servers]
        wrapped = [
            conn_wrap(i, c) if conn_wrap is not None else c
            for i, c in enumerate(self.conns)
        ]
        kw = dict(
            degrade=True, replicas=2, breaker_factory=_fast_breakers,
            member_ids=[f"127.0.0.1:{s.port}" for s in self.servers],
        )
        kw.update(cluster_kw)
        self.cluster = ClusterKVConnector(wrapped, SPEC, "member-test",
                                          max_blocks=8, **kw)
        self.contents = {}
        self.prompts = []
        self.src = np.array([3, 9], np.int32)

    def seed_roots(self, n_roots, rng_seed=5):
        rng = np.random.default_rng(rng_seed)
        self.prompts = [
            rng.integers(0, 1000, size=2 * SPEC.block_tokens).tolist()
            for _ in range(n_roots)
        ]
        for i, p in enumerate(self.prompts):
            self.contents[i] = _mk_caches(i)
            asyncio.run(self.cluster.save(p, self.contents[i], self.src))

    def sweep(self):
        """(reads, misses, wrong) over every saved root."""
        reads = misses = wrong = 0
        dst = np.array([6, 2], np.int32)
        for i, p in enumerate(self.prompts):
            reads += 1
            loaded, n = asyncio.run(self.cluster.load(p, SPEC.make_caches(), dst))
            if n == 0:
                misses += 1
                continue
            wrong += any(
                not np.array_equal(
                    np.asarray(
                        gather_blocks(loaded[layer][kind], jnp.asarray(dst)),
                        np.float32,
                    ),
                    np.asarray(
                        gather_blocks(
                            self.contents[i][layer][kind], jnp.asarray(self.src)
                        ),
                        np.float32,
                    ),
                )
                for layer in range(SPEC.num_layers)
                for kind in (0, 1)
            )
        return reads, misses, wrong

    def join(self):
        srv = _start_server()
        self.servers.append(srv)
        conn = _connect(srv.port)
        self.conns.append(conn)
        return srv, conn, self.cluster.add_member(conn)

    def close(self):
        self.cluster.close()
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass
        for s in self.servers:
            s.stop()


@pytest.fixture()
def pool3():
    p = _Pool(3)
    try:
        yield p
    finally:
        p.close()


def _kvmap_len(server) -> int:
    from infinistore_tpu._native import lib as native

    return int(native.its_server_kvmap_len(server.handle))


class TestLiveResharding:
    def test_join_migrates_only_the_delta_and_reads_stay_correct(self, pool3):
        pool3.seed_roots(16)
        place_before = list(pool3.cluster.membership.view().placement_ids())
        srv4, _, view = pool3.join()
        assert view.epoch == 2
        reads, misses, wrong = pool3.sweep()  # mid-reshard (maybe): failover
        assert (misses, wrong) == (0, 0)
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        # Finalized: joiner ACTIVE, single placement again.
        view = pool3.cluster.membership.view()
        joiner_id = f"127.0.0.1:{srv4.port}"
        assert view.state_of(joiner_id) == MemberState.ACTIVE
        # Only the rendezvous delta moved: the joiner holds exactly the
        # roots whose new top-R set contains it.
        new_place = place_before + [joiner_id]
        delta = sum(
            joiner_id in [
                new_place[k]
                for k in rendezvous_ranked(new_place, pool3.cluster._root_of(p))[:2]
            ]
            for p in pool3.prompts
        )
        progress = pool3.cluster.resharder.progress()
        assert progress["reshard_moved_roots"] == delta
        assert progress["reshard_debt_roots"] == 0
        assert _kvmap_len(srv4) > 0
        reads, misses, wrong = pool3.sweep()
        assert (misses, wrong) == (0, 0)
        # Migration traffic was BACKGROUND-tagged on the wire (ITS-P003's
        # runtime half): the joiner's connection only ever saw bg batches.
        assert pool3.conns[-1].qos_stats()["bg_ops"] > 0

    def test_graceful_leave_re_mirrors_before_the_node_goes_away(self, pool3):
        pool3.seed_roots(12)
        leaver = pool3.cluster.member_ids[0]
        view = pool3.cluster.remove_member(leaver)
        assert view.state_of(leaver) == MemberState.LEAVING
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        assert (
            pool3.cluster.membership.view().state_of(leaver)
            == MemberState.REMOVED
        )
        # NOW the operator may stop the node: every root has R copies on
        # the survivors, so reads never miss or touch the leaver.
        pool3.servers[0].stop()
        reads, misses, wrong = pool3.sweep()
        assert (misses, wrong) == (0, 0)
        assert pool3.cluster.resharder.progress()["reshard_debt_roots"] == 0

    def test_mark_dead_re_replicates_from_surviving_replica(self, pool3):
        pool3.seed_roots(12)
        victim = pool3.cluster.member_ids[1]
        pool3.servers[1].stop()  # crash, copies lost
        pool3.cluster.mark_dead(victim)
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        reads, misses, wrong = pool3.sweep()
        assert (misses, wrong) == (0, 0)
        # R=2 restored: every root is on both survivors.
        with pool3.cluster._cat_lock:
            holders = [sorted(r.holders) for r in pool3.cluster._catalog.values()]
        survivors = sorted(
            m for m in pool3.cluster.member_ids if m != victim
        )
        assert all(h == survivors for h in holders)

    def test_save_during_join_lands_on_new_placement_without_debt(self, pool3):
        pool3.seed_roots(6)
        pool3.join()
        # New data saved mid-reshard routes by the NEW placement: it never
        # becomes migration debt.
        rng = np.random.default_rng(99)
        extra = rng.integers(0, 1000, size=2 * SPEC.block_tokens).tolist()
        idx = len(pool3.prompts)
        pool3.prompts.append(extra)
        pool3.contents[idx] = _mk_caches(idx)
        asyncio.run(pool3.cluster.save(extra, pool3.contents[idx], pool3.src))
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        reads, misses, wrong = pool3.sweep()
        assert (misses, wrong) == (0, 0)
        assert pool3.cluster.resharder.progress()["reshard_debt_roots"] == 0

    def test_partial_save_never_overclaims_a_holder(self, pool3):
        """A first_block>0 extension landing on a member WITHOUT the base
        must not make it look like a complete holder — that mistake would
        let the resharder prune the only copy of the base blocks."""
        pool3.seed_roots(1)
        p = pool3.prompts[0]
        root = pool3.cluster._root_of(p)
        long_p = p + p[:SPEC.block_tokens]  # one more complete block
        with pool3.cluster._cat_lock:
            holders0 = set(pool3.cluster._catalog[root].holders)
        # The one member R=2 did NOT place this root on.
        newcomer = next(
            m for m in pool3.cluster.member_ids if m not in holders0
        )
        # Tail-only save attributed to a member that never took the base.
        pool3.cluster._catalog_record(long_p, 3, [newcomer], first_block=2)
        with pool3.cluster._cat_lock:
            rec = pool3.cluster._catalog[root]
            assert rec.holders.get(newcomer, 0) == 0  # no overclaim
            full = [m for m, lv in rec.holders.items() if lv == rec.blocks]
        # Contiguous extension on an existing holder DOES raise its level.
        pool3.cluster._catalog_record(long_p, 3, [full[0]], first_block=2)
        with pool3.cluster._cat_lock:
            rec = pool3.cluster._catalog[root]
            assert rec.holders[full[0]] == 3 and rec.blocks == 3
        # The plan never uses a level-0 holder as a source, and never
        # prunes while a wanted member lacks the full level.
        for task in pool3.cluster.reshard_plan():
            if task.root == root:
                assert newcomer not in task.sources

    def test_copy_of_a_dropped_root_is_undone(self, pool3):
        """The drop-vs-copy race, pinned deterministically: a copy whose
        root vanished from the catalog mid-flight (dropped) must be undone
        on the destination — otherwise the new owner would serve a dropped
        prompt forever (no later plan can prune an uncataloged root)."""
        from infinistore_tpu.membership import _RootTask

        pool3.seed_roots(1)
        root = pool3.cluster._root_of(pool3.prompts[0])
        with pool3.cluster._cat_lock:
            rec = pool3.cluster._catalog.pop(root)  # the concurrent drop
        # Destination: a fresh member with nothing on it.
        srv4, _, _ = pool3.join()
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        before = _kvmap_len(srv4)
        task = _RootTask(
            root=root, tokens=rec.tokens, blocks=rec.blocks,
            sources=sorted(rec.holders),
            targets=[f"127.0.0.1:{srv4.port}"],
        )
        assert pool3.cluster.resharder._copy_root(task, task.targets[0])
        # The copy landed and was immediately undone: nothing stray stays.
        assert _kvmap_len(srv4) == before
        moved = pool3.cluster.resharder.progress()["reshard_moved_keys"]
        assert moved > 0  # the copy really ran before the undo

    def test_drop_mid_reshard_deletes_every_copy(self, pool3):
        pool3.seed_roots(8)
        pool3.join()
        victim_prompt = pool3.prompts[0]
        assert pool3.cluster.drop(victim_prompt) > 0
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        assert pool3.cluster.lookup(victim_prompt) == 0
        # The dropped root is gone from the catalog too: nothing re-mirrors
        # it back.
        with pool3.cluster._cat_lock:
            assert pool3.cluster._root_of(victim_prompt) not in pool3.cluster._catalog


class TestManagePlane:
    def test_membership_get_post_and_metrics(self, pool3):
        from infinistore_tpu.config import ServerConfig
        from infinistore_tpu.server import ManageServer

        pool3.seed_roots(6)
        extra_srv = _start_server()
        pool3.servers.append(extra_srv)

        async def drive():
            manage = ManageServer(
                ServerConfig(service_port=pool3.servers[0].port, manage_port=0),
                cluster=pool3.cluster,
            )
            server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = server.sockets[0].getsockname()[1]

            async def req(method, path, body=None):
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
                payload = json.dumps(body).encode() if body is not None else b""
                writer.write(
                    f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
                    f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
                )
                await writer.drain()
                raw = await reader.read()
                writer.close()
                head, _, body_bytes = raw.partition(b"\r\n\r\n")
                return int(head.split()[1]), body_bytes

            status, body = await req("GET", "/membership")
            doc = json.loads(body)
            assert status == 200 and doc["enabled"] and doc["epoch"] == 1
            assert doc["membership_settled"] == 1
            assert {m["state"] for m in doc["members"]} == {"active"}

            status, body = await req("POST", "/membership", {
                "action": "add", "host": "127.0.0.1",
                "service_port": extra_srv.port,
            })
            assert status == 200 and json.loads(body)["epoch"] == 2

            status, body = await req("POST", "/membership", {
                "action": "remove", "member_id": pool3.cluster.member_ids[0],
            })
            assert status == 200

            status, _ = await req("POST", "/membership", {"action": "nope"})
            assert status == 400
            status, _ = await req(
                "POST", "/membership", {"action": "remove", "member_id": "ghost"}
            )
            assert status == 400
            status, _ = await req("DELETE", "/membership")
            assert status == 405

            status, body = await req("GET", "/metrics")
            assert status == 200
            assert b"infinistore_membership_epoch" in body
            assert b"infinistore_reshard_debt_roots" in body

            server.close()
            await server.wait_closed()

        asyncio.run(drive())
        # The POSTed transitions really drove the cluster: joiner admitted,
        # leaver drained, reads stay whole.
        assert pool3.cluster.resharder.wait_idle(timeout=30.0)
        reads, misses, wrong = pool3.sweep()
        assert (misses, wrong) == (0, 0)
        extra_conn = pool3.cluster.members[-1].conn
        try:
            reads2 = _kvmap_len(extra_srv)
            assert reads2 >= 0  # joiner server alive and queried
        finally:
            extra_conn.close()


# ---------------------------------------------------------------------------
# chaos: churn under failure (CI chaos job, hard timeout)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestChurnChaos:
    def test_member_killed_during_inflight_reshard_replans(self):
        """A source member dies mid-migration: the pass aborts, the next
        epoch's replan re-sources every remaining root from the surviving
        replica, and the pool converges with 0 debt and 0 wrong reads."""
        from infinistore_tpu.faults import FaultRule, FaultyConnection

        # Slow every migration read (sync read_cache) down so the reshard
        # is reliably in flight when the kill lands; foreground loads ride
        # read_cache_async and stay fast.
        def wrap(i, conn):
            return FaultyConnection(conn, [
                FaultRule(op="read_cache", action="delay", delay_s=0.05)
            ], seed=i)

        pool = _Pool(3, conn_wrap=wrap)
        try:
            pool.seed_roots(12)
            pool.join()  # reshard starts, throttled by the delays
            victim = next(
                mid for mid in pool.cluster.member_ids[:3]
                if pool.cluster.membership.view().state_of(mid) == "active"
            )
            vi = pool.cluster.member_index(victim)
            pool.servers[vi].stop()  # the kill, mid-reshard
            pool.cluster.mark_dead(victim)  # epoch change -> replan
            assert pool.cluster.resharder.wait_idle(timeout=60.0)
            progress = pool.cluster.resharder.progress()
            assert progress["reshard_debt_roots"] == 0
            reads, misses, wrong = pool.sweep()
            assert (misses, wrong) == (0, 0)
        finally:
            pool.close()

    def test_join_while_another_members_breaker_is_open(self):
        """A join must complete while one member is dark behind an OPEN
        breaker: the resharder sources every root from the surviving
        holder instead of burning timeouts on the open one."""
        pool = _Pool(3)
        try:
            # 24 roots: the dark member owns (rank-0) ~1/3 of them, and
            # only rank-0 lookups reach it (rank-1 is never probed when
            # the owner serves) — with 24 the odds it owns none are
            # negligible, and repeated sweeps accumulate the consecutive
            # errors the fail_threshold=2 breaker needs.
            pool.seed_roots(24)
            dark = pool.cluster.member_ids[2]
            di = pool.cluster.member_index(dark)
            pool.servers[2].stop()
            # Trip the breaker with doomed reads: sweep until it opens.
            for _ in range(4):
                for p in pool.prompts:
                    pool.cluster.lookup(p)
                    if (
                        pool.cluster._health[di].breaker.state
                        == CircuitBreaker.OPEN
                    ):
                        break
                if pool.cluster._health[di].breaker.state != CircuitBreaker.CLOSED:
                    break
            assert pool.cluster._health[di].breaker.state != CircuitBreaker.CLOSED
            pool.join()
            assert pool.cluster.resharder.wait_idle(timeout=60.0)
            assert pool.cluster.resharder.progress()["reshard_debt_roots"] == 0
            reads, misses, wrong = pool.sweep()
            assert (misses, wrong) == (0, 0)
            # The dark member never served as a migration source: its only
            # traffic was the doomed lookups and (maybe) half-open probes.
            assert _kvmap_len(pool.servers[-1]) > 0
        finally:
            pool.close()
