"""int8 KV-cache quantization: the quantizer's error bounds, the fused
dequantizing decode kernel (interpret mode) against the XLA fallback and the
full-precision oracle, and the two-connector store roundtrip (half the data
bytes per block, commit order making a data hit imply scales)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.tpu.kv_quant import (
    QuantizedKVConnector,
    _quant_decode_pallas,
    _quant_decode_xla,
    dequantize_kv,
    quantize_kv,
)
from infinistore_tpu.tpu.paged import PagedKVCacheSpec
from infinistore_tpu.tpu.paged_attention import paged_decode_attention_xla_batched

SPEC = PagedKVCacheSpec(
    num_layers=2, num_blocks=16, block_tokens=8, num_kv_heads=2, head_dim=32,
    dtype=jnp.float32,
)


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((16, 8, 2, 32)) * 3.0, jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    back = dequantize_kv(q, s)
    # Per-vector bound: half a quantization step of that vector's absmax.
    step = np.asarray(jnp.max(jnp.abs(x), axis=-1)) / 127.0
    err = np.abs(np.asarray(back) - np.asarray(x))
    assert (err <= step[..., None] * 0.5000001 + 1e-7).all()
    # Zero vectors: scale 0, exact zeros back.
    zq, zs = quantize_kv(jnp.zeros((4, 8)))
    assert float(jnp.abs(dequantize_kv(zq, zs)).max()) == 0.0


def test_kernel_matches_xla_and_tracks_full_precision():
    rng = np.random.default_rng(2)
    N, bt, kvh, d, h, ntbl, bsz = 16, 8, 4, 16, 8, 8, 3
    k = jnp.asarray(rng.standard_normal((N, bt, kvh, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((N, bt, kvh, d)), jnp.float32)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    q = jnp.asarray(rng.standard_normal((bsz, h, d)), jnp.float32)
    tbls = jnp.asarray(
        np.stack([rng.permutation(N)[:ntbl] for _ in range(bsz)]), jnp.int32
    )
    sls = jnp.asarray([1, 30, ntbl * bt], jnp.int32)
    got = _quant_decode_pallas(q, kq, ks, vq, vs, tbls, sls, interpret=True)
    want = _quant_decode_xla(q, kq, ks, vq, vs, tbls, sls)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    # Against full precision: bounded by the int8 scheme, not exploding
    # through the softmax.
    full = paged_decode_attention_xla_batched(q, k, v, tbls, sls)
    assert float(jnp.max(jnp.abs(want - full))) < 5e-2


def _quant_caches(seed):
    out = []
    rng = np.random.default_rng(seed)
    for _ in range(SPEC.num_layers):
        k = jnp.asarray(rng.standard_normal(SPEC.cache_shape), jnp.float32)
        v = jnp.asarray(rng.standard_normal(SPEC.cache_shape), jnp.float32)
        out.append((quantize_kv(k), quantize_kv(v)))
    return out


def test_store_roundtrip_half_bytes(conn):
    qc = QuantizedKVConnector(conn, SPEC, "quant-demo", max_blocks=4)
    tokens = list(range(16))  # 2 blocks
    caches = _quant_caches(3)
    src = np.array([3, 9], np.int32)
    assert asyncio.run(qc.save(tokens, caches, src)) == 2 * 2 * SPEC.num_layers
    assert qc.lookup(tokens) == 2

    fresh = [
        (
            (jnp.zeros(SPEC.cache_shape, jnp.int8),
             jnp.zeros((*SPEC.cache_shape[:-1],), jnp.float32)),
            (jnp.zeros(SPEC.cache_shape, jnp.int8),
             jnp.zeros((*SPEC.cache_shape[:-1],), jnp.float32)),
        )
        for _ in range(SPEC.num_layers)
    ]
    dst = np.array([5, 0], np.int32)
    loaded, n = asyncio.run(qc.load(tokens, fresh, dst))
    assert n == 2
    for layer in range(SPEC.num_layers):
        for side in (0, 1):
            dq_src = dequantize_kv(*caches[layer][side])
            dq_dst = dequantize_kv(*loaded[layer][side])
            np.testing.assert_array_equal(
                np.asarray(dq_src)[src], np.asarray(dq_dst)[dst]
            )
    # Drop removes BOTH key families (data + scales).
    assert qc.drop(tokens) == 2 * (2 * 2 * SPEC.num_layers)
    assert qc.lookup(tokens) == 0


def test_engine_harness_over_quantizing_adapter(conn):
    """A float engine runs unmodified over the quantizing adapter: its store
    footprint halves and prefix hits come back as dequantized floats within
    the int8 scheme's tolerance (verify_tol), with real hits on wave two."""
    from infinistore_tpu.engine import ContinuousBatchingHarness
    from infinistore_tpu.models import LlamaConfig, init_params
    from infinistore_tpu.tpu.kv_quant import QuantizingKVAdapter

    cfg = LlamaConfig(
        vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
        block_tokens=8, dtype=jnp.float32,
    )
    # 4 prompt blocks + 1 generated block per request.
    qc = QuantizedKVConnector(conn, cfg.kv_spec(5), "quant-engine", max_blocks=5)
    params = init_params(cfg, jax.random.PRNGKey(1))
    h = ContinuousBatchingHarness(
        QuantizingKVAdapter(qc), params, cfg, num_blocks=16, max_req_blocks=5,
        verify=True, verify_tol=5e-2,
    )
    rng = np.random.default_rng(6)
    prompts = [
        rng.integers(0, cfg.vocab, size=4 * cfg.block_tokens).tolist()
        for _ in range(3)
    ]

    async def drive():
        m1 = await h.run(prompts, concurrency=3)
        h.stats.clear()
        # Second wave also GENERATES: full hits + lockstep decode waves over
        # dequantized prefixes in one flow.
        m2 = await h.run(prompts, concurrency=3, gen_tokens=cfg.block_tokens)
        return m1, m2

    m1, m2 = asyncio.run(drive())
    assert m1["all_verified"], "first wave (compute + quantized save) diverged"
    assert m2["hit_rate"] == 1.0, "second wave should be served from the store"
    assert m2["all_verified"], "dequantized blocks exceeded the int8 tolerance"
    assert m2["generated_tokens"] == 3 * cfg.block_tokens
    assert m2["max_wave_size"] >= 2


def test_scales_race_degrades_to_miss(conn):
    """Data sentinel present but scales evicted: load must report 0 (the
    engine recomputes) — never hand back data with garbage scales."""
    qc = QuantizedKVConnector(conn, SPEC, "quant-race", max_blocks=4)
    tokens = list(range(16))
    asyncio.run(qc.save(tokens, _quant_caches(4), np.array([1, 2], np.int32)))
    assert qc.scales.drop(tokens) > 0  # the race, made deterministic
    fresh = [
        (
            (jnp.zeros(SPEC.cache_shape, jnp.int8),
             jnp.zeros((*SPEC.cache_shape[:-1],), jnp.float32)),
            (jnp.zeros(SPEC.cache_shape, jnp.int8),
             jnp.zeros((*SPEC.cache_shape[:-1],), jnp.float32)),
        )
        for _ in range(SPEC.num_layers)
    ]
    _, n = asyncio.run(qc.load(tokens, fresh, np.array([4, 5], np.int32)))
    assert n == 0
