"""Overlapped layerwise prefill→decode handoff (docs/disaggregation.md).

The contracts of the disagg plane, on a small real store:

- **watermark semantics**: with ``watermark=1`` the first decode step
  launches after layer 0 installs, and every deeper layer's install
  precedes its compute (the trace-event invariant) while the transfer is
  still streaming behind the step;
- **byte identity**: the overlapped and blocking legs both produce
  first-token logits bitwise equal to the local-recompute oracle
  (``check_bytes``; ``disagg_wrong_bytes`` stays 0);
- **degenerate watermark**: ``watermark=n_layers`` is today's blocking
  fetch-all — every install strictly precedes every compute;
- **fallback**: a layer missing past the retry deadline flips the leg to
  the layer-chunked local recompute — counted, journaled as a
  ``disagg_fallback`` event, and STILL byte-identical to the oracle;
- **manage-plane export**: after a handoff, /metrics carries the
  ``infinistore_disagg_*`` families and ``GET /disagg`` the snapshot
  (ITS-C009);
- **(chaos)** a prefill ENGINE subprocess kill -9'd mid-stream (layers
  0..k durable, deeper layers never arrive) degrades to the fallback
  with zero wrong bytes.
"""

import asyncio
import json
import urllib.request

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import disagg, telemetry
from tools import fleet

CFG = disagg.demo_config(n_layers=4)
REQ_BLOCKS = 2
NUM_BLOCKS = 16


@pytest.fixture(autouse=True)
def _fresh_counters():
    telemetry.reset()
    ds = disagg.reset_counters()
    yield ds
    telemetry.reset()


@pytest.fixture(scope="module")
def store():
    srv = its.start_local_server(
        prealloc_bytes=64 << 20,
        block_bytes=max(64 << 10, CFG.kv_spec(1).block_nbytes),
    )
    yield srv
    srv.stop()


@pytest.fixture()
def harness(store):
    conns = []

    def make_conn():
        c = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=store.port, log_level="error",
        ))
        c.connect()
        conns.append(c)
        return c

    h = disagg.DisaggHarness(
        make_conn, CFG, num_blocks=NUM_BLOCKS, req_blocks=REQ_BLOCKS,
    )
    yield h
    for c in conns:
        c.close()


def _event_index(events, kind, layer):
    return events.index((kind, layer))


class TestWatermark:
    def test_install_precedes_compute_per_layer(self, harness):
        """The watermark invariant: layer l's attention never reads bytes
        still in flight — its install event precedes its compute event,
        for every layer, while deeper layers stream behind the step."""
        ev = []
        res = asyncio.run(harness.run_overlapped(
            harness.prompt(seed=1), watermark=1, trace_events=ev,
        ))["result"]
        assert not res.fallback
        for layer in range(CFG.n_layers):
            assert (
                _event_index(ev, "install", layer)
                < _event_index(ev, "compute", layer)
            ), f"layer {layer} computed before its install: {ev}"
        # Layerwise admission really happened: the first compute did not
        # wait for the deepest layer's install (blocking would order ALL
        # installs first).
        assert _event_index(ev, "compute", 0) < _event_index(
            ev, "install", CFG.n_layers - 1
        )

    def test_watermark_full_degenerates_to_blocking(self, harness):
        """``watermark=n_layers`` is the blocking fetch-all: every install
        strictly precedes every compute."""
        ev = []
        res = asyncio.run(harness.run_overlapped(
            harness.prompt(seed=2), watermark=CFG.n_layers, trace_events=ev,
        ))["result"]
        assert not res.fallback
        last_install = max(
            i for i, (kind, _) in enumerate(ev) if kind == "install"
        )
        first_compute = min(
            i for i, (kind, _) in enumerate(ev) if kind == "compute"
        )
        assert last_install < first_compute
        assert res.overlap_layers == 0

    def test_watermark_clamped(self, harness):
        """Out-of-range watermarks clamp to [1, n_layers] instead of
        deadlocking or skipping the gate."""
        for wm in (0, CFG.n_layers + 7):
            res = asyncio.run(harness.run_overlapped(
                harness.prompt(seed=3), watermark=wm,
            ))["result"]
            assert not res.fallback


class TestByteIdentity:
    def test_overlapped_and_blocking_match_oracle(self, harness, _fresh_counters):
        prompt = harness.prompt(seed=4)
        oracle = asyncio.run(harness.run_local(prompt))["result"]
        over = asyncio.run(
            harness.run_overlapped(prompt, watermark=1)
        )["result"]
        harness.drop(prompt)
        blocking = asyncio.run(harness.run_blocking(prompt))["result"]
        assert harness.check_bytes(over, oracle)
        assert harness.check_bytes(blocking, oracle)
        assert not over.fallback and not blocking.fallback
        assert _fresh_counters.status()["disagg_wrong_bytes"] == 0

    def test_multi_token_decode_matches(self, harness):
        """Identity holds past the first token: the greedy continuations
        of the handoff and local legs agree token for token."""
        prompt = harness.prompt(seed=5)
        oracle = asyncio.run(
            harness.run_local(prompt, gen_tokens=4)
        )["result"]
        over = asyncio.run(
            harness.run_overlapped(prompt, watermark=1, gen_tokens=4)
        )["result"]
        assert over.tokens == oracle.tokens
        assert harness.check_bytes(over, oracle)


class TestFallback:
    def test_missing_layers_fall_back_and_stay_correct(
        self, harness, _fresh_counters
    ):
        """No producer at all: every install misses the retry deadline,
        the leg recomputes locally — counted, journaled, byte-identical."""
        prompt = harness.prompt(seed=6)
        res = asyncio.run(harness.run_overlapped(
            prompt, watermark=1, prefill=False, retry_missing_s=0.05,
        ))["result"]
        assert res.fallback
        oracle = asyncio.run(harness.run_local(prompt))["result"]
        assert harness.check_bytes(res, oracle)
        st = _fresh_counters.status()
        assert st["disagg_fallback_recomputes"] == 1
        assert st["disagg_wrong_bytes"] == 0
        kinds = [e["kind"] for e in telemetry.get_journal().snapshot()]
        assert "disagg_fallback" in kinds

    def test_fallback_journal_names_the_failed_layer(self, harness):
        asyncio.run(harness.run_overlapped(
            harness.prompt(seed=7), watermark=1, prefill=False,
            retry_missing_s=0.05,
        ))
        ev = [
            e for e in telemetry.get_journal().snapshot()
            if e["kind"] == "disagg_fallback"
        ]
        assert ev and ev[0]["attrs"]["failed_layer"] == 0
        assert ev[0]["attrs"]["prefix_blocks"] == REQ_BLOCKS


class TestManagePlane:
    def test_metrics_and_disagg_route_export_counters(self, harness, store):
        """ITS-C009's runtime half: after a handoff in this process, the
        manage plane's /metrics carries the infinistore_disagg_* families
        and GET /disagg serves the same snapshot."""
        from infinistore_tpu import lib as its_lib
        from infinistore_tpu.server import ManageServer

        asyncio.run(harness.run_overlapped(harness.prompt(seed=8)))
        cfg = its.ServerConfig(
            host="127.0.0.1", service_port=0, manage_port=1,
            prealloc_size=1, minimal_allocate_size=16, pin_memory=False,
            log_level="error",
        )

        def get(port, path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as r:
                return r.read().decode()

        async def run():
            manage = ManageServer(cfg)
            manage._server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = manage._server.sockets[0].getsockname()[1]
            try:
                metrics = await asyncio.to_thread(get, port, "/metrics")
                doc = json.loads(await asyncio.to_thread(get, port, "/disagg"))
            finally:
                manage._server.close()
                await manage._server.wait_closed()
            return metrics, doc

        old = its_lib._server_handle
        its_lib._server_handle = store.handle
        try:
            metrics, doc = asyncio.run(run())
        finally:
            its_lib._server_handle = old
        st = disagg.counters().status()
        assert st["disagg_handoffs"] >= 1
        assert doc["enabled"] is True
        for key, val in st.items():
            assert doc[key] == val
            assert f"infinistore_{key} {val}" in metrics


@pytest.mark.chaos
class TestChaos:
    def test_prefill_killed_mid_stream_degrades_to_fallback(
        self, harness, store, _fresh_counters
    ):
        """kill -9 the prefill ENGINE subprocess mid-handoff: layers 0..1
        durable, deeper layers never arrive; the decode side's retry
        deadline expires and the leg recomputes — never wrong bytes.

        The kill window opens only after BOTH layers' durability markers
        (in any order — ships are concurrent, and under in-suite load
        layer 1's puts can finish before layer 0's): killing on the last
        marker alone could SIGKILL while layer 0 is still partially
        written, and the fallback would then fire at layer 0 instead of
        the first never-shipped layer (the one-flake-in-suite PR 17
        noted)."""
        member = fleet.spawn_disagg_prefill(
            store.port, blocks=REQ_BLOCKS, n_layers=CFG.n_layers,
            prompt_seed=9, stall_after_layer=1, stall_s=60.0,
        )
        try:
            fleet.read_until_markers(
                member, ["shipped layer 0", "shipped layer 1"],
                timeout_s=180.0,
            )
            assert fleet.kill_member(member) == -9
        finally:
            if member["proc"].poll() is None:
                member["proc"].kill()
        prompt = harness.prompt(seed=9)
        res = asyncio.run(harness.run_overlapped(
            prompt, watermark=1, prefill=False, retry_missing_s=0.5,
        ))["result"]
        assert res.fallback
        oracle = asyncio.run(harness.run_local(prompt))["result"]
        assert harness.check_bytes(res, oracle)
        st = _fresh_counters.status()
        assert st["disagg_fallback_recomputes"] == 1
        assert st["disagg_wrong_bytes"] == 0
        ev = [
            e for e in telemetry.get_journal().snapshot()
            if e["kind"] == "disagg_fallback"
        ]
        # The kill window pins the failed layer past the durable prefix.
        assert ev and ev[0]["attrs"]["failed_layer"] >= 2
