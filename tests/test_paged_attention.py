"""Fused paged decode attention: the Pallas kernel (interpret mode on CPU)
against the XLA fallback and a from-scratch numpy oracle, across GQA shapes,
partial blocks, and padded tables. The reference has no engine-side compute
at all (SURVEY.md §2.9) — this kernel is the TPU build's consumer-side hot
op (models/llama.py decode_step attends through it)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from infinistore_tpu.tpu.paged_attention import (
    _paged_decode_attention_pallas,
    paged_decode_attention_xla,
)


def _numpy_oracle(q, k_cache, v_cache, table, seq_len):
    """Dense decode attention in float64 numpy: gather, mask, softmax."""
    q = np.asarray(q, np.float64)
    h, d = q.shape
    kvh = k_cache.shape[2]
    groups = h // kvh
    k = np.asarray(k_cache, np.float64)[np.asarray(table)].reshape(-1, kvh, d)
    v = np.asarray(v_cache, np.float64)[np.asarray(table)].reshape(-1, kvh, d)
    k = np.repeat(k, groups, axis=1)
    v = np.repeat(v, groups, axis=1)
    logits = np.einsum("hd,thd->ht", q, k) / np.sqrt(d)
    logits[:, seq_len:] = -np.inf
    p = np.exp(logits - logits.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    return np.einsum("ht,thd->hd", p, v)


CASES = [
    # (num_blocks, block_tokens, kv_heads, head_dim, q_heads, table_len)
    (16, 8, 4, 16, 8, 8),  # GQA x2
    (32, 16, 2, 32, 8, 16),  # GQA x4
    (8, 8, 8, 16, 8, 4),  # MHA (no GQA)
    (16, 8, 1, 64, 4, 16),  # MQA (one kv head)
]


@pytest.mark.parametrize("case", CASES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_matches_oracle(case, dtype):
    n, bt, kvh, d, h, ntbl = case
    rng = np.random.default_rng(hash(case) % 2**32)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), dtype)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), dtype)
    q = jnp.asarray(rng.standard_normal((h, d)), dtype)
    table = jnp.asarray(rng.permutation(n)[:ntbl], jnp.int32)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    # seq lens: single token, partial block, block boundary, mid-table, full.
    for sl in (1, bt - 1, bt, ntbl * bt // 2 + 3, ntbl * bt):
        want = _numpy_oracle(q, k_cache, v_cache, table, sl)
        got = _paged_decode_attention_pallas(
            q, k_cache, v_cache, table, sl, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float64), want, rtol=tol, atol=tol,
            err_msg=f"sl={sl}",
        )
        got_xla = paged_decode_attention_xla(q, k_cache, v_cache, table, sl)
        np.testing.assert_allclose(
            np.asarray(got_xla, np.float64), want, rtol=tol, atol=tol
        )


def test_padded_table_entries_are_ignored():
    """Entries past seq_len may alias ANY valid block (engines pad with 0);
    their contents must not leak into the output."""
    n, bt, kvh, d, h = 8, 8, 2, 16, 4
    rng = np.random.default_rng(7)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((h, d)), jnp.float32)
    sl = bt + 3  # two blocks in play, second partial
    base = jnp.asarray([2, 5, 0, 0], jnp.int32)
    alias = jnp.asarray([2, 5, 7, 1], jnp.int32)  # different garbage tail
    out_base = _paged_decode_attention_pallas(
        q, k_cache, v_cache, base, sl, interpret=True
    )
    out_alias = _paged_decode_attention_pallas(
        q, k_cache, v_cache, alias, sl, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out_base), np.asarray(out_alias))


def test_batched_kernel_matches_oracle_ragged_seq_lens():
    """One launch, many requests: each grid row must reset its accumulators
    and mask by ITS seq_len — a carry-over from the previous request would
    poison every row after the first."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_batched,
        paged_decode_attention_xla_batched,
    )

    n, bt, kvh, d, h, ntbl, bsz = 32, 8, 2, 16, 4, 6, 5
    rng = np.random.default_rng(3)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((bsz, h, d)), jnp.float32)
    tables = jnp.asarray(
        np.stack([rng.permutation(n)[:ntbl] for _ in range(bsz)]), jnp.int32
    )
    seq_lens = jnp.asarray([1, bt, 2 * bt - 3, ntbl * bt, 5], jnp.int32)
    got = _paged_decode_attention_pallas_batched(
        q, k_cache, v_cache, tables, seq_lens, interpret=True
    )
    for b in range(bsz):
        want = _numpy_oracle(
            q[b], k_cache, v_cache, tables[b], int(seq_lens[b])
        )
        np.testing.assert_allclose(
            np.asarray(got[b], np.float64), want, rtol=1e-5, atol=1e-5,
            err_msg=f"row {b}",
        )
    # The vmap'd XLA fallback agrees too (it is what non-TPU backends run).
    got_xla = paged_decode_attention_xla_batched(
        q, k_cache, v_cache, tables, seq_lens
    )
    np.testing.assert_allclose(
        np.asarray(got_xla, np.float64), np.asarray(got, np.float64),
        rtol=1e-5, atol=1e-5,
    )


def test_decode_step_batched_matches_sequential():
    """A wave of requests through decode_step_batched must produce the same
    logits and cache bytes as advancing each request alone with decode_step
    (disjoint block tables, shared cache)."""
    from infinistore_tpu.models import (
        LlamaConfig, decode_step, decode_step_batched, init_params, prefill,
    )

    cfg = LlamaConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=64,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    max_blocks, num_blocks = 3, 16
    rng = np.random.default_rng(4)
    # Three requests at different positions, disjoint block tables.
    tables = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]], np.int32)
    prompts = [rng.integers(0, cfg.vocab, size=16).tolist() for _ in range(3)]
    caches = cfg.kv_spec(num_blocks).make_caches()
    for p, tab in zip(prompts, tables):
        _, caches = prefill(
            params, jnp.asarray(p, jnp.int32), caches, jnp.asarray(tab[:2]), cfg
        )

    next_toks = jnp.asarray([5, 9, 13], jnp.int32)
    positions = jnp.asarray([16, 16, 16], jnp.int32)

    seq_caches = caches
    seq_logits = []
    for b in range(3):
        lg, seq_caches = decode_step(
            params, next_toks[b], positions[b], seq_caches,
            jnp.asarray(tables[b]), cfg, max_blocks,
        )
        seq_logits.append(lg)

    bat_logits, bat_caches = decode_step_batched(
        params, next_toks, positions, caches, jnp.asarray(tables), cfg, max_blocks
    )
    np.testing.assert_allclose(
        np.asarray(bat_logits), np.asarray(jnp.stack(seq_logits)),
        rtol=2e-5, atol=2e-5,
    )
    for layer in range(cfg.n_layers):
        for kind in (0, 1):
            np.testing.assert_allclose(
                np.asarray(bat_caches[layer][kind]),
                np.asarray(seq_caches[layer][kind]),
                rtol=2e-5, atol=2e-5,
            )


def test_zero_length_row_returns_zeros_both_backends():
    """A just-admitted request with no cached tokens (seq_len 0) must read
    as zeros — not 0/0 NaN (kernel) or a uniform garbage average (naive
    softmax fallback)."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_batched,
        paged_decode_attention_xla_batched,
    )

    n, bt, kvh, d, h = 8, 8, 2, 16, 4
    rng = np.random.default_rng(21)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((2, h, d)), jnp.float32)
    tables = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
    sls = jnp.asarray([0, 5], jnp.int32)
    for out in (
        _paged_decode_attention_pallas_batched(
            q, k_cache, v_cache, tables, sls, interpret=True
        ),
        paged_decode_attention_xla_batched(q, k_cache, v_cache, tables, sls),
    ):
        row0 = np.asarray(out[0], np.float64)
        assert np.array_equal(row0, np.zeros_like(row0))
        assert np.isfinite(np.asarray(out, np.float64)).all()
        # The non-empty row is real attention, not zeros.
        assert np.abs(np.asarray(out[1], np.float64)).max() > 0


def _ragged_meta(tables, seq_lens, bt, pad_to=0):
    from infinistore_tpu.tpu.paged_attention import build_ragged_wave

    m = build_ragged_wave(tables, seq_lens, bt, pad_to=pad_to)
    return (
        jnp.asarray(m.pages), jnp.asarray(m.page_rows),
        jnp.asarray(m.page_starts), jnp.asarray(m.seq_lens),
    )


@pytest.mark.parametrize("case", CASES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ragged_kernel_matches_oracle(case, dtype):
    """The ragged kernel (flat page list, interpret mode) against the numpy
    oracle across GQA shapes, dtypes, and wave sizes 1/3/8 with skewed
    seq_lens — including a seq_len=1 row next to a near-max one (the 8:1
    length-skew shape the rectangular layout padded B * max(K_i) for)."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_ragged,
        paged_decode_attention_ragged,
    )

    n, bt, kvh, d, h, ntbl = case
    rng = np.random.default_rng(hash(("ragged", case)) % 2**32)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), dtype)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), dtype)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    full = ntbl * bt
    waves = {
        1: [full],
        3: [1, full, full // 2 + 1],  # seq_len=1 beside a near-max row
        8: [1, full, 3, full - 1, bt, bt - 1, full // 2, 2],
    }
    for bsz, lens in waves.items():
        q = jnp.asarray(rng.standard_normal((bsz, h, d)), dtype)
        tables = [rng.permutation(n)[:ntbl] for _ in range(bsz)]
        meta = _ragged_meta(tables, lens, bt)
        got = _paged_decode_attention_pallas_ragged(
            q, k_cache, v_cache, *meta, interpret=True
        )
        for b in range(bsz):
            want = _numpy_oracle(q[b], k_cache, v_cache, tables[b], lens[b])
            np.testing.assert_allclose(
                np.asarray(got[b], np.float64), want, rtol=tol, atol=tol,
                err_msg=f"wave={bsz} row={b} len={lens[b]}",
            )
        # The public dispatcher (XLA fallback on this backend) agrees.
        got_disp = paged_decode_attention_ragged(
            q, k_cache, v_cache, *meta, table_width=ntbl
        )
        np.testing.assert_allclose(
            np.asarray(got_disp, np.float64), np.asarray(got, np.float64),
            rtol=tol, atol=tol,
        )


def test_ragged_single_request_degenerates_to_batched():
    """A single-request wave through the ragged kernel is BITWISE the
    rectangular kernel's output (same fold sequence, so today's B=1 decode
    path is a strict special case of the ragged one)."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_batched,
        _paged_decode_attention_pallas_ragged,
    )

    n, bt, kvh, d, h, ntbl = 16, 8, 2, 16, 4, 6
    rng = np.random.default_rng(41)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((1, h, d)), jnp.float32)
    table = rng.permutation(n)[:ntbl]
    for sl in (1, bt, ntbl * bt):
        meta = _ragged_meta([table], [sl], bt)
        rect = _paged_decode_attention_pallas_batched(
            q, k_cache, v_cache, jnp.asarray(table[None], jnp.int32),
            jnp.asarray([sl], jnp.int32), interpret=True,
        )
        rag = _paged_decode_attention_pallas_ragged(
            q, k_cache, v_cache, *meta, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(rect), np.asarray(rag))


def test_ragged_padding_pages_are_bitwise_noops():
    """Bucket-padding the flat page list (what the engine does to bound jit
    compiles) must not change one output bit: padded pages fold fully
    masked — alpha = 1, p = 0 (see _attn_block_fold)."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_ragged,
    )

    n, bt, kvh, d, h = 16, 8, 2, 16, 4
    rng = np.random.default_rng(43)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((3, h, d)), jnp.float32)
    tables = [rng.permutation(n)[:4] for _ in range(3)]
    lens = [9, 30, 17]
    exact = _paged_decode_attention_pallas_ragged(
        q, k_cache, v_cache, *_ragged_meta(tables, lens, bt), interpret=True
    )
    padded = _paged_decode_attention_pallas_ragged(
        q, k_cache, v_cache, *_ragged_meta(tables, lens, bt, pad_to=16),
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(exact), np.asarray(padded))


def test_ragged_zero_length_row_returns_zeros():
    """A zero-length row carries one fully-masked page and must read as
    zeros on both backends — same contract as the rectangular layout."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_ragged,
        paged_decode_attention_ragged,
    )

    n, bt, kvh, d, h = 8, 8, 2, 16, 4
    rng = np.random.default_rng(47)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((2, h, d)), jnp.float32)
    meta = _ragged_meta([[0, 1], [2, 3]], [0, 5], bt)
    for out in (
        _paged_decode_attention_pallas_ragged(
            q, k_cache, v_cache, *meta, interpret=True
        ),
        paged_decode_attention_ragged(
            q, k_cache, v_cache, *meta, table_width=2
        ),
    ):
        row0 = np.asarray(out[0], np.float64)
        assert np.array_equal(row0, np.zeros_like(row0))
        assert np.isfinite(np.asarray(out, np.float64)).all()
        assert np.abs(np.asarray(out[1], np.float64)).max() > 0


def test_ragged_stats_kernel_matches_xla_stats():
    """The ragged stats kernel (interpret mode) and the reconstructed-table
    XLA stats normalize identically — the combinability contract ragged
    sharded decode rides."""
    from infinistore_tpu.tpu.paged_attention import (
        _decode_attention_stats_xla,
        _paged_decode_attention_pallas_ragged_stats,
        _ragged_row_tables,
    )

    n, bt, kvh, d, h = 16, 8, 2, 16, 4
    rng = np.random.default_rng(53)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((3, h, d)), jnp.float32)
    tables = [rng.permutation(n)[:4] for _ in range(3)]
    lens = [1, 4 * bt, 0]
    pages, rows, starts, sls = _ragged_meta(tables, lens, bt)
    a1, m1, l1 = _paged_decode_attention_pallas_ragged_stats(
        q, k_cache, v_cache, pages, rows, starts, sls, interpret=True
    )
    rect = _ragged_row_tables(pages, starts, 4)
    a2, m2, l2 = _decode_attention_stats_xla(q, k_cache, v_cache, rect, sls)
    for b in range(3):
        if float(l2[b].max()) == 0.0:
            assert float(l1[b].max()) == 0.0
            assert float(jnp.abs(a1[b]).max()) == 0.0
        else:
            np.testing.assert_allclose(
                np.asarray(a1[b] / l1[b]), np.asarray(a2[b] / l2[b]),
                rtol=1e-5, atol=1e-5,
            )


def test_ragged_sharded_wave_matches_dense_oracle():
    """A ragged WAVE with contexts sharded over the 8-way 'sp' mesh: per-
    shard ragged stats combined with pmax/psum must equal dense attention
    over each row's concatenated context — including rows absent from some
    shards entirely (local_len 0)."""
    from jax.sharding import Mesh

    from infinistore_tpu.tpu.paged_attention import (
        build_ragged_wave_sharded,
        paged_decode_attention_ragged_sharded,
    )

    P_, nb_local, bt, kvh, d, h, R = 8, 4, 4, 2, 16, 4, 3
    rng = np.random.default_rng(59)
    k_cache = jnp.asarray(
        rng.standard_normal((P_ * nb_local, bt, kvh, d)), jnp.float32
    )
    v_cache = jnp.asarray(
        rng.standard_normal((P_ * nb_local, bt, kvh, d)), jnp.float32
    )
    q = jnp.asarray(rng.standard_normal((R, h, d)), jnp.float32)
    local_tables = [
        [rng.permutation(nb_local)[:3] for _ in range(R)] for _ in range(P_)
    ]
    local_lens = rng.integers(0, 3 * bt + 1, size=(P_, R)).astype(np.int32)
    local_lens[0, 0] = max(local_lens[0, 0], 1)
    local_lens[:, 2] = 0
    local_lens[4, 2] = 7  # row 2 lives on exactly one shard

    pages, rows, starts, lens, width = build_ragged_wave_sharded(
        local_tables, local_lens, bt
    )
    devices = jax.devices()
    assert len(devices) == 8
    mesh = Mesh(np.array(devices), ("sp",))
    got = paged_decode_attention_ragged_sharded(
        q, k_cache, v_cache, pages, rows, starts, lens,
        mesh=mesh, table_width=width,
    )
    groups = h // kvh
    for r in range(R):
        ks, vs = [], []
        for p in range(P_):
            rowsg = p * nb_local + np.asarray(local_tables[p][r])
            ks.append(
                np.asarray(k_cache)[rowsg].reshape(-1, kvh, d)[: local_lens[p][r]]
            )
            vs.append(
                np.asarray(v_cache)[rowsg].reshape(-1, kvh, d)[: local_lens[p][r]]
            )
        k_all = np.concatenate(ks)
        v_all = np.concatenate(vs)
        k_rep = np.repeat(k_all, groups, axis=1).astype(np.float64)
        v_rep = np.repeat(v_all, groups, axis=1).astype(np.float64)
        logits = np.einsum(
            "hd,thd->ht", np.asarray(q[r], np.float64), k_rep
        ) / np.sqrt(d)
        p_ = np.exp(logits - logits.max(axis=1, keepdims=True))
        p_ /= p_.sum(axis=1, keepdims=True)
        want = np.einsum("ht,thd->hd", p_, v_rep)
        np.testing.assert_allclose(
            np.asarray(got[r], np.float64), want, rtol=1e-5, atol=1e-5,
            err_msg=f"row {r}",
        )


def test_build_ragged_wave_validates():
    """The metadata builder rejects short tables, undersized pad_to, and
    empty waves; pads belong to the last row with the sentinel terminating
    the map."""
    from infinistore_tpu.tpu.paged_attention import build_ragged_wave

    with pytest.raises(ValueError):
        build_ragged_wave([], [], 8)
    with pytest.raises(ValueError):
        build_ragged_wave([[0]], [9], 8)  # needs 2 pages for len 9
    with pytest.raises(ValueError):
        build_ragged_wave([[0, 1], [2]], [16, 3], 8, pad_to=2)
    m = build_ragged_wave([[0, 1], [2]], [16, 3], 8, pad_to=8)
    assert m.num_pages == 8 and m.pad_pages == 5
    assert list(m.page_rows[:3]) == [0, 0, 1]
    assert all(r == 1 for r in m.page_rows[3:8])  # padding rides row 1
    assert m.page_rows[8] == 2  # sentinel
    assert list(m.page_starts) == [0, 2]


def test_sharded_decode_matches_dense_oracle():
    """Context sharded over an 8-way 'sp' mesh: shard-local online-softmax
    stats combined with pmax/psum must equal dense attention over the
    concatenated context — including an EMPTY shard (len 0) and ragged
    per-shard lengths."""
    from jax.sharding import Mesh

    from infinistore_tpu.tpu.paged_attention import paged_decode_attention_sharded

    P_, nb_local, bt, kvh, d, h, n_local = 8, 4, 4, 2, 16, 4, 3
    rng = np.random.default_rng(11)
    k_cache = jnp.asarray(
        rng.standard_normal((P_ * nb_local, bt, kvh, d)), jnp.float32
    )
    v_cache = jnp.asarray(
        rng.standard_normal((P_ * nb_local, bt, kvh, d)), jnp.float32
    )
    q = jnp.asarray(rng.standard_normal((h, d)), jnp.float32)
    local_tables = np.stack(
        [rng.permutation(nb_local)[:n_local] for _ in range(P_)]
    ).astype(np.int32)
    local_lens = np.array([5, 12, 0, 3, 8, 1, 12, 2], np.int32)  # ragged + empty

    devices = jax.devices()
    assert len(devices) == 8
    mesh = Mesh(np.array(devices), ("sp",))
    got = paged_decode_attention_sharded(
        q, k_cache, v_cache, local_tables, local_lens, mesh=mesh
    )

    # Oracle: concatenate every shard's valid tokens, dense softmax.
    ctx_k, ctx_v = [], []
    for p in range(P_):
        rows = p * nb_local + local_tables[p]
        k_toks = np.asarray(k_cache)[rows].reshape(-1, kvh, d)[: local_lens[p]]
        v_toks = np.asarray(v_cache)[rows].reshape(-1, kvh, d)[: local_lens[p]]
        ctx_k.append(k_toks)
        ctx_v.append(v_toks)
    k_all = np.concatenate(ctx_k)  # [T, KVH, D]
    v_all = np.concatenate(ctx_v)
    groups = h // kvh
    k_rep = np.repeat(k_all, groups, axis=1).astype(np.float64)
    v_rep = np.repeat(v_all, groups, axis=1).astype(np.float64)
    logits = np.einsum("hd,thd->ht", np.asarray(q, np.float64), k_rep) / np.sqrt(d)
    p_ = np.exp(logits - logits.max(axis=1, keepdims=True))
    p_ /= p_.sum(axis=1, keepdims=True)
    want = np.einsum("ht,thd->hd", p_, v_rep)
    np.testing.assert_allclose(np.asarray(got, np.float64), want, rtol=1e-5, atol=1e-5)


def test_sharded_stats_kernel_matches_xla_stats():
    """The Pallas stats kernel (interpret mode) and the XLA stats fallback
    must produce combinable (acc, m, l) that normalize to the same output."""
    from infinistore_tpu.tpu.paged_attention import (
        _decode_attention_stats_xla,
        _paged_decode_attention_pallas_stats,
    )

    n, bt, kvh, d, h, ntbl, bsz = 16, 8, 2, 16, 4, 4, 3
    rng = np.random.default_rng(13)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((bsz, h, d)), jnp.float32)
    tables = jnp.asarray(
        np.stack([rng.permutation(n)[:ntbl] for _ in range(bsz)]), jnp.int32
    )
    sls = jnp.asarray([1, ntbl * bt, 0], jnp.int32)  # incl. an empty row
    a1, m1, l1 = _paged_decode_attention_pallas_stats(
        q, k_cache, v_cache, tables, sls, interpret=True
    )
    a2, m2, l2 = _decode_attention_stats_xla(q, k_cache, v_cache, tables, sls)
    # Stats normalize identically for non-empty rows; the empty row has
    # l == 0 and acc == 0 in both (its combine weight is zero).
    for b in range(bsz):
        if float(l2[b].max()) == 0.0:
            assert float(l1[b].max()) == 0.0 and float(jnp.abs(a1[b]).max()) == 0.0
            assert float(jnp.abs(a2[b]).max()) == 0.0
        else:
            np.testing.assert_allclose(
                np.asarray(a1[b] / l1[b]), np.asarray(a2[b] / l2[b]),
                rtol=1e-5, atol=1e-5,
            )


def test_decode_step_uses_contract_matching_prefill():
    """decode_step routes attention through the dispatcher; on CPU that is
    the XLA fallback, and the f32-softmax contract keeps incremental decode
    equal to full prefill (the tight-tolerance invariant the model tests
    pin). This guards the dispatcher wiring specifically."""
    from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill

    cfg = LlamaConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=64,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    full = jax.random.randint(jax.random.PRNGKey(1), (24,), 0, cfg.vocab)
    table = jnp.asarray([3, 1, 6, 2], jnp.int32)
    caches = cfg.kv_spec(8).make_caches()
    ref_logits, _ = prefill(
        params, full, cfg.kv_spec(8).make_caches(), table[:3], cfg
    )
    logits, caches = prefill(params, full[:16], caches, table[:2], cfg)
    for pos in range(16, 24):
        logits, caches = decode_step(
            params, full[pos], jnp.int32(pos), caches, table, cfg, 4
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
