"""Fused paged decode attention: the Pallas kernel (interpret mode on CPU)
against the XLA fallback and a from-scratch numpy oracle, across GQA shapes,
partial blocks, and padded tables. The reference has no engine-side compute
at all (SURVEY.md §2.9) — this kernel is the TPU build's consumer-side hot
op (models/llama.py decode_step attends through it)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from infinistore_tpu.tpu.paged_attention import (
    _paged_decode_attention_pallas,
    paged_decode_attention_xla,
)


def _numpy_oracle(q, k_cache, v_cache, table, seq_len):
    """Dense decode attention in float64 numpy: gather, mask, softmax."""
    q = np.asarray(q, np.float64)
    h, d = q.shape
    kvh = k_cache.shape[2]
    groups = h // kvh
    k = np.asarray(k_cache, np.float64)[np.asarray(table)].reshape(-1, kvh, d)
    v = np.asarray(v_cache, np.float64)[np.asarray(table)].reshape(-1, kvh, d)
    k = np.repeat(k, groups, axis=1)
    v = np.repeat(v, groups, axis=1)
    logits = np.einsum("hd,thd->ht", q, k) / np.sqrt(d)
    logits[:, seq_len:] = -np.inf
    p = np.exp(logits - logits.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    return np.einsum("ht,thd->hd", p, v)


CASES = [
    # (num_blocks, block_tokens, kv_heads, head_dim, q_heads, table_len)
    (16, 8, 4, 16, 8, 8),  # GQA x2
    (32, 16, 2, 32, 8, 16),  # GQA x4
    (8, 8, 8, 16, 8, 4),  # MHA (no GQA)
    (16, 8, 1, 64, 4, 16),  # MQA (one kv head)
]


@pytest.mark.parametrize("case", CASES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_matches_oracle(case, dtype):
    n, bt, kvh, d, h, ntbl = case
    rng = np.random.default_rng(hash(case) % 2**32)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), dtype)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), dtype)
    q = jnp.asarray(rng.standard_normal((h, d)), dtype)
    table = jnp.asarray(rng.permutation(n)[:ntbl], jnp.int32)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    # seq lens: single token, partial block, block boundary, mid-table, full.
    for sl in (1, bt - 1, bt, ntbl * bt // 2 + 3, ntbl * bt):
        want = _numpy_oracle(q, k_cache, v_cache, table, sl)
        got = _paged_decode_attention_pallas(
            q, k_cache, v_cache, table, sl, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float64), want, rtol=tol, atol=tol,
            err_msg=f"sl={sl}",
        )
        got_xla = paged_decode_attention_xla(q, k_cache, v_cache, table, sl)
        np.testing.assert_allclose(
            np.asarray(got_xla, np.float64), want, rtol=tol, atol=tol
        )


def test_padded_table_entries_are_ignored():
    """Entries past seq_len may alias ANY valid block (engines pad with 0);
    their contents must not leak into the output."""
    n, bt, kvh, d, h = 8, 8, 2, 16, 4
    rng = np.random.default_rng(7)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((h, d)), jnp.float32)
    sl = bt + 3  # two blocks in play, second partial
    base = jnp.asarray([2, 5, 0, 0], jnp.int32)
    alias = jnp.asarray([2, 5, 7, 1], jnp.int32)  # different garbage tail
    out_base = _paged_decode_attention_pallas(
        q, k_cache, v_cache, base, sl, interpret=True
    )
    out_alias = _paged_decode_attention_pallas(
        q, k_cache, v_cache, alias, sl, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out_base), np.asarray(out_alias))


def test_batched_kernel_matches_oracle_ragged_seq_lens():
    """One launch, many requests: each grid row must reset its accumulators
    and mask by ITS seq_len — a carry-over from the previous request would
    poison every row after the first."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_batched,
        paged_decode_attention_xla_batched,
    )

    n, bt, kvh, d, h, ntbl, bsz = 32, 8, 2, 16, 4, 6, 5
    rng = np.random.default_rng(3)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((bsz, h, d)), jnp.float32)
    tables = jnp.asarray(
        np.stack([rng.permutation(n)[:ntbl] for _ in range(bsz)]), jnp.int32
    )
    seq_lens = jnp.asarray([1, bt, 2 * bt - 3, ntbl * bt, 5], jnp.int32)
    got = _paged_decode_attention_pallas_batched(
        q, k_cache, v_cache, tables, seq_lens, interpret=True
    )
    for b in range(bsz):
        want = _numpy_oracle(
            q[b], k_cache, v_cache, tables[b], int(seq_lens[b])
        )
        np.testing.assert_allclose(
            np.asarray(got[b], np.float64), want, rtol=1e-5, atol=1e-5,
            err_msg=f"row {b}",
        )
    # The vmap'd XLA fallback agrees too (it is what non-TPU backends run).
    got_xla = paged_decode_attention_xla_batched(
        q, k_cache, v_cache, tables, seq_lens
    )
    np.testing.assert_allclose(
        np.asarray(got_xla, np.float64), np.asarray(got, np.float64),
        rtol=1e-5, atol=1e-5,
    )


def test_decode_step_batched_matches_sequential():
    """A wave of requests through decode_step_batched must produce the same
    logits and cache bytes as advancing each request alone with decode_step
    (disjoint block tables, shared cache)."""
    from infinistore_tpu.models import (
        LlamaConfig, decode_step, decode_step_batched, init_params, prefill,
    )

    cfg = LlamaConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=64,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    max_blocks, num_blocks = 3, 16
    rng = np.random.default_rng(4)
    # Three requests at different positions, disjoint block tables.
    tables = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]], np.int32)
    prompts = [rng.integers(0, cfg.vocab, size=16).tolist() for _ in range(3)]
    caches = cfg.kv_spec(num_blocks).make_caches()
    for p, tab in zip(prompts, tables):
        _, caches = prefill(
            params, jnp.asarray(p, jnp.int32), caches, jnp.asarray(tab[:2]), cfg
        )

    next_toks = jnp.asarray([5, 9, 13], jnp.int32)
    positions = jnp.asarray([16, 16, 16], jnp.int32)

    seq_caches = caches
    seq_logits = []
    for b in range(3):
        lg, seq_caches = decode_step(
            params, next_toks[b], positions[b], seq_caches,
            jnp.asarray(tables[b]), cfg, max_blocks,
        )
        seq_logits.append(lg)

    bat_logits, bat_caches = decode_step_batched(
        params, next_toks, positions, caches, jnp.asarray(tables), cfg, max_blocks
    )
    np.testing.assert_allclose(
        np.asarray(bat_logits), np.asarray(jnp.stack(seq_logits)),
        rtol=2e-5, atol=2e-5,
    )
    for layer in range(cfg.n_layers):
        for kind in (0, 1):
            np.testing.assert_allclose(
                np.asarray(bat_caches[layer][kind]),
                np.asarray(seq_caches[layer][kind]),
                rtol=2e-5, atol=2e-5,
            )


def test_zero_length_row_returns_zeros_both_backends():
    """A just-admitted request with no cached tokens (seq_len 0) must read
    as zeros — not 0/0 NaN (kernel) or a uniform garbage average (naive
    softmax fallback)."""
    from infinistore_tpu.tpu.paged_attention import (
        _paged_decode_attention_pallas_batched,
        paged_decode_attention_xla_batched,
    )

    n, bt, kvh, d, h = 8, 8, 2, 16, 4
    rng = np.random.default_rng(21)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((2, h, d)), jnp.float32)
    tables = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
    sls = jnp.asarray([0, 5], jnp.int32)
    for out in (
        _paged_decode_attention_pallas_batched(
            q, k_cache, v_cache, tables, sls, interpret=True
        ),
        paged_decode_attention_xla_batched(q, k_cache, v_cache, tables, sls),
    ):
        row0 = np.asarray(out[0], np.float64)
        assert np.array_equal(row0, np.zeros_like(row0))
        assert np.isfinite(np.asarray(out, np.float64)).all()
        # The non-empty row is real attention, not zeros.
        assert np.abs(np.asarray(out[1], np.float64)).max() > 0


def test_sharded_decode_matches_dense_oracle():
    """Context sharded over an 8-way 'sp' mesh: shard-local online-softmax
    stats combined with pmax/psum must equal dense attention over the
    concatenated context — including an EMPTY shard (len 0) and ragged
    per-shard lengths."""
    from jax.sharding import Mesh

    from infinistore_tpu.tpu.paged_attention import paged_decode_attention_sharded

    P_, nb_local, bt, kvh, d, h, n_local = 8, 4, 4, 2, 16, 4, 3
    rng = np.random.default_rng(11)
    k_cache = jnp.asarray(
        rng.standard_normal((P_ * nb_local, bt, kvh, d)), jnp.float32
    )
    v_cache = jnp.asarray(
        rng.standard_normal((P_ * nb_local, bt, kvh, d)), jnp.float32
    )
    q = jnp.asarray(rng.standard_normal((h, d)), jnp.float32)
    local_tables = np.stack(
        [rng.permutation(nb_local)[:n_local] for _ in range(P_)]
    ).astype(np.int32)
    local_lens = np.array([5, 12, 0, 3, 8, 1, 12, 2], np.int32)  # ragged + empty

    devices = jax.devices()
    assert len(devices) == 8
    mesh = Mesh(np.array(devices), ("sp",))
    got = paged_decode_attention_sharded(
        q, k_cache, v_cache, local_tables, local_lens, mesh=mesh
    )

    # Oracle: concatenate every shard's valid tokens, dense softmax.
    ctx_k, ctx_v = [], []
    for p in range(P_):
        rows = p * nb_local + local_tables[p]
        k_toks = np.asarray(k_cache)[rows].reshape(-1, kvh, d)[: local_lens[p]]
        v_toks = np.asarray(v_cache)[rows].reshape(-1, kvh, d)[: local_lens[p]]
        ctx_k.append(k_toks)
        ctx_v.append(v_toks)
    k_all = np.concatenate(ctx_k)  # [T, KVH, D]
    v_all = np.concatenate(ctx_v)
    groups = h // kvh
    k_rep = np.repeat(k_all, groups, axis=1).astype(np.float64)
    v_rep = np.repeat(v_all, groups, axis=1).astype(np.float64)
    logits = np.einsum("hd,thd->ht", np.asarray(q, np.float64), k_rep) / np.sqrt(d)
    p_ = np.exp(logits - logits.max(axis=1, keepdims=True))
    p_ /= p_.sum(axis=1, keepdims=True)
    want = np.einsum("ht,thd->hd", p_, v_rep)
    np.testing.assert_allclose(np.asarray(got, np.float64), want, rtol=1e-5, atol=1e-5)


def test_sharded_stats_kernel_matches_xla_stats():
    """The Pallas stats kernel (interpret mode) and the XLA stats fallback
    must produce combinable (acc, m, l) that normalize to the same output."""
    from infinistore_tpu.tpu.paged_attention import (
        _decode_attention_stats_xla,
        _paged_decode_attention_pallas_stats,
    )

    n, bt, kvh, d, h, ntbl, bsz = 16, 8, 2, 16, 4, 4, 3
    rng = np.random.default_rng(13)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((bsz, h, d)), jnp.float32)
    tables = jnp.asarray(
        np.stack([rng.permutation(n)[:ntbl] for _ in range(bsz)]), jnp.int32
    )
    sls = jnp.asarray([1, ntbl * bt, 0], jnp.int32)  # incl. an empty row
    a1, m1, l1 = _paged_decode_attention_pallas_stats(
        q, k_cache, v_cache, tables, sls, interpret=True
    )
    a2, m2, l2 = _decode_attention_stats_xla(q, k_cache, v_cache, tables, sls)
    # Stats normalize identically for non-empty rows; the empty row has
    # l == 0 and acc == 0 in both (its combine weight is zero).
    for b in range(bsz):
        if float(l2[b].max()) == 0.0:
            assert float(l1[b].max()) == 0.0 and float(jnp.abs(a1[b]).max()) == 0.0
            assert float(jnp.abs(a2[b]).max()) == 0.0
        else:
            np.testing.assert_allclose(
                np.asarray(a1[b] / l1[b]), np.asarray(a2[b] / l2[b]),
                rtol=1e-5, atol=1e-5,
            )


def test_decode_step_uses_contract_matching_prefill():
    """decode_step routes attention through the dispatcher; on CPU that is
    the XLA fallback, and the f32-softmax contract keeps incremental decode
    equal to full prefill (the tight-tolerance invariant the model tests
    pin). This guards the dispatcher wiring specifically."""
    from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill

    cfg = LlamaConfig(
        vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=64,
        block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    full = jax.random.randint(jax.random.PRNGKey(1), (24,), 0, cfg.vocab)
    table = jnp.asarray([3, 1, 6, 2], jnp.int32)
    caches = cfg.kv_spec(8).make_caches()
    ref_logits, _ = prefill(
        params, full, cfg.kv_spec(8).make_caches(), table[:3], cfg
    )
    logits, caches = prefill(params, full[:16], caches, table[:2], cfg)
    for pos in range(16, 24):
        logits, caches = decode_step(
            params, full[pos], jnp.int32(pos), caches, table, cfg, 4
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
