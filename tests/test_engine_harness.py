"""Engine-shaped connector proof: the continuous-batching harness drives the
KVConnector the way a vLLM-TPU-style engine does — N interleaved requests
with overlapping prefixes against the demo Llama, block tables owned by the
engine, evictions racing admissions — and every request's cache blocks are
verified against the model's own prefill oracle (BASELINE.md config 4 in
spirit; the reference's LMCache integration contract, reference README.md:22,
docs/source/design.rst:33-37)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu.connector import KVConnector
from infinistore_tpu.engine import (
    BlockPool,
    ContinuousBatchingHarness,
    DeviceGate,
    EngineKVAdapter,
)
from infinistore_tpu.models import LlamaConfig, init_params

CFG = LlamaConfig(
    vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
    block_tokens=8, dtype=jnp.float32,  # float32: oracle comparisons
)
NUM_BLOCKS = 32  # engine-side physical blocks
MAX_REQ_BLOCKS = 4


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _prompts(n, shared_blocks, total_blocks, seed=0):
    """n prompts sharing the first shared_blocks blocks, diverging after."""
    bt = CFG.block_tokens
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, CFG.vocab, size=shared_blocks * bt).tolist()
    out = []
    for i in range(n):
        tail = rng.integers(
            0, CFG.vocab, size=(total_blocks - shared_blocks) * bt
        ).tolist()
        out.append(shared + tail)
    return out


def _harness(conn, params, model_id, verify=True):
    spec = CFG.kv_spec(NUM_BLOCKS)
    kvc = KVConnector(conn, spec, model_id, max_blocks=MAX_REQ_BLOCKS)
    return ContinuousBatchingHarness(
        EngineKVAdapter(kvc), params, CFG, NUM_BLOCKS, MAX_REQ_BLOCKS,
        verify=verify,
    )


@pytest.fixture()
def server():
    srv = its.start_local_server(
        prealloc_bytes=64 << 20, block_bytes=64 << 10, enable_shm=True
    )
    yield srv
    srv.stop()


@pytest.fixture()
def conn(server):
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=server.port, log_level="error"
        )
    )
    c.connect()
    yield c
    c.close()


def test_concurrent_requests_share_prefix(conn, params):
    """8 requests, 4 in flight, sharing a 2-block prefix: the first to save
    seeds the store, later admissions hit. All verified vs the oracle."""
    h = _harness(conn, params, "engine-a")
    prompts = _prompts(8, shared_blocks=2, total_blocks=4)
    m = asyncio.run(h.run(prompts, concurrency=4))
    assert m["requests"] == 8
    assert m["max_live_requests"] >= 2, "harness never had 2 requests in flight"
    assert m["all_verified"], "a request's cache blocks diverged from the oracle"
    # The shared prefix must have produced real hits (the first request can't
    # hit; at least some of the other 7 must).
    assert m["loaded_blocks"] > 0
    assert m["hit_rate"] > 0
    # Store I/O overlapped: two saves were in flight at once at some point.
    assert m["max_concurrent_saves"] >= 2
    assert m["recompute_saved_s"] > 0


def test_repeat_prompt_full_hit(conn, params):
    """The same prompt twice: the second admission loads every block and
    computes none."""
    h = _harness(conn, params, "engine-b")
    p = _prompts(1, 1, 4)[0]
    s1 = asyncio.run(h.run_request(p))
    s2 = asyncio.run(h.run_request(p))
    assert s1.loaded_blocks == 0 and s1.computed_blocks == 4
    assert s2.loaded_blocks == 4 and s2.computed_blocks == 0
    assert s2.verified


def test_eviction_churn_correctness(params):
    """A store pool far smaller than the workload: evictions race admissions
    continuously. Every request must still verify — a raced load yields
    recompute, never stale bytes. (Cache semantics: the reference's design
    position, SURVEY.md §5.3.)"""
    spec = CFG.kv_spec(NUM_BLOCKS)
    # Each request saves 4 blocks x 2 layers x K+V = 16 store values of
    # block_nbytes; pool of 24 such blocks holds ~1.5 requests.
    srv = its.start_local_server(
        prealloc_bytes=24 * spec.block_nbytes,
        block_bytes=spec.block_nbytes,
        enable_shm=True,
        evict_min=0.5,
        evict_max=0.8,
    )
    c = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error"
        )
    )
    c.connect()
    try:
        h = _harness(c, params, "engine-churn")
        # 12 requests over 3 distinct prompt families -> repeats would hit if
        # not evicted; the small pool guarantees heavy eviction in between.
        fams = _prompts(3, 1, 4, seed=7)
        prompts = [fams[i % 3] for i in range(12)]
        m = asyncio.run(h.run(prompts, concurrency=3))
        assert m["requests"] == 12
        assert m["all_verified"], "eviction churn delivered wrong bytes"
        # The workload must actually have churned: the store saw far more
        # saves than it can hold, so SOME admissions missed or raced.
        assert m["computed_blocks"] > 0
    finally:
        c.close()
        srv.stop()


def test_resume_is_chunked_and_generation_waves_batch(conn, params):
    """Prefix-hit resumes compute their suffix as ONE chunked continuation
    (no per-token decode), while GENERATION rides the shared WaveDecoder:
    with several requests generating concurrently, at least one wave must
    carry >= 2 requests, lockstep must merge steps, and everything still
    verifies against the oracle."""

    async def drive():
        h = _harness(conn, params, "engine-waves")
        # Seed one 2-block family so later admissions hit 2 and resume.
        fams = _prompts(4, shared_blocks=2, total_blocks=3, seed=13)
        await h.run_request(fams[0])
        h.stats.clear()
        m = await h.run(fams[1:], concurrency=3, gen_tokens=8)
        return m

    m = asyncio.run(drive())
    assert m["all_verified"]
    assert m["loaded_blocks"] >= 3 * 2  # each resumed the seeded prefix
    assert m["generated_tokens"] == 3 * 8
    assert m["decode_waves"] > 0
    assert m["max_wave_size"] >= 2, (
        "concurrent generations never coalesced into one batched wave"
    )
    # Lockstep actually reduced step count: 3 requests x 8 tokens would be
    # 24 sequential steps; waves must have merged a chunk of them.
    assert m["decode_waves"] < 24


def test_generation_is_deterministic_under_wave_interleaving(conn, params):
    """Greedy generation depends only on a request's own cache blocks, so
    concurrent lockstep waves must produce token-for-token the same output
    as running each prompt alone."""

    async def concurrent():
        h = _harness(conn, params, "engine-det", verify=False)
        prompts = _prompts(3, shared_blocks=1, total_blocks=3, seed=17)
        sem = asyncio.Semaphore(3)

        async def one(p):
            async with sem:
                return await h.run_request(p, gen_tokens=8)

        # Keep the PROMPT -> OUTPUT pairing: set-compare would miss waves
        # handing one request another's continuation.
        stats = await asyncio.gather(*(one(p) for p in prompts))
        return prompts, [tuple(s.generated) for s in stats]

    prompts, together = asyncio.run(concurrent())

    async def solo():
        h = _harness(conn, params, "engine-det", verify=False)
        out = []
        for p in prompts:
            s = await h.run_request(p, gen_tokens=8)
            out.append(tuple(s.generated))
        return out

    alone = asyncio.run(solo())
    assert together == alone


def test_multi_turn_conversation_hits_generated_blocks(conn, params):
    """Turn 2's prompt = turn 1's prompt + its generated response: the
    response blocks were saved under the extended chain, so the follow-up
    admission is a FULL prefix hit — the conversation's KV never recomputes
    across turns."""

    async def drive():
        h = _harness(conn, params, "engine-turns")
        bt = CFG.block_tokens
        turn1 = _prompts(1, 1, 2, seed=23)[0]  # 2 complete blocks
        s1 = await h.run_request(turn1, gen_tokens=bt)  # fills 1 more block
        assert len(s1.generated) == bt
        turn2 = turn1 + s1.generated  # the conversation so far, 3 blocks
        s2 = await h.run_request(turn2)
        return s1, s2

    s1, s2 = asyncio.run(drive())
    assert s2.hit_blocks == 3, "generated block should extend the cached chain"
    assert s2.loaded_blocks == 3 and s2.computed_blocks == 0
    assert s2.verified


def test_wave_sizes_bucket_to_powers_of_two(conn, params, monkeypatch):
    """Varied wave shapes must reach the jitted ragged step only at
    power-of-two PADDED (B, T, P) buckets — table rows, flat token rows,
    flat attention pages (jit keys its cache on shape, so distinct shapes
    == compiles): a run whose natural wave sizes wander over 1..5 buckets
    to the power-of-two ladder, and the tail padding rows must not perturb
    any request's output (all verified)."""
    import infinistore_tpu.engine as engine_mod

    shapes_seen = set()
    real = engine_mod.verify_step_ragged

    def recording(params_, tokens, positions, row_of, pages, *a, **kw):
        shapes_seen.add(
            (int(a[3].shape[0]), int(tokens.shape[0]), int(pages.shape[0]))
        )
        return real(params_, tokens, positions, row_of, pages, *a, **kw)

    monkeypatch.setattr(engine_mod, "verify_step_ragged", recording)

    async def drive():
        h = _harness(conn, params, "engine-buckets")
        # 5 requests, staggered admission via concurrency 5 but different
        # prompt lengths -> wave sizes vary as requests finish prefill at
        # different times and drain at different steps.
        prompts = _prompts(5, shared_blocks=1, total_blocks=2, seed=29)
        return await h.run(prompts, concurrency=5, gen_tokens=6)

    m = asyncio.run(drive())
    assert m["all_verified"], "padding rows corrupted a request's blocks"
    assert m["generated_tokens"] == 5 * 6
    assert shapes_seen, "no waves decoded"
    for b, t, p in shapes_seen:
        assert b & (b - 1) == 0, f"non-power-of-two table-row bucket {b}"
        assert t & (t - 1) == 0, f"non-power-of-two flat-row bucket {t}"
        assert p & (p - 1) == 0, f"non-power-of-two page bucket {p}"
    # Compile count is bounded by the bucket ladder, not by how many
    # distinct natural sizes occurred. The (B, T, P) ladder is wider than
    # the old (B, K) one (P steps through pow2s as contexts lengthen), but
    # it must stay a LADDER — a change that buckets exactly instead of to
    # powers of two would proliferate shapes (= whole-model recompiles)
    # far past this cap.
    assert shapes_seen == set(m["wave_buckets"])
    assert len(shapes_seen) <= 8, sorted(shapes_seen)
    # Pure-decode waves: every chunk is one token, so ragged assembly pads
    # at most T_bucket - B rows per wave — strictly no more than the old
    # rectangle's (B_bucket - B) duplicated rows at K = 1.
    assert 0.0 <= m["wave_pad_fraction"] < 0.5, m["wave_pad_fraction"]


def test_ngram_drafter_proposes_recurring_continuations():
    """Prompt-lookup drafting: the continuation after the most recent
    earlier occurrence of the suffix n-gram, longest n first; empty when
    nothing recurs."""
    from infinistore_tpu.engine import NGramDrafter

    d = NGramDrafter(max_draft=3, ngram=2)
    # suffix (7, 8) occurred earlier, followed by 9, 10, 11.
    assert d.draft([7, 8, 9, 10, 11, 5, 7, 8]) == [9, 10, 11]
    # Only a 1-gram recurs.
    assert d.draft([4, 9, 1, 2, 9]) == [1, 2, 9]
    # Nothing recurs.
    assert d.draft([1, 2, 3, 4]) == []
    # Most RECENT earlier occurrence wins (8 -> 6, not 8 -> 2).
    assert d.draft([8, 2, 5, 8, 6, 8]) == [6, 8]
    # max_draft caps the proposal.
    assert NGramDrafter(max_draft=1, ngram=2).draft([7, 8, 9, 7, 8]) == [9]


def test_speculative_generation_matches_greedy_exactly(conn, params):
    """Greedy acceptance makes speculative output token-for-token IDENTICAL
    to plain decode — on a repetitive prompt the drafter must also actually
    accept tokens (tokens/step > 1), or speculation is dead weight."""
    from infinistore_tpu.engine import NGramDrafter

    bt = CFG.block_tokens
    # Period-3 repetition: the 2-gram suffix always recurs and the model-
    # agnostic draft is often wrong (the model decides) — exercising both
    # accept and reject paths.
    prompts = [
        ([11, 12, 13] * (2 * bt))[: 2 * bt],
        ([3, 7] * bt)[: 2 * bt],
        ([9, 9, 4, 2] * bt)[: 2 * bt],
    ]

    async def run_with(drafter):
        h = _harness(conn, params, "engine-spec", verify=False)
        h.drafter = drafter
        stats = []
        for p in prompts:  # sequential: identical per-request wave makeup
            stats.append(await h.run_request(p, gen_tokens=2 * bt))
        return h, [tuple(s.generated) for s in stats]

    h_plain, plain = asyncio.run(run_with(None))
    h_spec, spec = asyncio.run(run_with(NGramDrafter(max_draft=4)))
    assert spec == plain, "speculation changed greedy output"
    m = h_spec.metrics()
    assert m["spec_drafted_tokens"] > 0, "drafter never proposed on a repetitive prompt"
    assert m["spec_tokens_per_step"] > 1.0, (
        f"speculation accepted nothing: {m['spec_tokens_per_step']}"
    )
    assert h_spec.spec_rounds < h_plain.spec_rounds, (
        "speculation did not reduce model rounds"
    )


def test_mixed_spec_and_decode_requests_share_waves(conn, params):
    """A drafting request and a plain-decode request coalesce into the SAME
    wave (chunks of different lengths CONCATENATE into one ragged launch —
    the decode rows no longer pad to the draft chunk's width) and both
    verify against the oracle."""
    from infinistore_tpu.engine import NGramDrafter

    bt = CFG.block_tokens

    async def drive():
        h = _harness(conn, params, "engine-mixed")
        h.drafter = NGramDrafter(max_draft=4)
        rng = np.random.default_rng(31)
        # One highly repetitive prompt (drafts fire) + ones with no
        # repetition (drafter proposes nothing -> 1-token chunks).
        p_rep = ([21, 22] * bt)[: 2 * bt]
        p_rand = [rng.integers(0, CFG.vocab, size=2 * bt).tolist() for _ in range(2)]
        return await h.run([p_rep] + p_rand, concurrency=3, gen_tokens=bt)

    m = asyncio.run(drive())
    assert m["all_verified"]
    assert m["generated_tokens"] == 3 * CFG.block_tokens
    assert m["max_wave_size"] >= 2, "requests never shared a wave"
    # At least one wave carried a chunk wider than 1 (the drafting row):
    # its flat-row bucket exceeds its table-row bucket.
    assert any(t > b for b, t, _ in m["wave_buckets"]), m["wave_buckets"]


def test_ragged_wave_byte_identical_to_sequential_decode(params):
    """THE ragged-assembly determinism pin: a MIXED wave (two 1-token
    decode rows beside a 3-token verification chunk, concatenated ragged —
    no row duplication) must produce logits AND cache bytes IDENTICAL to
    advancing each request alone, one wave of one request at a time. This
    is the guarantee that lets the scheduler coalesce whatever happens to
    be ready without ever changing a request's output."""
    from infinistore_tpu.engine import ContinuousBatchingHarness, WaveDecoder
    from infinistore_tpu.models import prefill

    rng = np.random.default_rng(61)
    tables = np.array(
        [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9, 10, 11]], np.int32
    )
    prompts = [
        rng.integers(0, CFG.vocab, size=16).tolist() for _ in range(3)
    ]
    base = CFG.kv_spec(NUM_BLOCKS).make_caches()
    for p, tab in zip(prompts, tables):
        _, base = prefill(
            params, jnp.asarray(p, jnp.int32), base, jnp.asarray(tab[:2]), CFG
        )

    def mk():
        h = ContinuousBatchingHarness.__new__(ContinuousBatchingHarness)
        h.params = params
        h.config = CFG
        h.caches = base
        h.max_req_blocks = MAX_REQ_BLOCKS
        h.gate = DeviceGate()
        return h

    # Request 1 verifies a 3-token chunk; 0 and 2 decode one token each.
    chunks = [([5], [16]), ([9, 11, 12], [16, 17, 18]), ([13], [16])]

    async def wave_run():
        h = mk()
        wave = WaveDecoder(h)
        outs = await asyncio.gather(*(
            wave.step_chunk(toks, pos, jnp.asarray(tables[b]))
            for b, (toks, pos) in enumerate(chunks)
        ))
        return [np.asarray(o) for o in outs], h.caches, wave

    async def seq_run():
        h = mk()
        outs = []
        for b, (toks, pos) in enumerate(chunks):
            wave = WaveDecoder(h)  # fresh decoder: every wave is solo
            outs.append(
                np.asarray(
                    await wave.step_chunk(toks, pos, jnp.asarray(tables[b]))
                )
            )
        return outs, h.caches

    wave_outs, wave_caches, wave = asyncio.run(wave_run())
    seq_outs, seq_caches = asyncio.run(seq_run())
    assert wave.max_wave == 3, "requests did not coalesce into one wave"
    for b in range(3):
        np.testing.assert_array_equal(
            wave_outs[b], seq_outs[b],
            err_msg=f"request {b} logits diverged in the mixed wave",
        )
    for layer in range(CFG.n_layers):
        for kind in (0, 1):
            np.testing.assert_array_equal(
                np.asarray(wave_caches[layer][kind]),
                np.asarray(seq_caches[layer][kind]),
                err_msg=f"cache bytes diverged (layer {layer})",
            )
    # Ragged pad accounting: 5 real flat rows bucket to 8 (3 pad rows) —
    # the rectangle would have launched 4 requests x 4-token chunks = 16.
    assert (wave.launched_rows, wave.pad_rows) == (8, 3)


def test_wave_decoder_failure_fails_all_waiters(params):
    """A flush that dies (model error) must fail every waiter — taken batch
    AND still-pending — and leave the decoder usable for the next wave, not
    wedge decode forever."""
    from infinistore_tpu.engine import ContinuousBatchingHarness, WaveDecoder

    class _Boom(Exception):
        pass

    h = ContinuousBatchingHarness.__new__(ContinuousBatchingHarness)
    h.params = params
    h.config = CFG
    h.caches = CFG.kv_spec(NUM_BLOCKS).make_caches()
    h.max_req_blocks = MAX_REQ_BLOCKS
    h.gate = DeviceGate()
    wave = WaveDecoder(h)

    async def run():
        bad = np.zeros(MAX_REQ_BLOCKS, np.int32)
        # Poison one step: a wrong-shaped table makes decode_step_batched
        # raise for the whole wave.
        t1 = asyncio.ensure_future(wave.step(1, 8, jnp.asarray(bad)))
        t2 = asyncio.ensure_future(wave.step(2, 8, jnp.asarray(bad[:2])))
        r1, r2 = await asyncio.gather(t1, t2, return_exceptions=True)
        assert isinstance(r1, Exception) and isinstance(r2, Exception)
        # The decoder recovered: a good wave still decodes.
        good = np.arange(MAX_REQ_BLOCKS, dtype=np.int32)
        logits = await wave.step(3, 8, jnp.asarray(good))
        assert np.isfinite(np.asarray(logits, np.float32)).all()
        assert wave.waves >= 1

    asyncio.run(run())


def test_block_pool_backpressure():
    """alloc() waits for free blocks instead of failing (scheduler-style
    admission deferral)."""

    async def run():
        pool = BlockPool(4)
        a = await pool.alloc(3)
        waiter = asyncio.ensure_future(pool.alloc(2))
        await asyncio.sleep(0.01)
        assert not waiter.done(), "alloc should have backpressured"
        await pool.free(a)
        got = await asyncio.wait_for(waiter, 1)
        assert len(got) == 2

    asyncio.run(run())


def test_device_gate_excludes_mutators():
    """Shared holders overlap; an exclusive phase waits for them and blocks
    new ones (the cache-consistency discipline the harness relies on)."""

    async def run():
        gate = DeviceGate()
        order = []

        async def reader(name, hold):
            async with gate.shared():
                order.append(f"{name}+")
                await asyncio.sleep(hold)
                order.append(f"{name}-")

        async def writer():
            async with gate.exclusive():
                order.append("w+")
                order.append("w-")

        r1 = asyncio.ensure_future(reader("a", 0.02))
        r2 = asyncio.ensure_future(reader("b", 0.02))
        await asyncio.sleep(0.005)
        w = asyncio.ensure_future(writer())
        await asyncio.sleep(0.005)
        # Writer priority: a reader arriving while the writer WAITS must
        # queue behind it, or a steady reader stream starves every mutator.
        r3 = asyncio.ensure_future(reader("c", 0.0))
        await asyncio.gather(r1, r2, w, r3)
        # Both early readers overlapped (a+ b+ before a- b-), writer after
        # them, late reader after the writer.
        assert order.index("b+") < order.index("a-")
        assert order.index("w+") > order.index("a-")
        assert order.index("w+") > order.index("b-")
        assert order.index("c+") > order.index("w-")

    asyncio.run(run())


def test_device_gate_expedite_jumps_queued_writers():
    """An expedited exclusive (a prefix INSTALL — short, device-transfer
    bound) queued behind a normal exclusive (a prefill) must acquire first
    when the gate frees: installs arrive late by construction (their fetch
    runs gate-free first), so FIFO would park every cache hit behind a
    convoy of misses' prefills."""

    async def run():
        gate = DeviceGate()
        order = []

        async def holder():
            async with gate.exclusive():
                order.append("hold")
                await asyncio.sleep(0.03)

        async def normal():
            async with gate.exclusive():
                order.append("prefill")

        async def install():
            async with gate.exclusive(expedite=True):
                order.append("install")

        h = asyncio.ensure_future(holder())
        await asyncio.sleep(0.005)
        n1 = asyncio.ensure_future(normal())
        n2 = asyncio.ensure_future(normal())
        await asyncio.sleep(0.005)
        i1 = asyncio.ensure_future(install())  # arrives LAST...
        await asyncio.gather(h, n1, n2, i1)
        assert order[0] == "hold"
        assert order[1] == "install", order  # ...acquires first
        assert sorted(order[2:]) == ["prefill", "prefill"]

    asyncio.run(asyncio.wait_for(run(), 10))


def test_device_gate_cancelled_writer_releases_queued_readers():
    """A reader queued behind a WAITING writer must wake when that writer's
    task is cancelled (e.g. a timed-out request) — not sleep forever on a
    free gate."""

    async def run():
        gate = DeviceGate()
        got = []

        async def hold_shared():
            async with gate.shared():
                await asyncio.sleep(0.05)

        async def writer():
            async with gate.exclusive():
                got.append("w")

        async def late_reader():
            async with gate.shared():
                got.append("r2")

        r1 = asyncio.ensure_future(hold_shared())
        await asyncio.sleep(0.01)
        w = asyncio.ensure_future(writer())
        await asyncio.sleep(0.01)
        r2 = asyncio.ensure_future(late_reader())
        await asyncio.sleep(0.01)
        w.cancel()
        await asyncio.gather(r1, r2, w, return_exceptions=True)
        assert got == ["r2"], got
        async with gate.exclusive():  # gate still fully functional
            got.append("w2")
        assert got == ["r2", "w2"], got

    asyncio.run(asyncio.wait_for(run(), 10))


# ---------------------------------------------------------------------------
# Skew-aware wave flush policy (docs/serving_load.md, ROADMAP-6)
# ---------------------------------------------------------------------------

def _bare_wave_harness(params, caches=None):
    """A harness skeleton for driving a WaveDecoder directly (no store)."""
    from infinistore_tpu.engine import ContinuousBatchingHarness

    h = ContinuousBatchingHarness.__new__(ContinuousBatchingHarness)
    h.params = params
    h.config = CFG
    h.caches = caches if caches is not None else CFG.kv_spec(NUM_BLOCKS).make_caches()
    h.max_req_blocks = MAX_REQ_BLOCKS
    h.gate = DeviceGate()
    return h


def _skew_scenario(params):
    """Two 1-token decode rows + one 3-token chunk whose admission bumps
    the T bucket 2 -> 8 at pad 3/8 > 0.25: the canonical deferral case."""
    from infinistore_tpu.models import prefill

    rng = np.random.default_rng(61)
    tables = np.array(
        [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9, 10, 11]], np.int32
    )
    prompts = [
        rng.integers(0, CFG.vocab, size=16).tolist() for _ in range(3)
    ]
    base = CFG.kv_spec(NUM_BLOCKS).make_caches()
    for p, tab in zip(prompts, tables):
        _, base = prefill(
            params, jnp.asarray(p, jnp.int32), base, jnp.asarray(tab[:2]), CFG
        )
    chunks = [([5], [16]), ([9, 11, 12], [16, 17, 18]), ([13], [16])]
    return tables, chunks, base


def test_skew_policy_off_is_behavior_identical(params):
    """wave_skew_policy=False (the default) must reproduce the blind
    flush exactly: same coalescing, same pad accounting, same bytes, no
    policy counters, process ledger untouched."""
    from infinistore_tpu.engine import (
        WaveDecoder, reset_wave_counters, wave_counters,
    )

    tables, chunks, base = _skew_scenario(params)
    reset_wave_counters()

    async def run(**kw):
        h = _bare_wave_harness(params, jax.tree_util.tree_map(lambda x: x, base))
        wave = WaveDecoder(h, **kw)
        outs = await asyncio.gather(*(
            wave.step_chunk(toks, pos, jnp.asarray(tables[b]))
            for b, (toks, pos) in enumerate(chunks)
        ))
        return [np.asarray(o) for o in outs], h.caches, wave

    default_outs, default_caches, default_wave = asyncio.run(run())
    off_outs, off_caches, off_wave = asyncio.run(run(skew_policy=False))
    assert default_wave.skew_policy is False  # the default IS off
    for a, b in zip(default_outs, off_outs):
        np.testing.assert_array_equal(a, b)
    for layer in range(CFG.n_layers):
        for kind in (0, 1):
            np.testing.assert_array_equal(
                np.asarray(default_caches[layer][kind]),
                np.asarray(off_caches[layer][kind]),
            )
    for w in (default_wave, off_wave):
        # Exactly the blind flush of the byte-identity pin: one 3-entry
        # wave, 5 real flat rows bucketed to 8.
        assert w.max_wave == 3
        assert (w.launched_rows, w.pad_rows) == (8, 3)
        assert w.deferrals == 0 and w.aging_escapes == 0
        assert w.held_flushes == 0 and w.defer_ages_us == []
    st = wave_counters().status()
    assert all(v == 0 for v in st.values()), st


def test_skew_policy_defers_outlier_and_stays_byte_identical(params):
    """Policy on: the bucket-bumping 3-token chunk rides a later wave
    (deferral counted, process ledger bumped) while logits AND cache
    bytes stay identical to per-request sequential decode — the
    scheduling-only guarantee."""
    from infinistore_tpu.engine import (
        WaveDecoder, reset_wave_counters, wave_counters,
    )

    tables, chunks, base = _skew_scenario(params)
    reset_wave_counters()

    async def wave_run():
        h = _bare_wave_harness(params, base)
        wave = WaveDecoder(h, skew_policy=True, hold_max_s=0.0)
        outs = await asyncio.gather(*(
            wave.step_chunk(toks, pos, jnp.asarray(tables[b]))
            for b, (toks, pos) in enumerate(chunks)
        ))
        return [np.asarray(o) for o in outs], h.caches, wave

    async def seq_run():
        h = _bare_wave_harness(params, base)
        outs = []
        for b, (toks, pos) in enumerate(chunks):
            wave = WaveDecoder(h)
            outs.append(np.asarray(
                await wave.step_chunk(toks, pos, jnp.asarray(tables[b]))
            ))
        return outs, h.caches

    wave_outs, wave_caches, wave = asyncio.run(wave_run())
    seq_outs, seq_caches = asyncio.run(seq_run())
    assert wave.deferrals >= 1, "the outlier chunk was never deferred"
    assert wave.max_wave == 2, "the outlier rode the first wave anyway"
    assert wave.waves == 2
    # The deferred wave's rows: wave 1 = 2 rows -> bucket 2 (0 pad),
    # wave 2 = 3 rows -> bucket 4 (1 pad). Blind flush padded 3 of 8.
    assert (wave.launched_rows, wave.pad_rows) == (6, 1)
    assert len(wave.defer_ages_us) >= 1
    for b in range(3):
        np.testing.assert_array_equal(
            wave_outs[b], seq_outs[b],
            err_msg=f"request {b} logits diverged under deferral",
        )
    for layer in range(CFG.n_layers):
        for kind in (0, 1):
            np.testing.assert_array_equal(
                np.asarray(wave_caches[layer][kind]),
                np.asarray(seq_caches[layer][kind]),
                err_msg=f"cache bytes diverged under deferral (layer {layer})",
            )
    st = wave_counters().status()
    assert st["engine_wave_deferrals"] >= 1
    assert st["engine_wave_policy_waves"] == 2
    assert st["engine_wave_defer_age_us_p99"] > 0
    assert 0 < st["engine_wave_bucket_occupancy"] <= 1


def test_skew_policy_aging_escape_under_outlier_flood(params):
    """Starvation-proof: a permanent flood of small decode rows would
    justify deferring the bucket-bumping outlier forever, but once its
    age crosses wave_defer_max_s it force-launches (an aging escape) —
    every future resolves, nothing strands."""
    from infinistore_tpu.engine import WaveDecoder, reset_wave_counters

    tables, chunks, base = _skew_scenario(params)
    reset_wave_counters()

    async def run():
        h = _bare_wave_harness(params, base)
        wave = WaveDecoder(
            h, skew_policy=True, defer_max_s=0.02, hold_max_s=0.0
        )
        toks, pos = chunks[1]
        outlier = asyncio.ensure_future(
            wave.step_chunk(toks, pos, jnp.asarray(tables[1]))
        )
        floods = 0
        for _ in range(300):
            if outlier.done():
                break
            await asyncio.gather(
                wave.step(5, 16, jnp.asarray(tables[0])),
                wave.step(13, 16, jnp.asarray(tables[2])),
            )
            floods += 1
        logits = np.asarray(await asyncio.wait_for(outlier, 30))
        return wave, logits, floods

    wave, logits, floods = asyncio.run(run())
    assert floods >= 1
    assert wave.deferrals >= 1, "the flood never deferred the outlier"
    assert wave.aging_escapes >= 1, (
        "the outlier resolved without an aging escape — the starvation "
        "bound never fired"
    )
    assert np.isfinite(logits).all() and logits.shape[0] == 3
    # The escape is bounded: its recorded deferral age crossed the bound
    # (that is WHY it launched), and the decoder is drained.
    assert max(wave.defer_ages_us) >= 0.02 * 1e6
    assert not wave._pending


def test_skew_policy_end_to_end_verified(conn, params):
    """Integration: a verify=True harness with the policy on serves a
    shared-prefix workload — every request oracle-verified, TTFT
    percentiles and the wave-policy ledger exposed via metrics()."""
    h = ContinuousBatchingHarness(
        EngineKVAdapter(KVConnector(
            conn, CFG.kv_spec(NUM_BLOCKS), "engine-skew",
            max_blocks=MAX_REQ_BLOCKS,
        )),
        params, CFG, NUM_BLOCKS, MAX_REQ_BLOCKS, verify=True,
        wave_skew_policy=True, wave_hold_max_s=0.0,
    )
    assert h.wave.skew_policy is True
    prompts = _prompts(6, shared_blocks=1, total_blocks=2, seed=3)
    m = asyncio.run(h.run(prompts, concurrency=6, gen_tokens=CFG.block_tokens))
    assert m["all_verified"], "a request diverged with the skew policy on"
    assert m["requests"] == 6
    for k in ("wave_deferrals", "wave_aging_escapes", "wave_held_flushes",
              "wave_defer_age_us_p99", "p50_ttft_us", "p99_ttft_us",
              "p99_ttft_fg_us"):
        assert k in m, f"metrics() missing {k}"
    assert m["p99_ttft_us"] > 0
    assert m["p99_ttft_fg_us"] > 0  # default priority is FOREGROUND


def test_skew_policy_canonical_buckets_and_prewarm(conn, params):
    """Policy on: every launched wave lands on the DECLARED canonical
    bucket (T, T, T * max_req_blocks) — table rows pad to the flat-row
    rung (free: a padded table row neither scatters nor attends), pages
    pad to the rung maximum (masked) — and prewarm_wave_buckets()
    compiles exactly that ladder at startup, so serving can never mint
    a jit bucket startup didn't declare. Policy off: prewarm is a no-op
    (a blind flush has no declared shape set)."""
    h = ContinuousBatchingHarness(
        EngineKVAdapter(KVConnector(
            conn, CFG.kv_spec(NUM_BLOCKS), "engine-canon",
            max_blocks=MAX_REQ_BLOCKS,
        )),
        params, CFG, NUM_BLOCKS, MAX_REQ_BLOCKS, verify=True,
        wave_skew_policy=True, wave_hold_max_s=0.0,
    )

    async def drive():
        ladder = await h.prewarm_wave_buckets(max_rows=16)
        prompts = _prompts(5, shared_blocks=1, total_blocks=2, seed=47)
        m = await h.run(prompts, concurrency=5, gen_tokens=6)
        return ladder, m

    ladder, m = asyncio.run(drive())
    mrb = MAX_REQ_BLOCKS
    assert ladder == [(t, t, t * mrb) for t in (1, 2, 4, 8, 16)]
    assert m["wave_prewarmed_buckets"] == ladder
    assert m["all_verified"], "canonical padding corrupted a request"
    assert m["wave_buckets"], "no waves decoded"
    for b, t, p in m["wave_buckets"]:
        assert (b, t, p) == (t, t, t * mrb), (
            f"off-ladder launch {(b, t, p)} — the canonical rule leaked"
        )
        # 5 concurrent 1-token chunks never exceed the declared ladder.
        assert (b, t, p) in set(ladder), f"{(b, t, p)} was never declared"

    # Policy off: nothing to prewarm, organic pow2 buckets untouched.
    h_blind = ContinuousBatchingHarness(
        EngineKVAdapter(KVConnector(
            conn, CFG.kv_spec(NUM_BLOCKS), "engine-canon-off",
            max_blocks=MAX_REQ_BLOCKS,
        )),
        params, CFG, NUM_BLOCKS, MAX_REQ_BLOCKS,
    )
    assert asyncio.run(h_blind.prewarm_wave_buckets()) == []
    assert h_blind.wave.prewarmed == set()
