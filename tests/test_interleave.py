"""Dynamic side of the ITS-R concurrency discipline
(tools/analysis/interleave.py): the deterministic schedule explorer and
the lock-tracer shim.

Three guarantees:

1. **Determinism**: the forced schedule reproduces a data race on EVERY
   run — not one run in ten thousand — so a race report is a failing
   test, not a flake.
2. **The confirmed race stays fixed**: PR 13's ITS-R001 finding —
   ``TierManager._c`` counters bumped from the reconciler thread and the
   read-path hooks with no guard — was reproduced with this harness
   before the fix (two ``note_cold_hit`` calls, counter ends at 1).
   The regression test drives the SAME schedule against the fixed
   TierManager and asserts the opposite verdict: the schedule stalls
   (``serialized`` — the stats lock excludes the second thread) and no
   update is lost.
3. **The lock tracer sees real acquisition orders**: a journal
   compaction's nested ``DurableLog._lock -> ClusterKVConnector._cat_lock``
   acquisition (hidden from static inference behind the snapshot
   callable) is observed at test time, and the union of observed and
   statically inferred edges stays acyclic.
"""

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.analysis import races  # noqa: E402
from tools.analysis.core import Context  # noqa: E402
from tools.analysis.interleave import (  # noqa: E402
    Interleaver,
    find_cycle,
    force_lost_update,
    trace_locks,
)


# ---------------------------------------------------------------------------
# Deterministic schedule explorer.
# ---------------------------------------------------------------------------

class _UnguardedCounters:
    """The verbatim PRE-FIX TierManager increment shape (tiering.py before
    PR 13): a bare ``self._c[key] += 1`` with no stats lock. Kept as the
    harness's known-racy reference so the determinism guarantee is pinned
    against code that provably loses updates."""

    def __init__(self):
        self._c = {"tier_cold_hits": 0}

    def note_cold_hit(self):
        self._c["tier_cold_hits"] += 1


class TestInterleaverDeterminism:
    def test_lost_update_reproduces_every_run(self):
        """The satellite's determinism requirement: 5/5 runs of the forced
        schedule lose the same update (final == 1 after two increments)."""
        for _ in range(5):
            obj = _UnguardedCounters()
            report, final = force_lost_update(
                lambda d: (setattr(obj, "_c", d), obj.note_cold_hit()),
                lambda d: obj.note_cold_hit(),
                dict(obj._c), "tier_cold_hits",
            )
            assert report.completed and not report.errors
            assert final == 1  # two increments, one survived — every time

    def test_unscheduled_labels_pass_through(self):
        """Checkpoints not named in the schedule must not block — one
        instrumented dict serves schedules that only pin two accesses."""
        il = Interleaver(["t1:load"], stall_timeout_s=2.0)
        d = il.instrument_mapping({"k": 0, "other": 0}, "k")
        done = []

        def actor():
            d["other"] += 1  # not the instrumented key: free
            d["k"] += 1      # load scheduled, store unscheduled
            done.append(True)

        report = il.run({"t1": actor})
        assert report.completed and done and d["k"] == 1 and d["other"] == 1

    def test_stall_watchdog_reports_serialized(self):
        """A schedule no thread can satisfy (t2 never reaches its point
        because a lock excludes it) must end in a bounded, clean abort —
        the 'serialized' verdict — with every actor joined."""
        import threading

        lock = threading.Lock()
        il = Interleaver(
            ["t1:load", "t2:load", "t2:store", "t1:store"],
            stall_timeout_s=0.3,
        )
        d = il.instrument_mapping({"k": 0}, "k")

        def bump():
            with lock:
                d["k"] += 1

        report = il.run({"t1": bump, "t2": bump})
        assert report.serialized
        assert report.stalled_at == "t2:load"
        assert not report.errors
        assert d["k"] == 2  # both increments landed after the abort


class TestTierManagerRaceRegression:
    """The PR 13 confirmed-and-fixed ITS-R001 race, end to end."""

    def _manager(self):
        from infinistore_tpu.tiering import (
            TierManager, TierPolicy, TierPolicyConfig,
        )

        class _FakeCluster:
            cold_ids = []
            cold_index = {}

        return TierManager(
            _FakeCluster(), policy=TierPolicy(TierPolicyConfig()),
            interval_s=0,
        )

    def test_fixed_note_cold_hit_serializes(self):
        """The regression assertion: the exact schedule that reproduced
        the lost update pre-fix now STALLS on the stats lock (the second
        thread never reaches its load), and both increments land."""
        tm = self._manager()
        report, final = force_lost_update(
            lambda d: (setattr(tm, "_c", d), tm.note_cold_hit("root-a")),
            lambda d: tm.note_cold_hit("root-b"),
            dict(tm._c), "tier_cold_hits",
        )
        assert report.serialized, (
            "TierManager._c increments interleaved — the _stats_lock "
            "guard (ITS-R001) regressed"
        )
        assert final == 2  # nothing lost once the abort releases the lock

    def test_static_checker_still_owns_the_site(self):
        """The static side of the same contract: TierManager._c must keep
        its declared guard (removing the annotation or the lock re-fires
        ITS-R001 on the real tree — covered in test_static_analysis)."""
        ctx = Context(str(REPO))
        idx = races.PackageIndex(ctx)
        registry = races.build_registry(ctx, idx=idx)
        tiers = [
            sc for sc in registry if sc.cls.name == "TierManager"
        ]
        assert tiers, "TierManager must be classified cross-thread"
        assert tiers[0].cls.guards.get("_c") == ("_stats_lock", "full")


# ---------------------------------------------------------------------------
# Lock tracer.
# ---------------------------------------------------------------------------

class TestLockTracer:
    def _cluster(self, tmp_path):
        """A real ClusterKVConnector (fake member, durable journal) built
        under the tracer — no servers, no jax arrays."""
        from infinistore_tpu.cluster import ClusterKVConnector

        class _FakeConn:
            pass

        return ClusterKVConnector(
            [_FakeConn()], spec=None, model_id="trace-test", max_blocks=8,
            member_ids=["m0:1"], member_factory=lambda c: c,
            journal_path=str(tmp_path / "journal.bin"),
        )

    def test_shim_observes_known_nested_acquisition(self, tmp_path):
        """The satellite's lock-tracer requirement: the journal
        compaction's snapshot callable takes the catalog lock UNDER the
        log lock — invisible to static inference (races.py seeds it via
        an `its: acquires[...]` summary), but the shim must observe it."""
        with trace_locks() as tracer:
            cluster = self._cluster(tmp_path)
            tracer.adopt(cluster, "ClusterKVConnector")
            tracer.adopt(cluster._journal_log, "DurableLog")
            tracer.adopt(cluster.membership, "Membership")
        try:
            cluster.catalog_restore([{
                "root": "r0", "tokens": [1, 2, 3, 4], "blocks": 1,
                "holders": {"m0:1": 1},
            }])
            cluster.compact_journal()
        finally:
            cluster.close()
        edges = tracer.edge_set()
        assert ("DurableLog._lock", "ClusterKVConnector._cat_lock") in edges
        # And the catalog lock is never taken the other way around.
        assert ("ClusterKVConnector._cat_lock", "DurableLog._lock") not in edges

    def test_observed_union_static_graph_is_acyclic(self, tmp_path):
        """The validation loop the tentpole names: real acquisition orders
        recorded at test time must embed into the static lock-order graph
        without creating a cycle (a dynamic-only inversion of a static
        edge IS a potential deadlock, even if each run alone looks fine)."""
        with trace_locks() as tracer:
            cluster = self._cluster(tmp_path)
            tracer.adopt(cluster, "ClusterKVConnector")
            tracer.adopt(cluster._journal_log, "DurableLog")
            tracer.adopt(cluster.membership, "Membership")
        try:
            cluster.catalog_restore([{
                "root": "r0", "tokens": [1, 2, 3, 4], "blocks": 1,
                "holders": {"m0:1": 1},
            }])
            cluster.compact_journal()
            cluster.membership.mark_dead("m0:1")
        finally:
            cluster.close()
        static_edges = set(
            races.lock_order_edges(races.PackageIndex(Context(str(REPO))))
        )
        combined = static_edges | tracer.edge_set()
        cycle = find_cycle(sorted(combined))
        assert cycle is None, f"lock-order cycle: {' -> '.join(cycle)}"

    def test_tracer_counts_acquisitions(self, tmp_path):
        with trace_locks() as tracer:
            cluster = self._cluster(tmp_path)
            tracer.adopt(cluster, "ClusterKVConnector")
        try:
            # catalog_get takes the catalog lock once per call.
            before = tracer.acquisitions.get("ClusterKVConnector._cat_lock", 0)
            cluster.catalog_get("nope")
            cluster.catalog_get("nope")
            after = tracer.acquisitions.get("ClusterKVConnector._cat_lock", 0)
        finally:
            cluster.close()
        assert after - before == 2


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
