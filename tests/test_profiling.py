"""Continuous profiling + metrics history (docs/observability.md).

Covers the contracts the profiling PR established:

- the sampler resolves stage attribution by DESTINATION (a sample between
  two stamps tags the boundary it was traveling toward), retrospectively,
  with untagged/overflow/pending all counted;
- the thread->span map is fed from the tracing bind hook and cleared on
  unbind; samples of an untraced thread count as untagged;
- aggregation is bounded: collapsed-stack buckets overflow into a counted
  `~overflow` bucket, the pending queue force-resolves at capacity;
- ``GET /profile`` over real HTTP: folded output non-empty under load,
  ``?fmt=chrome`` is valid trace-event JSON whose sampling track shares
  the CLOCK_MONOTONIC timeline with ``/trace`` spans for the same traced
  op, ``?save=``/``?diff=`` round-trip a well-formed differential;
- ``telemetry.MetricsHistory``: bounded rings, bounded series, source
  failures survive the pass, the change-point detector fires EXACTLY ONE
  journaled ``metric_anomaly`` on a step and zero on clean (with
  hysteresis re-arm), ``GET /timeseries`` serves index and points;
- ``/metrics`` exports the ``infinistore_prof_*`` (sampler + native
  reactor phases) and ``infinistore_timeseries_*`` families;
- ``tools.top`` renders sparkline trends in both the unicode and the
  plain-ASCII fallback modes.
"""

import asyncio
import json
import random
import threading
import time
import urllib.parse

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu import lib as its_lib
from infinistore_tpu import profiling, telemetry, tracing
from infinistore_tpu.profiling import SamplingProfiler
from infinistore_tpu.server import ManageServer


@pytest.fixture()
def profiled():
    """Process profiling enabled with a fresh profiler; module state
    restored afterwards."""
    old = profiling._profiler
    profiling._profiler = None
    tracing.configure(enabled=True, capacity=256, slow_op_us=0)
    prof = profiling.configure(enabled=True, hz=500.0)
    yield prof
    profiling.configure(enabled=False)
    profiling._profiler = old
    tracing.configure(enabled=False)


@pytest.fixture(autouse=True)
def _off_after():
    yield
    profiling.configure(enabled=False)
    tracing.configure(enabled=False)


def _span(stages):
    """A Span with the given [(stage, t_us)] stamps, without touching the
    recorder (identity fields only matter for the tests that read them)."""
    sp = tracing.Span("t")
    sp.stages = list(stages)
    return sp


# ---------------------------------------------------------------------------
# Stage resolution semantics (destination naming, retrospective).
# ---------------------------------------------------------------------------


class TestStageResolution:
    def test_sample_between_stamps_tags_destination(self):
        p = SamplingProfiler()
        sp = _span([("submit", 100), ("completion_ring", 200)])
        assert p._stage_of(sp, 150, force=False) == "completion_ring"
        assert p._stage_of(sp, 50, force=False) == "submit"

    def test_sample_past_last_stamp_waits_until_finished(self):
        p = SamplingProfiler()
        sp = _span([("submit", 100)])
        assert p._stage_of(sp, 150, force=False) is None  # still open
        sp.status = "ok"
        assert p._stage_of(sp, 150, force=False) == "submit"

    def test_force_resolves_trailing_interval(self):
        p = SamplingProfiler()
        sp = _span([("install", 100)])
        assert p._stage_of(sp, 150, force=True) == "install"

    def test_no_span_is_untagged(self):
        p = SamplingProfiler()
        assert p._stage_of(None, 1, force=False) == profiling._UNTAGGED

    def test_pending_resolves_when_span_finishes(self):
        p = SamplingProfiler()
        sp = _span([("submit", 100)])
        with p._lock:
            p._pending.append((150, 1, sp, "a;b"))
            p._resolve_locked(now_us=150)
        assert p.status()["prof_pending"] == 1  # open span, young sample
        sp.stages.append(("completion_ring", 200))
        p.flush()
        st = p.status()
        assert st["prof_pending"] == 0
        assert p.stage_counts() == {"completion_ring": 1}

    def test_bucket_overflow_is_bounded_and_counted(self):
        p = SamplingProfiler(max_buckets=2)
        with p._lock:
            for i in range(5):
                p._pending.append((10, 1, None, f"stack{i}"))
        p.flush()
        st = p.status()
        assert st["prof_buckets"] <= 3  # 2 + the overflow bucket
        assert st["prof_bucket_drops"] == 3
        assert (profiling._UNTAGGED, "~overflow") in p.buckets()

    def test_pending_capacity_force_resolves_oldest(self):
        p = SamplingProfiler(pending_capacity=2)
        now = tracing._now_us()
        sp = _span([("submit", now)])  # open span: samples cannot resolve
        with p._lock:
            p._pending.append((now + 1, 1, sp, "a"))
            p._pending.append((now + 2, 1, sp, "a"))
        # Next sample pass must force-resolve the oldest instead of growing.
        p.track_thread()  # ensure a tracked thread exists

        def spin():
            t0 = time.time()
            while time.time() - t0 < 0.05:
                pass

        t = threading.Thread(target=spin)
        t.start()
        p.track_thread(ident=t.ident)
        p.sample_once()
        t.join()
        assert len(p._pending) <= 2
        assert p.status()["prof_pending_drops"] >= 1
        # buckets() flushes, which must NOT force-resolve the remaining
        # young open-span samples — only the capacity overflow guessed.
        forced = {
            (stage, stack): n for (stage, stack), n in p.buckets().items()
            if stage == "submit"
        }
        assert sum(forced.values()) == p.status()["prof_pending_drops"]

    def test_flush_never_guesses_an_open_spans_young_sample(self):
        """GET /profile mid-workload must not book an in-flight sample one
        boundary early: flush resolves finished spans and aged samples
        only (the review-confirmed destination-naming contract)."""
        p = SamplingProfiler()
        sp = _span([("submit", 100)])  # open, no later stamp yet
        with p._lock:
            p._pending.append((tracing._now_us(), 1, sp, "a"))
        p.flush()
        assert p.status()["prof_pending"] == 1  # still undecided
        sp.stages.append(("completion_ring", tracing._now_us() + 1))
        p.flush()
        assert p.stage_counts() == {"completion_ring": 1}


# ---------------------------------------------------------------------------
# Sampling real threads + the tracing bind hook.
# ---------------------------------------------------------------------------


class TestSampling:
    def test_samples_tracked_thread_frames(self):
        p = SamplingProfiler()
        stop = threading.Event()

        def busy_worker_fn():
            while not stop.is_set():
                sum(i for i in range(100))

        t = threading.Thread(target=busy_worker_fn, daemon=True)
        t.start()
        p.track_thread(ident=t.ident, name="w")
        try:
            for _ in range(5):
                p.sample_once()
        finally:
            stop.set()
            t.join()
        p.flush()
        assert p.status()["prof_samples"] >= 1
        assert "busy_worker_fn" in p.folded()

    def test_bind_hook_feeds_thread_span_map(self, profiled):
        tid = threading.get_ident()
        with tracing.trace_op("op", stage="enqueue") as sp:
            assert profiled._thread_spans.get(tid) is sp
        assert profiled._thread_spans.get(tid) is None

    def test_worker_thread_samples_carry_trace_id(self, profiled):
        """A traced op running on a worker thread tags that thread's
        samples with its span — the whole thread->span feed, end to end,
        driven deterministically from the test thread."""
        release = threading.Event()
        seen = {}

        def traced_worker():
            with tracing.trace_op("slow", stage="enqueue") as sp:
                seen["trace_id"] = sp.trace_id
                release.wait(2.0)
                sp.stage("install")

        t = threading.Thread(target=traced_worker, daemon=True)
        t.start()
        for _ in range(200):
            if seen.get("trace_id"):
                break
            time.sleep(0.001)
        for _ in range(5):
            profiled.sample_once()
        release.set()
        t.join()
        profiled.flush()
        samples = [
            s for s in profiled.recent_samples()
            if s["trace_id"] == seen["trace_id"]
        ]
        assert samples, "no sample carried the worker op's trace id"
        # Destination naming: mid-op samples travel toward `install`.
        assert {s["stage"] for s in samples} <= {"install", "enqueue"}

    def test_disable_keeps_data_for_postmortem(self, profiled):
        profiled.track_thread()
        with profiled._lock:
            profiled._pending.append((1, 2, None, "x"))
        profiling.configure(enabled=False)
        assert not profiling.enabled()
        assert profiling.profiler() is profiled
        profiled.flush()
        assert profiling.profiler().status()["prof_samples"] == 1

    def test_clear_resets_aggregates(self):
        p = SamplingProfiler()
        with p._lock:
            p._pending.append((1, 2, None, "x"))
        p.flush()
        assert p.status()["prof_samples"] == 1
        p.clear()
        st = p.status()
        assert st["prof_samples"] == 0 and st["prof_buckets"] == 0


# ---------------------------------------------------------------------------
# Snapshots, diffs, chrome export.
# ---------------------------------------------------------------------------


class TestExport:
    def _prof_with(self, stacks):
        p = SamplingProfiler()
        with p._lock:
            for s in stacks:
                p._pending.append((10, 1, None, s))
        p.flush()
        return p

    def test_folded_format(self):
        p = self._prof_with(["a;b", "a;b", "a;c"])
        lines = set(p.folded().splitlines())
        assert f"{profiling._UNTAGGED};a;b 2" in lines
        assert f"{profiling._UNTAGGED};a;c 1" in lines

    def test_diff_is_well_formed(self):
        p = self._prof_with(["a;b"])
        p.snapshot_save("base")
        with p._lock:
            p._pending.append((11, 1, None, "a;b"))
            p._pending.append((11, 1, None, "new;stack"))
        p.flush()
        d = p.diff("base")
        assert d["base"] == "base" and d["samples_delta"] == 2
        lines = set(d["folded_delta"].splitlines())
        assert f"{profiling._UNTAGGED};a;b 1" in lines
        assert f"{profiling._UNTAGGED};new;stack 1" in lines
        assert p.diff("missing") is None

    def test_snapshots_bounded(self):
        p = self._prof_with(["a"])
        p.max_snapshots = 2
        for name in ("s1", "s2", "s3"):
            p.snapshot_save(name)
        assert p.snapshot_names() == ["s2", "s3"]

    def test_chrome_events_schema(self):
        p = self._prof_with(["a;b"])
        events = p.chrome_events()
        assert events[0]["ph"] == "M"  # process_name metadata
        sample = events[1]
        assert sample["ph"] == "i" and sample["pid"] == 2
        assert sample["name"] == "b"
        assert sample["args"]["stack"] == "a;b"


# ---------------------------------------------------------------------------
# MetricsHistory: rings, bounds, detection, journal.
# ---------------------------------------------------------------------------


class TestMetricsHistory:
    def _history(self, journal=None, **kw):
        clk = [0.0]
        kw.setdefault("select", None)
        h = telemetry.MetricsHistory(
            journal=journal or telemetry.EventJournal(),
            clock=lambda: clk[0], **kw
        )
        return h, clk

    def test_ring_and_window(self):
        h, clk = self._history(capacity=4)
        vals = {"m": 0.0}
        h.add_source("", lambda: dict(vals))
        for i in range(10):
            clk[0] += 1.0
            vals["m"] = float(i)
            h.sample_once()
        pts = h.points("m")
        assert len(pts) == 4 and pts[-1][1] == 9.0  # ring-bounded
        # window horizon is inclusive: now=10, window 1.5 -> t in {9, 10}
        assert len(h.points("m", window_s=1.5)) == 2

    def test_max_series_bounded_and_counted(self):
        h, clk = self._history(max_series=2)
        h.add_source("", lambda: {"a": 1.0, "b": 2.0, "c": 3.0, "d": 4.0})
        clk[0] = 1.0
        h.sample_once()
        st = h.status()
        assert st["timeseries_series"] == 2
        assert st["timeseries_dropped_series"] == 2

    def test_source_failure_survives_pass(self):
        h, clk = self._history()

        def bad():
            raise RuntimeError("down")

        h.add_source("bad", bad)
        h.add_source("good", lambda: {"m": 1.0})
        clk[0] = 1.0
        out = h.sample_once()
        assert out["series"] == 1
        assert h.status()["timeseries_source_failures"] == 1
        assert h.points("good:m")

    def test_select_prefixes_filter(self):
        h, clk = self._history(select=("keep_",))
        h.add_source("", lambda: {"keep_x": 1.0, "drop_y": 2.0})
        clk[0] = 1.0
        h.sample_once()
        assert h.series_names() == ["keep_x"]

    def test_step_fires_exactly_one_anomaly_and_rearms(self):
        journal = telemetry.EventJournal()
        h, clk = self._history(journal=journal, detect_base_n=6,
                               detect_probe_n=2)
        rng = random.Random(7)
        vals = {"m": 10.0}
        h.add_source("", lambda: dict(vals))

        def run(n, level):
            for _ in range(n):
                clk[0] += 1.0
                vals["m"] = level * (1.0 + rng.uniform(-0.02, 0.02))
                h.sample_once()

        run(20, 10.0)  # clean
        assert h.status()["timeseries_anomalies"] == 0
        run(12, 25.0)  # step: one edge, then quiet at the new level
        assert h.status()["timeseries_anomalies"] == 1
        events = [e for e in journal.snapshot()
                  if e["kind"] == "metric_anomaly"]
        assert len(events) == 1
        attrs = events[0]["attrs"]
        assert attrs["metric"] == "m"
        assert attrs["current"] > attrs["baseline"]
        run(12, 50.0)  # re-armed: a second step fires a second edge
        assert h.status()["timeseries_anomalies"] == 2

    def test_flat_series_never_fires(self):
        h, clk = self._history()
        h.add_source("", lambda: {"m": 5.0})
        for _ in range(40):
            clk[0] += 1.0
            h.sample_once()
        assert h.status()["timeseries_anomalies"] == 0


# ---------------------------------------------------------------------------
# Manage plane over real HTTP: /profile, /timeseries, /metrics.
# ---------------------------------------------------------------------------


async def _get(port, path):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, body = raw.split(b"\r\n\r\n", 1)
    status = int(head.split(b" ", 2)[1])
    return status, head.decode("latin-1"), body


class TestManagePlane:
    @pytest.fixture()
    def profiled_server(self, server, profiled):
        """A live store + manage plane + history, with traced load driven
        through a real connection so the profiler holds samples."""
        conn = its.InfinityConnection(its.ClientConfig(
            host_addr="127.0.0.1", service_port=server["port"],
            log_level="error",
        ))
        conn.connect()
        n, block = 64, 16 << 10
        buf = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
        conn.register_mr(buf)
        pairs = [(f"prof-{i}", i * block) for i in range(n)]

        def drive(reads=20):
            async def go():
                await conn.write_cache_async(pairs, block, buf.ctypes.data)
                for _ in range(reads):
                    with tracing.trace_op("batched_get", stage="enqueue") as sp:
                        await conn.read_cache_async(
                            pairs, block, buf.ctypes.data
                        )
                        if sp is not None:
                            sp.stage("install")
            asyncio.run(go())

        hist = telemetry.MetricsHistory(select=None)
        hist.add_source("", lambda: {"probe_metric": 1.0})
        old = its_lib._server_handle
        its_lib._server_handle = server["handle"]
        yield {"drive": drive, "hist": hist, "config": server["config"],
               "prof": profiled}
        its_lib._server_handle = old
        conn.close()

    def _with_manage(self, ps, coro):
        async def main():
            manage = ManageServer(ps["config"], history=ps["hist"])
            manage._server = await asyncio.start_server(
                manage._handle, host="127.0.0.1", port=0
            )
            port = manage._server.sockets[0].getsockname()[1]
            try:
                return await coro(port)
            finally:
                manage._server.close()
                await manage._server.wait_closed()

        return asyncio.run(main())

    def test_profile_folded_nonempty_under_load(self, profiled_server):
        ps = profiled_server
        for _ in range(10):
            ps["drive"]()
            ps["prof"].flush()
            if ps["prof"].status()["prof_samples"]:
                break

        async def check(port):
            status, head, body = await _get(port, "/profile")
            assert status == 200
            assert "text/plain" in head
            return body.decode()

        folded = self._with_manage(ps, check)
        assert folded.strip(), "folded /profile body empty under load"
        for line in folded.strip().splitlines():
            stack, count = line.rsplit(" ", 1)
            assert int(count) >= 1 and stack

    def test_profile_chrome_shares_timeline_with_trace(self, profiled_server):
        """The acceptance criterion: /profile?fmt=chrome samples for a
        traced op land inside that op's /trace span window, on the same
        CLOCK_MONOTONIC timeline."""
        ps = profiled_server
        tagged = []
        for _ in range(20):
            ps["drive"]()
            ps["prof"].flush()
            tagged = [
                s for s in ps["prof"].recent_samples() if s["trace_id"]
            ]
            if tagged:
                break
        assert tagged, "no sample carried a trace id under traced load"

        async def check(port):
            s1, _, body1 = await _get(port, "/profile?fmt=chrome")
            s2, _, body2 = await _get(port, "/trace")
            assert s1 == 200 and s2 == 200
            return json.loads(body1), json.loads(body2)

        chrome, trace = self._with_manage(ps, check)
        events = chrome["traceEvents"]
        assert all("ph" in e and "ts" in e and "pid" in e for e in events)
        samples = [e for e in events if e.get("cat") == "sample"]
        assert samples
        spans = {s["trace_id"]: s for s in trace["spans"]
                 if s["name"] == "batched_get"}
        aligned = 0
        for e in samples:
            tid = int(e["args"]["trace_id"], 16)
            span = spans.get(tid)
            if span is None:
                continue
            assert span["start_us"] <= e["ts"] <= span["end_us"], (
                "sample outside its op's span window"
            )
            aligned += 1
        assert aligned >= 1, "no sample joined a recorded span's timeline"

    def test_profile_save_and_diff(self, profiled_server):
        ps = profiled_server
        ps["drive"]()

        async def check(port):
            s, _, body = await _get(port, "/profile?save=base")
            assert s == 200
            saved = json.loads(body)
            assert saved["saved"]["name"] == "base"
            await asyncio.to_thread(ps["drive"])
            ps["prof"].flush()
            s, _, body = await _get(port, "/profile?diff=base")
            assert s == 200
            diff = json.loads(body)
            assert diff["base"] == "base"
            assert diff["samples"] >= diff["base_samples"]
            assert "folded_delta" in diff
            s, _, body = await _get(port, "/profile?diff=nope")
            assert s == 404
            assert "snapshots" in json.loads(body)

        self._with_manage(ps, check)

    def test_profile_disabled_reports_off(self, server):
        old = profiling._profiler
        profiling._profiler = None
        try:
            async def check(port):
                s, _, body = await _get(port, "/profile")
                doc = json.loads(body)
                assert s == 200 and doc["enabled"] is False

            self._with_manage(
                {"config": server["config"], "hist": None}, check
            )
        finally:
            profiling._profiler = old

    def test_timeseries_index_points_and_errors(self, profiled_server):
        ps = profiled_server
        ps["hist"].sample_once()
        ps["hist"].sample_once()

        async def check(port):
            s, _, body = await _get(port, "/timeseries")
            index = json.loads(body)
            assert s == 200 and index["enabled"]
            assert "probe_metric" in index["series"]
            assert index["timeseries_samples"] >= 2
            metric = urllib.parse.quote("probe_metric")
            s, _, body = await _get(
                port, f"/timeseries?metric={metric}&window=3600"
            )
            doc = json.loads(body)
            assert s == 200 and len(doc["points"]) == 2
            assert all(len(p) == 2 for p in doc["points"])
            s, _, _ = await _get(port, "/timeseries?metric=unknown")
            assert s == 404
            s, _, _ = await _get(
                port, f"/timeseries?metric={metric}&window=zzz"
            )
            assert s == 400
            # Non-finite windows parse as floats but would poison the
            # horizon compare and serialize as bare NaN (invalid JSON).
            s, _, _ = await _get(
                port, f"/timeseries?metric={metric}&window=nan"
            )
            assert s == 400
            # Batch form (repeated params — the tools.top frame fetch):
            # one response, unknown names omitted rather than 404.
            s, _, body = await _get(
                port, f"/timeseries?metric={metric}&metric=unknown&window=60"
            )
            doc = json.loads(body)
            assert s == 200 and list(doc["metrics"]) == ["probe_metric"]
            assert len(doc["metrics"]["probe_metric"]) == 2

        self._with_manage(ps, check)

    def test_metrics_exports_prof_and_timeseries_families(
            self, profiled_server):
        ps = profiled_server
        ps["drive"]()
        ps["hist"].sample_once()

        async def check(port):
            s, _, body = await _get(port, "/metrics")
            assert s == 200
            return body.decode()

        text = self._with_manage(ps, check)
        assert "infinistore_prof_samples " in text
        assert "infinistore_prof_tick_us " in text
        assert 'infinistore_prof_loop_us{phase="wait"}' in text
        assert "infinistore_prof_loop_passes " in text
        assert "infinistore_timeseries_series " in text
        assert "infinistore_timeseries_anomalies " in text


# ---------------------------------------------------------------------------
# tools.top sparkline rendering, both modes.
# ---------------------------------------------------------------------------


class TestTopSparklines:
    def _frame(self):
        return {
            "t": "00:00:00", "base": "x", "error": None,
            "slo": {"verdict": "ok"},
            "events": {"events": [], "emitted": 0},
            "metrics": {}, "membership": {},
            "trends": {
                'infinistore_op_p99_latency_us{op="G"}':
                    [1.0, 2.0, 8.0, 4.0, 2.0],
            },
        }

    def test_unicode_mode_renders_blocks(self):
        from tools.top import render

        lines = render(self._frame(), ascii_only=False)
        assert any("TRENDS" in line for line in lines)
        assert any(any(c in line for c in "▁▂▃▄▅▆▇█") for line in lines)

    def test_ascii_mode_is_pure_ascii(self):
        from tools.top import render

        lines = render(self._frame(), ascii_only=True)
        assert any("TRENDS" in line for line in lines)
        assert all(ord(c) < 128 for line in lines for c in line)
        trend = next(line for line in lines if "p99" in line)
        assert any(c in trend for c in "._-=+*#@")

    def test_sparkline_edge_cases(self):
        from tools.top import sparkline

        assert sparkline([], width=8, ascii_only=True) == " " * 8
        flat = sparkline([5.0] * 4, width=8, ascii_only=True)
        assert len(flat) == 8 and flat.strip()
        ramp = sparkline([1.0, 2.0, 3.0], width=3, ascii_only=False)
        assert ramp[0] != ramp[2]
