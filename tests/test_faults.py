"""faults.py: the deterministic fault-injection shim every chaos test
drives. The shim's own contract is what is under test here — faults fire
exactly where scripted (op index / op name / key pattern), replay
identically from a seed, and a ``reset`` really severs the transport (so
breaker/quarantine/reconnect machinery exercises its true paths) — plus the
pass-through guarantee: an unfaulted op is byte-identical to the bare
connection's.
"""

import numpy as np
import pytest

import infinistore_tpu as its
from infinistore_tpu.faults import FaultRule, FaultyConnection, kill_transport

BLOCK = 4 << 10


@pytest.fixture()
def faulty_pair():
    """A live loopback server + a FaultyConnection factory over it; each
    call builds a fresh wrapped connection with the given rules/seed."""
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=BLOCK)
    made = []

    def make(rules, seed=0, **cfg_kw):
        cfg = its.ClientConfig(
            host_addr="127.0.0.1", service_port=srv.port, log_level="error",
            connect_timeout_ms=1000, **cfg_kw,
        )
        c = its.InfinityConnection(cfg)
        c.connect()
        fc = FaultyConnection(c, rules, seed=seed)
        made.append(c)
        return fc

    yield make
    for c in made:
        try:
            c.close()
        except Exception:
            pass
    srv.stop()


def _bufs(conn, n=1):
    src = np.zeros(BLOCK, dtype=np.uint8)
    dst = np.zeros(BLOCK, dtype=np.uint8)
    conn.register_mr(src)
    conn.register_mr(dst)
    return src, dst


def test_unfaulted_ops_pass_through_byte_identical(faulty_pair):
    fc = faulty_pair([])
    src, dst = _bufs(fc)
    src[:] = 42
    fc.write_cache([("k0", 0)], BLOCK, src.ctypes.data)
    fc.read_cache([("k0", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 42).all()
    assert fc.check_exist("k0")
    assert fc.fired == [] and fc.op_index == 3


def test_error_fires_on_exact_op_index_and_op_name(faulty_pair):
    fc = faulty_pair([
        FaultRule(op="read_cache", op_indices=[2], action="error"),
    ])
    src, dst = _bufs(fc)
    src[:] = 7
    fc.write_cache([("a", 0)], BLOCK, src.ctypes.data)  # op 0
    fc.read_cache([("a", 0)], BLOCK, dst.ctypes.data)  # op 1: passes
    with pytest.raises(its.InfiniStoreException, match="injected error"):
        fc.read_cache([("a", 0)], BLOCK, dst.ctypes.data)  # op 2: fires
    fc.read_cache([("a", 0)], BLOCK, dst.ctypes.data)  # op 3: passes again
    assert (dst == 7).all()
    assert [f["index"] for f in fc.fired] == [2]
    # A write at the firing index would NOT have fired (op name mismatch).
    assert fc.fired[0]["op"] == "read_cache"


def test_key_pattern_targets_one_family(faulty_pair):
    fc = faulty_pair([
        FaultRule(key_pattern=r"^victim/", action="error"),
    ])
    src, dst = _bufs(fc)
    fc.write_cache([("safe/0", 0)], BLOCK, src.ctypes.data)
    with pytest.raises(its.InfiniStoreException):
        fc.write_cache([("victim/0", 0)], BLOCK, src.ctypes.data)
    fc.read_cache([("safe/0", 0)], BLOCK, dst.ctypes.data)
    assert {f["keys"][0] for f in fc.fired} == {"victim/0"}


def test_every_and_max_fires_schedule(faulty_pair):
    fc = faulty_pair([
        FaultRule(op="check_exist", every=2, max_fires=2, action="error"),
    ])
    outcomes = []
    for _ in range(6):
        try:
            fc.check_exist("nope")
            outcomes.append("ok")
        except its.InfiniStoreException:
            outcomes.append("err")
    # Every 2nd matching op, disarmed after 2 fires.
    assert outcomes == ["err", "ok", "err", "ok", "ok", "ok"]


def test_probability_replays_identically_from_seed(faulty_pair):
    def run(seed):
        fc = faulty_pair([
            FaultRule(op="check_exist", probability=0.5, action="error"),
        ], seed=seed)
        hits = []
        for i in range(20):
            try:
                fc.check_exist("k")
                hits.append(0)
            except its.InfiniStoreException:
                hits.append(1)
        return hits

    a, b, c = run(7), run(7), run(8)
    assert a == b  # deterministic replay
    assert a != c  # and actually seed-driven
    assert 0 < sum(a) < 20


def test_timeout_and_delay_actions(faulty_pair):
    import time as _time

    fc = faulty_pair([
        FaultRule(op="check_exist", op_indices=[0], action="timeout"),
        FaultRule(op="check_exist", op_indices=[1], action="delay",
                  delay_s=0.05),
    ])
    with pytest.raises(its.InfiniStoreException, match="injected timeout"):
        fc.check_exist("k")
    t0 = _time.perf_counter()
    assert fc.check_exist("k") is False  # delayed but correct
    assert _time.perf_counter() - t0 >= 0.05


def test_short_read_truncates_tcp_get(faulty_pair):
    fc = faulty_pair([
        FaultRule(op="tcp_read_cache", op_indices=[2], action="short_read",
                  truncate_to=100),
    ])
    payload = np.arange(BLOCK, dtype=np.uint8) % 251
    fc.tcp_write_cache("t", payload.ctypes.data, BLOCK)  # op 0
    full = fc.tcp_read_cache("t")  # op 1
    assert full.nbytes == BLOCK
    short = fc.tcp_read_cache("t")  # op 2: truncated
    assert short.nbytes == 100
    np.testing.assert_array_equal(short, payload[:100])


def test_reset_severs_transport_and_reconnect_heals(faulty_pair):
    fc = faulty_pair(
        [FaultRule(op="write_cache", op_indices=[1], action="reset")],
        auto_reconnect=False,
    )
    src, dst = _bufs(fc)
    src[:] = 9
    fc.write_cache([("r", 0)], BLOCK, src.ctypes.data)  # op 0
    assert fc.is_connected
    with pytest.raises(its.InfiniStoreException, match="injected connection reset"):
        fc.write_cache([("r", 0)], BLOCK, src.ctypes.data)  # op 1
    # The transport is REALLY down, not just an exception.
    assert not fc.is_connected
    with pytest.raises(its.InfiniStoreException):
        fc.read_cache([("r", 0)], BLOCK, dst.ctypes.data)
    # ... and recovery is the true reconnect path (plain MRs re-registered).
    fc.reconnect()
    assert fc.is_connected
    fc.write_cache([("r", 0)], BLOCK, src.ctypes.data)
    fc.read_cache([("r", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 9).all()


def test_kill_transport_spares_close_and_auto_reconnect(faulty_pair):
    fc = faulty_pair([], auto_reconnect=True)
    src, dst = _bufs(fc)
    src[:] = 33
    fc.write_cache([("x", 0)], BLOCK, src.ctypes.data)
    assert kill_transport(fc.inner)
    assert not fc.is_connected
    assert not kill_transport(fc.inner)  # idempotent: already dead
    # auto_reconnect self-heals the next sync op transparently (the store
    # restarted empty is a different test; same server here, data survives).
    fc.read_cache([("x", 0)], BLOCK, dst.ctypes.data)
    assert (dst == 33).all()
    assert fc.is_connected


def test_async_ops_fault_and_pass_through(faulty_pair):
    import asyncio

    fc = faulty_pair([
        FaultRule(op="read_cache_async", op_indices=[1], action="error"),
    ])
    src, dst = _bufs(fc)
    src[:] = 5

    async def go():
        await fc.write_cache_async([("z", 0)], BLOCK, src.ctypes.data)  # op 0
        with pytest.raises(its.InfiniStoreException, match="injected error"):
            await fc.read_cache_async([("z", 0)], BLOCK, dst.ctypes.data)
        await fc.read_cache_async([("z", 0)], BLOCK, dst.ctypes.data)

    asyncio.run(go())
    assert (dst == 5).all()
    assert [f["op"] for f in fc.fired] == ["read_cache_async"]
