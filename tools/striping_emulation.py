#!/usr/bin/env python
"""Rate-shaped cross-host emulation: prove striping where it can win.

On this single-core loopback box, striping measurably HURTS (memcpy-bound;
docs/multistream.md) — but the knob exists for cross-host DCN, where one TCP
stream caps well below the NIC. This harness builds that regime on-box:
``pacing_rate_mbps`` (SO_MAX_PACING_RATE — TCP internal pacing, no qdisc or
privileges needed) caps every connection's egress in BOTH directions
(client knob caps PUTs, server knob caps GETs), exactly the shape of a
bandwidth-limited cross-host stream. Under the cap:

  - 1 stream pins at the per-connection rate,
  - ``StripedConnection(streams=N)`` scales ~linearly until the payload is
    small enough that per-stream fixed costs bite.

Two experiments, one JSON line each:

1. ``scaling``: the loopback bench's exact workload (batched write+read,
   shm disabled so everything rides the paced socket) at 1/2/4 streams.
2. ``disagg``  (BASELINE config 5 emulation): two PROCESSES — a prefill
   role that streams L layers of paged-KV blocks to the store, and a decode
   role that reads them back — over the shaped link, the 2-host
   prefill→decode split this environment cannot run for real (reference
   cross-node usage: /root/reference/README.md:13-16,
   docs/source/design.rst:33-37).

Run: ``python tools/striping_emulation.py [--cap-mbps 50] [--mb 16]``
"""

import argparse
import asyncio
import json
import multiprocessing as mp
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import infinistore_tpu as its  # noqa: E402
from infinistore_tpu.shaping import (  # noqa: E402
    BLOCK,
    shaped_config as _shaped_config,
    shaped_roundtrip_mbps,
)


def measure_streams(port: int, cap_mbps: int, streams: int, nbytes: int) -> float:
    """Aggregate write+read MB/s of the headline workload over N stripes
    (the shared shaped-roundtrip measurement, infinistore_tpu/shaping.py)."""
    mbps, _ = shaped_roundtrip_mbps(port, cap_mbps, streams, nbytes, key_prefix="em")
    return mbps


# ---- BASELINE config 5: two-process prefill→decode over the shaped link ----


def _prefill_role(port, cap_mbps, layers, blocks_per_layer, streams, done_q):
    """Producer process: stream L layers of KV blocks to the store, layer 0
    last (the connector's sentinel ordering, tpu/layerwise.py). Keys are
    namespaced by stream count: each experiment must write fresh keys, or a
    later run's decode role would see the previous run's layer-0 sentinel
    and read stale bytes while the new prefill is still writing."""
    cfg = _shaped_config(port, cap_mbps)
    conn = its.StripedConnection(cfg, streams=streams) if streams > 1 else its.InfinityConnection(cfg)
    conn.connect()
    buf = np.random.randint(0, 256, size=blocks_per_layer * BLOCK, dtype=np.uint8)
    conn.register_mr(buf)

    async def run():
        for layer in list(range(1, layers)) + [0]:
            pairs = [(f"d{streams}/L{layer}/{i}", i * BLOCK) for i in range(blocks_per_layer)]
            await conn.write_cache_async(pairs, BLOCK, buf.ctypes.data)

    t0 = time.perf_counter()
    asyncio.run(run())
    done_q.put(("prefill_s", time.perf_counter() - t0, buf[:64].tolist()))
    conn.close()


def _decode_role(port, cap_mbps, layers, blocks_per_layer, streams, done_q):
    """Consumer process: wait for the layer-0 sentinel, then pull every
    layer's blocks (what the decode host does before serving tokens).

    With striping the layer-0 batch commits per-stripe, so one key is not a
    sufficient sentinel — confirm every layer-0 block before reading (the
    real connector gets this per-block granularity from lookup()'s
    longest-prefix match over per-block chain keys)."""
    cfg = _shaped_config(port, cap_mbps)
    conn = its.StripedConnection(cfg, streams=streams) if streams > 1 else its.InfinityConnection(cfg)
    conn.connect()
    buf = np.zeros(blocks_per_layer * BLOCK, dtype=np.uint8)
    conn.register_mr(buf)
    t0 = time.perf_counter()
    pending = set(range(blocks_per_layer))
    while pending:
        pending = {i for i in pending if not conn.check_exist(f"d{streams}/L0/{i}")}
        if not pending:
            break
        time.sleep(0.005)
        if time.perf_counter() - t0 > 120:
            done_q.put(("decode_timeout", -1.0, []))
            return

    async def run():
        for layer in range(layers):
            pairs = [(f"d{streams}/L{layer}/{i}", i * BLOCK) for i in range(blocks_per_layer)]
            await conn.read_cache_async(pairs, BLOCK, buf.ctypes.data)

    t1 = time.perf_counter()
    asyncio.run(run())
    done_q.put(("decode_s", time.perf_counter() - t1, buf[:64].tolist()))
    conn.close()


def disagg_emulation(port, cap_mbps, streams, layers=8, blocks_per_layer=32):
    """Returns (prefill MB/s, decode MB/s, verified) for the 2-process split."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=role, args=(port, cap_mbps, layers, blocks_per_layer, streams, q)
        )
        for role in (_prefill_role, _decode_role)
    ]
    for p in procs:
        p.start()
    results = {}
    payloads = {}
    for _ in range(2):
        tag, secs, head = q.get(timeout=180)
        results[tag] = secs
        payloads[tag] = head
    for p in procs:
        p.join(timeout=30)
    if "decode_timeout" in results:
        raise RuntimeError("decode role never saw the layer-0 sentinel")
    nbytes = layers * blocks_per_layer * BLOCK
    verified = payloads["prefill_s"] == payloads["decode_s"]
    return (
        nbytes / results["prefill_s"] / (1 << 20),
        nbytes / results["decode_s"] / (1 << 20),
        verified,
    )


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cap-mbps", type=int, default=50,
                    help="per-connection egress cap, both directions")
    ap.add_argument("--mb", type=int, default=16, help="payload MB per direction")
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--blocks-per-layer", type=int, default=32)
    args = ap.parse_args()

    srv = its.start_local_server(
        prealloc_bytes=max(256 << 20, 4 * args.mb << 20),
        block_bytes=BLOCK,
        enable_shm=False,
        pacing_rate_mbps=args.cap_mbps,
    )
    try:
        scaling = {
            str(s): round(measure_streams(srv.port, args.cap_mbps, s, args.mb << 20), 1)
            for s in (1, 2, 4)
        }
        print(json.dumps({
            "experiment": "scaling",
            "cap_mbps": args.cap_mbps,
            "aggregate_mbps_by_streams": scaling,
            "speedup_4_over_1": round(scaling["4"] / scaling["1"], 2),
        }))

        for streams in (1, 4):
            pre, dec, ok = disagg_emulation(
                srv.port, args.cap_mbps, streams, args.layers, args.blocks_per_layer
            )
            print(json.dumps({
                "experiment": "disagg_prefill_decode",
                "streams": streams,
                "cap_mbps": args.cap_mbps,
                "prefill_mbps": round(pre, 1),
                "decode_mbps": round(dec, 1),
                "data_verified": ok,
            }))
    finally:
        srv.stop()


if __name__ == "__main__":
    main()
