#!/usr/bin/env bash
# Hermetic wheel build: run tools/build_wheel.sh inside the pinned container
# (Dockerfile.build) and extract the wheel + provenance into dist/.
#
# Usage: tools/build_wheel_container.sh [image-digest-or-tag]
#   e.g. tools/build_wheel_container.sh \
#        quay.io/pypa/manylinux_2_28_x86_64@sha256:<digest>
#
# The reference's equivalent: build_manylinux_wheels.sh driving
# Dockerfile.build. CI runs this in the wheel-hermetic job.
set -euo pipefail
cd "$(dirname "$0")/.."

BASE="${1:-quay.io/pypa/manylinux_2_28_x86_64}"
TAG=infinistore-tpu-wheel:build

docker build -f Dockerfile.build --build-arg "BASE=$BASE" -t "$TAG" .
# Record the EXACT image the build ran on (digest of the resolved base is in
# the image history; the built image id pins the whole toolchain state).
mkdir -p dist
CID=$(docker create "$TAG")
trap 'docker rm -f "$CID" >/dev/null' EXIT
docker cp "$CID":/out/. dist/
docker image inspect "$TAG" --format 'image_id: {{.Id}}' >> dist/BUILD_PROVENANCE.txt
echo "hermetic wheel + provenance in dist/:"
ls -l dist/
cat dist/BUILD_PROVENANCE.txt
