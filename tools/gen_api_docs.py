#!/usr/bin/env python
"""Autodoc: generate docs/api_reference.md from the live package docstrings.

The reference ships Sphinx autodoc built in CI and deployed to GH Pages
(reference docs/source/api.rst, .github/workflows/deploy-docs.yml). This
environment has no sphinx, so the autodoc step is this self-contained
generator: it introspects the public surface (signatures + docstrings, the
same inputs sphinx.ext.autodoc consumes) and emits deterministic markdown.
CI runs it with --check so the committed reference can never drift from the
code; the docs-deploy workflow publishes docs/ to Pages.

Usage: python tools/gen_api_docs.py [--check]
"""

import argparse
import dataclasses
import enum
import inspect
import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "api_reference.md")

# (module, [public names]; None = every public callable/class in __all__ or
# module order). Curated so the page reads top-down like the reference's
# api.rst rather than alphabetically.
SURFACE = [
    ("infinistore_tpu.config", ["ClientConfig", "ServerConfig"]),
    ("infinistore_tpu.lib", [
        "InfinityConnection", "StripedConnection", "LocalServer",
        "start_local_server", "register_server", "unregister_server",
        "InfiniStoreException", "InfiniStoreKeyNotFound", "InfiniStoreNoMatch",
        "Logger",
    ]),
    ("infinistore_tpu.connector", ["KVConnector", "token_chain_hashes"]),
    ("infinistore_tpu.engine", [
        "EngineKVAdapter", "ContinuousBatchingHarness", "BlockPool",
        "WaveDecoder", "DeviceGate", "RequestStats", "WaveCounters",
        "wave_counters", "reset_wave_counters",
    ]),
    ("infinistore_tpu.cluster", [
        "ClusterKVConnector", "rendezvous_owner", "rendezvous_ranked",
        "CircuitBreaker",
    ]),
    ("infinistore_tpu.membership", [
        "MemberState", "MembershipView", "Membership", "Resharder",
        "DurableLog",
    ]),
    ("infinistore_tpu.tiering", [
        "TemperatureSketch", "TierPolicyConfig", "TierPolicy", "TierManager",
        "note_demotion_hit", "demotion_hits", "note_cold_read_us",
    ]),
    ("infinistore_tpu.faults", [
        "FaultRule", "FaultyConnection", "kill_transport", "crash_process",
    ]),
    ("infinistore_tpu.tracing", [
        "configure", "enabled", "recorder", "Span", "FlightRecorder",
        "trace_op", "start_span", "use_span", "active_span",
        "server_tick_spans", "chrome_trace_events", "stage_breakdown",
    ]),
    ("infinistore_tpu.telemetry", [
        "EventJournal", "SloObjective", "SloEngine", "FleetScraper",
        "GossipAgent", "MetricsHistory",
        "default_objectives", "cluster_spans", "cluster_chrome_events",
        "get_journal", "emit", "slo_engine", "configure_slo",
        "note_qos_aged", "metrics_http_source", "scraper_source",
        "parse_metrics_text",
    ]),
    ("infinistore_tpu.profiling", [
        "SamplingProfiler", "configure", "enabled", "profiler",
    ]),
    ("infinistore_tpu.vllm_v1", [
        "KVConnectorRole",
        "KVConnectorBase_V1",
        "InfiniStoreKVConnectorV1",
        "InfiniStoreConnectorMetadata",
    ]),
    ("infinistore_tpu.loadgen", [
        "TraceRequest", "Trace", "generate", "preset", "replay",
    ]),
    ("infinistore_tpu.disagg", [
        "DisaggCounters", "DisaggHarness", "counters", "reset_counters",
        "demo_config", "demo_prompt", "stream_prefill", "overlapped_decode",
        "local_decode",
    ]),
    ("infinistore_tpu.tpu.paged", None),
    ("infinistore_tpu.tpu.paged_attention", None),
    ("infinistore_tpu.tpu.flash_prefill", None),
    ("infinistore_tpu.tpu.kv_quant", [
        "quantize_kv", "dequantize_kv", "paged_decode_attention_quantized",
        "QuantizedKVConnector", "QuantizingKVAdapter",
    ]),
    ("infinistore_tpu.tpu.staging", None),
    ("infinistore_tpu.tpu.layerwise", None),
    ("infinistore_tpu.tpu.ici", None),
    ("infinistore_tpu.shaping", None),
    ("infinistore_tpu.models", None),
    ("infinistore_tpu.models.pipeline", None),
    ("infinistore_tpu.models.ring_attention", None),
    ("infinistore_tpu.models.long_context", None),
    ("infinistore_tpu.models.ulysses", None),
]


def _doc(obj) -> str:
    d = inspect.getdoc(obj)
    return d.strip() if d else "*(undocumented)*"


def _sig(obj) -> str:
    # Enum constructor signatures are a CPython implementation detail that
    # changed across 3.10 -> 3.12 ("(value, names=None, ...)" vs
    # "(*values)"); rendering one would make --check depend on the
    # interpreter that generated the file. Members are the actual surface.
    if inspect.isclass(obj) and issubclass(obj, enum.Enum):
        return "(" + ", ".join(m.name for m in obj) + ")"
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def _render_class(name, cls, out):
    out.append(f"### `{name}{_sig(cls) if not dataclasses.is_dataclass(cls) else ''}`\n")
    out.append(_doc(cls) + "\n")
    if dataclasses.is_dataclass(cls):
        out.append("| field | default |\n|---|---|")
        for f in dataclasses.fields(cls):
            default = (
                f.default if f.default is not dataclasses.MISSING
                else ("(factory)" if f.default_factory is not dataclasses.MISSING
                      else "(required)")
            )
            out.append(f"| `{f.name}` | `{default!r}` |")
        out.append("")
    methods = [
        (n, m) for n, m in inspect.getmembers(cls, inspect.isfunction)
        if not n.startswith("_") and n in cls.__dict__
    ]
    # Preserve definition order (autodoc default), not getmembers' sort.
    order = {n: i for i, n in enumerate(cls.__dict__)}
    for n, m in sorted(methods, key=lambda kv: order.get(kv[0], 1 << 30)):
        out.append(f"#### `{name}.{n}{_sig(m)}`\n")
        out.append(textwrap.indent(_doc(m), "") + "\n")


def _render_module(modname, names, out):
    mod = __import__(modname, fromlist=["*"])
    out.append(f"## `{modname}`\n")
    head = (inspect.getdoc(mod) or "").strip().split("\n\n")[0]
    if head:
        out.append(head + "\n")
    if names is None:
        names = getattr(mod, "__all__", None) or [
            n for n, o in vars(mod).items()
            if not n.startswith("_")
            and (inspect.isclass(o) or inspect.isfunction(o))
            # Defined HERE — re-exports would otherwise duplicate their
            # home module's section.
            and getattr(o, "__module__", "") == modname
        ]
    for n in names:
        obj = getattr(mod, n)
        if inspect.isclass(obj):
            _render_class(n, obj, out)
        elif callable(obj):
            out.append(f"### `{n}{_sig(obj)}`\n")
            out.append(_doc(obj) + "\n")


def generate() -> str:
    out = [
        "# API reference (generated)",
        "",
        "<!-- GENERATED by tools/gen_api_docs.py — do not edit. CI enforces"
        " `python tools/gen_api_docs.py --check`. -->",
        "",
        "Introspected from the live package docstrings (the autodoc step;"
        " see docs/api.md for the hand-written guide with examples).",
        "",
    ]
    for modname, names in SURFACE:
        _render_module(modname, names, out)
    return "\n".join(out).rstrip() + "\n"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="fail if docs/api_reference.md is out of date")
    args = ap.parse_args()
    text = generate()
    if args.check:
        on_disk = open(OUT).read() if os.path.exists(OUT) else ""
        if on_disk != text:
            sys.stderr.write(
                "docs/api_reference.md is stale — run python tools/gen_api_docs.py\n"
            )
            return 1
        print("docs/api_reference.md is up to date")
        return 0
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {OUT} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
