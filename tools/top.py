#!/usr/bin/env python
"""``python -m tools.top`` — live fleet dashboard over the manage plane.

One screen over ``GET /metrics`` + ``GET /slo`` + ``GET /events``
(docs/observability.md, fleet section): the SLO verdict and firing
burn-rate alerts, per-objective SLI/burn gauges, per-member scraper rows
(throughput, queue depths, scrape health), breaker states when a cluster
is attached to the manage plane (``GET /membership``), and the tail of
the causal event journal.

Usage:
    python -m tools.top --manage 127.0.0.1:28080             # live (curses)
    python -m tools.top --manage 127.0.0.1:28080 --once      # one frame
    python -m tools.top --manage 127.0.0.1:28080 --plain     # no curses

Stdlib only (urllib + optional curses), like the rest of tools/.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

# Sparkline ramps (8 levels). The unicode blocks need a UTF-8-capable
# terminal; the ASCII ramp is the fallback when the encoding (or curses)
# cannot carry them — same data, coarser glyphs.
_SPARK_UTF8 = " ▁▂▃▄▅▆▇█"
_SPARK_ASCII = " ._-=+*#@"

# Series worth a sparkline column, in display priority order (prefix
# match against the /timeseries index; bounded — a dashboard is not a
# TSDB).
_TREND_PREFIXES = (
    "infinistore_op_p99_latency_us",
    "infinistore_slo_burn_rate_max",
    "infinistore_pool_usage_ratio",
    "infinistore_qos_queued",
    "member_ops_per_s",
)
_TREND_MAX_SERIES = 6
_TREND_WINDOW_S = 120.0


def sparkline(values, width: int = 24, ascii_only: bool = False) -> str:
    """Render ``values`` (oldest first) as a fixed-width sparkline,
    min-max normalized; a flat series renders at mid-level so presence
    is still visible. Empty input -> all-blank bar."""
    ramp = _SPARK_ASCII if ascii_only else _SPARK_UTF8
    if not values:
        return ramp[0] * width
    tail = values[-width:]
    lo, hi = min(tail), max(tail)
    span = hi - lo
    out = []
    for v in tail:
        if span <= 0:
            idx = (len(ramp) - 1) // 2
        else:
            idx = 1 + int((v - lo) / span * (len(ramp) - 2))
        out.append(ramp[min(idx, len(ramp) - 1)])
    return "".join(out).rjust(width, ramp[0])


def _get(base: str, path: str, timeout: float):
    try:
        with urllib.request.urlopen(f"http://{base}{path}", timeout=timeout) as r:
            body = r.read()
    except (urllib.error.URLError, OSError) as e:
        return None, repr(e)
    try:
        return json.loads(body), None
    except ValueError:
        return body.decode(errors="replace"), None


def _metric_families(text: str) -> dict:
    """Flat ``name{labels} -> value`` map from Prometheus exposition text
    (exemplar suffixes, comments and TYPE lines skipped). Deliberate twin
    of ``telemetry.parse_metrics_text`` — tools/ stays stdlib-only with
    no package import; a format change must touch both."""
    out = {}
    if not isinstance(text, str):
        return out
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        # An exemplar suffix (" # {...} v") never appears without the flag,
        # but strip defensively.
        line = line.split(" # ", 1)[0]
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


def _trend_series(base: str, timeout: float) -> dict:
    """``series name -> [values]`` for the sparkline rows, from the manage
    plane's metrics history (``GET /timeseries``; empty when none is
    attached). Bounded: prefix-selected, at most ``_TREND_MAX_SERIES``;
    fetched as ONE batch request (repeated ``metric`` params) so a frame
    costs two /timeseries round trips total, not one per series."""
    index, _ = _get(base, "/timeseries", timeout)
    if not isinstance(index, dict) or not index.get("enabled"):
        return {}
    picked = []
    for prefix in _TREND_PREFIXES:
        picked += [
            n for n in index.get("series", []) if n.startswith(prefix)
        ]
    picked = picked[:_TREND_MAX_SERIES]
    if not picked:
        return {}
    query = "&".join(
        f"metric={urllib.parse.quote(name)}" for name in picked
    )
    doc, _ = _get(
        base,
        f"/timeseries?{query}&window={_TREND_WINDOW_S:g}",
        timeout,
    )
    if not isinstance(doc, dict):
        return {}
    return {
        name: [v for _, v in doc.get("metrics", {}).get(name, [])]
        for name in picked
    }


def snapshot(base: str, timeout: float = 2.0) -> dict:
    """One dashboard frame's raw data."""
    slo, slo_err = _get(base, "/slo", timeout)
    events, _ = _get(base, "/events?limit=12", timeout)
    metrics, _ = _get(base, "/metrics", timeout)
    membership, _ = _get(base, "/membership", timeout)
    return {
        "t": time.strftime("%H:%M:%S"),
        "base": base,
        "error": slo_err,
        "slo": slo if isinstance(slo, dict) else {},
        "events": events if isinstance(events, dict) else {},
        "metrics": _metric_families(metrics),
        "membership": membership if isinstance(membership, dict) else {},
        "trends": _trend_series(base, timeout),
    }


def render(frame: dict, width: int = 100, ascii_only=None) -> list:
    """Plain-text lines for one frame (shared by --plain/--once and the
    curses loop). ``ascii_only=None`` auto-detects from the stdout
    encoding: a terminal that cannot carry the unicode sparkline blocks
    gets the ASCII ramp instead of mojibake."""
    if ascii_only is None:
        ascii_only = not (
            (getattr(sys.stdout, "encoding", "") or "").lower()
            .replace("-", "").startswith("utf")
        )
    lines = []
    slo = frame["slo"]
    verdict = slo.get("verdict", "?")
    sep = " | " if ascii_only else " · "
    lines.append(
        f"infinistore top{sep}{frame['base']}{sep}{frame['t']}{sep}"
        f"verdict={verdict.upper()}"
    )
    if frame["error"]:
        lines.append(f"  manage plane unreachable: {frame['error']}")
        return lines
    lines.append("-" * min(width, 100))

    # SLO gauges + firing alerts.
    lines.append(
        f"SLO  avail={slo.get('slo_availability', 1.0):.6f}  "
        f"fg_p99={slo.get('slo_fg_p99_us', 0.0):.0f}us  "
        f"miss={slo.get('slo_miss_rate', 0.0):.4f}  "
        f"reshard_drain={slo.get('slo_reshard_drain', 1.0):.3f}  "
        f"burn_max={slo.get('slo_burn_rate_max', 0.0):.2f}"
    )
    alerts = slo.get("alerts", [])
    if alerts:
        for a in alerts:
            lines.append(
                f"  ALERT {a['objective']}: burn {a['burn_short']:.1f}x/"
                f"{int(a['short_window_s'])}s {a['burn_long']:.1f}x/"
                f"{int(a['long_window_s'])}s (>= {a['threshold']}x)"
            )
    else:
        lines.append("  no burn-rate alerts firing")

    # Per-member scraper rows.
    members = slo.get("scraper", {}).get("members", [])
    if members:
        lines.append(
            f"{'MEMBER':<22}{'OPS/S':>8}{'QUEUE':>7}{'AGE':>7}"
            f"{'SCRAPES':>9}{'FAILS':>7}  STATE"
        )
        for m in members:
            state = "ok" if m["ok"] else f"skip({m['consecutive_failures']})"
            age = m["last_scrape_age_s"]
            lines.append(
                f"{m['member']:<22}{m['ops_per_s']:>8.1f}"
                f"{m['queue_depth']:>7}{(f'{age:.1f}s' if age >= 0 else '-'):>7}"
                f"{m['scrapes']:>9}{m['failures']:>7}  {state}"
            )
    # Breaker states from the cluster's manage surface, when attached.
    ms = frame["membership"]
    if ms.get("enabled"):
        pairs = ", ".join(
            f"{m['member_id']}:{m['state']}" for m in ms.get("members", [])
        )
        lines.append(
            f"membership epoch={ms.get('membership_epoch', '?')} "
            f"settled={ms.get('membership_settled', '?')} "
            f"debt={ms.get('reshard_debt_roots', 0)} [{pairs}]"
        )

    # Local process gauges from /metrics.
    fam = frame["metrics"]
    if fam:
        kv = fam.get("infinistore_kvmap_entries")
        usage = fam.get("infinistore_pool_usage_ratio")
        fgq = fam.get('infinistore_qos_queued{class="fg"}')
        bgq = fam.get('infinistore_qos_queued{class="bg"}')
        bits = []
        if kv is not None:
            bits.append(f"kvmap={kv:.0f}")
        if usage is not None:
            bits.append(f"pool={100 * usage:.1f}%")
        if fgq is not None or bgq is not None:
            bits.append(f"queued fg={fgq or 0:.0f} bg={bgq or 0:.0f}")
        if bits:
            lines.append("local " + "  ".join(bits))
        # Descriptor-ring data plane (docs/descriptor_ring.md): live depth,
        # lifetime descriptor volume, and the doorbell coalescing ratio
        # (descriptors per rx doorbell — high is good: posts were pure
        # shared memory while the server stayed awake).
        # Tiered capacity plane (docs/tiering.md): per-tier bytes (RAM
        # pool + local spill from the local server's gauges, cold-root
        # count from the cluster plane), hit ratios across ram / cold /
        # demotion-hit / miss outcomes, movement totals, and the two
        # backlogs (demote = idle roots awaiting shipment, promote =
        # admitted cold hits awaiting copy-back).
        tcold = fam.get("infinistore_tier_cold_members")
        if tcold is not None:
            ram_b = fam.get('infinistore_pool_bytes{kind="used"}', 0)
            spill_b = fam.get('infinistore_spill_bytes{kind="used"}', 0)
            hits_ram = fam.get('infinistore_tier_hits{tier="ram"}', 0)
            hits_cold = fam.get('infinistore_tier_hits{tier="cold"}', 0)
            hits_dem = fam.get('infinistore_tier_hits{tier="demotion"}', 0)
            miss = fam.get("infinistore_tier_misses", 0)
            total = hits_ram + hits_cold + hits_dem + miss
            ratio = (
                f"ram {100 * hits_ram / total:.0f}% cold "
                f"{100 * hits_cold / total:.0f}% miss "
                f"{100 * miss / total:.0f}%" if total else "-"
            )
            lines.append(
                f"tiers cold_members={tcold:.0f}  "
                f"ram={ram_b / (1 << 20):.1f}MB spill={spill_b / (1 << 20):.1f}MB "
                f"cold_roots={fam.get('infinistore_tier_cold_roots', 0):.0f}  "
                f"hits [{ratio}]  "
                f"demote={fam.get('infinistore_tier_demotions', 0):.0f}"
                f"(bl={fam.get('infinistore_tier_demote_backlog', 0):.0f})  "
                f"promote={fam.get('infinistore_tier_promotions', 0):.0f}"
                f"(bl={fam.get('infinistore_tier_promote_backlog', 0):.0f})  "
                f"cold_p99={fam.get('infinistore_tier_cold_read_p99_us', 0):.0f}us"
            )
        rconns = fam.get("infinistore_ring_conns")
        if rconns:
            descs = fam.get("infinistore_ring_descriptors", 0)
            db_rx = fam.get('infinistore_ring_doorbells{dir="rx"}', 0)
            db_tx = fam.get('infinistore_ring_doorbells{dir="tx"}', 0)
            bad = fam.get("infinistore_ring_bad_descriptors", 0)
            torn = fam.get("infinistore_ring_torn_descriptors", 0)
            coalesce = f"{descs / db_rx:.1f}" if db_rx else "-"
            # Batch-slot + adaptive-poll mechanism counters (PR 16): ops
            # per multi-op slot (high = flushes coalescing well), poll
            # windows that caught work vs parked, and doorbells the server
            # skipped because the client was awake polling.
            bslots = fam.get("infinistore_ring_batch_slots", 0)
            bops = fam.get("infinistore_ring_batch_ops", 0)
            ops_per_slot = f"{bops / bslots:.1f}" if bslots else "-"
            phits = fam.get("infinistore_ring_poll_hits", 0)
            parms = fam.get("infinistore_ring_poll_arms", 0)
            elided = fam.get("infinistore_ring_doorbell_elided", 0)
            lines.append(
                f"ring  conns={rconns:.0f}  "
                f"sq_depth={fam.get('infinistore_ring_sq_depth', 0):.0f}  "
                f"pending={fam.get('infinistore_ring_pending', 0):.0f}  "
                f"descs={descs:.0f}  db rx={db_rx:.0f} tx={db_tx:.0f}  "
                f"descs/db={coalesce}  bad={bad:.0f} torn={torn:.0f}"
            )
            lines.append(
                f"      batch slots={bslots:.0f} ops={bops:.0f} "
                f"ops/slot={ops_per_slot}  "
                f"poll hit={phits:.0f} arm={parms:.0f}  "
                f"db_elided={elided:.0f}"
            )

    # Metrics-history sparklines (docs/observability.md, time-series
    # section): last-2-minutes trend per selected series, burn-rate
    # included — the "when did it move" column the one-shot gauges above
    # cannot show. Absent (no history attached) the section is omitted.
    trends = frame.get("trends", {})
    if trends:
        lines.append(f"TRENDS (last {_TREND_WINDOW_S:.0f}s)")
        for name, values in trends.items():
            spark = sparkline(values, width=24, ascii_only=ascii_only)
            last = f"{values[-1]:.6g}" if values else "-"
            lines.append(f"  {name[:52]:<52} {spark} {last}"[:width])

    # Event journal tail.
    events = frame["events"].get("events", [])
    lines.append("-" * min(width, 100))
    lines.append(f"EVENTS (last {len(events)} of {frame['events'].get('emitted', 0)})")
    for e in events:
        trace = f" trace={e['trace_id']:#x}" if e.get("trace_id") else ""
        member = f" member={e['member']}" if e.get("member") else ""
        epoch = f" epoch={e['epoch']}" if e.get("epoch") else ""
        attrs = ""
        if e.get("attrs"):
            attrs = " " + " ".join(f"{k}={v}" for k, v in e["attrs"].items())
        lines.append(
            f"  #{e['seq']:<5} {e['kind']:<18}{member}{epoch}{trace}{attrs}"[:width]
        )
    return lines


def _curses_loop(base: str, interval: float, ascii_only=None):
    import curses

    def loop(stdscr):
        curses.curs_set(0)
        stdscr.nodelay(True)
        while True:
            frame = snapshot(base)
            stdscr.erase()
            h, w = stdscr.getmaxyx()
            for i, line in enumerate(
                render(frame, width=w - 1, ascii_only=ascii_only)[: h - 1]
            ):
                try:
                    stdscr.addstr(i, 0, line[: w - 1])
                except curses.error:
                    pass
            footer_sep = " | " if ascii_only else " · "
            stdscr.addstr(
                h - 1, 0, f"q to quit{footer_sep}refresh every "
                f"{interval:g}s"[: w - 1]
            )
            stdscr.refresh()
            t0 = time.time()
            while time.time() - t0 < interval:
                ch = stdscr.getch()
                if ch in (ord("q"), 27):
                    return
                time.sleep(0.05)

    curses.wrapper(loop)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.top",
        description="live fleet dashboard over /metrics + /slo + /events",
    )
    parser.add_argument(
        "--manage", default="127.0.0.1:28080",
        help="manage-plane host:port (default 127.0.0.1:28080)",
    )
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="print one plain-text frame and exit")
    parser.add_argument("--plain", action="store_true",
                        help="plain-text loop (no curses)")
    parser.add_argument("--ascii", action="store_true",
                        help="force the ASCII sparkline ramp (default: "
                             "auto-detect from the stdout encoding)")
    args = parser.parse_args(argv)
    ascii_only = True if args.ascii else None

    if args.once:
        print("\n".join(render(snapshot(args.manage), ascii_only=ascii_only)))
        return 0
    if args.plain or not sys.stdout.isatty():
        try:
            while True:
                print(
                    "\n".join(
                        render(snapshot(args.manage), ascii_only=ascii_only)
                    ),
                    flush=True,
                )
                print()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
    try:
        _curses_loop(args.manage, args.interval, ascii_only=ascii_only)
    except ImportError:
        # No curses on this host: the plain loop renders the same frames
        # — with the ASCII ramp, since a curses-less environment rarely
        # guarantees a UTF-8-capable terminal either.
        print("curses unavailable; falling back to --plain --ascii",
              file=sys.stderr)
        return main([*(argv or sys.argv[1:]), "--plain", "--ascii"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
