"""Fleet-of-server-subprocesses spawner, shared by the bench telemetry leg
and tests/test_telemetry.py: both drive the same N-process fleet (real
server subprocesses with their own manage planes), and the spawn argv +
readiness protocol must not diverge between them."""

import socket
import subprocess
import sys
import time
import urllib.request


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_fleet_servers(n: int = 2, timeout_s: float = 20.0):
    """``n`` REAL server subprocesses (own manage planes), ready to serve:
    the service socket accepts and ``GET /health`` answers. Returns
    ``[{"service_port", "manage_port", "proc"}]``; on a readiness timeout
    every spawned process is killed and RuntimeError raised."""
    members = []
    for _ in range(n):
        service_port, manage_port = free_port(), free_port()
        proc = subprocess.Popen([
            sys.executable, "-m", "infinistore_tpu.server",
            "--host", "127.0.0.1",
            "--service-port", str(service_port),
            "--manage-port", str(manage_port),
            "--prealloc-size", "1", "--minimal-allocate-size", "16",
            "--no-pin-memory", "--log-level", "error",
        ])
        members.append({
            "service_port": service_port, "manage_port": manage_port,
            "proc": proc,
        })
    deadline = time.time() + timeout_s
    pending = list(members)
    while pending and time.time() < deadline:
        m = pending[0]
        try:
            with socket.create_connection(
                ("127.0.0.1", m["service_port"]), timeout=0.3
            ):
                pass
            urllib.request.urlopen(
                f"http://127.0.0.1:{m['manage_port']}/health", timeout=0.5
            )
            pending.pop(0)
        except OSError:
            time.sleep(0.1)
    if pending:
        for m in members:
            m["proc"].kill()
        raise RuntimeError("fleet servers did not come up")
    return members
