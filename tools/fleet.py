"""Fleet-of-subprocesses harness, shared by the bench legs and tests.

Two populations, one spawn/readiness/kill/restart protocol:

- **server members** (``spawn_fleet_servers``): real
  ``python -m infinistore_tpu.server`` store processes with their own
  manage planes (the PR 8 two-subprocess pattern — bench telemetry leg +
  tests/test_telemetry.py drive the same argv).
- **client members** (``spawn_fleet_client``): real
  ``python -m infinistore_tpu.fleet_client`` cluster-client processes —
  each owning a ``ClusterKVConnector`` with a durable journal, a manage
  plane, and a gossip agent. The crash-recovery bench leg and
  tests (docs/membership.md) kill these with ``kill -9`` mid-reshard and
  restart them **with the same argv** (``restart_member``), which is the
  whole point: a member dict remembers its ``argv``, so a restart is a
  faithful crash-recovery, not a reconfiguration.

Every member dict carries ``{"argv", "proc", ...ports}``; ``kill_member``
is SIGKILL (no shutdown handlers — the crash the durable journal exists
to survive), ``restart_member`` re-Popens the recorded argv and waits for
the member's own readiness probe.
"""

import json
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# Manage-plane HTTP helpers (bench + tests poll membership/health/events).
# ---------------------------------------------------------------------------


def manage_json(port: int, path: str, timeout_s: float = 2.0) -> dict:
    """GET a manage-plane JSON endpoint on 127.0.0.1:``port``."""
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout_s
    ) as resp:
        return json.loads(resp.read(8 << 20))


def manage_post_json(port: int, path: str, payload: dict,
                     timeout_s: float = 10.0) -> dict:
    """POST JSON to a manage-plane endpoint; returns the parsed body
    (structured error bodies included — callers read ``reason``/``epoch``
    instead of matching prose)."""
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read(8 << 20))
    except urllib.error.HTTPError as e:
        return json.loads(e.read() or b"{}")


def wait_manage(port: int, path: str = "/health", timeout_s: float = 30.0,
                predicate=None, proc=None) -> dict:
    """Poll a manage endpoint until it answers (and ``predicate(doc)``
    holds, when given). Fails fast when ``proc`` exits first — a crashed
    member must raise, not eat the whole timeout."""
    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"member exited (rc={proc.returncode}) while waiting for "
                f"{path}"
            )
        try:
            doc = manage_json(port, path, timeout_s=1.0)
            if predicate is None or predicate(doc):
                return doc
            last = doc
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    raise RuntimeError(
        f"manage endpoint {path} on :{port} not ready in {timeout_s}s "
        f"(last: {str(last)[:200]})"
    )


# ---------------------------------------------------------------------------
# Server members.
# ---------------------------------------------------------------------------


def _server_argv(service_port: int, manage_port: int):
    return [
        sys.executable, "-m", "infinistore_tpu.server",
        "--host", "127.0.0.1",
        "--service-port", str(service_port),
        "--manage-port", str(manage_port),
        "--prealloc-size", "1", "--minimal-allocate-size", "16",
        "--no-pin-memory", "--log-level", "error",
    ]


def _wait_server_ready(member: dict, timeout_s: float):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with socket.create_connection(
                ("127.0.0.1", member["service_port"]), timeout=0.3
            ):
                pass
            urllib.request.urlopen(
                f"http://127.0.0.1:{member['manage_port']}/health",
                timeout=0.5,
            )
            return
        except OSError:
            time.sleep(0.1)
    raise RuntimeError("server member did not come up")


def spawn_fleet_servers(n: int = 2, timeout_s: float = 20.0):
    """``n`` REAL server subprocesses (own manage planes), ready to serve:
    the service socket accepts and ``GET /health`` answers. Returns
    ``[{"service_port", "manage_port", "proc", "argv"}]``; on a readiness
    timeout every spawned process is killed and RuntimeError raised."""
    members = []
    for _ in range(n):
        service_port, manage_port = free_port(), free_port()
        argv = _server_argv(service_port, manage_port)
        members.append({
            "service_port": service_port, "manage_port": manage_port,
            "proc": subprocess.Popen(argv), "argv": argv,
        })
    try:
        for m in members:
            _wait_server_ready(m, timeout_s)
    except RuntimeError:
        for m in members:
            m["proc"].kill()
        raise
    return members


# ---------------------------------------------------------------------------
# Disaggregated prefill engine (infinistore_tpu.disagg subprocess).
# ---------------------------------------------------------------------------


def spawn_disagg_prefill(port: int, **kw):
    """One prefill-ENGINE subprocess (``python -m infinistore_tpu.disagg``,
    one-shot mode), stdout piped: it prints ``shipped layer N`` as each
    layer's KV becomes durable in the store at ``port`` and ``prefill done
    wrote=...`` at the end. The chaos test reads the per-layer markers to
    know how far the handoff got, then ``kill_member``s it mid-stream;
    ``kw`` passes through to ``disagg.prefill_argv`` (``stall_after_layer``
    / ``stall_s`` hold the window open). Returns the usual member dict."""
    from infinistore_tpu import disagg

    argv = disagg.prefill_argv(port, **kw)
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE, text=True)
    return {"service_port": port, "proc": proc, "argv": argv}


def read_until_marker(member: dict, marker: str, timeout_s: float = 120.0):
    """Read the member's piped stdout line by line until ``marker`` is a
    substring; returns the matching line. The caller owns the deadline
    semantics (a dead process raises RuntimeError — its stream EOFs)."""
    return read_until_markers(member, [marker], timeout_s=timeout_s)[marker]


def read_until_markers(
    member: dict, markers, timeout_s: float = 120.0
) -> dict:
    """Read piped stdout until EVERY marker in ``markers`` has appeared,
    in ANY order; returns ``{marker: matching line}``. The order-free
    contract matters for durability gating: ``stream_prefill`` ships
    layers concurrently (``max_inflight_ships``), so ``shipped layer 1``
    can legally print before ``shipped layer 0`` under load — a caller
    that waits for the LAST marker alone can act while an earlier
    layer's puts are still in flight."""
    want = {m: None for m in markers}
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = member["proc"].stdout.readline()
        if not line:
            raise RuntimeError(
                f"stdout EOF before markers {list(want)!r} "
                f"(exit={member['proc'].poll()})"
            )
        for m in want:
            if want[m] is None and m in line:
                want[m] = line.strip()
        if all(v is not None for v in want.values()):
            return want
    missing = [m for m, v in want.items() if v is None]
    raise RuntimeError(f"timeout waiting for markers {missing!r}")


# ---------------------------------------------------------------------------
# Client members (infinistore_tpu.fleet_client subprocesses).
# ---------------------------------------------------------------------------


def client_argv(
    manage_port: int,
    stores=(),
    journal: str = "",
    peers=(),
    seed: int = 23,
    roots: int = 0,
    replicas: int = 2,
    gossip_interval_s: float = 0.25,
    crash_after_moved: int = 0,
    bootstrap: bool = False,
    verify: bool = False,
    reshard_batch_bytes: int = 0,
):
    """The fleet-client argv (one place — restart_member replays it
    verbatim, which is what makes a restart a crash-recovery)."""
    argv = [
        sys.executable, "-m", "infinistore_tpu.fleet_client",
        "--manage-port", str(manage_port),
        "--seed", str(seed),
        "--roots", str(roots),
        "--replicas", str(replicas),
        "--gossip-interval", str(gossip_interval_s),
    ]
    if stores:
        argv += ["--stores", ",".join(stores)]
    if journal:
        argv += ["--journal", journal]
    if peers:
        argv += ["--peers", ",".join(peers)]
    if crash_after_moved:
        argv += ["--crash-after-moved", str(crash_after_moved)]
    if reshard_batch_bytes:
        argv += ["--reshard-batch-bytes", str(reshard_batch_bytes)]
    if bootstrap:
        argv += ["--bootstrap"]
    if verify:
        argv += ["--verify"]
    return argv


def spawn_fleet_client(manage_port: int = 0, wait_ready: bool = True,
                       timeout_s: float = 60.0, capture: bool = False,
                       **kw):
    """One cluster-client subprocess. ``capture=True`` pipes stdout (the
    ``--verify`` report is a single JSON line). Returns
    ``{"manage_port", "proc", "argv"}``; with ``wait_ready`` the member's
    ``GET /membership`` must answer before this returns."""
    manage_port = manage_port or free_port()
    argv = client_argv(manage_port, **kw)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE if capture else None,
    )
    member = {"manage_port": manage_port, "proc": proc, "argv": argv}
    if wait_ready:
        try:
            wait_manage(manage_port, "/membership", timeout_s, proc=proc)
        except RuntimeError:
            proc.kill()
            raise
    return member


# ---------------------------------------------------------------------------
# Kill -9 / restart-with-same-argv (the crash-recovery primitives).
# ---------------------------------------------------------------------------


def kill_member(member: dict, timeout_s: float = 10.0) -> int:
    """``kill -9`` a member (server or client): SIGKILL, reaped. No
    shutdown handlers run — the in-memory catalog/view die with the
    process, which is the failure the durable journal exists to survive.
    Returns the (negative-signal) exit code."""
    proc = member["proc"]
    proc.kill()
    proc.wait(timeout=timeout_s)
    return proc.returncode


def wait_member_exit(member: dict, timeout_s: float = 60.0) -> int:
    """Block until a member exits ON ITS OWN (e.g. a scripted
    ``faults.crash_process`` mid-reshard); returns the exit code
    (``-9`` for a SIGKILL self-crash)."""
    return member["proc"].wait(timeout=timeout_s)


def restart_member(member: dict, timeout_s: float = 60.0,
                   ready: str = "auto"):
    """Restart a dead member **with the same argv** it was first spawned
    with — crash recovery, not reconfiguration: a fleet client re-reads
    its durable journal and resumes; a server re-binds its ports. The
    member dict is updated in place (fresh ``proc``) and returned.
    ``ready``: ``"auto"`` picks the member's own readiness probe
    (``/membership`` for clients, service socket + ``/health`` for
    servers), ``None`` skips waiting."""
    if member["proc"].poll() is None:
        raise RuntimeError("member still running — kill_member first")
    member["proc"] = subprocess.Popen(member["argv"])
    if ready == "auto":
        if "service_port" in member:
            _wait_server_ready(member, timeout_s)
        else:
            wait_manage(member["manage_port"], "/membership", timeout_s,
                        proc=member["proc"])
    return member


def stop_members(members, grace_s: float = 5.0):
    """Best-effort teardown for any member list (SIGINT, then SIGKILL)."""
    for m in members:
        if m["proc"].poll() is None:
            try:
                m["proc"].send_signal(2)
            except OSError:
                pass
    for m in members:
        try:
            m["proc"].wait(timeout=grace_s)
        except Exception:
            m["proc"].kill()
