#!/usr/bin/env python3
"""Emit a compile_commands.json for the native tree.

clang-tidy (and clangd) need a compilation database; meson/cmake generate
one for free but our native build is a plain Makefile, and `bear` is not
in the toolchain. The Makefile invokes this script with ITS OWN $(CXX) /
$(CXXFLAGS), so the database can never drift from the real build line:

    make -C native compile_commands.json

Usage: gen_compile_commands.py --cxx g++ --flags "-O3 ..." --dir DIR \
           --out compile_commands.json src/a.cpp src/b.cpp ...
"""

import argparse
import json
import os


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--cxx", required=True)
    parser.add_argument("--flags", required=True)
    parser.add_argument("--dir", default=os.getcwd())
    parser.add_argument("--out", required=True)
    parser.add_argument("sources", nargs="+")
    args = parser.parse_args()

    directory = os.path.abspath(args.dir)
    db = [
        {
            "directory": directory,
            "command": f"{args.cxx} {args.flags} -c {src} -o {os.path.splitext(src)[0]}.o",
            "file": src,
        }
        for src in args.sources
    ]
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(db, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out} ({len(db)} entries)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
