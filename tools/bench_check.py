#!/usr/bin/env python
"""Data-plane regression gate over a BENCH receipt.

Reads one or more bench JSON files and exits non-zero when a known
regression signature is present. The founding check is the striping
inversion BENCH_r05 shipped (striped_4_gbps = 3.14 < striped_1_gbps = 5.03:
a 4-stripe transfer LOSING to one stream, the head-of-line failure the
adaptive work-stealing scheduler + same-host auto-collapse eliminate) —
wired here so it can never silently return. Further checks guard the other
data-plane invariants the striped PR established.

Accepted inputs, per file:
  - raw ``bench.py`` output: {"metric": ..., "value": ..., "extra": {...}}
  - a driver receipt: {"cmd": ..., "rc": ..., "tail": "..."} where ``tail``
    is the (possibly TRUNCATED, mid-JSON) last bytes of the bench output —
    metrics are recovered by key-value scan, so a clipped head is fine.

Usage:
    python tools/bench_check.py BENCH.json [MORE.json ...]
    python bench.py --check BENCH.json      # same gate, wired into the bench

Exit status: 0 = every applicable check passed on every file; 1 = at least
one check failed; 2 = no usable metrics found (an empty receipt must not
masquerade as a passing one).
"""

import argparse
import json
import re
import sys

# "key": number — tolerant of truncated receipts (driver tails start
# mid-object); booleans/strings are ignored, last occurrence wins.
_NUM_RE = re.compile(r'"([A-Za-z0-9_]+)"\s*:\s*(-?[0-9]+(?:\.[0-9]+)?)')


def extract_metrics(text: str) -> dict:
    """Recover flat numeric metrics from a bench receipt in any of its
    shapes (raw output, driver wrapper, truncated tail)."""
    metrics = {}
    try:
        doc = json.loads(text)
    except (ValueError, TypeError):
        doc = None
    if isinstance(doc, dict):
        # Driver wrapper: the real payload hides in "tail"/"parsed".
        for inner in (doc.get("parsed"), doc.get("tail")):
            if isinstance(inner, dict):
                doc.update(inner)
            elif isinstance(inner, str):
                text = text + "\n" + inner
    for key, val in _NUM_RE.findall(text):
        metrics[key] = float(val)
    return metrics


class Check:
    """One named invariant over the metric dict; not-applicable (missing
    keys) is reported but never fails — receipts predating a metric must
    stay checkable for the metrics they do carry."""

    def __init__(self, name, keys, predicate, describe):
        self.name = name
        self.keys = keys
        self.predicate = predicate
        self.describe = describe

    def run(self, m: dict):
        if any(k not in m for k in self.keys):
            missing = [k for k in self.keys if k not in m]
            return None, f"skipped (missing {', '.join(missing)})"
        return self.predicate(m), self.describe(m)


CHECKS = [
    # Threshold calibration: the same-host auto-collapse makes striped_4
    # structurally EQUAL to striped_1 (both run the single-stream memcpy
    # path), so the honest ratio is ~1.0 plus measurement weather — and a
    # strict >= gate flakes whenever weather dips a reading below parity.
    # Same-day A/B vs a clean pre-profiling-PR HEAD worktree measured
    # 0.985-1.022 on HEAD and 0.895-1.023 on the candidate tree (equal
    # spreads, both sides of 1.0 — the gate sat ON the line; a prior
    # session saw 0.999 once with 1.005-1.034 on re-runs). 0.95 clears
    # that scatter while the inversion this gate exists for — the r05
    # head-of-line failure — read 0.62, and any real scheduler regression
    # costs tens of percent.
    Check(
        "striping_inversion",
        ["striped_4_gbps", "striped_1_gbps"],
        lambda m: m["striped_4_gbps"] >= 0.95 * m["striped_1_gbps"],
        lambda m: (
            f"striped_4={m['striped_4_gbps']:.3f} GB/s vs "
            f"striped_1={m['striped_1_gbps']:.3f} GB/s "
            "(4 stripes must never lose to one stream; >= 0.95x parity, "
            "r05 inversion read 0.62x)"
        ),
    ),
    Check(
        "shaped_striping_scaling",
        ["shaped_striped_4_mbps", "shaped_striped_1_mbps"],
        lambda m: m["shaped_striped_4_mbps"] >= 2.0 * m["shaped_striped_1_mbps"],
        lambda m: (
            f"shaped 4-stripe {m['shaped_striped_4_mbps']:.1f} MB/s vs "
            f"1-stripe {m['shaped_striped_1_mbps']:.1f} MB/s "
            "(bandwidth-capped stripes must scale >= 2x)"
        ),
    ),
    # Threshold calibration: the async/sync ratio's structural floor is
    # (sync + eventfd loop wake) / sync ~= 1.6x on this box, and the
    # measured history swings with host weather — r03 2.64x, r04 1.69x,
    # r05 1.27x. 3.0x sits just above the worst honest measurement ever
    # recorded while still catching the pathological regressions this gate
    # exists for (e.g. falling back to a per-op call_soon_threadsafe hop,
    # historically 3-5x).
    # The self-healing invariant is binary, not a threshold: with R=2 over 3
    # members a single node death must cost ZERO availability (every read is
    # correct bytes from the replica or a typed miss) and ZERO wrong-data
    # reads. Any other value means failover served lies or nothing.
    Check(
        "chaos_availability",
        ["chaos_availability", "chaos_wrong_reads"],
        lambda m: m["chaos_availability"] >= 1.0 and m["chaos_wrong_reads"] == 0,
        lambda m: (
            f"availability={m['chaos_availability']:.4f}, "
            f"wrong_reads={m['chaos_wrong_reads']:.0f} under a member kill "
            "(must be 1.0 / 0 with R=2 replication)"
        ),
    ),
    # Breaker recovery: a restarted member must be re-admitted by a
    # half-open probe, and promptly (probe backoff caps at 0.4s in the
    # chaos leg; 5s leaves room for restart-bind retries + host weather).
    # -1 means the member never recovered at all.
    Check(
        "chaos_breaker_recovery",
        ["chaos_breaker_recovery_ms"],
        lambda m: 0 <= m["chaos_breaker_recovery_ms"] <= 5000,
        lambda m: (
            f"breaker re-closed {m['chaos_breaker_recovery_ms']:.0f}ms after "
            "restart (must be within one probe window; gate at 5s)"
        ),
    ),
    # Elastic membership churn (docs/membership.md): a live JOIN and a
    # member DEATH mid-workload. Binary like the chaos gate: epoch-aware
    # read failover must hold availability at 1.0 with ZERO wrong reads
    # AND ZERO misses across every sweep (including the mid-reshard
    # ones). Misses are gated separately from the availability ratio —
    # (reads-wrong)/reads stays 1.0 even if every read degrades to a
    # miss, and "failover quietly turned the cache off mid-reshard" is
    # exactly the regression this leg exists to catch (with R=2 every
    # root survives both churn events, so a miss is never legitimate
    # here).
    Check(
        "churn_availability",
        ["churn_availability", "churn_wrong_reads", "churn_misses"],
        lambda m: (
            m["churn_availability"] >= 1.0
            and m["churn_wrong_reads"] == 0
            and m["churn_misses"] == 0
        ),
        lambda m: (
            f"availability={m['churn_availability']:.4f}, "
            f"wrong_reads={m['churn_wrong_reads']:.0f}, "
            f"misses={m['churn_misses']:.0f} under membership churn "
            "(must be 1.0 / 0 / 0 with epoch-aware read failover)"
        ),
    ),
    # The rendezvous-delta property: a join must move ONLY the roots whose
    # top-R placement gained the joiner — measured against the delta
    # fraction computed independently of the resharder (analytic
    # expectation R/(N+1); a full reshuffle or naive-mod remap is ~1.0).
    # 0.10 slack covers roots that legitimately resolve either way during
    # the overlap window (a concurrent re-save landing on the joiner).
    Check(
        "churn_join_delta",
        ["churn_join_moved_fraction", "churn_join_delta_fraction"],
        lambda m: (
            abs(m["churn_join_moved_fraction"] - m["churn_join_delta_fraction"])
            <= 0.10
            and m["churn_join_moved_fraction"] <= 0.80
        ),
        lambda m: (
            f"join moved {100 * m['churn_join_moved_fraction']:.1f}% of roots "
            f"vs rendezvous delta {100 * m['churn_join_delta_fraction']:.1f}% "
            "(only the delta may move; a full reshuffle is ~100%)"
        ),
    ),
    # Bounded migration debt: the reconciler must drain within the
    # workload — leftover debt means the pool never converges to R copies
    # on the new placement.
    Check(
        "churn_migration_debt",
        ["churn_migration_debt"],
        lambda m: m["churn_migration_debt"] == 0,
        lambda m: (
            f"reshard ended with {m['churn_migration_debt']:.0f} unmigrated "
            "roots (debt must drain to 0)"
        ),
    ),
    # QoS two-class isolation (docs/qos.md): with the churn tagged
    # BACKGROUND, the innocent foreground 4KB read's contended p99 must
    # improve by >= 2x over the untagged (FIFO) run — measured history
    # 4.2-6.0x; 2.0 catches the scheduler silently degrading to FIFO while
    # riding out host weather — and the isolation must not be bought by
    # starving the background class: its save throughput gives up <= 20%
    # (measured 14-18%; aging + cooldown tunables set the tradeoff).
    Check(
        "qos_isolation",
        ["qos_isolation_ratio"],
        lambda m: m["qos_isolation_ratio"] >= 2.0,
        lambda m: (
            f"foreground contended p99 improves {m['qos_isolation_ratio']:.2f}x "
            "with QoS on (must be >= 2x)"
        ),
    ),
    # Calibration (2026-08-04): honest history 14-19%, but the same leg on
    # the PRE-tracing HEAD measured 21.0%/20.2% back-to-back that day
    # (host weather — the tracing tree measured 21.2% in the same window,
    # i.e. no change), so 0.20 sat ON the honest distribution and flaked.
    # 0.25 stays far below the pathologies this gate exists for (the
    # polling-gate resume-lag regression alone cost background ~15-23% ON
    # TOP of the steady cost; a scheduler silently starving background
    # shows up as aged-slice starvation and a cost way past 30%).
    Check(
        "qos_bg_cost",
        ["qos_bg_throughput_cost"],
        lambda m: m["qos_bg_throughput_cost"] <= 0.25,
        lambda m: (
            f"background gives up {100 * m['qos_bg_throughput_cost']:.1f}% "
            "throughput under QoS (must be <= 25%)"
        ),
    ),
    # End-to-end tracing (docs/observability.md): the flight-recorder hooks
    # must be effectively free — tracing-on batched-get throughput within
    # 3% of tracing-off (measured ~0.3%; sampled interleaved per the
    # weather rule, min-estimator + bounded noise guard in bench.py) — and
    # the OFF path must be byte-identical on the wire (an untraced op
    # encodes zero trace bytes).
    Check(
        "trace_overhead",
        ["trace_overhead_cost", "trace_wire_identical"],
        lambda m: (
            m["trace_overhead_cost"] <= 0.03 and m["trace_wire_identical"] == 1
        ),
        lambda m: (
            f"tracing-on costs {100 * m['trace_overhead_cost']:.2f}% batched-get "
            f"throughput (must be <= 3%), off-path wire identical="
            f"{m['trace_wire_identical']:.0f} (must be 1)"
        ),
    ),
    # The load-bearing signal is the server-tick JOIN rate: per-span stage
    # fractions sum to 1.0 by construction over WHATEVER stages are
    # present, so a silently broken tick join (empty ring, dropped wire
    # context, clock drift) keeps the sum green while the server-side
    # stages vanish. Gate: >= 90% of the bench's traced gets joined a
    # server tick, the sum stays ~1.0 (clock/producer sanity), and GET
    # /trace actually served Perfetto-loadable events for the ops.
    Check(
        "trace_stage_breakdown",
        ["trace_stage_fraction_sum", "trace_server_join_fraction",
         "trace_endpoint_events"],
        lambda m: (
            abs(m["trace_stage_fraction_sum"] - 1.0) <= 0.02
            and m["trace_server_join_fraction"] >= 0.9
            and m["trace_endpoint_events"] > 0
        ),
        lambda m: (
            f"{100 * m['trace_server_join_fraction']:.0f}% of traced gets "
            f"joined a server tick (must be >= 90%), stage fractions sum to "
            f"{m['trace_stage_fraction_sum']:.4f} (~1.0), /trace served "
            f"{m['trace_endpoint_events']:.0f} Chrome trace events"
        ),
    ),
    # Descriptor-ring data plane (docs/descriptor_ring.md), four gates.
    # The ROADMAP-2 target, raised by the PR 16 batch-slot + adaptive
    # poll-then-park work: the loopback batched leg (which rides the ring)
    # must reach >= 0.90 of the SAME round's measured memcpy ceiling — the
    # paired-round sampling in bench.py keeps numerator and denominator in
    # one weather window, so this is transport quality, not weather.
    Check(
        "ring_ceiling_fraction",
        ["ring_ceiling_fraction"],
        lambda m: m["ring_ceiling_fraction"] >= 0.90,
        lambda m: (
            f"loopback batched leg reaches {m['ring_ceiling_fraction']:.3f} of "
            "the paired memcpy ceiling (must be >= 0.90)"
        ),
    ),
    # Batch-slot coalescing receipts: the K-concurrent-ops flush phase must
    # actually pack multiple ops per descriptor slot (> 1 op/slot — 1.0
    # means every op paid its own descriptor and the multi-op format never
    # engaged), and every op must be accounted for: ring-posted or a
    # COUNTED fallback, nothing silently dropped or silently rerouted.
    Check(
        "ring_batch",
        ["ring_batch_slots", "ring_batch_ops", "ring_batch_ops_per_slot",
         "ring_batch_uncounted"],
        lambda m: (
            m["ring_batch_slots"] >= 1
            and m["ring_batch_ops_per_slot"] > 1.0
            and m["ring_batch_uncounted"] == 0
        ),
        lambda m: (
            f"{m['ring_batch_ops']:.0f} ops over "
            f"{m['ring_batch_slots']:.0f} batch slots = "
            f"{m['ring_batch_ops_per_slot']:.2f} ops/slot (must be > 1), "
            f"{m['ring_batch_uncounted']:.0f} uncounted ops (must be 0)"
        ),
    ),
    # The A/B leg: the ring must never lose to the socket path it replaces.
    # At the copy-dominated batched shape the honest effect is ~1.00-1.02x
    # (the ring removes per-op syscalls + serialize, not the memcpys), and
    # the paired estimator's residual scatter was measured 0.98-1.02
    # run-to-run — 0.95 clears the noise floor while a real structural
    # loss (e.g. ring ops serializing behind each other) reads 0.8 or
    # worse.
    Check(
        "ring_vs_socket",
        ["ring_vs_socket_speedup"],
        lambda m: m["ring_vs_socket_speedup"] >= 0.95,
        lambda m: (
            f"descriptor ring runs {m['ring_vs_socket_speedup']:.3f}x the "
            "socket path on the batched A/B leg (must be >= 0.95)"
        ),
    ),
    # Mechanism receipts: every A/B-leg op actually rode the ring (zero
    # backpressure/oversize fallbacks at this depth — a silent fallback
    # would A/B the socket against itself) and the doorbell discipline
    # coalesced (> 1 descriptor per doorbell frame; 1.0 means every post
    # paid the syscall the ring exists to remove).
    Check(
        "ring_mechanism",
        ["ring_posted", "ring_completions", "ring_full_fallbacks",
         "ring_meta_fallbacks", "ring_doorbell_ratio"],
        lambda m: (
            m["ring_posted"] >= 1
            and m["ring_completions"] == m["ring_posted"]
            and m["ring_full_fallbacks"] == 0
            and m["ring_meta_fallbacks"] == 0
            and m["ring_doorbell_ratio"] > 1.0
        ),
        lambda m: (
            f"{m['ring_posted']:.0f} descriptors posted, "
            f"{m['ring_completions']:.0f} completed, "
            f"{m['ring_full_fallbacks']:.0f}+{m['ring_meta_fallbacks']:.0f} "
            f"fallbacks (must be 0), {m['ring_doorbell_ratio']:.2f} "
            "descriptors/doorbell (must be > 1)"
        ),
    ),
    # The PR 7 receipt attributed ~0.80 of traced batched-get wall time to
    # first_slice->last_slice (the server's sliced copy loop); the ring's
    # adaptive slice quantum must hold the fraction visibly below that.
    Check(
        "ring_stage_shift",
        ["trace_frac_first_slice_to_last_slice", "ring_posted"],
        lambda m: m["trace_frac_first_slice_to_last_slice"] <= 0.79,
        lambda m: (
            "first_slice->last_slice is "
            f"{m['trace_frac_first_slice_to_last_slice']:.4f} of traced "
            "batched-get wall time (must be <= 0.79; PR 7 receipt ~0.80)"
        ),
    ),
    # Fleet telemetry (docs/observability.md, fleet section). Binary gates:
    # the availability burn-rate alert must FIRE during the fault-injected
    # window and be SILENT in the clean run (a false positive teaches
    # operators to delete the alert — silence-when-clean is as load-bearing
    # as firing-when-burning), and the member kill's breaker_open journal
    # event must carry a live trace id (the causal link the journal exists
    # for).
    Check(
        "telemetry_slo_alerts",
        ["telemetry_alert_fired_faulty", "telemetry_alert_fired_clean"],
        lambda m: (
            m["telemetry_alert_fired_faulty"] == 1
            and m["telemetry_alert_fired_clean"] == 0
        ),
        lambda m: (
            f"burn-rate alert fired_faulty="
            f"{m['telemetry_alert_fired_faulty']:.0f} (must be 1), "
            f"fired_clean={m['telemetry_alert_fired_clean']:.0f} "
            "(must be 0: zero false positives)"
        ),
    ),
    Check(
        "telemetry_breaker_link",
        ["telemetry_event_breaker_trace_linked"],
        lambda m: m["telemetry_event_breaker_trace_linked"] >= 1,
        lambda m: (
            f"{m['telemetry_event_breaker_trace_linked']:.0f} breaker_open "
            "event(s) linked to a live trace id (must be >= 1)"
        ),
    ),
    # The cluster trace join: one traced fan-out op's spans must arrive
    # from >= 2 DISTINCT server processes through GET /trace?scope=cluster
    # over real HTTP — the whole point of the fleet scraper.
    Check(
        "telemetry_cluster_trace",
        ["telemetry_cluster_trace_members"],
        lambda m: m["telemetry_cluster_trace_members"] >= 2,
        lambda m: (
            f"{m['telemetry_cluster_trace_members']:.0f} server processes "
            "joined one traced fan-out op (must be >= 2)"
        ),
    ),
    # Scrape+SLO overhead, same discipline as the tracing gate: <= 3% on
    # the batched-get hot path, interleaved paired sampling with the
    # min(median-of-ratios, ratio-of-sums) estimator.
    Check(
        "telemetry_overhead",
        ["telemetry_overhead_cost"],
        lambda m: m["telemetry_overhead_cost"] <= 0.03,
        lambda m: (
            f"fleet scraping costs {100 * m['telemetry_overhead_cost']:.2f}% "
            "batched-get throughput (must be <= 3%)"
        ),
    ),
    # Continuous profiling + metrics history (docs/observability.md,
    # profiling and time-series sections), three gates. Overhead: the
    # 101 Hz sampler plus the metrics history must cost <= 3% of traced
    # batched-get wall time. Composite measurement (see the bench leg's
    # docstring): the sampler — a continuous cost — is A/B'd in
    # order-alternating paired min-filtered rounds, min(median-of-ratios,
    # ratio-of-sums, min-by-field) (the weather rule), bounded by its
    # self-accounted duty cycle; the history — a periodic cost — is its
    # measured pass duration amortized over the production interval.
    Check(
        "prof_overhead",
        ["prof_overhead_cost"],
        lambda m: m["prof_overhead_cost"] <= 0.03,
        lambda m: (
            f"profiler+history cost {100 * m['prof_overhead_cost']:.2f}% "
            "traced batched-get wall time (must be <= 3%, "
            "paired-interleaved)"
        ),
    ),
    # Stage attribution — the ROADMAP-5 scoping receipt: under a traced
    # workload >= 90% of samples must carry a stage-interval tag (the
    # thread->span feed is the whole point of the instrument), and the
    # completion_ring interval must have a frame-level breakdown (the
    # busy-poll-vs-eventfd evidence for the multi-op descriptor work).
    Check(
        "prof_stage_attribution",
        ["prof_stage_tag_fraction", "prof_completion_ring_samples"],
        lambda m: (
            m["prof_stage_tag_fraction"] >= 0.9
            and m["prof_completion_ring_samples"] >= 1
        ),
        lambda m: (
            f"{100 * m['prof_stage_tag_fraction']:.1f}% of samples carry a "
            "stage tag (must be >= 90%), "
            f"{m['prof_completion_ring_samples']:.0f} completion_ring "
            "interval sample(s) broken down by frame (must be >= 1)"
        ),
    ),
    # The anomaly journal's A-B discipline: an injected latency step must
    # fire EXACTLY ONE journaled metric_anomaly (edge-triggering works),
    # and the clean run must fire ZERO (a detector that false-fires on
    # noise teaches operators to delete the alert — silence-when-clean is
    # as load-bearing as firing-on-step).
    Check(
        "timeseries_anomaly",
        ["timeseries_anomaly_faulty", "timeseries_anomaly_clean"],
        lambda m: (
            m["timeseries_anomaly_faulty"] == 1
            and m["timeseries_anomaly_clean"] == 0
        ),
        lambda m: (
            f"injected step fired "
            f"{m['timeseries_anomaly_faulty']:.0f} metric_anomaly event(s) "
            f"(must be exactly 1), clean run fired "
            f"{m['timeseries_anomaly_clean']:.0f} (must be 0)"
        ),
    ),
    # Ragged decode attention (tpu/paged_attention.py), two gates on the
    # TPU-backend receipt keys (skipped on hosts without the TPU leg).
    # Wave 1: the fused kernel must not lose to gather+dense — BENCH_r05
    # recorded the tie (0.99) this work closed; 0.95 clears the paired
    # estimator's residual scatter while a structural loss (the kernel
    # re-materializing what dense gather gets for free) reads well below.
    Check(
        "decode_attn_wave1",
        ["tpu_decode_attn_speedup"],
        lambda m: m["tpu_decode_attn_speedup"] >= 0.95,
        lambda m: (
            f"fused decode attention runs {m['tpu_decode_attn_speedup']:.2f}x "
            "gather+dense at wave size 1 (must be >= 0.95, paired-interleaved)"
        ),
    ),
    # The ragged win itself: on the 8:1 length-skew wave the flat-page-list
    # kernel must beat the padded-dense rectangle (which pays
    # skew_factor x the real pages in padding) — ANY ratio <= 1.0 means the
    # ragged path stopped earning its complexity.
    Check(
        "decode_attn_ragged",
        ["tpu_decode_attn_ragged_vs_padded", "tpu_decode_attn_skew_factor"],
        lambda m: m["tpu_decode_attn_ragged_vs_padded"] > 1.0,
        lambda m: (
            f"ragged wave runs {m['tpu_decode_attn_ragged_vs_padded']:.2f}x "
            f"padded-dense on the skew-{m['tpu_decode_attn_skew_factor']:.2f} "
            "wave (must be > 1.0, paired-interleaved)"
        ),
    ),
    # Tiered capacity plane (ROADMAP-4, docs/tiering.md), three gates.
    # Hot-set isolation: with a Zipf working set 4x the serving-RAM budget
    # and the tail demoted to the pooled cold tier, the HOT set's load p99
    # must stay within noise of the same workload on an all-RAM pool —
    # sampled as order-alternating paired rounds over the two live pools
    # with the min(median-of-ratios, ratio-of-sums) estimator (the weather
    # rule). Honest history 0.87-1.02; 1.25 clears the single-core scatter
    # while a tier plane that stalls hot reads (policy hooks on the hot
    # path, fall-through probing serving hits) reads well past 1.5.
    Check(
        "tiering_hot_isolation",
        ["tiering_hot_p99_ratio"],
        lambda m: m["tiering_hot_p99_ratio"] <= 1.25,
        lambda m: (
            f"hot-set load p99 is {m['tiering_hot_p99_ratio']:.3f}x the "
            "all-RAM run under a 4x working set (must be <= 1.25, "
            "paired-interleaved)"
        ),
    ),
    # Cold reads above the spill floor: the SAME tail roots read from the
    # serving members' local spill (pre-demotion) vs the pooled cold tier
    # (post-demotion). Honest range 0.90-2.25 on loopback (standalone the
    # cold member's roomy RAM wins ~2x; inside the full bench the two
    # phases straddle different weather windows and the ratio compresses
    # toward 1) — 0.6 clears that spread while a per-key fallback storm
    # or a broken batched cold path reads ~0.2.
    Check(
        "tiering_cold_floor",
        ["tiering_cold_vs_spill_floor"],
        lambda m: m["tiering_cold_vs_spill_floor"] >= 0.6,
        lambda m: (
            f"pooled-cold reads run {m['tiering_cold_vs_spill_floor']:.3f}x "
            "the local-spill floor (must be >= 0.6)"
        ),
    ),
    # Mechanism receipts: the temperature plane actually MOVED data both
    # directions (demotion of the idle tail, promotion of an admitted
    # reuse), the anti-scan admission rejected the one-touch cold reads,
    # and every byte came back correct from whatever tier served it.
    Check(
        "tiering_mechanism",
        ["tiering_demotions", "tiering_promotions", "tiering_admit_rejects",
         "tiering_wrong_reads", "tiering_misses"],
        lambda m: (
            m["tiering_demotions"] >= 1
            and m["tiering_promotions"] >= 1
            and m["tiering_admit_rejects"] >= 1
            and m["tiering_wrong_reads"] == 0
            and m["tiering_misses"] == 0
        ),
        lambda m: (
            f"{m['tiering_demotions']:.0f} demotions / "
            f"{m['tiering_promotions']:.0f} promotions / "
            f"{m['tiering_admit_rejects']:.0f} scan rejects, "
            f"wrong={m['tiering_wrong_reads']:.0f} "
            f"misses={m['tiering_misses']:.0f} "
            "(needs movement both directions, rejects >= 1, 0 / 0)"
        ),
    ),
    # Crash-safe fleet coordination (ROADMAP-3, docs/membership.md), four
    # gates over the recovery leg's REAL-subprocess flow. Convergence is
    # binary: the client that kill -9'd itself mid-reshard (rc must be
    # SIGKILL's -9) restarts, resumes, and settles with zero debt; the
    # cold bootstrap client's sweep returns correct bytes for EVERY root
    # (with R=2 and a completed reshard a miss is never legitimate).
    Check(
        "recovery_convergence",
        ["recovery_converged", "recovery_debt", "recovery_crash_rc",
         "recovery_wrong_reads", "recovery_misses"],
        lambda m: (
            m["recovery_converged"] == 1
            and m["recovery_debt"] == 0
            and m["recovery_crash_rc"] == -9
            and m["recovery_wrong_reads"] == 0
            and m["recovery_misses"] == 0
        ),
        lambda m: (
            f"kill -9 (rc={m['recovery_crash_rc']:.0f}) mid-reshard -> "
            f"restart converged={m['recovery_converged']:.0f} with "
            f"debt={m['recovery_debt']:.0f}; bootstrap sweep "
            f"wrong={m['recovery_wrong_reads']:.0f} "
            f"misses={m['recovery_misses']:.0f} (must be 1/0/0/0)"
        ),
    ),
    # The RESUME property: the journal replay recovered every saved root,
    # flagged the in-flight reshard, and the restarted process moved only
    # the REMAINING debt — crash_moved + resumed equals the independently
    # computed rendezvous delta (+-1 for a root legitimately in flight at
    # the crash edge). A restart that re-copied everything (moved_total ~=
    # crash + delta) or replanned from zero knowledge (replayed_roots 0)
    # fails.
    Check(
        "recovery_journal_resume",
        ["recovery_replayed_roots", "recovery_roots", "recovery_resume_flag",
         "recovery_resumed_moved_roots", "recovery_moved_total",
         "recovery_delta_roots"],
        lambda m: (
            m["recovery_replayed_roots"] == m["recovery_roots"]
            and m["recovery_resume_flag"] == 1
            and m["recovery_resumed_moved_roots"] >= 1
            and abs(m["recovery_moved_total"] - m["recovery_delta_roots"]) <= 1
        ),
        lambda m: (
            f"replayed {m['recovery_replayed_roots']:.0f}/"
            f"{m['recovery_roots']:.0f} roots, resume_flag="
            f"{m['recovery_resume_flag']:.0f}, moved "
            f"{m['recovery_moved_total']:.0f} total vs rendezvous delta "
            f"{m['recovery_delta_roots']:.0f} (resumed "
            f"{m['recovery_resumed_moved_roots']:.0f} post-restart — must "
            "resume the remainder, not re-copy from zero)"
        ),
    ),
    # Gossip anti-entropy: the epoch bump must reach the second client
    # process with NO manage-plane POST to it, and that process must
    # settle on the final view. Times are reported (the describe line is
    # the receipt) but not threshold-gated — wall-clock on this host is
    # weather; the binary convergence flag is the invariant.
    Check(
        "recovery_gossip",
        ["recovery_gossip_converged", "recovery_gossip_propagate_s",
         "recovery_gossip_settle_s", "recovery_bootstrap_members"],
        lambda m: (
            m["recovery_gossip_converged"] == 1
            and m["recovery_gossip_propagate_s"] > 0
            and m["recovery_bootstrap_members"] >= 4
        ),
        lambda m: (
            f"epoch reached peer via gossip alone in "
            f"{m['recovery_gossip_propagate_s']:.3f}s, settled 4-member "
            f"view in {m['recovery_gossip_settle_s']:.3f}s; cold bootstrap "
            f"saw {m['recovery_bootstrap_members']:.0f} members (must "
            "converge with zero manage-plane help)"
        ),
    ),
    # Journal write-path overhead, paired-interleaved per the weather rule
    # (min(median-of-ratios, ratio-of-sums) over order-alternating save
    # sweeps): the durable catalog must cost <= 10% of save throughput —
    # an fsync-per-record regression or an O(catalog) append would blow
    # far past this.
    Check(
        "recovery_journal_overhead",
        ["recovery_journal_overhead_cost"],
        lambda m: m["recovery_journal_overhead_cost"] <= 0.10,
        lambda m: (
            f"durable journal costs "
            f"{100 * m['recovery_journal_overhead_cost']:.2f}% of save "
            "throughput (paired-interleaved; must be <= 10%)"
        ),
    ),
    # Overlapped prefill->decode handoff (docs/disaggregation.md), two
    # gates. TTFT ratios ride the weather rule (order-alternating paired
    # rounds, min-of-reps per leg, min(median-of-ratios, ratio-of-sums))
    # against a real prefill-engine subprocess streaming layerwise KV:
    # the watermark pipeline must beat blocking fetch-all admission AND
    # the store-and-forward cold path outright.
    Check(
        "disagg_ttft",
        ["disagg_ttft_overlap_vs_blocking", "disagg_ttft_handoff_vs_cold"],
        lambda m: (
            m["disagg_ttft_overlap_vs_blocking"] > 1.0
            and m["disagg_ttft_handoff_vs_cold"] > 1.0
        ),
        lambda m: (
            f"overlapped TTFT {m['disagg_ttft_overlap_vs_blocking']:.3f}x "
            f"vs blocking fetch-all and "
            f"{m['disagg_ttft_handoff_vs_cold']:.3f}x vs store-and-forward "
            "cold (paired weather rule; both must exceed 1.0)"
        ),
    ),
    # The mechanism, not just the stopwatch: every measured overlapped
    # round issued its first token with layers still in flight (the
    # receipt keys are MINIMA over rounds), the overlapped decode is
    # byte-checked against the local-recompute oracle, and the clean legs
    # never took the fallback path.
    Check(
        "disagg_mechanism",
        ["disagg_overlap_layers", "disagg_inflight_at_first_token",
         "disagg_wrong_bytes", "disagg_fallback_recomputes"],
        lambda m: (
            m["disagg_overlap_layers"] >= 1
            and m["disagg_inflight_at_first_token"] >= 1
            and m["disagg_wrong_bytes"] == 0
            and m["disagg_fallback_recomputes"] == 0
        ),
        lambda m: (
            f"first token with {m['disagg_inflight_at_first_token']:.0f} "
            f"layers in flight / {m['disagg_overlap_layers']:.0f} installed "
            f"behind compute (min over rounds, both >= 1), "
            f"wrong_bytes={m['disagg_wrong_bytes']:.0f} "
            f"fallbacks={m['disagg_fallback_recomputes']:.0f} "
            "(both must be 0 on the clean legs)"
        ),
    ),
    # Skew-aware wave flush under trace-driven serving load
    # (docs/serving_load.md, ROADMAP-6). The ratio rides the weather rule
    # over order-alternating cold-start convergence BLOCKS of the SAME
    # skewed loadgen trace (jit cache cleared per block, scored at the
    # MEDIAN post-cold per-replay p99 — min(median-of-ratios,
    # ratio-of-sums) across block pairs): the prewarmed canonical
    # bucket ladder must cut converged-floor FOREGROUND p99 TTFT
    # (the blind flusher keeps minting fresh organic (B, T, P) buckets
    # and re-pays XLA compiles every round), and the pad fraction — the
    # bucket-economics figure the policy exists to move — must be
    # strictly below the skew-blind run's.
    Check(
        "serving_ttft",
        ["serving_p99_ttft_skew_ratio", "serving_wave_pad_fraction",
         "serving_wave_pad_fraction_blind"],
        lambda m: (
            m["serving_p99_ttft_skew_ratio"] > 1.0
            and m["serving_wave_pad_fraction"]
            < m["serving_wave_pad_fraction_blind"]
        ),
        lambda m: (
            f"skew-aware FOREGROUND p99 TTFT "
            f"{m['serving_p99_ttft_skew_ratio']:.3f}x vs blind (must "
            f"exceed 1.0) at pad fraction "
            f"{m['serving_wave_pad_fraction']:.4f} vs blind "
            f"{m['serving_wave_pad_fraction_blind']:.4f} (must be "
            "strictly below)"
        ),
    ),
    # The mechanism, not just the stopwatch: deferrals actually fired on
    # the measured rounds, the starvation bound produced aging escapes
    # under the outlier flood (deferral under permanent pressure never
    # strands), and the oracle verifier found zero wrong bytes — the
    # policy is scheduling-only by receipt, not by assertion.
    Check(
        "serving_mechanism",
        ["serving_wave_deferrals", "serving_wave_aging_escapes",
         "serving_wrong_bytes"],
        lambda m: (
            m["serving_wave_deferrals"] >= 1
            and m["serving_wave_aging_escapes"] > 0
            and m["serving_wrong_bytes"] == 0
        ),
        lambda m: (
            f"{m['serving_wave_deferrals']:.0f} deferrals on measured "
            f"rounds (>= 1), {m['serving_wave_aging_escapes']:.0f} aging "
            f"escapes under the outlier flood (> 0), "
            f"wrong_bytes={m['serving_wrong_bytes']:.0f} (must be 0)"
        ),
    ),
    Check(
        # Gate the bridge's OWN overhead, not asyncio's: the receipt measures
        # asyncio_efd_floor_us — a pure eventfd+add_reader wake with zero
        # infinistore code, the irreducible cost of staying on asyncio
        # (bench._asyncio_efd_floor_us: "anything above sync_p50 + floor is
        # bridge overhead we could still cut; anything below is impossible").
        # The old p50 <= 3x sync form billed that fixed floor to the bridge
        # and tripped whenever the SYNC path got faster.
        "async_bridge_overhead",
        ["p50_fetch_4k_us", "sync_p50_fetch_4k_us", "asyncio_efd_floor_us"],
        lambda m: (
            m["p50_fetch_4k_us"] - m["asyncio_efd_floor_us"]
            <= 3.0 * m["sync_p50_fetch_4k_us"]
        ),
        lambda m: (
            f"async p50 {m['p50_fetch_4k_us']:.1f}us minus the "
            f"{m['asyncio_efd_floor_us']:.1f}us asyncio wake floor vs sync "
            f"{m['sync_p50_fetch_4k_us']:.1f}us (bridge overhead beyond the "
            "event-loop floor must stay within 3x of the sync path at 4KB)"
        ),
    ),
]


def check_file(path: str, out=sys.stdout) -> int:
    """Run every applicable check against one receipt. Returns 0 pass,
    1 fail, 2 no metrics."""
    with open(path) as f:
        metrics = extract_metrics(f.read())
    applicable = 0
    failed = 0
    for check in CHECKS:
        ok, detail = check.run(metrics)
        if ok is None:
            print(f"[{path}] -    {check.name}: {detail}", file=out)
            continue
        applicable += 1
        if ok:
            print(f"[{path}] PASS {check.name}: {detail}", file=out)
        else:
            failed += 1
            print(f"[{path}] FAIL {check.name}: {detail}", file=out)
    if applicable == 0:
        print(f"[{path}] no usable data-plane metrics found", file=out)
        return 2
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_check", description="fail on data-plane regressions in BENCH json receipts"
    )
    parser.add_argument("files", nargs="+", help="bench output / driver receipt JSON files")
    args = parser.parse_args(argv)
    rc = 0
    for path in args.files:
        rc = max(rc, check_file(path))
    return rc


if __name__ == "__main__":
    sys.exit(main())
