#!/usr/bin/env python
"""Real-backend smoke test: run the TPU data plane end-to-end on whatever
backend is live (the real chip under the default env; CPU elsewhere).

The pytest suite pins JAX to a virtual CPU mesh, which masks TPU-only
behaviors — most importantly buffer donation (the CPU backend ignores it, so
aliased-donated-buffer bugs only surface on hardware as INVALID_ARGUMENT).
Run this after touching infinistore_tpu/tpu/ or models/. Exits nonzero on
any failure.
"""

import asyncio
import os
import sys

import numpy as np

# Runnable straight from a repo checkout.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    import infinistore_tpu as its
    from infinistore_tpu import KVConnector
    from infinistore_tpu.tpu import (
        HostStagingPool,
        PagedKVCacheSpec,
        gather_blocks,
        gather_blocks_xla,
        scatter_blocks,
        scatter_blocks_xla,
    )

    print(f"backend: {jax.default_backend()} ({jax.devices()})")
    spec = PagedKVCacheSpec(
        num_layers=3, num_blocks=64, block_tokens=16, num_kv_heads=4,
        head_dim=64, dtype=jnp.bfloat16,
    )

    # 1. Pallas gather/scatter vs XLA reference on this backend.
    cache = jax.random.normal(
        jax.random.PRNGKey(0), spec.cache_shape, jnp.float32
    ).astype(spec.dtype)
    ids = jnp.asarray(np.random.default_rng(1).permutation(64)[:8].astype(np.int32))
    got = np.asarray(gather_blocks(cache, ids))
    want = np.asarray(gather_blocks_xla(cache, ids))
    np.testing.assert_array_equal(got, want)
    blocks = gather_blocks_xla(cache, ids)
    s_got = np.asarray(scatter_blocks(jnp.copy(cache), ids, blocks))
    s_want = np.asarray(scatter_blocks_xla(jnp.copy(cache), ids, blocks))
    np.testing.assert_array_equal(s_got, s_want)
    print("1. pallas gather/scatter match XLA")

    # 2. Donation hazard regression: fresh caches must be distinct buffers.
    caches = spec.make_caches()
    upd = [
        (scatter_blocks(k, ids, blocks), scatter_blocks(v, ids, blocks))
        for k, v in caches
    ]
    jax.block_until_ready(upd)
    print("2. make_caches buffers survive donating scatter across K/V/layers")

    # 3. Full store roundtrip: connector save/load through a live server.
    srv = its.start_local_server(prealloc_bytes=128 << 20, block_bytes=1 << 20)
    conn = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    conn.connect()
    try:
        connector = KVConnector(conn, spec, model_id="smoke", max_blocks=8)
        tokens = list(range(64))  # 4 blocks
        full = [
            (
                jax.random.normal(jax.random.PRNGKey(7 + i), spec.cache_shape,
                                  jnp.float32).astype(spec.dtype),
                jax.random.normal(jax.random.PRNGKey(70 + i), spec.cache_shape,
                                  jnp.float32).astype(spec.dtype),
            )
            for i in range(spec.num_layers)
        ]
        src_ids = np.array([3, 9, 21, 40], dtype=np.int32)
        asyncio.run(connector.save(tokens, full, src_ids))
        assert connector.lookup(tokens) == 4, "lookup after save"
        fresh = spec.make_caches()
        dst_ids = np.array([1, 2, 4, 8], dtype=np.int32)
        loaded, n = asyncio.run(connector.load(tokens, fresh, dst_ids))
        assert n == 4, f"loaded {n} != 4"
        for layer in range(spec.num_layers):
            for side in (0, 1):
                a = np.asarray(gather_blocks(full[layer][side], jnp.asarray(src_ids)))
                b = np.asarray(gather_blocks(loaded[layer][side], jnp.asarray(dst_ids)))
                np.testing.assert_array_equal(a, b)
        print("3. connector save/load roundtrip verified through live store")

        # 4. Demo model prefill->decode against the paged cache.
        from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill

        cfg = LlamaConfig(vocab=256, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                          ffn_dim=256, block_tokens=16, dtype=jnp.bfloat16)
        params = init_params(cfg, jax.random.PRNGKey(0))
        mcaches = cfg.kv_spec(32).make_caches()
        table = jnp.arange(4, dtype=jnp.int32)
        prompt = jnp.arange(16, dtype=jnp.int32) % cfg.vocab
        logits, mcaches = prefill(params, prompt, mcaches, table[:1], cfg)
        logits, _ = decode_step(params, jnp.int32(5), jnp.int32(16), mcaches, table, cfg, 4)
        assert np.isfinite(np.asarray(logits.astype(jnp.float32))).all()
        print("4. demo model prefill+decode finite on this backend")

        # 5. Fused paged decode attention (the dispatcher's path on THIS
        # backend) vs the XLA reference, single and batched wave.
        from infinistore_tpu.tpu import (
            paged_decode_attention,
            paged_decode_attention_batched,
            paged_decode_attention_xla,
        )
        from infinistore_tpu.tpu.paged_attention import (
            paged_decode_attention_xla_batched,
        )

        rng = np.random.default_rng(3)
        aq = jnp.asarray(rng.standard_normal((cfg.n_heads, cfg.head_dim)), jnp.bfloat16)
        sl = jnp.int32(3 * cfg.block_tokens + 5)
        got = paged_decode_attention(aq, mcaches[0][0], mcaches[0][1], table, sl)
        want = paged_decode_attention_xla(aq, mcaches[0][0], mcaches[0][1], table, sl)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
        assert err < 3e-2, f"fused decode attention diverged: {err}"
        qb = jnp.asarray(rng.standard_normal((4, cfg.n_heads, cfg.head_dim)), jnp.bfloat16)
        tbls = jnp.stack([table] * 4)
        sls = jnp.asarray([1, 17, 0, int(sl)], jnp.int32)  # incl. empty row
        gotb = paged_decode_attention_batched(qb, mcaches[0][0], mcaches[0][1], tbls, sls)
        wantb = paged_decode_attention_xla_batched(qb, mcaches[0][0], mcaches[0][1], tbls, sls)
        errb = float(jnp.max(jnp.abs(gotb.astype(jnp.float32) - wantb.astype(jnp.float32))))
        assert errb < 3e-2, f"batched decode attention diverged: {errb}"
        assert float(jnp.abs(gotb[2].astype(jnp.float32)).max()) == 0.0, "empty row not zero"
        print(f"5. fused decode attention matches XLA (err {err:.1e}, wave err {errb:.1e})")

        # 6. int8 decode attention: the quantized kernel on THIS backend
        # against the dequantize-then-float fallback.
        from infinistore_tpu.tpu.kv_quant import (
            _quant_decode_xla,
            paged_decode_attention_quantized,
            quantize_kv,
        )

        kq, ksc = quantize_kv(mcaches[0][0])
        vq, vsc = quantize_kv(mcaches[0][1])
        gotq = paged_decode_attention_quantized(qb, kq, ksc, vq, vsc, tbls, sls)
        wantq = _quant_decode_xla(qb, kq, ksc, vq, vsc, tbls, sls)
        errq = float(
            jnp.max(jnp.abs(gotq.astype(jnp.float32) - wantq.astype(jnp.float32)))
        )
        assert errq < 3e-2, f"quantized decode attention diverged: {errq}"
        print(f"6. int8 decode attention matches dequantized fallback (err {errq:.1e})")

        # 7. Chunked continuation + speculative verify on this backend: a
        # perfect greedy draft must fully accept. f32 model: exact argmax
        # agreement between the chunked and token-by-token paths is only
        # guaranteed at f32 (a bf16 near-tie can round differently between
        # the two accumulation orders — the pytest pins f32 for the same
        # reason).
        from infinistore_tpu.models import speculative_verify

        f32 = LlamaConfig(
            vocab=256, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=256, block_tokens=16, dtype=jnp.float32,
        )
        fparams = init_params(f32, jax.random.PRNGKey(1))
        fcaches = f32.kv_spec(32).make_caches()
        logits0, fcaches = prefill(fparams, prompt, fcaches, table[:1], f32)
        tok, pos, greedy = int(jnp.argmax(logits0)), 16, []
        sc = fcaches
        for _ in range(5):
            greedy.append(tok)
            lg, sc = decode_step(
                fparams, jnp.int32(tok), jnp.int32(pos), sc, table, f32, 4
            )
            tok, pos = int(jnp.argmax(lg)), pos + 1
        n_acc, nxt, _ = speculative_verify(
            fparams, greedy, 16, fcaches, table, f32, 4
        )
        assert n_acc == 5, f"perfect draft should fully accept, got {n_acc}"
        assert nxt == tok
        print("7. speculative verify accepts a perfect greedy draft on this backend")
    finally:
        conn.close()
        srv.stop()
    print("tpu_smoke: all passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
