"""Smoke test run against an INSTALLED wheel (tools/build_wheel.sh copies
this file to a temp dir so the repo tree is not importable): the bundled .so
must load without a native/ source tree, and the full public surface must
work — server up, sync + async batched roundtrip, control ops, stats."""

import asyncio
import os
import sys

import numpy as np

import infinistore_tpu as its

pkg = os.path.dirname(its.__file__)
assert not os.path.exists(os.path.join(pkg, "..", "native")), (
    "smoke test imported the repo tree, not the installed wheel"
)

srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=64 << 10)
conn = its.InfinityConnection(
    its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
)
conn.connect()

n, block = 16, 64 << 10
src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
dst = np.zeros_like(src)
conn.register_mr(src)
conn.register_mr(dst)
pairs = [(f"wheel-{i}", i * block) for i in range(n)]
asyncio.run(conn.write_cache_async(pairs, block, src.ctypes.data))
conn.read_cache(pairs, block, dst.ctypes.data)
assert np.array_equal(src, dst), "roundtrip mismatch"

assert conn.check_exist("wheel-0") is True
assert conn.get_match_last_index([f"wheel-{i}" for i in range(n)]) == n - 1
assert conn.delete_keys([f"wheel-{i}" for i in range(n)]) == n
stats = conn.get_stats()
assert stats.get("conns_accepted", 0) >= 1

conn.close()
srv.stop()
print(f"wheel smoke ok (python {sys.version_info.major}.{sys.version_info.minor}, "
      f"{n * block >> 10}KB roundtrip verified)")
