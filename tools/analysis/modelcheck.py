"""ITS-M*: explicit-state protocol model checking (docs/static_analysis.md).

The repo carries four hand-written distributed protocols — the gossip
membership merge lattice, DurableLog crash replay, the zero-copy ring's
publish/park/doorbell discipline, and the QoS aging bound — each verified
until now only by example-based tests. This checker exhaustively explores
small executable models of them (tools/analysis/specs/) over ALL
interleavings, bounded by state hashing, and diffs each model's action
vocabulary against the real implementation's surface so the models cannot
silently rot (the wire_drift IR pattern):

- **ITS-M001** lockstep drift: a spec's ``MIRRORS`` descriptor binds model
  actions to real methods (Python classes via AST, C++ headers via the
  name-family regex). A covered/exempt name that no longer exists, a real
  surface name the model neither covers nor exempts, or a model action
  with no mapping is a finding — models rot loudly, never silently.
- **ITS-M002** safety violation: a reachable state (or explored edge)
  refutes an invariant. The finding carries the serialized action
  schedule — ``interleave.replay_schedule`` turns it into a deterministic
  regression test against the REAL classes (the PR-13 workflow).
- **ITS-M003** deadlock: a reachable non-final state with no enabled
  action (a lost wakeup, wedged backpressure).
- **ITS-M004** liveness: a reachable state from which no schedule reaches
  a declared goal (AG EF under the explored transition relation) —
  starvation with the schedule to prove it.
- **ITS-M005** exploration health: an empty state space, a state-cap
  overflow (incomplete exploration reads as a silent pass otherwise), or
  a spec with no invariants at all.

Per-spec wall-time and state counts land in ``Context.stats`` and the
``--json`` receipt, so exploration-budget regressions show up in CI logs
the same way per-checker timings do.

``python -m tools.analysis.modelcheck`` prints the exploration report.
"""

from __future__ import annotations

import ast
import json
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Context, Finding, register
from .specs import Spec, SpecResult, all_specs, explore

_KIND_RULE = {
    "invariant": "ITS-M002",
    "step": "ITS-M002",
    "deadlock": "ITS-M003",
    "liveness": "ITS-M004",
}


# ---------------------------------------------------------------------------
# ITS-M001: model <-> implementation lockstep.
# ---------------------------------------------------------------------------

def _py_class_surface(ctx: Context, rel: str,
                      cls_name: str) -> Optional[Tuple[Set[str], int]]:
    """Public method names of ``cls_name`` (AST; properties included,
    underscore/dunder names excluded) and the class' line."""
    try:
        tree = ast.parse(ctx.read(rel))
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            names = {
                item.name for item in node.body
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                and not item.name.startswith("_")
            }
            return names, node.lineno
    return None


def _cpp_surface(ctx: Context, rel: str,
                 pattern: str) -> Optional[Set[str]]:
    """Name-family surface of a C++ header: every distinct capture of
    ``pattern``, with ``//`` and ``/* */`` comments stripped first —
    prose like "bg_cooldown_us (hysteresis ...)" must not read as a
    surface name."""
    try:
        text = ctx.read(rel)
    except OSError:
        return None
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    text = re.sub(r"//[^\n]*", "", text)
    return set(re.findall(pattern, text))


def check_m001(ctx: Context, spec: Spec, mirrors: dict) -> List[Finding]:
    findings: List[Finding] = []
    rel = mirrors["file"]
    slug = f"ITS-M001:{rel}:{spec.name}"

    def finding(line: int, message: str, sub: str) -> Finding:
        return Finding(rule="ITS-M001", file=rel, line=line,
                       message=message, key=f"{slug}:{sub}")

    covered: Dict[str, str] = dict(mirrors.get("actions", {}))
    exempt: Dict[str, str] = dict(mirrors.get("exempt", {}))
    if mirrors["kind"] == "py_class":
        got = _py_class_surface(ctx, rel, mirrors["cls"])
        if got is None:
            return [finding(
                0, f"spec {spec.name!r} mirrors class {mirrors['cls']!r} "
                   f"in {rel}, which no longer parses or exists — update "
                   "the spec's MIRRORS descriptor", "missing-class",
            )]
        surface, line = got
    else:
        surface = _cpp_surface(ctx, rel, mirrors["pattern"])
        line = 0
        if surface is None:
            return [finding(
                0, f"spec {spec.name!r} mirrors {rel}, which is missing",
                "missing-file",
            )]
    # (a) every model action maps to something (or keys a family prefix:
    # `add` covers `add@0`..`add@2` — the peer-indexed action names).
    for action in spec.actions:
        base = action.name.split("@", 1)[0]
        if action.name not in covered and base not in covered:
            findings.append(finding(
                line, f"model action {action.name!r} of spec "
                      f"{spec.name!r} has no entry in MIRRORS['actions'] — "
                      "bind it to the real method it mirrors",
                f"unmapped:{base}",
            ))
    # (b) covered targets and exempt names must still exist on the real
    # surface (stale spec vocabulary).
    for target in sorted(set(covered.values())):
        if target not in surface:
            findings.append(finding(
                line, f"spec {spec.name!r} maps actions to "
                      f"{target!r}, which is not on the real surface of "
                      f"{rel} — the model's action list is stale",
                f"stale-covered:{target}",
            ))
    for name in sorted(exempt):
        if name not in surface:
            findings.append(finding(
                line, f"spec {spec.name!r} exempts {name!r}, which is not "
                      f"on the real surface of {rel} — prune the stale "
                      "exemption", f"stale-exempt:{name}",
            ))
    # (c) every real surface name is covered or exempted — a new method
    # landing without a model update fails the run (anti-rot).
    known = set(covered.values()) | set(exempt)
    for name in sorted(surface - known):
        findings.append(finding(
            line, f"{rel} grew {name!r}, which spec {spec.name!r} neither "
                  "models nor exempts — extend the model (or record the "
                  "audit reason in MIRRORS['exempt'])",
            f"unmodeled:{name}",
        ))
    return findings


# ---------------------------------------------------------------------------
# ITS-M002..M005: exploration findings.
# ---------------------------------------------------------------------------

def check_exploration(spec: Spec, result: SpecResult) -> List[Finding]:
    findings: List[Finding] = []
    # Spec modules live in this repo's tools tree; anchor findings there.
    rel = f"tools/analysis/specs/{spec.name}.py"
    for v in result.violations:
        rule = _KIND_RULE[v.kind]
        findings.append(Finding(
            rule=rule, file=rel, line=0,
            message=(
                f"spec {spec.name!r}: {v.message}; counterexample "
                f"schedule {json.dumps(v.schedule)} (replay with "
                "interleave.replay_schedule; docs/static_analysis.md "
                "ITS-M counterexample->test workflow)"
            ),
            key=f"{rule}:{spec.name}:{v.prop}",
        ))
    if result.states == 0:
        findings.append(Finding(
            rule="ITS-M005", file=rel, line=0,
            message=f"spec {spec.name!r} explored 0 states — no initial "
                    "states or a broken guard set",
            key=f"ITS-M005:{spec.name}:empty",
        ))
    elif not result.complete and not result.violations:
        findings.append(Finding(
            rule="ITS-M005", file=rel, line=0,
            message=(
                f"spec {spec.name!r} exploration incomplete at "
                f"{result.states} states (cap {spec.state_cap}) — an "
                "unbounded model reads as a silent pass; bound it with "
                "budgets/saturation"
            ),
            key=f"ITS-M005:{spec.name}:incomplete",
        ))
    if not spec.invariants and not spec.step_invariants:
        findings.append(Finding(
            rule="ITS-M005", file=rel, line=0,
            message=f"spec {spec.name!r} declares no invariants — it "
                    "explores but checks nothing",
            key=f"ITS-M005:{spec.name}:no-invariants",
        ))
    return findings


def scan(ctx: Context,
         specs: Optional[Sequence[Tuple[Spec, dict]]] = None,
         ) -> List[Finding]:
    """Run the lockstep diff + full bounded exploration of every spec;
    record per-spec stats (states, edges, ms, complete) in ``ctx.stats``
    for the --json receipt. ``specs`` is injectable for the seeded
    mutation tests."""
    findings: List[Finding] = []
    rows: Dict[str, dict] = {}
    for spec, mirrors in (all_specs() if specs is None else specs):
        findings += check_m001(ctx, spec, mirrors)
        result = explore(spec)
        findings += check_exploration(spec, result)
        rows[spec.name] = result.to_json()
    ctx.stats["modelcheck"] = {"specs": rows}
    return findings


@register("modelcheck",
          "explicit-state protocol model checking: membership merge, "
          "durable-log crash replay, ring publish/park, QoS aging (ITS-M*)",
          rule_prefix="ITS-M",
          scope=("infinistore_tpu/membership.py", "native/include/its/",
                 "tools/analysis/specs/", "tools/analysis/modelcheck.py"))
def check(ctx: Context) -> List[Finding]:
    return scan(ctx)


if __name__ == "__main__":  # pragma: no cover - exploration report helper
    ctx = Context()
    all_findings = scan(ctx)
    for name, row in ctx.stats["modelcheck"]["specs"].items():
        print(
            f"{name:18s} {row['states']:7d} states  {row['edges']:7d} edges"
            f"  {row['ms']:8.1f} ms  "
            f"{'complete' if row['complete'] else 'INCOMPLETE'}"
        )
    for f in all_findings:
        print(f.render())
    raise SystemExit(1 if all_findings else 0)
