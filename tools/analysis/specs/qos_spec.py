"""ITS-M spec: QoS aging / starvation bound
(native/include/its/server.h two-level fg/bg slice scheduler;
docs/qos.md).

The server's continuation scheduler runs foreground slices whenever
foreground work is pending and defers background (``bg_must_defer``)
behind a cooldown — EXCEPT that a time-based aging escape
(``bg_aging_us``) forces one background slice per aging window no
matter how hard foreground floods. The model abstracts wall-clock into
scheduler passes: each foreground pass under contention ages the
deferred background work by one tick; once the age reaches the bound,
``bg_must_defer`` turns false and the next pass MUST run background.

Nondeterminism: background ops arrive over time (budgeted), so the
explorer covers floods hitting an empty bg queue, arrivals mid-flood,
and back-to-back aged slices. The foreground flood itself is permanent
by construction — the adversary the bound is stated against.

Explored properties:

- **aging-bound** (invariant): deferral age never exceeds the bound —
  i.e. a permanent foreground flood cannot starve background past
  ``bg_aging_us`` (ages saturate one past the bound so a broken model
  stays finite and the violation state is reachable);
- **aged-slices-progress** (step invariant): an aged background slice
  always consumes a background op and resets the age — the escape does
  real work, it does not just clear the clock;
- **bg-drains** (liveness, AG EF): from every reachable state some
  schedule finishes all background ops — the escape suffices for
  progress with no cooperation from foreground.
"""

from __future__ import annotations

from typing import List

from . import Action, Spec

AGING_BOUND = 3   # abstract ticks of bg_aging_us
BG_OPS = 2        # background ops queued at start
BG_ARRIVALS = 1   # additional bg arrivals mid-flood (budget)

# State: (bg_remaining, bg_wait, bg_arrival_budget, aged_count)
BG, WAIT, ARR, AGED = range(4)


def initial_states() -> List[tuple]:
    return [(BG_OPS, 0, BG_ARRIVALS, 0)]


def must_run_bg(s: tuple) -> bool:
    """bg_must_defer() == false via the aging escape: deferred work aged
    past the bound forces the next pass to run one background slice."""
    return s[BG] > 0 and s[WAIT] >= AGING_BOUND


ACTIONS = (
    # One scheduler pass that picks FOREGROUND (the flood always has fg
    # pending). Deferring pending background work ages it one tick;
    # saturate one past the bound so a mutated model stays finite.
    Action(
        name="pass_fg",
        guard=lambda s: not must_run_bg(s),
        apply=lambda s: (
            s[BG],
            min(s[WAIT] + 1, AGING_BOUND + 1) if s[BG] > 0 else 0,
            s[ARR], s[AGED],
        ),
    ),
    # The aging escape: the pass runs ONE background slice, consumes a
    # background op, resets the deferral clock.
    Action(
        name="pass_bg_aged",
        guard=must_run_bg,
        apply=lambda s: (s[BG] - 1, 0, s[ARR], s[AGED] + 1),
    ),
    # A new background op arrives mid-flood (budgeted nondeterminism).
    Action(
        name="bg_arrive",
        guard=lambda s: s[ARR] > 0,
        apply=lambda s: (s[BG] + 1, s[WAIT], s[ARR] - 1, s[AGED]),
    ),
)


def inv_aging_bound(s: tuple) -> bool:
    return s[WAIT] <= AGING_BOUND


def step_aged_progress(prev: tuple, action: str, nxt: tuple) -> bool:
    if action != "pass_bg_aged":
        return True
    return nxt[BG] == prev[BG] - 1 and nxt[WAIT] == 0


SPEC = Spec(
    name="qos_aging",
    doc="permanent fg flood cannot starve bg past the aging bound; the "
        "escape does real bg work and always drains (its/server.h)",
    initial_states=initial_states,
    actions=ACTIONS,
    invariants=(
        ("aging-bound", inv_aging_bound),
    ),
    step_invariants=(
        ("aged-slices-progress", step_aged_progress),
    ),
    # pass_fg is enabled in every non-escape state, so quiescence never
    # occurs under the flood.
    is_done=lambda s: True,
    liveness=(
        ("bg-drains", lambda s: s[BG] == 0 and s[ARR] == 0),
    ),
)


MIRRORS = {
    "kind": "cpp_functions",
    "file": "native/include/its/server.h",
    # The QoS scheduling surface: the cont-pass family + the bg_* policy
    # predicates (field initializers carry no '(' and do not match).
    "pattern": r"\b(run_cont_pass|run_one_slice|note_op|bg_[a-z0-9_]+)"
               r"\s*\(",
    "actions": {
        "pass_fg": "run_cont_pass",
        "pass_bg_aged": "run_one_slice",
        "bg_arrive": "note_op",
    },
    "exempt": {
        "bg_must_defer": "mirrored as the must_run_bg guard predicate "
                         "(the pass_fg/pass_bg_aged action split)",
    },
}
