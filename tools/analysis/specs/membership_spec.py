"""ITS-M spec: the gossip membership merge lattice
(infinistore_tpu/membership.py ``Membership``).

Three peers gossip their knowledge of ONE contested member id ``x`` (the
steady members are constant and carry no merge information). A peer's
knowledge is the latest incarnation ``(state, since_epoch)`` it holds —
exactly what ``Membership._latest_remote`` reduces a payload to — plus
its epoch. Transitions mirror the real entry points (``add_member``,
``remove_member``, ``mark_dead``, ``finalize_transitions``, re-add after
a terminal tombstone) with small global budgets so epochs — and the
state space — stay finite; ``exchange i<-j`` applies the
``merge_apply`` lattice join (newest incarnation wins outright; within
one incarnation the state-rank order decides, so terminal knowledge
dominates stale liveness).

Explored properties:

- **join-commutes / join-idempotent** (invariants): the pairwise join the
  merge applies is order-insensitive and self-absorbing in EVERY
  reachable state — the algebra ``merge_apply``'s docstring promises.
- **full-exchange-converges** (invariant): from every reachable state, a
  bounded all-pairs exchange fixpoint leaves all three peers with
  identical ``(view, epoch)`` — convergence without coordination.
- **no-resurrection** (step invariant): no exchange moves a peer's entry
  except per ``_beats`` — in particular a DEAD/REMOVED tombstone is never
  replaced by a readable state of the SAME incarnation, and a re-add
  (the legitimate resurrection) always carries a strictly newer
  ``since_epoch``.
- **epoch-monotone** (step invariant): no action ever lowers a peer's
  epoch.
"""

from __future__ import annotations

from itertools import product
from typing import List, Optional, Tuple

from . import Action, Spec

# State ranks copied from Membership._STATE_RANK; the ITS-M001 lockstep
# diff (modelcheck) pins the mirrored class surface, and the replay tests
# (tests/test_modelcheck.py) drive the REAL class through these schedules.
JOINING, ACTIVE, LEAVING, DEAD, REMOVED = "J", "A", "L", "D", "R"
RANK = {JOINING: 1, ACTIVE: 2, LEAVING: 3, DEAD: 4, REMOVED: 5}
TERMINAL = (DEAD, REMOVED)

# Entry: (state, since_epoch) or None (peer has never heard of x).
Entry = Optional[Tuple[str, int]]
# Peer: (entry, epoch). Global state:
#   ((peer0, peer1, peer2), (budget_add, budget_remove, budget_dead,
#                            budget_readd, budget_finalize))
N_PEERS = 3


def beats(a: Entry, b: Entry) -> bool:
    """Does b supersede a? (Membership._beats, None = unknown.)"""
    if b is None:
        return False
    if a is None:
        return True
    if b[1] != a[1]:
        return b[1] > a[1]
    return RANK[b[0]] > RANK[a[0]]


def join(a: Entry, b: Entry) -> Entry:
    return b if beats(a, b) else a


def initial_states() -> List[tuple]:
    peers = tuple((None, 1) for _ in range(N_PEERS))
    return [(peers, (1, 1, 1, 1, 2))]


def _mutate(state: tuple, i: int, new_state: str, spend: int) -> tuple:
    """Local transition at peer i: entry -> (new_state, epoch+1), epoch
    bump — the _mutate/epoch discipline of the real class."""
    peers, budgets = state
    entry, epoch = peers[i]
    new_peers = list(peers)
    new_peers[i] = ((new_state, epoch + 1), epoch + 1)
    new_budgets = list(budgets)
    new_budgets[spend] -= 1
    return (tuple(new_peers), tuple(new_budgets))


def _entry(state: tuple, i: int) -> Entry:
    return state[0][i][0]


def _make_actions() -> List[Action]:
    actions: List[Action] = []
    for i in range(N_PEERS):
        # add_member: rejected for a live entry; unknown id only.
        actions.append(Action(
            name=f"add@{i}",
            guard=lambda s, i=i: s[1][0] > 0 and _entry(s, i) is None,
            apply=lambda s, i=i: _mutate(s, i, JOINING, 0),
        ))
        # remove_member: JOINING/ACTIVE -> LEAVING (graceful drain; the
        # last-placement-member refusal concerns the steady members, which
        # always remain in placement here).
        actions.append(Action(
            name=f"remove@{i}",
            guard=lambda s, i=i: (
                s[1][1] > 0
                and _entry(s, i) is not None
                and _entry(s, i)[0] in (JOINING, ACTIVE)
            ),
            apply=lambda s, i=i: _mutate(s, i, LEAVING, 1),
        ))
        # mark_dead: any non-terminal -> DEAD.
        actions.append(Action(
            name=f"mark_dead@{i}",
            guard=lambda s, i=i: (
                s[1][2] > 0
                and _entry(s, i) is not None
                and _entry(s, i)[0] not in TERMINAL
            ),
            apply=lambda s, i=i: _mutate(s, i, DEAD, 2),
        ))
        # add_member on a tombstoned id: the legitimate re-add — a NEW
        # incarnation whose since_epoch beats the tombstone.
        actions.append(Action(
            name=f"readd@{i}",
            guard=lambda s, i=i: (
                s[1][3] > 0
                and _entry(s, i) is not None
                and _entry(s, i)[0] in TERMINAL
            ),
            apply=lambda s, i=i: _mutate(s, i, JOINING, 3),
        ))
        # finalize_transitions: JOINING -> ACTIVE, LEAVING -> REMOVED.
        actions.append(Action(
            name=f"finalize@{i}",
            guard=lambda s, i=i: (
                s[1][4] > 0
                and _entry(s, i) is not None
                and _entry(s, i)[0] in (JOINING, LEAVING)
            ),
            apply=lambda s, i=i: _mutate(
                s, i, ACTIVE if _entry(s, i)[0] == JOINING else REMOVED, 4,
            ),
        ))
    for i, j in product(range(N_PEERS), repeat=2):
        if i == j:
            continue
        # merge_apply at peer i of peer j's view: lattice join of the
        # entry, epoch = max(local, remote).
        def exchange(s: tuple, i=i, j=j) -> tuple:
            peers, budgets = s
            (ei, epi), (ej, epj) = peers[i], peers[j]
            new_peers = list(peers)
            new_peers[i] = (join(ei, ej), max(epi, epj))
            return (tuple(new_peers), budgets)

        actions.append(Action(
            name=f"exchange@{i}<-{j}",
            guard=lambda s: True,
            apply=exchange,
        ))
    return actions


# -- invariants --------------------------------------------------------------

def inv_join_commutes(state: tuple) -> bool:
    entries = [_entry(state, i) for i in range(N_PEERS)]
    return all(
        join(a, b) == join(b, a) for a in entries for b in entries
    )


def inv_join_idempotent(state: tuple) -> bool:
    return all(
        join(_entry(state, i), _entry(state, i)) == _entry(state, i)
        for i in range(N_PEERS)
    )


def inv_converges(state: tuple) -> bool:
    """A bounded all-pairs exchange fixpoint from here leaves every peer
    identical — the convergence promise of commutative+idempotent joins."""
    peers = list(state[0])
    for _ in range(2 * N_PEERS):
        changed = False
        for i, j in product(range(N_PEERS), repeat=2):
            if i == j:
                continue
            (ei, epi), (ej, epj) = peers[i], peers[j]
            merged = (join(ei, ej), max(epi, epj))
            if merged != peers[i]:
                peers[i] = merged
                changed = True
        if not changed:
            break
    return len(set(peers)) == 1


def step_no_resurrection(prev: tuple, action: str, nxt: tuple) -> bool:
    """Entries only move forward per ``beats`` on exchange edges; a
    terminal tombstone is replaced by a READABLE state only with a
    strictly newer incarnation. Within one incarnation the only legal
    move out of a tombstone is the terminal rank advance DEAD ->
    REMOVED (concurrent mark_dead/finalize at the same epoch both
    produce terminal knowledge; the rank order picks REMOVED on every
    peer deterministically)."""
    if not action.startswith("exchange"):
        return True
    for i in range(N_PEERS):
        a, b = _entry(prev, i), _entry(nxt, i)
        if a == b:
            continue
        if not beats(a, b):
            return False
        if (a is not None and a[0] in TERMINAL
                and b is not None and b[0] not in TERMINAL
                and b[1] <= a[1]):
            return False  # resurrection within the dead incarnation
    return True


def step_epoch_monotone(prev: tuple, action: str, nxt: tuple) -> bool:
    return all(
        nxt[0][i][1] >= prev[0][i][1] for i in range(N_PEERS)
    )


SPEC = Spec(
    name="membership_merge",
    doc="gossip lattice join: commutes/idempotent/converges; tombstone "
        "no-resurrection; epoch monotone (membership.Membership)",
    initial_states=initial_states,
    actions=tuple(_make_actions()),
    invariants=(
        ("join-commutes", inv_join_commutes),
        ("join-idempotent", inv_join_idempotent),
        ("full-exchange-converges", inv_converges),
    ),
    step_invariants=(
        ("no-resurrection", step_no_resurrection),
        ("epoch-monotone", step_epoch_monotone),
    ),
    # Exchanges are always enabled: quiescence never occurs, so any state
    # is a legal stopping point.
    is_done=lambda s: True,
)


# ITS-M001 lockstep: the model's action vocabulary against the REAL class
# surface. ``actions`` maps each model action family to the method it
# mirrors; ``exempt`` lists real public methods deliberately outside the
# model, each with the audit reason.
MIRRORS = {
    "kind": "py_class",
    "file": "infinistore_tpu/membership.py",
    "cls": "Membership",
    "actions": {
        "add": "add_member",
        "readd": "add_member",
        "remove": "remove_member",
        "mark_dead": "mark_dead",
        "finalize": "finalize_transitions",
        "exchange": "merge_apply",
    },
    "exempt": {
        "view": "read-only snapshot accessor (no transition)",
        "settled": "derived predicate over the view",
        "prev_placement": "derived read-failover accessor",
        "owns_transition": "derived originator flag",
        "index_of": "entry-index lookup (no transition)",
        "merge_plan": "dry run of merge_apply's delta (same join, no "
                      "state change)",
        "restore": "construction-time journal install — exercised by the "
                   "durable_log spec's replay path",
        "status": "observability snapshot",
    },
}
