"""Explicit-state protocol specs + BFS explorer — the model side of the
ITS-M checker (tools/analysis/modelcheck.py; docs/static_analysis.md).

A *spec* is a small executable model of one of the repo's hand-written
distributed protocols, written next to the real code it mirrors:

- **states** are hashable values (tuples of tuples — never dicts);
- **actions** are named, guarded transitions (``Action``); an action's
  ``apply`` may return ONE successor or a LIST of successors
  (nondeterminism, e.g. a crash that leaves the old or the new file);
- **invariants** are predicates over single states (safety), and
  **step invariants** are predicates over ``(prev, action, next)`` edges
  (monotonicity properties like tombstone no-resurrection);
- ``is_done`` marks states where quiescence is LEGAL — a state with no
  enabled action that is not done is a deadlock (a lost wakeup);
- **liveness goals** assert AG EF *goal*: from every reachable state some
  schedule reaches the goal. Checked by backward reachability over the
  fully-explored edge set, this is the fairness-modulo-scheduling reading
  of "the aging escape cannot be starved": no reachable state is ever cut
  off from progress. Only evaluated when exploration completed.

Exploration (:func:`explore`) is plain BFS over ALL interleavings,
bounded by state hashing (the visited set), never by depth guessing: the
explorer terminates exactly when the model's state space is finite, and
``state_cap`` is the runaway backstop (an incomplete run is an ITS-M005
finding, not a silent pass). Every violation carries the full action
schedule from an initial state, reconstructed from BFS parent pointers —
the serialized counterexample ``interleave.replay_schedule`` turns into a
deterministic regression test against the REAL classes.

The four shipped specs (membership merge, DurableLog crash/replay, the
zero-copy ring's publish/park/doorbell, QoS aging) each publish a
``SPEC`` object plus a ``MIRRORS`` descriptor binding the model's action
vocabulary to the real implementation's method surface — the ITS-M001
lockstep diff that keeps models from silently rotting.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from time import perf_counter
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Action:
    """One named, guarded transition. ``apply(state)`` returns the
    successor state or a list of successors (nondeterministic outcome)."""

    name: str
    guard: Callable[[tuple], bool]
    apply: Callable[[tuple], object]


@dataclass
class Violation:
    """One refuted property with its replayable counterexample."""

    kind: str        # "invariant" | "step" | "deadlock" | "liveness"
    prop: str        # property name (invariant/goal name, or the action)
    message: str
    schedule: List[str]  # action names from an initial state (serialized
    #                      counterexample; replay_schedule() input)
    state: tuple = ()


@dataclass
class SpecResult:
    """Outcome of exploring one spec's full bounded state space."""

    spec: str
    states: int = 0
    edges: int = 0
    complete: bool = False
    ms: float = 0.0
    violations: List[Violation] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "spec": self.spec,
            "states": self.states,
            "edges": self.edges,
            "complete": self.complete,
            "ms": round(self.ms, 1),
            "violations": [
                {"kind": v.kind, "prop": v.prop, "schedule": v.schedule}
                for v in self.violations
            ],
        }


@dataclass
class Spec:
    """One protocol model. All callables are pure; states are hashable."""

    name: str
    doc: str
    initial_states: Callable[[], Sequence[tuple]]
    actions: Sequence[Action]
    # (name, predicate(state) -> bool): must hold in EVERY reachable state.
    invariants: Sequence[Tuple[str, Callable[[tuple], bool]]] = ()
    # (name, predicate(prev, action_name, next) -> bool): must hold on
    # every explored edge (monotonicity / no-resurrection properties).
    step_invariants: Sequence[
        Tuple[str, Callable[[tuple, str, tuple], bool]]
    ] = ()
    # Quiescence predicate: a state with no enabled action and
    # ``not is_done(state)`` is a deadlock (e.g. a lost wakeup).
    is_done: Callable[[tuple], bool] = lambda s: True
    # (name, goal(state) -> bool): AG EF goal — every reachable state must
    # be able to reach a goal state (checked only on complete exploration).
    liveness: Sequence[Tuple[str, Callable[[tuple], bool]]] = ()
    state_cap: int = 200_000


def _schedule_to(parent: Dict[tuple, Optional[Tuple[tuple, str]]],
                 state: tuple) -> List[str]:
    """Reconstruct the action schedule from an initial state via the BFS
    parent pointers (shortest counterexample by construction)."""
    names: List[str] = []
    cur: Optional[tuple] = state
    while cur is not None:
        link = parent[cur]
        if link is None:
            break
        prev, action = link
        names.append(action)
        cur = prev
    return list(reversed(names))


def explore(spec: Spec, max_violations: int = 3) -> SpecResult:
    """BFS over every interleaving of ``spec``'s actions, bounded by state
    hashing. Collects up to ``max_violations`` safety/deadlock violations
    (exploration stops early once reached: a broken model need not finish
    its — possibly unbounded — mutated state space); liveness goals are
    evaluated afterwards, only when exploration completed violation-free."""
    t0 = perf_counter()
    res = SpecResult(spec=spec.name)
    parent: Dict[tuple, Optional[Tuple[tuple, str]]] = {}
    edges: List[Tuple[tuple, str, tuple]] = []
    queue: deque = deque()
    for s in spec.initial_states():
        if s not in parent:
            parent[s] = None
            queue.append(s)

    def violated(kind: str, prop: str, message: str, state: tuple):
        res.violations.append(Violation(
            kind=kind, prop=prop, message=message,
            schedule=_schedule_to(parent, state), state=state,
        ))

    capped = False
    while queue and len(res.violations) < max_violations:
        state = queue.popleft()
        for name, pred in spec.invariants:
            if not pred(state):
                violated("invariant", name,
                         f"invariant {name!r} violated", state)
        if len(res.violations) >= max_violations:
            break
        enabled = 0
        for action in spec.actions:
            if not action.guard(state):
                continue
            enabled += 1
            nxt = action.apply(state)
            successors = nxt if isinstance(nxt, list) else [nxt]
            for succ in successors:
                for name, pred in spec.step_invariants:
                    if not pred(state, action.name, succ):
                        # Anchor the counterexample at the PREV state and
                        # append the offending action by hand (succ may be
                        # a brand-new state with no parent entry yet).
                        v = Violation(
                            kind="step", prop=name,
                            message=f"step invariant {name!r} violated by "
                                    f"action {action.name!r}",
                            schedule=_schedule_to(parent, state)
                            + [action.name],
                            state=succ,
                        )
                        res.violations.append(v)
                if succ not in parent:
                    if len(parent) >= spec.state_cap:
                        capped = True
                        continue
                    parent[succ] = (state, action.name)
                    queue.append(succ)
                edges.append((state, action.name, succ))
        if enabled == 0 and not spec.is_done(state):
            violated(
                "deadlock", "deadlock",
                "no action enabled in a non-final state (lost wakeup / "
                "stuck backpressure)", state,
            )
    res.states = len(parent)
    res.edges = len(edges)
    res.complete = not capped and not queue and not res.violations
    # Liveness (AG EF goal): backward reachability from the goal set over
    # the explored edges; any reachable state outside the backward set can
    # NEVER reach the goal — starvation, with the schedule to prove it.
    if res.complete:
        rev: Dict[tuple, List[tuple]] = {}
        for src, _a, dst in edges:
            rev.setdefault(dst, []).append(src)
        for goal_name, goal in spec.liveness:
            can_reach = {s for s in parent if goal(s)}
            frontier = deque(can_reach)
            while frontier:
                s = frontier.popleft()
                for p in rev.get(s, ()):
                    if p not in can_reach:
                        can_reach.add(p)
                        frontier.append(p)
            for s in parent:
                if s not in can_reach:
                    violated(
                        "liveness", goal_name,
                        f"state cannot reach liveness goal {goal_name!r} "
                        "by any schedule", s,
                    )
                    break
        if res.violations:
            res.complete = False
    res.ms = (perf_counter() - t0) * 1e3
    res.violations = res.violations[:max_violations]
    return res


def all_specs() -> List[Tuple[Spec, dict]]:
    """The shipped (spec, mirrors) pairs, import-cycle-free: spec modules
    import only this framework module."""
    from . import durable_log_spec, membership_spec, qos_spec, ring_spec

    return [
        (membership_spec.SPEC, membership_spec.MIRRORS),
        (durable_log_spec.SPEC, durable_log_spec.MIRRORS),
        (ring_spec.SPEC, ring_spec.MIRRORS),
        (qos_spec.SPEC, qos_spec.MIRRORS),
    ]
