"""ITS-M spec: the zero-copy descriptor ring's publish / park / doorbell
discipline (native/include/its/ring.h; docs/zero_copy.md).

Three actors over a 1-slot SQ and 1-slot CQ, two ops end to end:

- **producer** (the client's submit path): write the slot record and its
  generation stamp (``gen = seq + 1``, release), THEN publish the tail
  (release), THEN fence + ``ring_flag_take`` — if the consumer's park
  flag was set, exactly one doorbell wakes it. Backpressure: no free SQ
  slot means the submit action is simply not enabled (the real producer
  waits on head).
- **server** (SQ consumer / CQ producer): consume only when the
  acquire-loaded tail shows work; park via the Dekker pairing — set the
  seq_cst ``sq_waiting`` flag, RE-CHECK the tail, only then sleep.
  Completions mirror the producer discipline on the CQ.
- **reaper** (the client's CQ consumer): same consume/park protocol
  against the CQ flag.

Model granularity: each release-store (record+gen, tail) is its own
atomic action, so every interleaving of "record written but tail not
yet published" with both consumers is explored; empty-observation, flag
set and tail re-check are separate actions, so the classic lost-wakeup
window (publish+doorbell BETWEEN flag-set and sleep, or before
flag-set) is explored too. The doorbell itself is a SOCKET FRAME
(kOpRingDoorbell / kStatusRingEvent; ring.h's doze/wake comment): a
frame posted before the consumer blocks leaves the socket readable and
epoll returns immediately, so the wake channel is sticky — modeled as
the ``s_wake``/``r_wake`` tokens a park re-check drains. Dropping that
stickiness (or the re-check) makes exploration find the stranded-parker
schedule: a stale doorbell for an already-consumed publish takes the
freshly-set flag, the "wake" hits a not-yet-sleeping consumer, and the
consumer then sleeps with its flag down, undoorbellable.

Explored properties:

- **publish-order** (invariant): ``tail <= gen_written`` on both rings —
  no slot is ever visible before its record+gen landed (no CQE consumed
  before publish);
- **consume-order** (invariant): ``head <= tail`` on both rings;
- **parked-flag-consistent** (invariant): a parked actor still has its
  flag set (the doorbell that clears it also wakes) and never sleeps
  past a pending doorbell frame;
- **deadlock** (built-in): no enabled action in a non-final state — a
  dropped re-check or a lost doorbell strands a parked consumer behind
  full-ring backpressure, and BFS finds the exact schedule;
- **all-ops-complete** (liveness, AG EF): from every reachable state
  some schedule reaps both ops — backpressure never wedges the pipeline.
"""

from __future__ import annotations

from typing import List

from . import Action, Spec

N_OPS = 2      # ops submitted end to end
SQ_CAP = 1     # SQ slots (1 => submit backpressure is exercised)
CQ_CAP = 1     # CQ slots

# State tuple indices. Counters are cumulative sequence numbers (the
# real ring's monotonically increasing seq space); pc_* are tiny
# per-actor program counters. s_wake/r_wake model the doorbell SOCKET
# FRAME in flight: the real doorbell is a kOpRingDoorbell /
# kStatusRingEvent message, so a doorbell that lands before the consumer
# blocks leaves the socket readable and the consumer's epoll_wait
# returns immediately — the wake channel is STICKY, which is exactly
# what makes the stale-doorbell race (flag taken between the consumer's
# flag-set and its sleep) benign.
(SQ_GEN, SQ_TAIL, SQ_HEAD, CQ_GEN, CQ_TAIL, CQ_HEAD,
 SQ_FLAG, CQ_FLAG, S_PARKED, R_PARKED, S_WAKE, R_WAKE,
 PC_P, PC_S, PC_R) = range(15)

IDLE, WROTE, PUBLISHED = "idle", "wrote", "published"
PARKING = "parking"


def initial_states() -> List[tuple]:
    return [(0, 0, 0, 0, 0, 0, 0, 0, False, False, False, False,
             IDLE, IDLE, IDLE)]


def _set(state: tuple, **kv) -> tuple:
    names = {
        "sq_gen": SQ_GEN, "sq_tail": SQ_TAIL, "sq_head": SQ_HEAD,
        "cq_gen": CQ_GEN, "cq_tail": CQ_TAIL, "cq_head": CQ_HEAD,
        "sq_flag": SQ_FLAG, "cq_flag": CQ_FLAG,
        "s_parked": S_PARKED, "r_parked": R_PARKED,
        "s_wake": S_WAKE, "r_wake": R_WAKE,
        "pc_p": PC_P, "pc_s": PC_S, "pc_r": PC_R,
    }
    out = list(state)
    for k, v in kv.items():
        out[names[k]] = v
    return tuple(out)


ACTIONS = (
    # -- producer: submit path ----------------------------------------------
    Action(  # write record + gen stamp (release-store #1)
        name="p_write_gen",
        guard=lambda s: (
            s[PC_P] == IDLE and s[SQ_GEN] < N_OPS
            and s[SQ_GEN] - s[SQ_HEAD] < SQ_CAP   # a free SQ slot
        ),
        apply=lambda s: _set(s, sq_gen=s[SQ_GEN] + 1, pc_p=WROTE),
    ),
    Action(  # publish tail (release-store #2, after the gen stamp)
        name="p_publish_tail",
        guard=lambda s: s[PC_P] == WROTE,
        apply=lambda s: _set(s, sq_tail=s[SQ_GEN], pc_p=PUBLISHED),
    ),
    Action(  # fence + ring_flag_take: exactly one doorbell if parked flag
        #        set. The doorbell is a socket frame: it wakes a sleeping
        #        consumer directly, and a consumer that has not yet slept
        #        finds the frame waiting (sticky wake token).
        name="p_doorbell",
        guard=lambda s: s[PC_P] == PUBLISHED,
        apply=lambda s: _set(
            s, pc_p=IDLE,
            **(
                {"sq_flag": 0, "s_parked": False, "s_wake": False}
                if s[SQ_FLAG] and s[S_PARKED]
                else {"sq_flag": 0, "s_wake": True} if s[SQ_FLAG]
                else {}
            ),
        ),
    ),
    # -- server: SQ consume, CQ produce, park -------------------------------
    Action(  # acquire-load tail, gen matches -> consume one descriptor
        name="s_consume_sqe",
        guard=lambda s: (
            s[PC_S] == IDLE and not s[S_PARKED]
            and s[SQ_TAIL] > s[SQ_HEAD]
        ),
        apply=lambda s: _set(s, sq_head=s[SQ_HEAD] + 1, pc_s="have_op"),
    ),
    Action(  # write CQE record + gen (release-store #1 on the CQ)
        name="s_write_cqe",
        guard=lambda s: (
            s[PC_S] == "have_op" and s[CQ_GEN] - s[CQ_HEAD] < CQ_CAP
        ),
        apply=lambda s: _set(s, cq_gen=s[CQ_GEN] + 1, pc_s="cq_wrote"),
    ),
    Action(  # publish CQ tail (release-store #2)
        name="s_publish_cq_tail",
        guard=lambda s: s[PC_S] == "cq_wrote",
        apply=lambda s: _set(s, cq_tail=s[CQ_GEN], pc_s="cq_published"),
    ),
    Action(  # fence + flag_take on the reaper's park flag (sticky, as above)
        name="s_doorbell",
        guard=lambda s: s[PC_S] == "cq_published",
        apply=lambda s: _set(
            s, pc_s=IDLE,
            **(
                {"cq_flag": 0, "r_parked": False, "r_wake": False}
                if s[CQ_FLAG] and s[R_PARKED]
                else {"cq_flag": 0, "r_wake": True} if s[CQ_FLAG]
                else {}
            ),
        ),
    ),
    Action(  # park step 0: the poll loop observes an empty SQ and decides
        #        to park (the decision and the flag store are NOT atomic —
        #        this window is where a publish+doorbell can slip in)
        name="s_observe_empty",
        guard=lambda s: (
            s[PC_S] == IDLE and not s[S_PARKED] and s[SQ_FLAG] == 0
            and s[SQ_TAIL] == s[SQ_HEAD] and s[SQ_HEAD] < N_OPS
        ),
        apply=lambda s: _set(s, pc_s="saw_empty"),
    ),
    Action(  # park step 1: seq_cst store of the waiting flag
        name="s_park_set_flag",
        guard=lambda s: s[PC_S] == "saw_empty",
        apply=lambda s: _set(s, sq_flag=1, pc_s=PARKING),
    ),
    Action(  # park step 2: the Dekker RE-CHECK of the tail, then sleep.
        #        A pending doorbell frame (stale flag_take between our
        #        flag-set and here) makes the sleep return immediately:
        #        modeled as bailing out and draining the wake token.
        name="s_park_recheck",
        guard=lambda s: s[PC_S] == PARKING,
        apply=lambda s: (
            _set(s, sq_flag=0, s_wake=False, pc_s=IDLE)  # insta-wake
            if s[S_WAKE]
            else _set(s, sq_flag=0, pc_s=IDLE)           # work arrived: bail
            if s[SQ_TAIL] > s[SQ_HEAD]
            else _set(s, s_parked=True, pc_s=IDLE)       # really sleep
        ),
    ),
    # -- reaper: CQ consume, park -------------------------------------------
    Action(
        name="r_reap_cqe",
        guard=lambda s: (
            s[PC_R] == IDLE and not s[R_PARKED]
            and s[CQ_TAIL] > s[CQ_HEAD]
        ),
        apply=lambda s: _set(s, cq_head=s[CQ_HEAD] + 1),
    ),
    Action(
        name="r_observe_empty",
        guard=lambda s: (
            s[PC_R] == IDLE and not s[R_PARKED] and s[CQ_FLAG] == 0
            and s[CQ_TAIL] == s[CQ_HEAD] and s[CQ_HEAD] < N_OPS
        ),
        apply=lambda s: _set(s, pc_r="saw_empty"),
    ),
    Action(
        name="r_park_set_flag",
        guard=lambda s: s[PC_R] == "saw_empty",
        apply=lambda s: _set(s, cq_flag=1, pc_r=PARKING),
    ),
    Action(
        name="r_park_recheck",
        guard=lambda s: s[PC_R] == PARKING,
        apply=lambda s: (
            _set(s, cq_flag=0, r_wake=False, pc_r=IDLE)
            if s[R_WAKE]
            else _set(s, cq_flag=0, pc_r=IDLE)
            if s[CQ_TAIL] > s[CQ_HEAD]
            else _set(s, r_parked=True, pc_r=IDLE)
        ),
    ),
)


def inv_publish_order(s: tuple) -> bool:
    return s[SQ_TAIL] <= s[SQ_GEN] and s[CQ_TAIL] <= s[CQ_GEN]


def inv_consume_order(s: tuple) -> bool:
    return s[SQ_HEAD] <= s[SQ_TAIL] and s[CQ_HEAD] <= s[CQ_TAIL]


def inv_parked_flag(s: tuple) -> bool:
    # A sleeping actor's flag stays set until the (atomic) flag_take that
    # also wakes it — a parked actor with a cleared flag can never be
    # doorbelled again. And no actor sleeps past a pending doorbell
    # frame: the recheck's insta-wake consumes it before parking.
    if s[S_PARKED] and (s[SQ_FLAG] == 0 or s[S_WAKE]):
        return False
    if s[R_PARKED] and (s[CQ_FLAG] == 0 or s[R_WAKE]):
        return False
    return True


def is_done(s: tuple) -> bool:
    # Clean quiescence: both ops reaped and every actor's pc back at idle
    # (parked-while-no-more-work never happens here because the park
    # guards stop at N_OPS; mid-protocol pcs with no enabled action are
    # exactly the lost-wakeup states).
    return s[CQ_HEAD] == N_OPS and (s[PC_P], s[PC_S], s[PC_R]) == (
        IDLE, IDLE, IDLE,
    )


SPEC = Spec(
    name="ring_sq_cq",
    doc="publish/park/doorbell: no CQE before publish, Dekker re-check "
        "has no lost wakeup, backpressure never deadlocks (its/ring.h)",
    initial_states=initial_states,
    actions=ACTIONS,
    invariants=(
        ("publish-order", inv_publish_order),
        ("consume-order", inv_consume_order),
        ("parked-flag-consistent", inv_parked_flag),
    ),
    is_done=is_done,
    liveness=(
        ("all-ops-reaped", lambda s: s[CQ_HEAD] == N_OPS),
    ),
)


MIRRORS = {
    "kind": "cpp_functions",
    "file": "native/include/its/ring.h",
    # One capture group: the function-name family the model must track.
    "pattern": r"\b(ring_[a-z0-9_]+)\s*\(",
    "actions": {
        "p_write_gen": "ring_store_rel",
        "p_publish_tail": "ring_store_rel",
        "p_doorbell": "ring_flag_take",
        "s_consume_sqe": "ring_load_acq",
        "s_write_cqe": "ring_store_rel",
        "s_publish_cq_tail": "ring_store_rel",
        "s_doorbell": "ring_flag_take",
        "s_observe_empty": "ring_load_acq",
        "s_park_set_flag": "ring_flag_park",
        "s_park_recheck": "ring_flag_clear",
        "r_reap_cqe": "ring_load_acq",
        "r_observe_empty": "ring_load_acq",
        "r_park_set_flag": "ring_flag_park",
        "r_park_recheck": "ring_flag_clear",
    },
    # Every ring_* name in the header must be covered or exempted.
    "exempt": {
        "ring_fence": "modeled implicitly: doorbell actions read the "
                      "flag AFTER the tail store (the fence's ordering)",
        "ring_align64": "layout geometry, no concurrency",
        "ring_sq_off": "layout geometry, no concurrency",
        "ring_cq_off": "layout geometry, no concurrency",
        "ring_meta_off": "layout geometry, no concurrency",
        "ring_segment_bytes": "layout geometry, no concurrency",
        "ring_view_init": "attach-time geometry validation",
        "ring_poll_budget": "adaptive poll pacing (performance, not "
                            "safety; bench-gated)",
        "ring_gap_note": "adaptive poll pacing (performance, not safety)",
    },
}
