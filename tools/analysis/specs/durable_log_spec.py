"""ITS-M spec: DurableLog crash/replay
(infinistore_tpu/membership.py ``DurableLog``).

The model drives one fixed journal script — the record vocabulary the
cluster actually writes (root adds, a reshard ``plan``, per-root
``migrated`` marks, a ``drop`` tombstone, the plan's ``fin``) — through
every crash point the framing allows:

- ``append``: the next script record lands as an intact frame, or as a
  frame whose payload will fail its crc at replay (``append_badcrc`` —
  bit rot / a torn mid-frame rewrite);
- ``crash``: the process dies now; ``crash_torn`` additionally leaves
  the NEXT record as a truncated frame (the write in flight at death);
- ``compact`` (end of script): the atomic snapshot rewrite — its crash
  outcomes are exactly ``os.replace``'s: the OLD file intact or the NEW
  file intact, never a mix;
- ``replay``: parse the surviving file with the real replay policy
  (stop at the first torn frame, skip bad-checksum frames, apply in
  order).

The oracle is an independent reference interpreter over the *durable
prefix* (intact frames before the first torn one, bad-crc frames
skipped). Explored properties:

- **replay-matches-durable-prefix**: the replayed summary equals the
  reference semantics — in particular a dropped root NEVER resurrects
  (the ``drop`` tombstone is last-record-wins);
- **no-root-resurrection**: stated independently of the interpreter —
  if a durable ``drop r`` has no later durable ``root r``, then ``r``
  is not live after replay;
- **reshard-debt-analytic**: the resumed reshard debt equals the
  analytic delta — planned roots minus durable ``migrated`` marks, zero
  once the ``fin`` landed;
- **compact-preserves-semantics** (step invariant): a compacted file
  replays to the same summary as the file it replaced.
"""

from __future__ import annotations

from typing import List, Tuple

from . import Action, Spec

# Script ops: ("root", r) add; ("plan", epoch, roots) reshard plan;
# ("migrated", epoch, r) one root done; ("drop", r) tombstone;
# ("fin", epoch) plan finalized.
SCRIPT: Tuple[tuple, ...] = (
    ("root", "r1"),
    ("root", "r2"),
    ("plan", 2, ("r1", "r2")),
    ("migrated", 2, "r1"),
    ("drop", "r1"),
    ("fin", 2),
)

# Frame: ("ok" | "badcrc" | "torn", op).
# State: (phase, script_idx, file_frames, summary)
#   phase: "run" | "crashed" | "replayed" | "compacted"
#   summary: () until replayed, then the replayed reference tuple.
PH, IDX, FILE, SUM = range(4)


def initial_states() -> List[tuple]:
    return [("run", 0, (), ())]


# -- reference semantics -----------------------------------------------------

def durable_prefix(frames: tuple) -> tuple:
    """Intact frames the real replay would parse: stop at the first torn
    frame (nothing after a broken length prefix can be delimited), skip
    bad-checksum frames (the length prefix still delimits them)."""
    out = []
    for kind, op in frames:
        if kind == "torn":
            break
        if kind == "badcrc":
            continue
        out.append(op)
    return tuple(out)


def interpret(ops: tuple) -> tuple:
    """Reference interpreter: (live_roots, open_plan_epoch, debt_roots).
    Last record wins per key; a plan's debt shrinks per ``migrated`` and
    collapses at ``fin``."""
    live: List[str] = []
    plan_epoch = 0
    debt: List[str] = []
    for op in ops:
        if op[0] == "root":
            if op[1] not in live:
                live.append(op[1])
        elif op[0] == "drop":
            if op[1] in live:
                live.remove(op[1])
        elif op[0] == "plan":
            plan_epoch = op[1]
            debt = list(op[2])
        elif op[0] == "migrated":
            if op[1] == plan_epoch and op[2] in debt:
                debt.remove(op[2])
        elif op[0] == "fin":
            if op[1] == plan_epoch:
                plan_epoch = 0
                debt = []
    return (tuple(sorted(live)), plan_epoch, tuple(sorted(debt)))


def model_replay(frames: tuple) -> tuple:
    """The model's mirror of DurableLog.replay + the cluster's record
    application: torn tail discarded, bad checksum skipped, append order
    preserved. (The seeded ITS-M tests mutate THIS to e.g. resurrect
    past a torn cut; the invariants below then fire.)"""
    return interpret(durable_prefix(frames))


def snapshot_ops(frames: tuple) -> tuple:
    """The compaction snapshot: the current semantics re-serialized as a
    minimal record sequence (live roots, the open plan + residual debt),
    tombstones and superseded increments discarded."""
    live, plan_epoch, debt = model_replay(frames)
    ops: List[tuple] = [("root", r) for r in live]
    if plan_epoch:
        ops.append(("plan", plan_epoch, debt))
    return tuple(ops)


# -- actions -----------------------------------------------------------------

def _next_op(state: tuple) -> tuple:
    return SCRIPT[state[IDX]]


ACTIONS = (
    Action(
        name="append",
        guard=lambda s: s[PH] == "run" and s[IDX] < len(SCRIPT),
        apply=lambda s: (
            "run", s[IDX] + 1, s[FILE] + (("ok", _next_op(s)),), (),
        ),
    ),
    Action(
        name="append_badcrc",
        guard=lambda s: s[PH] == "run" and s[IDX] < len(SCRIPT),
        apply=lambda s: (
            "run", s[IDX] + 1, s[FILE] + (("badcrc", _next_op(s)),), (),
        ),
    ),
    Action(
        name="crash",
        guard=lambda s: s[PH] == "run",
        apply=lambda s: ("crashed", s[IDX], s[FILE], ()),
    ),
    Action(
        name="crash_torn",
        guard=lambda s: s[PH] == "run" and s[IDX] < len(SCRIPT),
        apply=lambda s: (
            "crashed", s[IDX] + 1, s[FILE] + (("torn", _next_op(s)),), (),
        ),
    ),
    # Atomic compaction at end of script: tmp file + fsync + os.replace.
    # Crash outcomes are old-file OR new-file, never a mix.
    Action(
        name="compact",
        guard=lambda s: s[PH] == "run" and s[IDX] == len(SCRIPT),
        apply=lambda s: [
            ("crashed", s[IDX], s[FILE], ()),  # died before replace
            ("crashed", s[IDX],
             tuple(("ok", op) for op in snapshot_ops(s[FILE])), ()),
            ("compacted", s[IDX],
             tuple(("ok", op) for op in snapshot_ops(s[FILE])), ()),
        ],
    ),
    Action(
        name="replay",
        guard=lambda s: s[PH] == "crashed",
        apply=lambda s: ("replayed", s[IDX], s[FILE], model_replay(s[FILE])),
    ),
)


# -- invariants --------------------------------------------------------------

def inv_replay_matches_prefix(state: tuple) -> bool:
    if state[PH] != "replayed":
        return True
    return state[SUM] == interpret(durable_prefix(state[FILE]))


def inv_no_root_resurrection(state: tuple) -> bool:
    """A durable drop with no later durable re-add keeps the root dead —
    stated straight from the frames, independent of the interpreter."""
    if state[PH] != "replayed":
        return True
    prefix = durable_prefix(state[FILE])
    live = set(state[SUM][0])
    for i, op in enumerate(prefix):
        if op[0] != "drop":
            continue
        readded = any(
            later[0] == "root" and later[1] == op[1]
            for later in prefix[i + 1:]
        )
        if not readded and op[1] in live:
            return False
    return True


def inv_debt_analytic(state: tuple) -> bool:
    """Resumed reshard debt == planned roots minus durable migrated marks
    (empty once the fin landed) — the analytic delta a restart resumes."""
    if state[PH] != "replayed":
        return True
    prefix = durable_prefix(state[FILE])
    plan_epoch, planned = 0, ()
    migrated = set()
    finned = False
    for op in prefix:
        if op[0] == "plan":
            plan_epoch, planned = op[1], op[2]
            migrated = set()
            finned = False
        elif op[0] == "migrated" and op[1] == plan_epoch:
            migrated.add(op[2])
        elif op[0] == "fin" and op[1] == plan_epoch:
            finned = True
    expect = () if finned or not plan_epoch else tuple(
        sorted(set(planned) - migrated)
    )
    return state[SUM][2] == expect


def step_compact_preserves(prev: tuple, action: str, nxt: tuple) -> bool:
    """Every compact outcome (old file, new file) replays to the same
    summary the pre-compact file had — os.replace atomicity + snapshot
    fidelity."""
    if action != "compact":
        return True
    return model_replay(nxt[FILE]) == model_replay(prev[FILE])


SPEC = Spec(
    name="durable_log",
    doc="crash at every frame boundary: replay == durable-prefix "
        "semantics, drop never resurrects, reshard debt analytic, "
        "compaction atomic (membership.DurableLog)",
    initial_states=initial_states,
    actions=ACTIONS,
    invariants=(
        ("replay-matches-durable-prefix", inv_replay_matches_prefix),
        ("no-root-resurrection", inv_no_root_resurrection),
        ("reshard-debt-analytic", inv_debt_analytic),
    ),
    step_invariants=(
        ("compact-preserves-semantics", step_compact_preserves),
    ),
    is_done=lambda s: s[PH] in ("replayed", "compacted"),
)


MIRRORS = {
    "kind": "py_class",
    "file": "infinistore_tpu/membership.py",
    "cls": "DurableLog",
    "actions": {
        "append": "append",
        "append_badcrc": "append",
        "crash": "append",       # a crash is the absence of the next append
        "crash_torn": "append",  # ... with the in-flight frame truncated
        "compact": "compact",
        "replay": "replay",
    },
    "exempt": {
        "close": "clean shutdown == crash with a flushed tail; subsumed "
                 "by the crash action",
        "size_bytes": "observability",
        "status": "observability",
    },
}
