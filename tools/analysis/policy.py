"""ITS-P*: degrade-policy and QoS-tagging discipline.

Two conventions hold the self-healing (PR 3) and QoS (PR 4) planes
together, and both are enforceable only by reading every call site —
exactly what this pass does:

- ITS-P001 **transport errors route through the degrade policy.** An
  ``except`` clause that names ``InfiniStoreException`` (the TRANSPORT
  error type; the semantic subclasses KeyNotFound / ResourcePressure /
  NoMatch are legitimate control flow) must re-raise, feed a breaker /
  quarantine / degrade routine, or park the error on a future. A handler
  that just logs-and-continues turns a dead store into silent data loss
  (docs/robustness.md's failure-policy matrix). ``faults.py`` is exempt —
  it manufactures transport errors by design.

- ITS-P002 **batched-op producers tag a QoS class at the source.** Calls
  to the batched data-plane ops (``*_cache_async`` / ``write_cache`` /
  ``read_cache``) outside the transport layer itself must pass
  ``priority`` explicitly (kwarg, 4th positional, or a ``**kw`` splat
  that forwards it, e.g. ``wire.qos_kwargs``). An untagged producer
  defaults to FOREGROUND silently and erodes the isolation the two-class
  scheduler measures (docs/qos.md); the decision must be visible at the
  call site. ``benchmark.py`` is exempt: its synthetic legs measure the
  untagged default path on purpose.

- ITS-P003 **migration traffic is BACKGROUND, always.** Inside the
  membership subsystem (``membership.py`` — the resharder's copy/prune
  machinery) and the tiered capacity plane (``tiering.py`` — the
  demotion/promotion copy engine, docs/tiering.md), every data-plane
  call (the batched ops AND the single-key ``tcp_*_cache`` ops) must
  pass a ``priority`` whose expression names BACKGROUND
  (``PRIORITY_BACKGROUND`` / ``wire.PRIORITY_BACKGROUND``).
  ITS-P002's "any explicit class" is not enough here: a reshard moving
  ~1/N of the pool at FOREGROUND priority would push the decode-blocking
  p99 exactly when the fleet is already churning (docs/membership.md,
  docs/qos.md). Membership-transition handlers also fall under ITS-P001
  like everyone else — their ``except InfiniStoreException`` clauses
  must feed the degrade machinery (the cluster's ``_begin``/``_done``
  breaker plumbing), not swallow a dying member mid-migration.

- ITS-P004 **layer-streaming saves name their class at the source.**
  ``stage_layer_save`` producers (``disagg.py`` — the prefill→decode
  handoff stream, docs/disaggregation.md; ``vllm_v1.py`` — the engine's
  own save-behind-the-forward-pass) must pass a ``priority`` whose
  expression literally names a class (``PRIORITY_FOREGROUND`` /
  ``PRIORITY_BACKGROUND``). Handoff ships feed a decode consumer that
  is actively blocked on those exact bytes and must be FOREGROUND;
  engine background saves must not be — and because the same one-line
  call sits in both regimes, an inherited default or an opaque variable
  is exactly how the wrong class sneaks in. Connector-layer *forwards*
  (``cluster.py``, ``tpu/kv_quant.py`` re-shipping ``priority=priority``)
  are not producers and are out of scope: the decision was already made
  upstream.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Context, Finding, register

PACKAGE_REL = "infinistore_tpu"

# Transport exception names (the base type). Semantic subclasses are NOT
# transport failures and may be caught freely.
TRANSPORT_EXC = {"InfiniStoreException"}
SEMANTIC_EXC = {
    "InfiniStoreKeyNotFound", "InfiniStoreResourcePressure", "InfiniStoreNoMatch",
}

# A handler body containing any of these routes the error into the degrade
# machinery: breaker records, member attribution, stripe quarantine,
# future-parking, or the cluster degrade accounting.
ROUTING_CALLS = {
    "_degrade", "_done", "_quarantine", "record_failure", "set_exception",
    "_absorb", "_record", "fail", "tier_done", "_cold_done",
}

# ITS-P001 exemptions (whole files): fault injection exists to fabricate
# and absorb transport errors.
P001_EXEMPT_FILES = {"infinistore_tpu/faults.py"}

# Batched data-plane ops whose producers must tag a class.
BATCHED_OPS = {
    "rdma_write_cache_async", "rdma_read_cache_async",
    "write_cache_async", "read_cache_async",
    "write_cache", "read_cache",
}

# ITS-P002 scope exclusions: the transport layer itself (lib.py owns the
# default), the fault shim (pass-through), and the benchmark harness
# (deliberately measures the untagged default path).
P002_EXEMPT_FILES = {
    "infinistore_tpu/lib.py",
    "infinistore_tpu/faults.py",
    "infinistore_tpu/benchmark.py",
}

# ITS-P003 scope: the membership subsystem's migration machinery AND the
# tiered capacity plane's demotion/promotion copies (docs/tiering.md),
# where every data-plane op — batched AND single-key — must be BACKGROUND.
P003_FILES = {"infinistore_tpu/membership.py", "infinistore_tpu/tiering.py"}
P003_OPS = BATCHED_OPS | {"tcp_read_cache", "tcp_write_cache"}

# ITS-P004 scope: the layer-streaming PRODUCERS — the disaggregated
# prefill stream (FOREGROUND: a decode consumer is blocked on the bytes)
# and the engine's save-behind-the-forward-pass (BACKGROUND). Connector
# layers that forward priority=priority are out of scope by file.
P004_FILES = {"infinistore_tpu/disagg.py", "infinistore_tpu/vllm_v1.py"}
P004_OPS = {"stage_layer_save"}


def _scope_map(tree: ast.Module) -> dict:
    """node -> dotted name of the nearest enclosing function/class scope.
    Finding keys anchor on the scope (plus a within-scope index only when
    a scope holds several hits), so adding a handler elsewhere in the file
    cannot re-key someone else's baseline entry."""
    scopes: dict = {}

    def visit(node, qual: str):
        for child in ast.iter_child_nodes(node):
            q = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual else child.name
            scopes[child] = q
            visit(child, q)

    visit(tree, "")
    return scopes


def _scoped_key(rule: str, rel: str, scope: str, slug: str, nth: dict) -> str:
    base = f"{rule}:{rel}:{scope or '<module>'}" + (f":{slug}" if slug else "")
    nth[base] = nth.get(base, 0) + 1
    return base if nth[base] == 1 else f"{base}:{nth[base]}"


def _exc_names(handler: ast.ExceptHandler) -> Set[str]:
    node = handler.type
    if node is None:
        return set()
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    names = set()
    for e in elts:
        if isinstance(e, ast.Name):
            names.add(e.id)
        elif isinstance(e, ast.Attribute):
            names.add(e.attr)
    return names


def _routes_error(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name in ROUTING_CALLS:
                return True
    return False


def _passes_priority(call: ast.Call) -> bool:
    if any(kw.arg == "priority" for kw in call.keywords):
        return True
    if any(kw.arg is None for kw in call.keywords):  # **splat (qos_kwargs)
        return True
    return len(call.args) >= 4  # (blocks, block_size, ptr, priority)


def scan(ctx: Context, package_rel: str = PACKAGE_REL,
         p001_exempt: Optional[Set[str]] = None,
         p002_exempt: Optional[Set[str]] = None,
         p003_files: Optional[Set[str]] = None,
         p004_files: Optional[Set[str]] = None) -> List[Finding]:
    p001_exempt = P001_EXEMPT_FILES if p001_exempt is None else p001_exempt
    p002_exempt = P002_EXEMPT_FILES if p002_exempt is None else p002_exempt
    p003_files = P003_FILES if p003_files is None else p003_files
    p004_files = P004_FILES if p004_files is None else p004_files
    findings: List[Finding] = []
    for rel in ctx.walk_py(package_rel):
        try:
            tree = ast.parse(ctx.read(rel))
        except SyntaxError:
            continue
        if rel not in p001_exempt:
            findings += _scan_p001(rel, tree)
        if rel not in p002_exempt:
            findings += _scan_p002(rel, tree)
        if rel in p003_files:
            findings += _scan_p003(rel, tree)
        if rel in p004_files:
            findings += _scan_p004(rel, tree)
    return findings


def _scan_p001(rel: str, tree: ast.Module) -> List[Finding]:
    out: List[Finding] = []
    scopes = _scope_map(tree)
    nth: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = _exc_names(node)
        if not (names & TRANSPORT_EXC):
            continue
        if _routes_error(node):
            continue
        out.append(Finding(
            rule="ITS-P001", file=rel, line=node.lineno,
            message="except clause catches the TRANSPORT error type "
                    "(InfiniStoreException) without re-raising or routing "
                    "it through the degrade policy (breaker / quarantine / "
                    "_degrade / set_exception) — a dead store degrades to "
                    "silent data loss here (docs/robustness.md)",
            key=_scoped_key("ITS-P001", rel, scopes.get(node, ""), "", nth),
        ))
    return out


def _scan_p002(rel: str, tree: ast.Module) -> List[Finding]:
    out: List[Finding] = []
    scopes = _scope_map(tree)
    nth: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in BATCHED_OPS):
            continue
        if _passes_priority(node):
            continue
        out.append(Finding(
            rule="ITS-P002", file=rel, line=node.lineno,
            message=f".{fn.attr}() without an explicit QoS class — pass "
                    "priority= (or **wire.qos_kwargs(conn, priority)) so "
                    "the FOREGROUND/BACKGROUND decision is visible at the "
                    "producing call site (docs/qos.md)",
            key=_scoped_key("ITS-P002", rel, scopes.get(node, ""), fn.attr, nth),
        ))
    return out


def _names_background(node) -> bool:
    """Does this expression reference the BACKGROUND class (a Name or
    Attribute whose identifier names BACKGROUND, e.g. PRIORITY_BACKGROUND /
    wire.PRIORITY_BACKGROUND), anywhere inside it?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "BACKGROUND" in sub.id:
            return True
        if isinstance(sub, ast.Attribute) and "BACKGROUND" in sub.attr:
            return True
    return False


def _scan_p003(rel: str, tree: ast.Module) -> List[Finding]:
    out: List[Finding] = []
    scopes = _scope_map(tree)
    nth: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in P003_OPS):
            continue
        tagged = False
        for kw in node.keywords:
            # An explicit priority kwarg naming BACKGROUND, or a **splat
            # whose expression does (wire.qos_kwargs(conn,
            # PRIORITY_BACKGROUND)).
            if kw.arg == "priority" and _names_background(kw.value):
                tagged = True
            if kw.arg is None and _names_background(kw.value):
                tagged = True
        if len(node.args) >= 4 and _names_background(node.args[3]):
            tagged = True
        if tagged:
            continue
        out.append(Finding(
            rule="ITS-P003", file=rel, line=node.lineno,
            message=f".{fn.attr}() in the membership subsystem without an "
                    "explicit BACKGROUND tag — migration traffic must pass "
                    "priority=PRIORITY_BACKGROUND (or a qos_kwargs splat "
                    "naming it) so a reshard can never move the foreground "
                    "p99 (docs/membership.md, docs/qos.md)",
            key=_scoped_key("ITS-P003", rel, scopes.get(node, ""), fn.attr, nth),
        ))
    return out


def _names_priority_class(node) -> bool:
    """Does this expression literally name a QoS class — a Name or
    Attribute identifier containing FOREGROUND or BACKGROUND (e.g.
    PRIORITY_FOREGROUND / wire.PRIORITY_BACKGROUND)?"""
    for sub in ast.walk(node):
        ident = (
            sub.id if isinstance(sub, ast.Name)
            else sub.attr if isinstance(sub, ast.Attribute) else ""
        )
        if "FOREGROUND" in ident or "BACKGROUND" in ident:
            return True
    return False


def _scan_p004(rel: str, tree: ast.Module) -> List[Finding]:
    out: List[Finding] = []
    scopes = _scope_map(tree)
    nth: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in P004_OPS):
            continue
        tagged = any(
            kw.arg == "priority" and _names_priority_class(kw.value)
            for kw in node.keywords
        )
        if tagged:
            continue
        out.append(Finding(
            rule="ITS-P004", file=rel, line=node.lineno,
            message=f".{fn.attr}() in a layer-streaming producer without a "
                    "priority= that names the class — handoff streams are "
                    "PRIORITY_FOREGROUND (a decode consumer is blocked on "
                    "these bytes), engine background saves "
                    "PRIORITY_BACKGROUND; the choice must be literal at the "
                    "call site (docs/disaggregation.md, docs/qos.md)",
            key=_scoped_key("ITS-P004", rel, scopes.get(node, ""), fn.attr, nth),
        ))
    return out


@register("policy",
          "transport errors route through the degrade policy; producers tag a QoS class (ITS-P*)",
          rule_prefix="ITS-P")
def check(ctx: Context) -> List[Finding]:
    return scan(ctx)
