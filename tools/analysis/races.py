"""ITS-R*: cross-thread shared-state race analysis (the static side of the
concurrency discipline; the dynamic side — lock tracer + deterministic
interleaving — lives in tools/analysis/interleave.py).

PRs 6-12 grew seven daemon threads (resharder, fleet scraper, gossip agent,
tier reconciler, slow-op watchdog, the QoS gate executor, the vllm IO loop)
that mutate state also touched from the asyncio loop, and every race fixed
so far (breaker `_breaker_lock` serialization, SloEngine fire/clear
atomicity, admin-lock rollback) was found by a reviewer reading diffs. This
pass makes the discipline mechanical, ThreadSanitizer-style:

- **ITS-R001** shared-attribute guard discipline. A *shared-state registry*
  is inferred from the AST: any class whose methods are reachable both from
  a ``threading.Thread(target=...)`` / ``to_thread`` / ``run_in_executor``
  worker and from an ``async def`` (the loop side) has its instance
  attributes classified. An attribute written on one side and read or
  written on the other must be covered by a declared guard —
  ``# its: guard[attr: lock]`` in the class body — and every access must be
  dominated by ``with self.<lock>`` (or a ``# its: requires[lock]``
  caller-holds contract on the method). Guard modes:

  * ``guard[attr: lock]`` — every access under the lock;
  * ``guard[attr: lock!w]`` — writes under the lock, reads lock-free (the
    published-snapshot pattern: ``Membership._view``);
  * ``guard[attr: single_writer]`` — all writes confined to ONE side
    (counter dicts snapshot-read by the manage plane).

  Attributes assigned only in ``__init__`` (or ``# its: construction``
  methods) and synchronization primitives themselves are exempt.

- **ITS-R002** lock-order graph. Nested ``with``-acquisitions (direct, via
  resolvable calls while a lock is held, and via ``# its: acquires[Lock]``
  summaries for callback indirection like ``DurableLog.compact``) build a
  directed acquired-after graph; any cycle — or re-acquiring a
  non-reentrant ``Lock`` already held — is a potential deadlock.

- **ITS-R003** journal-outside-lock discipline. ``EventJournal.emit`` /
  ``telemetry.emit`` / the cluster's ``_journal_append``-family sinks must
  never run while an engine lock (breaker, catalog, membership, SLO,
  reconciler CV, ...) is held — structurally, not by convention.

- **ITS-R004** condition-variable waits must loop on a predicate
  (``wait()`` inside a ``while``; ``wait_for`` carries its own loop;
  ``Event.wait`` is exempt — the event IS the predicate).

- **ITS-R005** docs lockstep: the guard registry is the source of truth
  for the "concurrency model" section of docs/design.md
  (``concurrency_model_lines``); a guard added without a docs row — or a
  stale docs row — fails the run, so the doc can never drift from the
  annotations ITS-R001 enforces.

Call resolution reuses loop_block's machinery (same-module names, ``self.``
methods, ``module.func`` import aliases) plus one extension: a method call
on an *unresolvable* receiver (``cluster.catalog_add_holder(...)``)
resolves when exactly one class in the package defines that method name and
the name is distinctive (not in ``COMMON_METHODS``) — that is what carries
reachability across the cluster/tiering/membership object graph without
type inference.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Context, Finding, register
from .loop_block import _is_threading_ctor  # the shared ctor fingerprint

PACKAGE_REL = "infinistore_tpu"
DESIGN_DOC_REL = "docs/design.md"

# Synchronization-primitive ctor names (threading.X / queue.X). LOCKABLE
# ones participate in `with` tracking; Event/queues are exempt state.
LOCKABLE = {"Lock", "RLock", "Condition"}

# Container mutations that count as a WRITE of the holding attribute
# (`self._promote_queue.append(...)` mutates `_promote_queue`).
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "sort",
}

# Names too generic for unique-method resolution: an ("any", name) edge is
# created only for names OUTSIDE this set, so `promote_queue.append` can
# never resolve to DurableLog.append and fabricate a call edge.
COMMON_METHODS = {
    "append", "add", "get", "pop", "put", "update", "clear", "extend",
    "remove", "discard", "items", "keys", "values", "copy", "sort", "index",
    "count", "insert", "setdefault", "popleft", "appendleft", "join",
    "start", "stop", "close", "run", "read", "write", "send", "recv",
    "flush", "acquire", "release", "wait", "notify", "notify_all", "set",
    "is_set", "record", "status", "health", "stats", "load", "save",
    "drop", "lookup", "connect", "reconnect", "encode", "decode", "kick",
    "tolist", "search", "match", "group", "split", "strip", "format",
    "exists", "mkdir", "unlink", "resolve", "to_thread", "submit",
}

# Classes excluded from R001 attribute classification, with the audit
# reason (the loop_block.AUDITED pattern). Their guard declarations still
# feed the registry/docs and their locks still feed R002/R003.
CLASS_EXEMPT = {
    "InfinityConnection":
        "native-reactor client: cross-thread discipline is the connection "
        "_lock + the C++ side's -Wthread-safety/TSAN jurisdiction "
        "(native/include/its/client.h GUARDED_BY annotations)",
    "StripedConnection":
        "fan-out over InfinityConnection stripes; same jurisdiction",
    "KVConnector":
        "engine-side wrapper over one connection; driven by one engine "
        "step at a time (the DeviceGate contract, docs/engine_integration)",
    "FaultyConnection":
        "scripted chaos harness: each wrapped conn is driven by one test "
        "thread by contract (faults.py module docstring)",
    "InfiniStoreConnector":
        "vllm v1 connector: scheduler-side state is single-threaded by the "
        "vLLM scheduler contract; worker/IO-loop KV handoff is _kv_lock",
    "CircuitBreaker":
        "lock-free by design: every access is serialized by the owning "
        "cluster's _breaker_lock (the PR-6 hardening; cluster.py _begin/"
        "_done/_cold_begin/_cold_done are the only callers)",
    "ContinuousBatchingHarness":
        "cache mutation is serialized by the engine's exclusive/shared "
        "DeviceGate (asyncio-level, one engine loop by contract); the "
        "executor-side snapshot binds the cache list under the shared gate "
        "before hopping",
}

_GUARD_RE = re.compile(r"its:\s*guard\[([^\]]+)\]")
_REQUIRES_RE = re.compile(r"its:\s*requires\[([^\]]+)\]")
_ACQUIRES_RE = re.compile(r"its:\s*acquires\[([^\]]+)\]")
_CONSTRUCTION_RE = re.compile(r"its:\s*construction\b")
_CROSS_RE = re.compile(r"its:\s*cross-thread\b")


# ---------------------------------------------------------------------------
# Scan model.
# ---------------------------------------------------------------------------

@dataclass
class Access:
    attr: str
    kind: str  # "r" | "w"
    line: int
    held: FrozenSet[str]
    meth: str = ""  # owning method name (filled by the registry pass)


@dataclass
class LockSite:
    token: str
    line: int
    held_before: Tuple[str, ...]


@dataclass
class CallSite:
    call: Tuple[str, ...]  # ("name", f) | ("self", m) | ("mod", mod, f) | ("any", m)
    line: int
    held: FrozenSet[str]


@dataclass
class WaitSite:
    token: str
    line: int
    looped: bool
    wait_for: bool


@dataclass
class Meth:
    name: str
    qual: str
    cls: Optional[str]
    file: str
    is_async: bool
    lineno: int
    accesses: List[Access] = field(default_factory=list)
    lock_sites: List[LockSite] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    waits: List[WaitSite] = field(default_factory=list)
    thread_targets: List[Tuple[str, ...]] = field(default_factory=list)
    requires: FrozenSet[str] = frozenset()
    acquires_decl: Tuple[Tuple[str, int], ...] = ()
    construction: bool = False


@dataclass
class Cls:
    name: str
    file: str
    lineno: int
    end_lineno: int
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> ctor
    guards: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    methods: Dict[str, Meth] = field(default_factory=dict)
    marked_cross: bool = False


class RaceModule:
    def __init__(self, rel: str, tree: ast.Module, source: str):
        self.rel = rel
        self.lines = source.splitlines()
        self.import_aliases: Dict[str, str] = {}
        self.module_locks: Dict[str, str] = {}  # name -> ctor
        self.classes: Dict[str, Cls] = {}
        self.functions: Dict[str, Meth] = {}  # module-level + nested
        self._collect(tree)

    # -- collection ---------------------------------------------------------

    def _collect(self, tree: ast.Module):
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = (
                        a.name.split(".")[-1]
                    )
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.import_aliases[a.asname or a.name] = a.name
            elif isinstance(node, ast.Assign) and _is_threading_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_locks[tgt.id] = node.value.func.attr
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_fn(node, qual=node.name, cls=None)
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node)

    def _collect_class(self, node: ast.ClassDef):
        cls = Cls(
            name=node.name, file=self.rel, lineno=node.lineno,
            end_lineno=getattr(node, "end_lineno", node.lineno),
        )
        self.classes[node.name] = cls
        # Lock discovery first (the body scanner consults it).
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _is_threading_ctor(sub.value):
                for tgt in sub.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        cls.lock_attrs[tgt.attr] = sub.value.func.attr
        span = self.lines[cls.lineno - 1: cls.end_lineno]
        for raw in span:
            if _CROSS_RE.search(raw):
                cls.marked_cross = True
            m = _GUARD_RE.search(raw)
            if m:
                self._parse_guard(cls, m.group(1))
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_fn(
                    item, qual=f"{node.name}.{item.name}", cls=node.name
                )

    def _parse_guard(self, cls: Cls, payload: str):
        # "attr: lock", "attr: lock!w", "a, b: lock", "attr: single_writer"
        if ":" not in payload:
            return
        attrs, lock = payload.rsplit(":", 1)
        lock = lock.strip()
        mode = "full"
        if lock.endswith("!w"):
            lock, mode = lock[:-2].strip(), "writes"
        elif lock == "single_writer":
            mode = "single_writer"
        for attr in attrs.split(","):
            attr = attr.strip()
            if attr:
                cls.guards[attr] = (lock, mode)

    def _def_markers(self, lineno: int,
                     body_lineno: int) -> Tuple[FrozenSet[str], bool]:
        """requires/construction markers on the line above the def, or
        anywhere in the (possibly multi-line) signature."""
        req: Set[str] = set()
        construction = False
        for ln in range(max(1, lineno - 1), min(body_lineno, len(self.lines) + 1)):
            raw = self.lines[ln - 1]
            m = _REQUIRES_RE.search(raw)
            if m:
                req |= {s.strip() for s in m.group(1).split(",") if s.strip()}
            if _CONSTRUCTION_RE.search(raw):
                construction = True
        return frozenset(req), construction

    def _collect_fn(self, node, qual: str, cls: Optional[str]):
        requires, construction = self._def_markers(
            node.lineno, node.body[0].lineno if node.body else node.lineno
        )
        info = Meth(
            name=node.name, qual=qual, cls=cls, file=self.rel,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            lineno=node.lineno, requires=requires,
            construction=construction or node.name == "__init__",
        )
        # acquires[] summaries anywhere in the body span.
        acq: List[Tuple[str, int]] = []
        end = getattr(node, "end_lineno", node.lineno)
        for i in range(node.lineno, min(end, len(self.lines)) + 1):
            m = _ACQUIRES_RE.search(self.lines[i - 1])
            if m:
                for tok in m.group(1).split(","):
                    tok = tok.strip()
                    if tok:
                        acq.append((tok, i))
        info.acquires_decl = tuple(acq)
        store = self.functions if cls is None else self.classes[cls].methods
        store[node.name if cls is not None else qual] = info
        scanner = _FnScanner(self, info)
        for stmt in node.body:
            scanner.visit(stmt)
        for inner in scanner.nested:
            # Nested defs: separate functions (module table, qualified), so
            # requires[] contracts attach to e.g. merge_remote_view.on_new.
            self._collect_fn(inner, qual=f"{qual}.<locals>.{inner.name}", cls=None)
            # Keep nested defs resolvable from the enclosing class' methods.
            nested = self.functions[f"{qual}.<locals>.{inner.name}"]
            nested.cls = cls


class _FnScanner(ast.NodeVisitor):
    """One function body: attribute accesses with the held-lock stack,
    lock acquisition sites, call edges, cv waits, thread-target refs."""

    _EXECUTORS = {"to_thread", "run_in_executor", "submit"}

    def __init__(self, mod: RaceModule, info: Meth):
        self.mod = mod
        self.info = info
        self.nested: List[ast.AST] = []
        self.held: List[str] = [*sorted(self._resolve_requires())]
        # Statement-context stack for the R004 gating rule: "while",
        # "if_cont" (branch ends with continue/return/raise/break — the
        # loop re-checks), "if_nocont" (falls through: the code below may
        # ACT on a predicate a spurious wake faked).
        self._ctx: List[str] = []

    def _resolve_requires(self) -> Set[str]:
        out = set()
        for name in self.info.requires:
            tok = self._token_for_name(name)
            if tok:
                out.add(tok)
        return out

    def _token_for_name(self, name: str) -> Optional[str]:
        if "." in name:  # already qualified: Class.attr
            return name
        cls = self.info.cls
        if cls and name in self.mod.classes.get(cls, Cls("", "", 0, 0)).lock_attrs:
            return f"{cls}.{name}"
        if name in self.mod.module_locks:
            return f"{self.mod.rel}:{name}"
        return name  # qualified elsewhere; resolved globally later

    # -- tokens -------------------------------------------------------------

    def _lock_token(self, expr) -> Optional[str]:
        """Lock identity of a with/wait receiver, or None."""
        if isinstance(expr, ast.Name) and expr.id in self.mod.module_locks:
            if self.mod.module_locks[expr.id] in LOCKABLE | {"Event"}:
                return f"{self.mod.rel}:{expr.id}"
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" and self.info.cls:
                cls = self.mod.classes.get(self.info.cls)
                if cls and expr.attr in cls.lock_attrs:
                    return f"{self.info.cls}.{expr.attr}"
            # Foreign receiver (cluster._cat_lock, self.cluster._cat_lock):
            # resolves when exactly one scanned class owns that lock attr.
            return f"?{expr.attr}"
        return None

    # -- visitors -----------------------------------------------------------

    def visit_FunctionDef(self, node):
        self.nested.append(node)

    def visit_AsyncFunctionDef(self, node):
        self.nested.append(node)

    def visit_Lambda(self, node):
        pass

    def visit_While(self, node):
        self._ctx.append("while")
        for stmt in node.body:
            self.visit(stmt)
        self._ctx.pop()
        for stmt in node.orelse:
            self.visit(stmt)
        self.visit(node.test)

    def visit_For(self, node):
        self._ctx.append("while")  # a for loop re-checks too
        for stmt in node.body:
            self.visit(stmt)
        self._ctx.pop()
        for stmt in node.orelse:
            self.visit(stmt)
        self.visit(node.iter)
        self._target(node.target, node.lineno)

    def visit_If(self, node):
        self.visit(node.test)
        for branch in (node.body, node.orelse):
            if not branch:
                continue
            exits = isinstance(
                branch[-1], (ast.Continue, ast.Return, ast.Raise, ast.Break)
            )
            self._ctx.append("if_cont" if exits else "if_nocont")
            for stmt in branch:
                self.visit(stmt)
            self._ctx.pop()

    def _wait_looped(self) -> bool:
        """True when the wait sits in a loop that re-checks its predicate:
        walking outward, a `while`/`for` before any fall-through `if`
        branch (`if not pred: cv.wait()` then acting below is the bug)."""
        for ctx in reversed(self._ctx):
            if ctx == "while":
                return True
            if ctx == "if_nocont":
                return False
        return False

    def visit_With(self, node):
        tokens = []
        for item in node.items:
            # In-scope tokens resolve here; "?attr" foreign-receiver
            # tokens are recorded as-is and resolved globally later
            # (unique lock-attr name across classes).
            tok = self._lock_token(item.context_expr)
            if tok is not None:
                self.info.lock_sites.append(LockSite(
                    token=tok, line=node.lineno,
                    held_before=tuple(self.held),
                ))
                tokens.append(tok)
        self.held.extend(tokens)
        for stmt in node.body:
            self.visit(stmt)
        for _ in tokens:
            self.held.pop()
        # items' context expressions may contain calls (rare) — skipped.

    def visit_AsyncWith(self, node):
        self.generic_visit(node)

    def _access(self, attr: str, kind: str, line: int):
        self.info.accesses.append(Access(
            attr=attr, kind=kind, line=line, held=frozenset(self.held),
        ))

    def visit_Attribute(self, node: ast.Attribute):
        if (
            isinstance(node.value, ast.Name) and node.value.id == "self"
            and isinstance(node.ctx, ast.Load)
        ):
            self._access(node.attr, "r", node.lineno)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._target(tgt, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._target(node.target, node.lineno, aug=True)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                base = tgt.value
                if (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    self._access(base.attr, "w", node.lineno)

    def _target(self, tgt, line: int, aug: bool = False):
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name) and tgt.value.id == "self"
        ):
            self._access(tgt.attr, "w", line)
            if aug:
                self._access(tgt.attr, "r", line)
        elif isinstance(tgt, ast.Subscript):
            base = tgt.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name) and base.value.id == "self"
            ):
                # self.x[k] = v mutates x (and aug also reads it).
                self._access(base.attr, "w", line)
                self._access(base.attr, "r", line)
            else:
                self.visit(tgt)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target(el, line)
        else:
            self.visit(tgt)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        held = frozenset(self.held)
        if isinstance(fn, ast.Name):
            if (
                fn.id == "getattr" and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                # The duck-typed hook pattern (`getattr(cluster,
                # "compact_journal", None)` then called via the local):
                # conservatively treat the reference as a call edge so
                # reachability crosses it.
                self.info.calls.append(CallSite(
                    ("any", node.args[1].value), node.lineno, held,
                ))
            else:
                self.info.calls.append(
                    CallSite(("name", fn.id), node.lineno, held)
                )
        elif isinstance(fn, ast.Attribute):
            self._attr_call(node, fn, held)
        self._thread_target(node)
        self.generic_visit(node)

    def _attr_call(self, node: ast.Call, fn: ast.Attribute, held):
        recv = fn.value
        # cv waits (R004): receiver must be a Condition / Event token.
        tok = self._lock_token(recv) if isinstance(recv, (ast.Name, ast.Attribute)) else None
        if fn.attr in ("wait", "wait_for") and tok is not None:
            self.info.waits.append(WaitSite(
                token=tok, line=node.lineno,
                looped=self._wait_looped(), wait_for=fn.attr == "wait_for",
            ))
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                self.info.calls.append(CallSite(("self", fn.attr), node.lineno, held))
                # A mutating call on self.<attr> would be Attribute recv;
                # self.meth() is a call edge only.
                return
            if recv.id in self.mod.import_aliases:
                self.info.calls.append(CallSite(
                    ("mod", self.mod.import_aliases[recv.id], fn.attr),
                    node.lineno, held,
                ))
                return
            self.info.calls.append(CallSite(("any", fn.attr), node.lineno, held))
            return
        if isinstance(recv, ast.Attribute):
            if (
                isinstance(recv.value, ast.Name) and recv.value.id == "self"
                and fn.attr in MUTATORS
            ):
                # self.x.append(...): a WRITE of x.
                self._access(recv.attr, "w", node.lineno)
            self.info.calls.append(CallSite(("any", fn.attr), node.lineno, held))
            return
        self.info.calls.append(CallSite(("any", fn.attr), node.lineno, held))

    def _thread_target(self, node: ast.Call):
        """threading.Thread(target=X), to_thread(X), run_in_executor(_, X),
        submit(X): X runs on a WORKER thread."""
        fn = node.func
        ref = None
        if (
            isinstance(fn, ast.Attribute) and fn.attr == "Thread"
            and isinstance(fn.value, ast.Name) and fn.value.id == "threading"
        ):
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = kw.value
        elif isinstance(fn, ast.Attribute) and fn.attr in self._EXECUTORS:
            args = node.args
            if fn.attr == "to_thread" and args:
                ref = args[0]
            elif fn.attr == "run_in_executor" and len(args) >= 2:
                ref = args[1]
            elif fn.attr == "submit" and args:
                ref = args[0]
        if ref is None:
            return
        if (
            isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Name) and ref.value.id == "self"
        ):
            self.info.thread_targets.append(("self", ref.attr))
        elif isinstance(ref, ast.Name):
            self.info.thread_targets.append(("name", ref.id))


# ---------------------------------------------------------------------------
# Package index + call resolution (loop_block's scheme + ("any", m)).
# ---------------------------------------------------------------------------

class PackageIndex:
    def __init__(self, ctx: Context, package_rel: str = PACKAGE_REL):
        self.modules: Dict[str, RaceModule] = {}
        for rel in ctx.walk_py(package_rel):
            try:
                src = ctx.read(rel)
                tree = ast.parse(src)
            except SyntaxError:
                continue
            self.modules[rel] = RaceModule(rel, tree, src)
        # Shallowest path wins on basename collisions (loop_block's rule).
        self.by_base: Dict[str, RaceModule] = {}
        for rel in sorted(self.modules, key=lambda r: (r.count("/"), r)):
            self.by_base.setdefault(rel.rsplit("/", 1)[-1][:-3], self.modules[rel])
        # Unique-method map: name -> (module, class, Meth) when exactly one
        # class in the package defines it.
        owner: Dict[str, List[Tuple[RaceModule, Cls, Meth]]] = {}
        self.lock_attr_owner: Dict[str, List[str]] = {}
        for m in self.modules.values():
            for cls in m.classes.values():
                for name, meth in cls.methods.items():
                    owner.setdefault(name, []).append((m, cls, meth))
                for attr in cls.lock_attrs:
                    self.lock_attr_owner.setdefault(attr, []).append(cls.name)
        self.unique_method = {
            n: v[0] for n, v in owner.items()
            if len(v) == 1 and n not in COMMON_METHODS
        }

    def resolve_lock_token(self, token: str) -> Optional[str]:
        """Globally resolve a '?attr' foreign-receiver lock token."""
        if not token.startswith("?"):
            return token
        attr = token[1:]
        owners = self.lock_attr_owner.get(attr, [])
        if len(owners) == 1:
            return f"{owners[0]}.{attr}"
        return None

    def meths(self):
        for m in self.modules.values():
            for meth in m.functions.values():
                yield m, None, meth
            for cls in m.classes.values():
                for meth in cls.methods.values():
                    yield m, cls, meth

    def resolve(self, mod: RaceModule, info: Meth,
                call: Tuple[str, ...]) -> Optional[Tuple[RaceModule, Meth]]:
        if call[0] == "name":
            nested = mod.functions.get(f"{info.qual}.<locals>.{call[1]}")
            if nested is not None:
                return mod, nested
            fn = mod.functions.get(call[1])
            if fn is not None:
                return mod, fn
            return None
        if call[0] == "self" and info.cls:
            cls = mod.classes.get(info.cls)
            if cls and call[1] in cls.methods:
                return mod, cls.methods[call[1]]
            # Fall through: a self-call on a class the module splits across
            # mixins resolves like ("any", m).
            call = ("any", call[1])
        if call[0] == "mod":
            target = self.by_base.get(call[1])
            if target:
                fn = target.functions.get(call[2])
                if fn is not None:
                    return target, fn
            return None
        if call[0] == "any":
            hit = self.unique_method.get(call[1])
            if hit is not None:
                return hit[0], hit[2]
        return None


def _closure(idx: PackageIndex, roots: List[Tuple[RaceModule, Meth]]) -> Set[int]:
    seen: Set[int] = set()
    stack = list(roots)
    while stack:
        mod, meth = stack.pop()
        if id(meth) in seen:
            continue
        seen.add(id(meth))
        for cs in meth.calls:
            got = idx.resolve(mod, meth, cs.call)
            if got is not None and id(got[1]) not in seen:
                stack.append(got)
    return seen


def thread_roots(idx: PackageIndex) -> List[Tuple[RaceModule, Meth]]:
    roots: List[Tuple[RaceModule, Meth]] = []
    for mod, cls, meth in idx.meths():
        for ref in meth.thread_targets:
            if ref[0] == "self" and meth.cls:
                c = mod.classes.get(meth.cls)
                if c and ref[1] in c.methods:
                    roots.append((mod, c.methods[ref[1]]))
            elif ref[0] == "name":
                nested = mod.functions.get(f"{meth.qual}.<locals>.{ref[1]}")
                if nested is not None:
                    roots.append((mod, nested))
                elif ref[1] in mod.functions:
                    roots.append((mod, mod.functions[ref[1]]))
    return roots


def async_roots(idx: PackageIndex) -> List[Tuple[RaceModule, Meth]]:
    return [(m, meth) for m, _c, meth in idx.meths() if meth.is_async]


# ---------------------------------------------------------------------------
# Shared-state registry (R001 + the docs generator's source of truth).
# ---------------------------------------------------------------------------

@dataclass
class SharedClass:
    file: str
    cls: Cls
    thread_methods: Set[str]
    other_methods: Set[str]
    own_thread_root: bool


def build_registry(ctx: Context, package_rel: str = PACKAGE_REL,
                   idx: Optional[PackageIndex] = None) -> List[SharedClass]:
    """The shared-state registry: every class with methods on both the
    worker-thread side and the loop/caller side, with the side split.
    Sorted for deterministic findings and docs output."""
    idx = idx or PackageIndex(ctx, package_rel)
    t_closure = _closure(idx, thread_roots(idx))
    a_closure = _closure(idx, async_roots(idx))
    out: List[SharedClass] = []
    for rel in sorted(idx.modules):
        mod = idx.modules[rel]
        for cname in sorted(mod.classes):
            cls = mod.classes[cname]
            t_m = {n for n, m in cls.methods.items() if id(m) in t_closure}
            a_m = {n for n, m in cls.methods.items() if id(m) in a_closure}
            own_root = any(
                ref[0] == "self" and ref[1] in cls.methods
                for m in cls.methods.values() for ref in m.thread_targets
            )
            # A method reachable from BOTH closures (view(), status(), ...)
            # is exactly the shared surface: it counts on both sides.
            other = set(a_m)
            if own_root or cls.marked_cross:
                other |= {
                    n for n, m in cls.methods.items()
                    if n not in t_m and not m.construction
                }
            if not t_m or not other:
                continue
            out.append(SharedClass(
                file=rel, cls=cls, thread_methods=t_m,
                other_methods=other, own_thread_root=own_root,
            ))
    return out


def _attr_table(sc: SharedClass) -> Dict[str, Dict[str, List[Access]]]:
    """attr -> side ("T"/"O"/"X") -> accesses (construction methods and
    methods on neither side are the X bucket — guarded like any other
    access once the attr is cross-side, but they do not make it so)."""
    table: Dict[str, Dict[str, List[Access]]] = {}
    for name, meth in sc.cls.methods.items():
        if meth.construction:
            continue
        sides = set()
        if name in sc.thread_methods:
            sides.add("T")
        if name in sc.other_methods:
            sides.add("O")
        if not sides:
            sides.add("X")
        for acc in meth.accesses:
            if acc.attr in sc.cls.lock_attrs:
                continue
            if acc.attr in sc.cls.methods:
                continue  # self.meth references, properties by name
            acc.meth = name
            for side in sides:
                table.setdefault(acc.attr, {}).setdefault(side, []).append(acc)
    return table


def _guard_token(cls: Cls, lock: str) -> Optional[str]:
    if lock in cls.lock_attrs:
        return f"{cls.name}.{lock}"
    return None


def _enforce_guard(findings: List[Finding], idx: PackageIndex, file: str,
                   cls: Cls, attr: str, sides: Dict[str, List[Access]]):
    """Hold a DECLARED guard to its contract (full / writes-only /
    single-writer) over every non-construction access."""
    lock, mode = cls.guards[attr]
    key = f"ITS-R001:{file}:{cls.name}.{attr}"
    writes_t = [a for a in sides.get("T", []) if a.kind == "w"]
    writes_o = [a for a in sides.get("O", []) if a.kind == "w"]
    if mode == "single_writer":
        if writes_t and writes_o:
            findings.append(Finding(
                rule="ITS-R001", file=file, line=writes_o[0].line,
                message=(
                    f"{cls.name}.{attr} is declared single_writer but is "
                    "written on BOTH the worker and loop sides (e.g. lines "
                    f"{writes_t[0].line} and {writes_o[0].line})"
                ),
                key=key + ":single-writer",
            ))
        return
    token = _guard_token(cls, lock)
    if token is None:
        findings.append(Finding(
            rule="ITS-R001", file=file, line=cls.lineno,
            message=(
                f"{cls.name}.{attr} declares guard {lock!r} but the class "
                "constructs no such lock attribute"
            ),
            key=key + ":unknown-guard",
        ))
        return
    checked_raw = (
        [a for accs in sides.values() for a in accs]
        if mode == "full" else
        [a for accs in sides.values() for a in accs if a.kind == "w"]
    )
    checked = list({id(a): a for a in checked_raw}.values())
    for acc in sorted(checked, key=lambda a: a.line):
        held = {idx.resolve_lock_token(t) or t for t in acc.held}
        if token in held:
            continue
        findings.append(Finding(
            rule="ITS-R001", file=file, line=acc.line,
            message=(
                f"{cls.name}.{attr} "
                f"{'write' if acc.kind == 'w' else 'read'} outside its "
                f"declared guard self.{lock} "
                f"(`guard[{attr}: {lock}{'!w' if mode == 'writes' else ''}]`)"
                " — take the lock or annotate the caller-holds contract "
                "(`# its: requires[...]`)"
            ),
            key=f"{key}:{acc.meth}:{acc.kind}",
        ))


def check_r001(ctx: Context, registry: Sequence[SharedClass],
               idx: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    handled: Set[Tuple[str, str, str]] = set()  # (file, class, attr)
    for sc in registry:
        cls = sc.cls
        if cls.name in CLASS_EXEMPT:
            continue
        table = _attr_table(sc)
        for attr in sorted(table):
            sides = table[attr]
            writes_t = [a for a in sides.get("T", []) if a.kind == "w"]
            writes_o = [a for a in sides.get("O", []) if a.kind == "w"]
            touched_t = sides.get("T", [])
            touched_o = sides.get("O", [])
            cross = (writes_t and touched_o) or (writes_o and touched_t)
            if not cross:
                continue
            if attr not in cls.guards:
                first = min(
                    (a for accs in sides.values() for a in accs),
                    key=lambda a: a.line,
                )
                findings.append(Finding(
                    rule="ITS-R001", file=sc.file, line=first.line,
                    message=(
                        f"{cls.name}.{attr} is written on "
                        f"{'the worker-thread side' if writes_t else 'the loop side'}"
                        f" and accessed on the other with no declared guard — "
                        f"add `# its: guard[{attr}: <lock>]` and take the lock, "
                        "or prove single-ownership (docs/static_analysis.md)"
                    ),
                    key=f"ITS-R001:{sc.file}:{cls.name}.{attr}",
                ))
            else:
                _enforce_guard(findings, idx, sc.file, cls, attr, sides)
            handled.add((sc.file, cls.name, attr))
    # Declared guards are contracts EVERYWHERE, not only on classes the
    # reachability inference classifies: a guard on FlightRecorder still
    # fails the run when an access bypasses the lock.
    shared_by_cls = {(sc.file, sc.cls.name): sc for sc in registry}
    for rel in sorted(idx.modules):
        mod = idx.modules[rel]
        for cname in sorted(mod.classes):
            cls = mod.classes[cname]
            if cls.name in CLASS_EXEMPT or not cls.guards:
                continue
            sc = shared_by_cls.get((rel, cname)) or SharedClass(
                file=rel, cls=cls, thread_methods=set(),
                other_methods=set(), own_thread_root=False,
            )
            table = _attr_table(sc)
            for attr in sorted(cls.guards):
                if (rel, cname, attr) in handled:
                    continue
                _enforce_guard(findings, idx, rel, cls, attr,
                               table.get(attr, {}))
    return findings


# ---------------------------------------------------------------------------
# R002: lock-order graph.
# ---------------------------------------------------------------------------

def lock_order_edges(idx: PackageIndex) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Directed acquired-after edges {(held, acquired): (file, line)} from
    lexical nesting, calls under a held lock (via a may-acquire fixpoint),
    and `# its: acquires[...]` summaries."""
    may: Dict[int, Set[str]] = {}

    def resolve_tok(t: str) -> Optional[str]:
        return idx.resolve_lock_token(t)

    # Fixpoint of may-acquire over the call graph.
    meth_list = [(m, meth) for m, _c, meth in idx.meths()]
    for _m, meth in meth_list:
        base: Set[str] = set()
        for ls in meth.lock_sites:
            tok = resolve_tok(ls.token)
            if tok:
                base.add(tok)
        for tok, _line in meth.acquires_decl:
            base.add(tok)
        may[id(meth)] = base
    changed = True
    while changed:
        changed = False
        for mod, meth in meth_list:
            cur = may[id(meth)]
            for cs in meth.calls:
                got = idx.resolve(mod, meth, cs.call)
                if got is None:
                    continue
                extra = may[id(got[1])] - cur
                if extra:
                    cur |= extra
                    changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add(src: str, dst: str, file: str, line: int):
        if src != dst:
            edges.setdefault((src, dst), (file, line))

    for mod, meth in meth_list:
        for ls in meth.lock_sites:
            dst = resolve_tok(ls.token)
            if not dst:
                continue
            for held in ls.held_before:
                src = resolve_tok(held)
                if src:
                    add(src, dst, meth.file, ls.line)
        for tok, line in meth.acquires_decl:
            for ls in meth.lock_sites:
                src = resolve_tok(ls.token)
                if src:
                    add(src, tok, meth.file, line)
        for cs in meth.calls:
            if not cs.held:
                continue
            got = idx.resolve(mod, meth, cs.call)
            if got is None:
                continue
            for held in cs.held:
                src = resolve_tok(held)
                if not src:
                    continue
                for dst in may[id(got[1])]:
                    add(src, dst, meth.file, cs.line)
    return edges


def find_cycles(edges) -> List[List[str]]:
    """Elementary cycles via DFS (graphs here are tiny)."""
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str], visited: Set[str]):
        for nxt in graph.get(node, ()):
            if nxt == start:
                canon = tuple(sorted(path))
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(path + [start])
            elif nxt not in visited and nxt > start:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return cycles


def check_r002(ctx: Context, idx: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    edges = lock_order_edges(idx)
    for cycle in find_cycles(edges):
        chain = " -> ".join(cycle)
        first_edge = edges.get((cycle[0], cycle[1]), ("", 0))
        findings.append(Finding(
            rule="ITS-R002", file=first_edge[0] or PACKAGE_REL, line=first_edge[1],
            message=(
                f"lock-order cycle {chain}: two threads taking these locks "
                "in opposite orders can deadlock — impose one global order "
                "or split the critical sections"
            ),
            key=f"ITS-R002:cycle:{':'.join(sorted(set(cycle)))}",
        ))
    # Re-acquiring a non-reentrant Lock already held (self-deadlock).
    lock_kinds: Dict[str, str] = {}
    for m in idx.modules.values():
        for name, ctor in m.module_locks.items():
            lock_kinds[f"{m.rel}:{name}"] = ctor
        for cls in m.classes.values():
            for attr, ctor in cls.lock_attrs.items():
                lock_kinds[f"{cls.name}.{attr}"] = ctor
    for _mod, _c, meth in idx.meths():
        for ls in meth.lock_sites:
            tok = idx.resolve_lock_token(ls.token)
            if not tok:
                continue
            helds = {idx.resolve_lock_token(t) for t in ls.held_before}
            if tok in helds and lock_kinds.get(tok) == "Lock":
                findings.append(Finding(
                    rule="ITS-R002", file=meth.file, line=ls.line,
                    message=(
                        f"{tok} re-acquired while already held "
                        f"(threading.Lock is not reentrant: self-deadlock)"
                    ),
                    key=f"ITS-R002:{meth.file}:{meth.qual}:reacquire:{tok}",
                ))
    return findings


# ---------------------------------------------------------------------------
# R003: journal/emit outside engine locks.
# ---------------------------------------------------------------------------

# Journal sinks: (class, method) pairs plus module functions. The journal's
# and durable log's OWN locks are exempt holders (they serialize the sink
# itself); everything else counts as an engine lock.
SINK_METHODS = {
    ("EventJournal", "emit"),
    ("DurableLog", "append"),
    ("ClusterKVConnector", "_journal_append"),
    ("ClusterKVConnector", "_journal_root"),
    ("ClusterKVConnector", "journal_reshard_event"),
}
SINK_MODULE_FNS = {("telemetry", "emit")}
JOURNAL_OWN_LOCKS = {"EventJournal._lock", "DurableLog._lock"}

# Coarse control-plane serialization locks where journaling INSIDE is
# deliberate, not a discipline violation: membership transitions must land
# in the journal in admission order (the admin lock IS that order), and the
# fleet scraper's pass lock serializes whole scrape passes (rare alert-edge
# emits inside are the pass's output). Hot state locks (breaker, catalog,
# membership._lock, SLO engine, reconciler CVs) stay non-exempt.
CONTROL_PLANE_LOCKS = {
    "ClusterKVConnector._admin_lock",
    "FleetScraper._pass_lock",
    # The gossip round lock serializes whole anti-entropy rounds; the
    # merge (which journals its epoch adoption) is the round's body.
    "GossipAgent._round_lock",
}


def check_r003(ctx: Context, idx: PackageIndex) -> List[Finding]:
    sink_ids: Set[int] = set()
    for mod in idx.modules.values():
        base = mod.rel.rsplit("/", 1)[-1][:-3]
        for cls in mod.classes.values():
            for name, meth in cls.methods.items():
                if (cls.name, name) in SINK_METHODS:
                    sink_ids.add(id(meth))
        for name, fn in mod.functions.items():
            if (base, name) in SINK_MODULE_FNS:
                sink_ids.add(id(fn))
    # may-emit fixpoint.
    meth_list = [(m, meth) for m, _c, meth in idx.meths()]
    emits: Dict[int, bool] = {id(meth): id(meth) in sink_ids for _m, meth in meth_list}
    changed = True
    while changed:
        changed = False
        for mod, meth in meth_list:
            if emits[id(meth)]:
                continue
            for cs in meth.calls:
                got = idx.resolve(mod, meth, cs.call)
                if got is not None and emits.get(id(got[1])):
                    emits[id(meth)] = True
                    changed = True
                    break
    findings: List[Finding] = []
    for mod, meth in meth_list:
        if id(meth) in sink_ids:
            continue  # the sink's own body may hold its own lock
        for cs in meth.calls:
            if not cs.held:
                continue
            got = idx.resolve(mod, meth, cs.call)
            if got is None or not emits.get(id(got[1])):
                continue
            engine = sorted(
                t for t in (
                    idx.resolve_lock_token(h) for h in cs.held
                ) if t and t not in JOURNAL_OWN_LOCKS
                and t not in CONTROL_PLANE_LOCKS
            )
            if not engine:
                continue
            callee = got[1].qual
            findings.append(Finding(
                rule="ITS-R003", file=meth.file, line=cs.line,
                message=(
                    f"journal/emit sink reached via {callee}() while holding "
                    f"{', '.join(engine)} — emit after releasing the lock "
                    "(the established emit/journal-outside-lock discipline; "
                    "docs/static_analysis.md ITS-R003)"
                ),
                key=f"ITS-R003:{meth.file}:{meth.qual}:{callee.rsplit('.', 1)[-1]}",
            ))
    return findings


# ---------------------------------------------------------------------------
# R004: condition waits loop on a predicate.
# ---------------------------------------------------------------------------

def check_r004(ctx: Context, idx: PackageIndex) -> List[Finding]:
    lock_kinds: Dict[str, str] = {}
    for m in idx.modules.values():
        for name, ctor in m.module_locks.items():
            lock_kinds[f"{m.rel}:{name}"] = ctor
        for cls in m.classes.values():
            for attr, ctor in cls.lock_attrs.items():
                lock_kinds[f"{cls.name}.{attr}"] = ctor
    findings: List[Finding] = []
    for _mod, _c, meth in idx.meths():
        for ws in meth.waits:
            tok = idx.resolve_lock_token(ws.token)
            if tok is None or lock_kinds.get(tok) != "Condition":
                continue  # Event.wait etc: the event IS the predicate
            if ws.wait_for or ws.looped:
                continue
            findings.append(Finding(
                rule="ITS-R004", file=meth.file, line=ws.line,
                message=(
                    f"bare {tok}.wait() outside a while loop: condition "
                    "waits can wake spuriously (and on broadcast) — loop on "
                    "the predicate (`while not pred: cv.wait(...)`) or use "
                    "wait_for"
                ),
                key=f"ITS-R004:{meth.file}:{meth.qual}:{tok}",
            ))
    return findings


# ---------------------------------------------------------------------------
# R005: docs/design.md concurrency-model lockstep.
# ---------------------------------------------------------------------------

def concurrency_model_lines(ctx: Context,
                            package_rel: str = PACKAGE_REL,
                            idx: Optional[PackageIndex] = None) -> List[str]:
    """The generated concurrency-model table for docs/design.md: one row
    per declared guard, `| Class.attr | lock | mode | file |`, sorted.
    ITS-R005 fails when docs/design.md's table and this list disagree —
    so the doc paragraph naming which locks guard what can never drift
    from the annotations ITS-R001 enforces."""
    idx = idx or PackageIndex(ctx, package_rel)
    rows: List[str] = []
    for rel in sorted(idx.modules):
        mod = idx.modules[rel]
        for cname in sorted(mod.classes):
            cls = mod.classes[cname]
            for attr in sorted(cls.guards):
                lock, mode = cls.guards[attr]
                mode_h = {
                    "full": "all accesses", "writes": "writes (lock-free reads)",
                    "single_writer": "single writer",
                }[mode]
                lk = f"`{lock}`" if mode != "single_writer" else "—"
                rows.append(
                    f"| `{cname}.{attr}` | {lk} | {mode_h} | `{rel}` |"
                )
    return rows


def check_r005(ctx: Context, idx: PackageIndex,
               package_rel: str = PACKAGE_REL) -> List[Finding]:
    if not ctx.exists(DESIGN_DOC_REL):
        return [Finding(
            rule="ITS-R005", file=DESIGN_DOC_REL, line=0,
            message="docs/design.md missing: the concurrency-model section "
                    "is generated from the guard registry",
            key="ITS-R005:docs-missing",
        )]
    doc = ctx.read(DESIGN_DOC_REL)
    findings: List[Finding] = []
    expected = concurrency_model_lines(ctx, package_rel, idx=idx)
    doc_rows = {
        ln.strip() for ln in doc.splitlines()
        if ln.strip().startswith("| `") and ln.strip().endswith("` |")
    }
    for row in expected:
        if row not in doc_rows:
            attr = row.split("|")[1].strip()
            findings.append(Finding(
                rule="ITS-R005", file=DESIGN_DOC_REL, line=0,
                message=(
                    f"guard registry row missing from the concurrency-model "
                    f"table: {row} (regenerate with "
                    "`python -m tools.analysis.races`)"
                ),
                key=f"ITS-R005:missing:{attr}",
            ))
    expected_set = set(expected)
    for row in sorted(doc_rows):
        if row.startswith("| `") and "|" in row[2:] and row not in expected_set:
            # Only rows shaped like registry rows (4 columns ending in .py)
            if row.count("|") == 5 and ".py` |" in row:
                attr = row.split("|")[1].strip()
                findings.append(Finding(
                    rule="ITS-R005", file=DESIGN_DOC_REL, line=0,
                    message=(
                        f"stale concurrency-model row (no matching guard "
                        f"annotation): {row}"
                    ),
                    key=f"ITS-R005:stale:{attr}",
                ))
    return findings


# ---------------------------------------------------------------------------
# Entry point.
# ---------------------------------------------------------------------------

def scan(ctx: Context, package_rel: str = PACKAGE_REL,
         docs: bool = True) -> List[Finding]:
    idx = PackageIndex(ctx, package_rel)
    registry = build_registry(ctx, package_rel, idx=idx)
    findings = []
    findings += check_r001(ctx, registry, idx)
    findings += check_r002(ctx, idx)
    findings += check_r003(ctx, idx)
    findings += check_r004(ctx, idx)
    if docs:
        findings += check_r005(ctx, idx, package_rel)
    return findings


@register("races",
          "cross-thread shared-state guard/lock-order/journal/cv discipline (ITS-R*)",
          rule_prefix="ITS-R")
def check(ctx: Context) -> List[Finding]:
    return scan(ctx)


if __name__ == "__main__":  # pragma: no cover - docs helper
    # Print the generated concurrency-model table for docs/design.md.
    print("| guarded state | lock | discipline | module |")
    print("| --- | --- | --- | --- |")
    for line in concurrency_model_lines(Context()):
        print(line)
