"""ITS-T*: trace stage vocabulary lockstep across producers, schema, docs.

The tracing layer (infinistore_tpu/tracing.py, docs/observability.md) works
only if every layer agrees on the stage names: a producer stamping a name
the /trace schema does not list yields spans dashboards cannot interpret,
and a renamed stage that docs/observability.md still describes is silent
observability drift — the same one-sided-edit failure the counters checker
(ITS-C) guards for metric keys. This pass extracts:

- the recorder constants — ``tracing.STAGES`` (the canonical tuple) and
  ``tracing.SERVER_TICK_STAGES`` (native tick field -> stage name),
- every stage literal a PRODUCER stamps: ``<span>.stage("...")`` calls and
  ``stage="..."`` keywords to ``trace_op`` anywhere under infinistore_tpu/
  plus bench.py,
- the /trace schema surface (``server.py`` must serve the route from the
  STAGES vocabulary),
- the documented vocabulary of docs/observability.md,

and cross-checks them:

- ITS-T001 a producer stamps a stage name missing from tracing.STAGES
- ITS-T002 a STAGES name is missing from docs/observability.md
- ITS-T003 /trace schema drift: the manage plane must serve GET /trace
  with the STAGES vocabulary (tracing.STAGES referenced in the payload),
  and every SERVER_TICK_STAGES value must be a STAGES member
- ITS-T004 a STAGES name no producer ever stamps (dead vocabulary — the
  tuple, the docs and the dashboards describe a stage that cannot occur)
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Context, Finding, register

TRACING_REL = "infinistore_tpu/tracing.py"
MANAGE_REL = "infinistore_tpu/server.py"
DOCS_REL = "docs/observability.md"
SERVER_CPP_REL = "native/src/server.cpp"

# Producer scan roots: every Python file here may stamp stages.
PRODUCER_ROOTS = ["infinistore_tpu"]
PRODUCER_EXTRA = ["bench.py"]


def recorder_stages(ctx: Context, rel: str = TRACING_REL) -> Tuple[List[str], Dict[str, str]]:
    """(STAGES tuple, SERVER_TICK_STAGES dict) from the tracing module."""
    tree = ast.parse(ctx.read(rel))
    stages: List[str] = []
    tick_map: Dict[str, str] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name == "STAGES" and isinstance(node.value, (ast.Tuple, ast.List)):
            stages = [
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
        elif name == "SERVER_TICK_STAGES" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    tick_map[k.value] = v.value
    return stages, tick_map


def producer_stamps(ctx: Context) -> List[Tuple[str, int, str]]:
    """Every (file, line, stage_name) a producer stamps: ``X.stage("n")``
    calls and ``stage="n"`` keywords (trace_op's entry stamp)."""
    out: List[Tuple[str, int, str]] = []
    files: List[str] = []
    for root in PRODUCER_ROOTS:
        files += ctx.walk_py(root)
    files += [f for f in PRODUCER_EXTRA if ctx.exists(f)]
    for rel in files:
        if rel == TRACING_REL:
            continue  # the module itself (docstrings/constants), not a producer
        try:
            tree = ast.parse(ctx.read(rel))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "stage"
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.append((rel, node.lineno, node.args[0].value))
            for kw in node.keywords:
                if (
                    kw.arg == "stage"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    out.append((rel, node.lineno, kw.value.value))
    return out


def scan(
    ctx: Context,
    tracing_rel: str = TRACING_REL,
    manage_rel: str = MANAGE_REL,
    docs_rel: str = DOCS_REL,
    server_cpp_rel: str = SERVER_CPP_REL,
) -> List[Finding]:
    findings: List[Finding] = []
    if not ctx.exists(tracing_rel):
        return findings
    stages, tick_map = recorder_stages(ctx, tracing_rel)
    stage_set: Set[str] = set(stages)

    def f(rule: str, file: str, line: int, slug: str, msg: str):
        findings.append(Finding(rule=rule, file=file, line=line, message=msg,
                                key=f"{rule}:{file}:{slug}"))

    # ITS-T001: producer stamps outside the vocabulary.
    stamps = producer_stamps(ctx)
    for rel, line, name in sorted(stamps):
        if name not in stage_set:
            f("ITS-T001", rel, line, name,
              f"producer stamps stage {name!r} which is not in "
              f"tracing.STAGES — add it to the vocabulary (and "
              f"{docs_rel}) or fix the stamp")

    # ITS-T002: vocabulary undocumented.
    docs = ctx.read(docs_rel) if ctx.exists(docs_rel) else ""
    doc_words = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", docs))
    for name in stages:
        if name not in doc_words:
            f("ITS-T002", docs_rel, 1, name,
              f"stage {name!r} (tracing.STAGES) is not described in "
              f"{docs_rel} — the span vocabulary table must cover every "
              "stage")

    # ITS-T003: /trace schema drift.
    manage_src = ctx.read(manage_rel) if ctx.exists(manage_rel) else ""
    if (
        not re.search(r'[\'"]/trace[\'"]', manage_src)
        or "_trace_payload" not in manage_src
    ):
        f("ITS-T003", manage_rel, 1, "trace-route",
          "manage plane must serve GET /trace (via _trace_payload) — the "
          "span dump + Chrome trace export surface (docs/observability.md)")
    if "STAGES" not in manage_src:
        f("ITS-T003", manage_rel, 1, "trace-schema",
          "/trace payload must serve the stage schema (tracing.STAGES) so "
          "consumers can interpret spans without reading the source")
    for field, name in sorted(tick_map.items()):
        if name not in stage_set:
            f("ITS-T003", tracing_rel, 1, f"tick:{field}",
              f"SERVER_TICK_STAGES maps native tick {field!r} to "
              f"{name!r}, which is not in tracing.STAGES")
    # The native reactor must emit every tick field the mapping names.
    cpp_src = ctx.read(server_cpp_rel) if ctx.exists(server_cpp_rel) else ""
    for field in sorted(tick_map):
        if f'\\"{field}\\"' not in cpp_src and f'"{field}"' not in cpp_src:
            f("ITS-T003", server_cpp_rel, 1, f"native:{field}",
              f"native stats_json trace entries do not emit {field!r}, "
              "but tracing.SERVER_TICK_STAGES maps it — the /trace join "
              "would silently drop the stage")

    # ITS-T004: dead vocabulary. Native-stamped stages count via tick_map.
    produced = {name for _, _, name in stamps} | set(tick_map.values())
    for name in stages:
        if name not in produced:
            f("ITS-T004", tracing_rel, 1, f"dead:{name}",
              f"stage {name!r} is in tracing.STAGES but no producer ever "
              "stamps it — dead vocabulary (docs and dashboards describe "
              "a stage that cannot occur)")
    return findings


@register("trace_stages",
          "trace stage vocabulary in lockstep across producers, /trace schema and docs (ITS-T*)",
          rule_prefix="ITS-T")
def check(ctx: Context) -> List[Finding]:
    return scan(ctx)
