"""Deterministic interleaving harness + lock tracer — the DYNAMIC side of
the ITS-R concurrency discipline (static side: tools/analysis/races.py).

Two instruments, no ``sys.settrace`` (tracing every opcode would perturb
the very schedules under test and cost ~30x):

- :class:`LockTracer` — a wrapped ``threading.Lock``/``RLock``/``Condition``
  factory shim. Code constructed under :func:`trace_locks` records every
  REAL acquisition order at test time: while a thread holds lock A and
  acquires lock B, the tracer records the edge ``A -> B``. Tests union the
  observed edges with the static lock-order graph
  (``races.lock_order_edges``) and assert the combined graph stays acyclic
  — so an acquisition order the static pass cannot see (callback
  indirection, data-dependent paths) still lands in the cycle check.

- :class:`Interleaver` — a bounded deterministic schedule explorer. A
  *schedule* is the exact global order in which named checkpoints may be
  passed (``["t1:load", "t2:load", "t2:store", "t1:store"]``); threads
  block at :meth:`Interleaver.point` until the front of the schedule is
  theirs. Shared state is instrumented (``instrument_mapping`` wraps a
  counter dict so its loads/stores are checkpoints), so a PLAUSIBLE static
  finding — "this ``d[k] += 1`` races" — becomes a REPRODUCIBLE failure:
  force ``t1`` to pause between its load and store while ``t2`` runs a
  full increment, and the lost update happens on every run, not one run in
  ten thousand. When the code is correctly locked the forced interleaving
  is IMPOSSIBLE: the second thread blocks on the guard before reaching its
  checkpoint, the explorer's stall watchdog trips, and the run reports
  ``serialized`` instead — which is exactly the regression assertion for a
  fixed race (tests/test_interleave.py).

A third, schedule-shaped bridge rides along: :func:`replay_schedule`
executes a model checker's serialized counterexample (an ITS-M violation's
action-name list, tools/analysis/modelcheck.py) against the REAL classes,
single-threaded and deterministic — how a refuted protocol invariant
becomes a committed regression test.

All are test-time instruments: nothing here imports the package, and
production code never pays for them.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Bound at import, BEFORE any trace_locks() patching: isinstance checks in
# adopt() must see the real Condition class even while the factory is
# swapped out.
_REAL_CONDITION = threading.Condition


# ---------------------------------------------------------------------------
# Lock tracer.
# ---------------------------------------------------------------------------

class TracedLock:
    """A real lock wrapped so every acquisition records ordering edges
    against the locks the acquiring thread already holds."""

    def __init__(self, tracer: "LockTracer", inner, name: str):
        self._tracer = tracer
        self._inner = inner
        self.name = name

    # threading.Condition probes these on its lock argument; delegate so a
    # TracedLock(RLock) behaves exactly like the RLock it wraps.
    def __getattr__(self, item):
        return getattr(self._inner, item)

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tracer._note_acquire(self)
        return got

    def release(self):
        self._tracer._note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class LockTracer:
    """Records (held -> acquired) edges and per-lock acquisition counts
    from every :class:`TracedLock` built under :func:`trace_locks`."""

    def __init__(self):
        self._tls = threading.local()
        self._mu = threading.Lock()
        self.locks: List[TracedLock] = []
        self.edges: Dict[Tuple[str, str], int] = {}
        self.acquisitions: Dict[str, int] = {}

    def _held(self) -> List[TracedLock]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    def _note_acquire(self, lock: TracedLock):
        held = self._held()
        with self._mu:
            self.acquisitions[lock.name] = self.acquisitions.get(lock.name, 0) + 1
            for h in held:
                if h.name != lock.name:
                    key = (h.name, lock.name)
                    self.edges[key] = self.edges.get(key, 0) + 1
        held.append(lock)

    def _note_release(self, lock: TracedLock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                break

    # -- naming -------------------------------------------------------------

    def adopt(self, obj, cls_name: Optional[str] = None):
        """Name every traced lock found in ``obj.__dict__`` as
        ``Class.attr`` — the same tokens the static graph uses, so
        observed and inferred edges join on identity. Only direct
        TracedLock attributes and REAL Condition objects (whose inner
        lock is traced) are renamed: a sub-object that happens to carry a
        ``_lock`` attribute (a DurableLog held by a cluster) keeps its
        own name and must be adopted itself, or its edges could never
        join the static graph's node for it."""
        cls_name = cls_name or type(obj).__name__
        for attr, val in vars(obj).items():
            if isinstance(val, TracedLock):
                val.name = f"{cls_name}.{attr}"
            elif isinstance(val, _REAL_CONDITION):
                inner = getattr(val, "_lock", None)
                if isinstance(inner, TracedLock):
                    inner.name = f"{cls_name}.{attr}"
        return obj

    def edge_set(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self.edges)


@contextmanager
def trace_locks():
    """Swap ``threading.Lock``/``RLock``/``Condition`` for traced
    factories while constructing the objects under test; restores the
    real factories on exit (already-built traced locks keep tracing)."""
    tracer = LockTracer()
    real_lock, real_rlock, real_cond = (
        threading.Lock, threading.RLock, threading.Condition,
    )
    counter = [0]

    def make(inner_factory, kind):
        def factory():
            counter[0] += 1
            lk = TracedLock(tracer, inner_factory(), f"{kind}#{counter[0]}")
            tracer.locks.append(lk)
            return lk
        return factory

    traced_lock = make(real_lock, "Lock")
    traced_rlock = make(real_rlock, "RLock")

    def traced_condition(lock=None):
        return real_cond(lock if lock is not None else traced_rlock())

    threading.Lock = traced_lock
    threading.RLock = traced_rlock
    threading.Condition = traced_condition
    try:
        yield tracer
    finally:
        threading.Lock = real_lock
        threading.RLock = real_rlock
        threading.Condition = real_cond


def find_cycle(edges: Sequence[Tuple[str, str]]) -> Optional[List[str]]:
    """First directed cycle in ``edges`` (as a node list), or None —
    the acyclicity assertion for static ∪ observed lock-order graphs."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    parent: Dict[str, str] = {}

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        for nxt in graph[n]:
            if color[nxt] == GREY:
                cycle = [nxt, n]
                cur = n
                while cur != nxt:
                    cur = parent[cur]
                    cycle.append(cur)
                return list(reversed(cycle))
            if color[nxt] == WHITE:
                parent[nxt] = n
                got = dfs(nxt)
                if got:
                    return got
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            got = dfs(n)
            if got:
                return got
    return None


# ---------------------------------------------------------------------------
# Deterministic schedule explorer.
# ---------------------------------------------------------------------------

@dataclass
class RunReport:
    """Outcome of one forced schedule.

    ``completed``  — every scheduled checkpoint was passed in order: the
                     forced interleaving HAPPENED (for a race schedule,
                     the racy outcome is now deterministic).
    ``serialized`` — the schedule stalled because some thread never
                     reached its next checkpoint (it was blocked on a
                     lock): the code under test MUTUALLY EXCLUDES the
                     sections — the regression verdict for a fixed race.
    ``stalled_at`` — the checkpoint the schedule was waiting on when the
                     watchdog tripped (None when completed).
    """

    completed: bool
    stalled_at: Optional[str]
    errors: List[BaseException] = field(default_factory=list)

    @property
    def serialized(self) -> bool:
        return not self.completed


class Interleaver:
    """Run two (or more) callables on real threads under a forced global
    checkpoint order. Instrumented shared state calls :meth:`point`
    with a label like ``"t1:load"``; the call blocks until the front of
    the schedule is that label. A thread that cannot reach its scheduled
    checkpoint within ``stall_timeout_s`` (because a lock correctly
    excludes it) trips the watchdog: the schedule aborts, every waiter is
    released, and the report says ``serialized``."""

    def __init__(self, schedule: Sequence[str], stall_timeout_s: float = 1.0):
        self.schedule: List[str] = list(schedule)
        self.stall_timeout_s = stall_timeout_s
        self._cv = threading.Condition()
        self._idx = 0
        self._aborted = False

    # -- checkpoints --------------------------------------------------------

    def point(self, label: str):
        """Block until the schedule's front equals ``label``. Labels not
        present anywhere in the schedule pass through immediately (so one
        instrumented dict can serve many schedules)."""
        with self._cv:
            if label not in self.schedule:
                return
            while not self._aborted:
                if self._idx >= len(self.schedule):
                    return  # schedule fully consumed: free-run to finish
                if self.schedule[self._idx] == label:
                    self._idx += 1
                    self._cv.notify_all()
                    return
                # Not our turn — but if this label never appears again,
                # fall through (a later loop iteration re-touches the key).
                if label not in self.schedule[self._idx:]:
                    return
                self._cv.wait(timeout=0.05)

    def thread_label(self) -> str:
        return threading.current_thread().name

    # -- instrumented state -------------------------------------------------

    def instrument_mapping(self, data: dict, key,
                           points: Tuple[str, str] = ("load", "store")) -> dict:
        """A dict replacement whose ``[key]`` load and store are
        checkpoints named ``<thread>:<load|store>`` — enough to force a
        scheduler switch INSIDE ``d[key] += 1``."""
        il = self
        load_tag, store_tag = points

        class _Instrumented(dict):
            def __getitem__(self, k):
                if k == key:
                    il.point(f"{il.thread_label()}:{load_tag}")
                return dict.__getitem__(self, k)

            def __setitem__(self, k, v):
                if k == key:
                    il.point(f"{il.thread_label()}:{store_tag}")
                dict.__setitem__(self, k, v)

        return _Instrumented(data)

    # -- driving ------------------------------------------------------------

    def run(self, actors: Dict[str, "callable"]) -> RunReport:
        """Run each actor callable on a thread named with its label;
        watchdog-abort when the schedule stops advancing."""
        errors: List[BaseException] = []

        def wrap(fn):
            def run():
                try:
                    fn()
                except BaseException as e:  # surfaced in the report
                    errors.append(e)
            return run

        threads = [
            threading.Thread(target=wrap(fn), name=label, daemon=True)
            for label, fn in actors.items()
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.stall_timeout_s
        last_idx = -1
        stalled_at: Optional[str] = None
        while True:
            with self._cv:
                idx = self._idx
                done = idx >= len(self.schedule)
            if done:
                break
            if idx != last_idx:
                last_idx = idx
                deadline = time.monotonic() + self.stall_timeout_s
            if time.monotonic() >= deadline:
                with self._cv:
                    stalled_at = (
                        self.schedule[self._idx]
                        if self._idx < len(self.schedule) else None
                    )
                    self._aborted = True
                    self._cv.notify_all()
                break
            time.sleep(0.002)
        for t in threads:
            t.join(timeout=5.0)
        alive = [t for t in threads if t.is_alive()]
        if alive:
            errors.append(RuntimeError(
                f"actors still alive after abort: {[t.name for t in alive]}"
            ))
        return RunReport(
            completed=stalled_at is None and not self._aborted,
            stalled_at=stalled_at, errors=errors,
        )


def replay_schedule(schedule: Sequence[str], actions: Dict[str, "callable"],
                    strict: bool = True) -> List[object]:
    """Drive REAL objects through a model-checker counterexample — the
    bridge from an ITS-M violation to a deterministic regression test.

    ``schedule`` is the serialized action-name list a spec violation
    carries (``specs.Violation.schedule``, JSON round-trippable);
    ``actions`` maps each action name to a callable over the real classes
    under test (e.g. ``{"exchange@0<-1": lambda: m0.merge_apply(...)}``).
    The schedule executes in order on THIS thread — the model's
    interleavings are total orders, so single-threaded replay is exact,
    with none of the Interleaver's watchdog machinery — and the per-step
    return values come back for the test to assert on.

    ``strict=False`` skips schedule entries with no mapping (pure-model
    steps like a crash marker the caller realizes some other way) instead
    of raising; skipped steps return ``None``.
    """
    results: List[object] = []
    for name in schedule:
        fn = actions.get(name)
        if fn is None:
            if strict:
                raise KeyError(
                    f"schedule step {name!r} has no action mapping; pass "
                    "strict=False to skip pure-model steps"
                )
            results.append(None)
            continue
        results.append(fn())
    return results


def force_lost_update(bump_a, bump_b, counters: dict, key,
                      stall_timeout_s: float = 1.0) -> Tuple[RunReport, int]:
    """The canonical ITS-R001 confirmation: force thread ``t1`` to pause
    between the load and store of ``counters[key] += 1`` while ``t2`` runs
    its full increment, then let ``t1`` store its stale value.

    ``bump_a``/``bump_b`` are callables that perform one increment of
    ``counters[key]`` (the REAL production code path under test — e.g.
    ``TierManager.note_cold_hit``). Returns ``(report, final_value)``:

    - unguarded increments  -> ``report.completed`` and final == initial+1
      (one update LOST, deterministically);
    - guarded increments    -> ``report.serialized`` (the second thread
      blocked on the guard; no interleaving possible) and final ==
      initial+2.
    """
    il = Interleaver(
        ["t1:load", "t2:load", "t2:store", "t1:store"],
        stall_timeout_s=stall_timeout_s,
    )
    instrumented = il.instrument_mapping(counters, key)
    report = il.run({
        "t1": lambda: bump_a(instrumented),
        "t2": lambda: bump_b(instrumented),
    })
    return report, instrumented[key]
