"""In-repo static-analysis suite: `python -m tools.analysis --all`.

Four project-specific checkers over invariants unit tests can only sample
(docs/static_analysis.md):

- ``wire_drift``  (ITS-W*): native/include/its/protocol.h and
  infinistore_tpu/wire.py must describe the same wire format.
- ``loop_block``  (ITS-L*): no blocking operation reachable from an
  ``async def`` body without an executor hop.
- ``counters``    (ITS-C*): every stat counter surfaces in the manage-plane
  exporters and the API reference — no silent observability drift.
- ``policy``      (ITS-P*): transport-error handling routes through the
  degrade policy; batched-op producers pass an explicit QoS class.
- ``trace_stages`` (ITS-T*): every stage name a tracing producer stamps
  must exist in tracing.STAGES, the /trace schema and
  docs/observability.md — the span vocabulary never drifts one-sided.
- ``races``       (ITS-R*): cross-thread shared-state guard discipline,
  lock-order acyclicity, journal-outside-lock, predicate-looped cv waits,
  concurrency-model docs lockstep; the dynamic confirmation side (lock
  tracer + deterministic interleaving) lives in interleave.py.
- ``modelcheck``  (ITS-M*): explicit-state model checking of the
  hand-written protocols (membership merge lattice, durable-log crash
  replay, ring publish/park/doorbell, QoS aging) over ALL interleavings,
  with a model<->implementation lockstep diff and replayable
  counterexample schedules (specs/ + interleave.replay_schedule).

Importing the subpackage registers every checker with core.CHECKERS.
"""

from . import core  # noqa: F401
from . import (  # noqa: F401
    counters, loop_block, modelcheck, policy, races, trace_stages,
    wire_drift,
)
from .core import CHECKERS, Context, Finding, run  # noqa: F401
